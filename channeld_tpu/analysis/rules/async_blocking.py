"""async-blocking: no synchronous blocking calls on the event loop.

The gateway is a single event loop: one ``time.sleep`` or sync socket
dial inside a coroutine stalls EVERY channel tick, trunk heartbeat and
client read for its duration — the exact failure mode the tick-budget
anomaly trigger exists to catch at runtime (doc/observability.md).
This rule catches it at lint time instead, across the event-loop
planes: core, federation, spatial.

Two scopes, union'd per function:

- **Lexical** (the original rule): any call site inside an ``async
  def`` (closures included — they run inline on the loop unless
  explicitly executor-bound).
- **Reachability** (doc/concurrency.md): any SYNC function whose
  thread-model domain set (analysis/threadmodel.py) includes a
  *steady* loop domain — tick-loop or trunk-reader — is on the loop
  just as surely as a coroutine is; per-function syntax cannot see the
  helper three calls below ``tick_once`` that opens a file.  The
  boot-loop domain is deliberately exempt: run_server/drain block
  before listeners open and after they close.

Detectors beyond the call table: ``Future.result()`` without a timeout
parks the loop indefinitely behind a worker (the device guard always
bounds its waits), and ``block_until_ready`` is a full device sync.
"""

from __future__ import annotations

import ast
import fnmatch

from .. import threadmodel
from ..astutil import call_name, direct_body_nodes, import_aliases, iter_functions
from ..engine import Finding, ModuleInfo, RepoContext, Rule

SCOPE_GLOBS = (
    "channeld_tpu/core/*.py",
    "channeld_tpu/federation/*.py",
    "channeld_tpu/spatial/*.py",
)

# Canonical call name -> short description of why it blocks.
BLOCKING_CALLS = {
    "time.sleep": "blocks the event loop; use await asyncio.sleep",
    "os.system": "spawns and WAITS for a shell on the loop",
    "os.popen": "synchronous pipe I/O on the loop",
    "os.fsync": "a disk flush can stall the loop for tens of ms; fsync "
                "belongs on a writer thread (core/wal.py discipline)",
    "subprocess.run": "synchronous subprocess wait on the loop",
    "subprocess.call": "synchronous subprocess wait on the loop",
    "subprocess.check_call": "synchronous subprocess wait on the loop",
    "subprocess.check_output": "synchronous subprocess wait on the loop",
    "subprocess.getoutput": "synchronous subprocess wait on the loop",
    "subprocess.Popen": "subprocess spawn blocks on fork/exec",
    "socket.create_connection": "synchronous TCP dial on the loop",
    "socket.socket": "raw sync socket in a coroutine",
    "socket.getaddrinfo": "synchronous DNS resolution on the loop",
    "open": "synchronous file open/read on the loop",
    "time.sleep_ms": "blocks the event loop",
    "jax.block_until_ready": "full device sync stalls the loop for the "
                             "whole dispatch queue",
}


class AsyncBlockingRule(Rule):
    name = "async-blocking"
    description = (
        "no time.sleep / sync socket / file I/O / fsync / subprocess / "
        "unbounded .result() calls on the event loop: async defs "
        "(lexical) plus sync functions reachable from the tick-loop/"
        "trunk-reader domains (call graph)"
    )

    def check_module(self, mod: ModuleInfo, repo: RepoContext) -> list[Finding]:
        lexical_scope = any(
            fnmatch.fnmatch(mod.rel, g) for g in SCOPE_GLOBS
        )
        reach_scope = threadmodel.in_scope(mod.rel)
        if not lexical_scope and not reach_scope:
            return []
        model = threadmodel.build_model(repo) if reach_scope else None
        aliases = import_aliases(mod.tree)
        findings: list[Finding] = []
        for fn in iter_functions(mod.tree):
            lexical = lexical_scope and fn.in_async
            reach = ""
            if not lexical and model is not None:
                domains = model.domains_of(mod.rel, fn.qualname)
                if model.is_steady_loop(domains):
                    reach = "/".join(sorted(
                        d for d in domains
                        if threadmodel.DOMAINS_BY_NAME[d].thread == "loop"
                        and threadmodel.DOMAINS_BY_NAME[d].steady
                    ))
            if not lexical and not reach:
                continue
            why_ctx = (
                "in async context" if lexical
                else f"reachable from the {reach} domain"
            )
            for node in direct_body_nodes(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                # Unbounded worker wait: fut.result() with no timeout
                # parks the loop behind the worker indefinitely. SYNC
                # functions only: inside a coroutine the receiver is
                # usually an asyncio Task/Future, whose result() is
                # non-blocking by contract (and takes no timeout — the
                # 'add a timeout' advice would be a TypeError there).
                if isinstance(func, ast.Attribute) \
                        and func.attr == "result" \
                        and not fn.in_async \
                        and not node.args \
                        and not any(kw.arg == "timeout"
                                    for kw in node.keywords):
                    findings.append(Finding(
                        rule=self.name, path=mod.rel, line=node.lineno,
                        message=(
                            f".result() without a timeout {why_ctx}: an "
                            "unbounded wait on a worker parks the loop "
                            "(the device guard always bounds its waits)"
                        ),
                        detector="result-no-timeout",
                        scope=fn.qualname,
                    ))
                    continue
                if isinstance(func, ast.Attribute) \
                        and func.attr == "block_until_ready":
                    findings.append(Finding(
                        rule=self.name, path=mod.rel, line=node.lineno,
                        message=(
                            f"block_until_ready() {why_ctx}: a full "
                            "device sync stalls the loop for the whole "
                            "dispatch queue"
                        ),
                        detector="block_until_ready",
                        scope=fn.qualname,
                    ))
                    continue
                name = call_name(node, aliases)
                if name is None:
                    continue
                why = BLOCKING_CALLS.get(name)
                if why is None:
                    continue
                findings.append(Finding(
                    rule=self.name,
                    path=mod.rel,
                    line=node.lineno,
                    message=f"blocking call {name}() {why_ctx}: {why}",
                    detector=name,
                    scope=fn.qualname,
                ))
        return findings

"""async-blocking: no synchronous blocking calls inside ``async def``.

The gateway is a single event loop: one ``time.sleep`` or sync socket
dial inside a coroutine stalls EVERY channel tick, trunk heartbeat and
client read for its duration — the exact failure mode the tick-budget
anomaly trigger exists to catch at runtime (doc/observability.md).
This rule catches it at lint time instead, across the event-loop
planes: core, federation, spatial.

Closures defined inside an ``async def`` are included: they run inline
on the loop unless explicitly shipped to an executor (if one ever is,
suppress with an inline ``# tpulint: disable`` and a reason).
"""

from __future__ import annotations

import ast

from ..astutil import call_name, direct_body_nodes, import_aliases, iter_functions
from ..engine import Finding, ModuleInfo, RepoContext, Rule

SCOPE_GLOBS = (
    "channeld_tpu/core/*.py",
    "channeld_tpu/federation/*.py",
    "channeld_tpu/spatial/*.py",
)

# Canonical call name -> short description of why it blocks.
BLOCKING_CALLS = {
    "time.sleep": "blocks the event loop; use await asyncio.sleep",
    "os.system": "spawns and WAITS for a shell on the loop",
    "os.popen": "synchronous pipe I/O on the loop",
    "subprocess.run": "synchronous subprocess wait on the loop",
    "subprocess.call": "synchronous subprocess wait on the loop",
    "subprocess.check_call": "synchronous subprocess wait on the loop",
    "subprocess.check_output": "synchronous subprocess wait on the loop",
    "subprocess.getoutput": "synchronous subprocess wait on the loop",
    "subprocess.Popen": "subprocess spawn blocks on fork/exec",
    "socket.create_connection": "synchronous TCP dial on the loop",
    "socket.socket": "raw sync socket in a coroutine",
    "socket.getaddrinfo": "synchronous DNS resolution on the loop",
    "open": "synchronous file open/read on the loop",
    "time.sleep_ms": "blocks the event loop",
}


class AsyncBlockingRule(Rule):
    name = "async-blocking"
    description = (
        "no time.sleep / sync socket / file I/O / subprocess calls "
        "inside async def (core, federation, spatial)"
    )

    def check_module(self, mod: ModuleInfo, repo: RepoContext) -> list[Finding]:
        import fnmatch

        if not any(fnmatch.fnmatch(mod.rel, g) for g in SCOPE_GLOBS):
            return []
        aliases = import_aliases(mod.tree)
        findings: list[Finding] = []
        for fn in iter_functions(mod.tree):
            if not fn.in_async:
                continue
            for node in direct_body_nodes(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node, aliases)
                if name is None:
                    continue
                # Normalize relative-import tails ("..core.time.sleep"
                # never happens for stdlib; aliases already canonical).
                why = BLOCKING_CALLS.get(name)
                if why is None:
                    continue
                findings.append(Finding(
                    rule=self.name,
                    path=mod.rel,
                    line=node.lineno,
                    message=f"blocking call {name}() in async context: {why}",
                    detector=name,
                    scope=fn.qualname,
                ))
        return findings

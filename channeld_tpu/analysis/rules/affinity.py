"""Concurrency-discipline rules over the thread model (doc/concurrency.md).

Five rules, all consuming ``analysis/threadmodel.py``'s call-graph
domain assignment:

- **thread-model** — every thread/executor entry point must be claimed
  by the declared model (a new ``threading.Thread``/``submit`` target
  outside the spec is a finding), and the spec itself must not rot
  (a seed matching nothing in a present module is stale).
- **shared-state** — a mutable instance attribute written from two or
  more OS threads must carry a declared handoff mechanism:
  ``# tpulint: shared=<lock|queue|fence|atomic|cond|event>`` on an
  assignment of that attribute inside the owner class.  An undeclared
  cross-domain write is exactly the bug class review kept catching
  (the PR 12 dead-writer flag, the PR 13 ring intake).
- **off-loop-asyncio** — asyncio primitives that are only safe on the
  loop thread (``call_soon``, ``call_later``, ``call_at``,
  ``create_task``, ``ensure_future``) are findings in any function
  reachable from an own-thread domain; off-loop code must use
  ``call_soon_threadsafe`` / ``run_coroutine_threadsafe``.
- **fence-discipline** — in ``ops/engine.py``, any store to
  engine-visible device state (``self._d_*``, ``self.generation``)
  reachable from the device-worker domain must be generation-fenced:
  a fence check (``_fence()`` or an ``if ... generation ... raise``)
  must sit between the staging work and the store, with no other call
  in between (the PR 9 ``_flush_host_state`` pattern, machine-checked).
- **live-iter** — an off-loop function iterating a loop-owned mapping
  view (``for x in self.thing.items()`` or a comprehension/genexp over
  one) races the loop's mutations across bytecode boundaries; it must
  snapshot first (``list(d.items())`` / ``sorted(d.items())`` are
  single C-level copies and stay allowed as direct arguments).
"""

from __future__ import annotations

import ast
import fnmatch
import re

from .. import threadmodel
from ..astutil import call_name, direct_body_nodes, dotted, import_aliases, iter_functions
from ..engine import Finding, ModuleInfo, RepoContext, Rule

# ---------------------------------------------------------------------------
# thread-model
# ---------------------------------------------------------------------------


class ThreadModelRule(Rule):
    name = "thread-model"
    description = (
        "every thread/executor entry point must be declared in the "
        "thread model (analysis/threadmodel.py DOMAINS); stale spec "
        "seeds are findings too"
    )
    # Stale-seed findings attribute to analysis/threadmodel.py while
    # the CAUSE is a rename in some other module (the same cross-file
    # attribution as proto-drift): they must survive the --changed
    # filter or the pre-commit hook passes exactly when the model rots.
    repo_wide = True

    def check_repo(self, repo: RepoContext) -> list[Finding]:
        model = threadmodel.build_model(repo)
        findings: list[Finding] = []
        for site in model.sites:
            if site.declared:
                continue
            findings.append(Finding(
                rule=self.name,
                path=site.rel,
                line=site.line,
                message=(
                    f"{site.kind} entry point {site.target_repr!r} is not "
                    "claimed by any execution domain — declare it in "
                    "analysis/threadmodel.py DOMAINS (seeds or "
                    "spawn_sites) so the concurrency rules see it"
                ),
                detector=f"undeclared-entry:{site.target_repr}",
                scope=site.site,
            ))
        for dom, glob, pattern in model.stale_seeds:
            findings.append(Finding(
                rule=self.name,
                path="channeld_tpu/analysis/threadmodel.py",
                line=1,
                message=(
                    f"domain {dom!r} seed ({glob!r}, {pattern!r}) matches "
                    "no function — the model is rotting (a rename moved "
                    "the entry point out from under it)"
                ),
                detector=f"stale-seed:{dom}:{pattern}",
                scope=dom,
            ))
        return findings


# ---------------------------------------------------------------------------
# shared-state
# ---------------------------------------------------------------------------

_MUTATORS = {
    "append", "add", "clear", "pop", "popitem", "update", "discard",
    "remove", "extend", "insert", "setdefault", "appendleft",
}

_SHARED_DECL_RE = re.compile(
    r"self\.(\w+)\s*(?::[^=#]+)?=.*#\s*tpulint:\s*shared=([a-z-]+)"
)
_SHARED_ANY_RE = re.compile(r"#\s*tpulint:\s*shared=([a-z-]+)")


def _self_attr_of(node: ast.AST) -> str | None:
    """The first attribute after ``self`` in a write-target chain
    (``self.a``, ``self.a.b``, ``self.a[k]`` all own attr ``a``)."""
    while isinstance(node, ast.Subscript):
        node = node.value
    chain = []
    while isinstance(node, ast.Attribute):
        chain.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name) and node.id == "self" and chain:
        return chain[-1]
    return None


def _attr_writes(fn_node: ast.AST):
    """(attr, line) pairs for every self-attribute mutation lexically in
    ``fn_node`` (nested defs excluded — they are their own functions)."""
    out = []
    for node in direct_body_nodes(fn_node):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                attr = _self_attr_of(t)
                if attr:
                    out.append((attr, node.lineno))
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            if getattr(node, "value", None) is None:
                continue
            attr = _self_attr_of(node.target)
            if attr:
                out.append((attr, node.lineno))
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                attr = _self_attr_of(t)
                if attr:
                    out.append((attr, node.lineno))
        elif isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Attribute) and func.attr in _MUTATORS:
                attr = _self_attr_of(func.value)
                if attr:
                    out.append((attr, node.lineno))
    return out


def _class_spans(tree: ast.AST):
    """[(class name, lineno, end_lineno)] innermost-last."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            out.append((node.name, node.lineno, node.end_lineno or node.lineno))
    return out


def _shared_declarations(mod: ModuleInfo):
    """{(class, attr): (mechanism, line)} plus findings for malformed
    declarations (unknown mechanism, or a shared= comment on a line
    that does not assign a self attribute)."""
    spans = _class_spans(mod.tree)
    decls: dict[tuple, tuple] = {}
    bad: list[tuple] = []  # (line, mechanism or None)
    for i, line in enumerate(mod.lines, start=1):
        m = _SHARED_ANY_RE.search(line)
        if not m:
            continue
        owner = None
        for name, lo, hi in spans:
            if lo <= i <= hi:
                owner = name  # innermost wins (spans walk outer-first)
        decl = _SHARED_DECL_RE.search(line)
        mech = m.group(1)
        if decl is None or owner is None:
            bad.append((i, None))
            continue
        if mech not in threadmodel.SHARED_MECHANISMS:
            bad.append((i, mech))
            continue
        decls[(owner, decl.group(1))] = (mech, i)
    return decls, bad


class SharedStateRule(Rule):
    name = "shared-state"
    description = (
        "instance attributes written from >=2 OS threads must declare "
        "their handoff mechanism: '# tpulint: shared=<mechanism>' on an "
        "assignment in the owner class (mechanisms: "
        + "/".join(threadmodel.SHARED_MECHANISMS) + ")"
    )

    def check_module(self, mod: ModuleInfo, repo: RepoContext) -> list[Finding]:
        if not threadmodel.in_scope(mod.rel):
            return []
        model = threadmodel.build_model(repo)
        decls, bad = _shared_declarations(mod)
        findings: list[Finding] = []
        for line, mech in bad:
            findings.append(Finding(
                rule=self.name, path=mod.rel, line=line,
                message=(
                    f"unknown shared= mechanism {mech!r} (use one of "
                    + ", ".join(threadmodel.SHARED_MECHANISMS) + ")"
                    if mech is not None else
                    "tpulint shared= declaration must sit on a self-"
                    "attribute assignment inside the owner class"
                ),
                detector="bad-shared-declaration",
            ))
        # attr key -> {fn qual: (domains, line)}
        per_attr: dict[tuple, dict] = {}
        for fn in iter_functions(mod.tree):
            parts = fn.qualname.split(".")
            if len(parts) < 2:
                continue
            cls = parts[0]
            domains = model.domains_of(mod.rel, fn.qualname)
            if not domains:
                continue  # unreached: tests/boot-construction only
            for attr, line in _attr_writes(fn.node):
                per_attr.setdefault((cls, attr), {})[fn.qualname] = (
                    domains, line
                )
        for (cls, attr), writers in sorted(per_attr.items()):
            threads = set()
            for domains, _line in writers.values():
                threads |= model.threads_of(domains)
            if len(threads) < 2:
                continue
            if (cls, attr) in decls:
                continue
            first = min(line for _d, line in writers.values())
            who = ", ".join(
                f"{q} [{'/'.join(sorted(d))}]"
                for q, (d, _l) in sorted(writers.items())
            )
            findings.append(Finding(
                rule=self.name, path=mod.rel, line=first,
                message=(
                    f"{cls}.{attr} is written from {len(threads)} threads "
                    f"({who}) with no declared handoff — protect it and "
                    "declare '# tpulint: shared=<mechanism>' on its "
                    "assignment in the class"
                ),
                detector="cross-domain-write",
                scope=f"{cls}.{attr}",
            ))
        return findings


# ---------------------------------------------------------------------------
# off-loop-asyncio
# ---------------------------------------------------------------------------

_LOOP_ONLY_METHODS = {"call_soon", "call_later", "call_at", "create_task"}
_LOOP_ONLY_CALLS = {"asyncio.ensure_future", "asyncio.create_task"}


class OffLoopAsyncioRule(Rule):
    name = "off-loop-asyncio"
    description = (
        "call_soon/call_later/call_at/create_task/ensure_future are "
        "loop-thread-only; functions reachable from an own-thread "
        "domain must use call_soon_threadsafe/run_coroutine_threadsafe"
    )

    def check_module(self, mod: ModuleInfo, repo: RepoContext) -> list[Finding]:
        if not threadmodel.in_scope(mod.rel):
            return []
        model = threadmodel.build_model(repo)
        aliases = import_aliases(mod.tree)
        findings: list[Finding] = []
        for fn in iter_functions(mod.tree):
            domains = model.domains_of(mod.rel, fn.qualname)
            off = model.off_loop(domains)
            if not off:
                continue
            for node in direct_body_nodes(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                hit = None
                if isinstance(func, ast.Attribute) \
                        and func.attr in _LOOP_ONLY_METHODS:
                    hit = func.attr
                else:
                    canonical = call_name(node, aliases)
                    if canonical in _LOOP_ONLY_CALLS:
                        hit = canonical.rsplit(".", 1)[1]
                if hit is None:
                    continue
                findings.append(Finding(
                    rule=self.name, path=mod.rel, line=node.lineno,
                    message=(
                        f"{hit}() in a function reachable from the "
                        f"{'/'.join(off)} thread(s): loop-only primitive "
                        "— use call_soon_threadsafe / "
                        "run_coroutine_threadsafe from off-loop code"
                    ),
                    detector=hit,
                    scope=fn.qualname,
                ))
        return findings


# ---------------------------------------------------------------------------
# fence-discipline
# ---------------------------------------------------------------------------

_ENGINE_REL = "channeld_tpu/ops/engine.py"


def _is_fence(stmt: ast.AST) -> bool:
    """A generation fence: a call to a ``*_fence`` helper, or an ``if``
    comparing against the generation whose body raises."""
    if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
        name = dotted(stmt.value.func) or ""
        return name.endswith("_fence") or name == "_fence"
    if isinstance(stmt, ast.If):
        mentions_gen = any(
            (isinstance(n, ast.Attribute) and n.attr == "generation")
            or (isinstance(n, ast.Name) and "generation" in n.id)
            or (isinstance(n, ast.Name) and n.id == "gen")
            for n in ast.walk(stmt.test)
        )
        raises = any(isinstance(n, ast.Raise) for s in stmt.body
                     for n in ast.walk(s))
        return mentions_gen and raises
    return False


def _has_unfenced_reset(stmt: ast.AST) -> bool:
    """True when the statement performs a call that could re-enter
    device work (anything but an allowlisted self.*.clear()/discard())."""
    for node in ast.walk(stmt):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Attribute) \
                    and func.attr in ("clear", "discard"):
                continue
            name = dotted(func) or ""
            if name.endswith("_fence"):
                continue
            return True
    return False


class FenceDisciplineRule(Rule):
    name = "fence-discipline"
    description = (
        "stores to engine-visible device state (self._d_*, generation) "
        "reachable from the device-worker domain must re-check the "
        "generation fence between staging and store (ops/engine.py "
        "_flush_host_state pattern)"
    )

    def _engine_store(self, stmt: ast.AST) -> list[tuple[str, int]]:
        out = []
        targets: list[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        for t in targets:
            attr = _self_attr_of(t)
            if attr and (attr.startswith("_d_") or attr == "generation"):
                out.append((attr, stmt.lineno))
        return out

    def _scan_body(self, body: list, fenced: bool, qual: str,
                   mod: ModuleInfo, findings: list) -> bool:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if _is_fence(stmt):
                fenced = True
                continue
            stores = self._engine_store(stmt)
            if stores:
                for attr, line in stores:
                    if not fenced:
                        findings.append(Finding(
                            rule=self.name, path=mod.rel, line=line,
                            message=(
                                f"store to engine-visible self.{attr} "
                                "without a generation re-check between "
                                "staging and store — a watchdog-"
                                "abandoned worker unwedging here would "
                                "commit stale arrays over a rebuilt "
                                "engine (doc/concurrency.md#fences)"
                            ),
                            detector=f"unfenced-store:{attr}",
                            scope=qual,
                        ))
                continue  # a fenced store keeps the fence for its block
            if isinstance(stmt, (ast.If, ast.For, ast.While, ast.With,
                                 ast.Try)):
                # The test/iter expression may itself call out.
                header = getattr(stmt, "test", None) or \
                    getattr(stmt, "iter", None)
                if header is not None and _has_unfenced_reset(
                        ast.Expr(value=header)):
                    fenced = False
                # Each branch is scanned from the PRE-statement state,
                # and the post-statement state is the conjunction of
                # every path's exit state — a fence inside one branch
                # must never license a store on the path that skipped
                # it (if-without-else, a zero-iteration loop, a raising
                # try body all fall through unfenced).
                exits = []
                branches = [getattr(stmt, "body", [])]
                if getattr(stmt, "orelse", None):
                    branches.append(stmt.orelse)
                elif isinstance(stmt, (ast.If, ast.For, ast.While)):
                    exits.append(fenced)  # the skipped/fall-through path
                if isinstance(stmt, (ast.For, ast.While)):
                    exits.append(fenced)  # zero iterations
                for h in getattr(stmt, "handlers", []):
                    branches.append(h.body)
                for sub in branches:
                    exits.append(self._scan_body(sub, fenced, qual, mod,
                                                 findings))
                fenced = all(exits) if exits else fenced
                if getattr(stmt, "finalbody", None):
                    fenced = self._scan_body(stmt.finalbody, fenced,
                                             qual, mod, findings)
                continue
            if _has_unfenced_reset(stmt):
                fenced = False
        return fenced

    def check_module(self, mod: ModuleInfo, repo: RepoContext) -> list[Finding]:
        if mod.rel != _ENGINE_REL:
            return []
        model = threadmodel.build_model(repo)
        findings: list[Finding] = []
        for fn in iter_functions(mod.tree):
            domains = model.domains_of(mod.rel, fn.qualname)
            if "device-worker" not in domains:
                continue
            self._scan_body(list(getattr(fn.node, "body", [])),
                            False, fn.qualname, mod, findings)
        return findings


# ---------------------------------------------------------------------------
# live-iter
# ---------------------------------------------------------------------------

_VIEW_METHODS = {"items", "values", "keys"}


class LiveIterRule(Rule):
    name = "live-iter"
    description = (
        "off-loop functions must not iterate loop-owned mapping views "
        "(for/comprehension over x.y.items()); snapshot with "
        "list()/sorted() first (single C-level copy)"
    )

    def check_module(self, mod: ModuleInfo, repo: RepoContext) -> list[Finding]:
        if not threadmodel.in_scope(mod.rel):
            return []
        model = threadmodel.build_model(repo)
        findings: list[Finding] = []
        for fn in iter_functions(mod.tree):
            domains = model.domains_of(mod.rel, fn.qualname)
            off = model.off_loop(domains)
            if not off:
                continue
            # Iteration under a held lock is the OTHER legitimate
            # pattern (the flight recorder's dump walks its ring dict
            # inside `with self._rings_lock:`): exempt With blocks
            # whose context expression names a lock/condition.
            locked_spans = []
            for node in ast.walk(fn.node):
                if isinstance(node, ast.With):
                    for item in node.items:
                        name = (dotted(item.context_expr) or "").lower()
                        if "lock" in name or "cond" in name:
                            locked_spans.append(
                                (node.lineno, node.end_lineno or node.lineno)
                            )
                            break
            iters: list[ast.AST] = []
            for node in direct_body_nodes(fn.node):
                if isinstance(node, ast.For):
                    iters.append(node.iter)
                elif isinstance(node, (ast.ListComp, ast.SetComp,
                                       ast.DictComp, ast.GeneratorExp)):
                    iters.extend(gen.iter for gen in node.generators)
            iters = [
                it for it in iters
                if not any(lo <= it.lineno <= hi for lo, hi in locked_spans)
            ]
            for it in iters:
                if not isinstance(it, ast.Call):
                    continue
                func = it.func
                if not (isinstance(func, ast.Attribute)
                        and func.attr in _VIEW_METHODS):
                    continue
                receiver = dotted(func.value)
                if receiver is None or "." not in receiver:
                    continue  # locals and bare names are out of scope
                findings.append(Finding(
                    rule=self.name, path=mod.rel, line=it.lineno,
                    message=(
                        f"iterating {receiver}.{func.attr}() from the "
                        f"{'/'.join(off)} thread(s) races loop mutations "
                        "across bytecode boundaries — snapshot first: "
                        f"list({receiver}.{func.attr}())"
                    ),
                    detector=f"live-iter:{receiver}.{func.attr}",
                    scope=fn.qualname,
                ))
        return findings

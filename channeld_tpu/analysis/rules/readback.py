"""hot-readback: no per-connection device->host syncs in tick paths.

ROADMAP item 1 measured the bug class this rule now pins: a
device->host readback per connection inside ``_apply_follow_interests``
cost ~330us per follower and was closed at ~11x by batching every
follower into ONE transfer (``engine.interested_cells_batch``,
BENCH_RESULTS.md round 12).  The fix only stays fixed if nobody
reintroduces an implicit sync — ``.item()``, ``np.asarray`` /
``np.array`` on engine arrays, ``float()`` over a scalar index, direct
scalar indexing of engine device arrays, or a call to the single-row
``interested_cells`` helper — inside the tick-path functions.

The allowlisted batched helpers (``interested_cells_batch``,
``handover_list``, ``undelivered_slots``) live in ``ops/engine.py``,
which is out of scope by construction: the engine owns its transfers,
the tick path must not add its own.  Designed one-transfer-per-tick
sites are baselined with a reason, not exempted by pattern.
"""

from __future__ import annotations

import ast

from ..astutil import dotted, import_aliases, iter_functions
from ..engine import Finding, ModuleInfo, RepoContext, Rule, match_scope

# (module glob, function-name regex): the tick/trunk/adoption hot paths.
HOT_PATHS: tuple[tuple[str, str], ...] = (
    ("channeld_tpu/spatial/tpu_controller.py",
     r"^(tick|_apply_follow_interests|_publish_due|_reap_followers|"
     r"device_due|_recenter_followers|collapse_micro_cells)$"),
    # The standing-query plane consumes its ONE pre-fetched changed-rows
    # blob per tick (doc/query_engine.md); every function that runs on
    # the tick path must stay transfer-free — the designed fetch lives
    # in engine.query_changed_rows / the guard's _step_body with
    # reasoned disables.
    ("channeld_tpu/spatial/queryplane.py",
     r"^(pump|_consume|_apply_pending|reap_closed|deregister|_install|"
     r"sensor_cells)$"),
    # Simulation plane (doc/simulation.md): the agent step is
    # device->device inside the guarded tick; the plane's ONLY readback
    # is the census-cadence batched fetch (reasoned disable in
    # on_result / the guard's prefetch) — everything else on its tick
    # path must stay transfer-free.
    ("channeld_tpu/sim/plane.py",
     r"^(pre_step|on_result|_micro_cells|_on_danger_cells|"
     r"on_geometry)$"),
    ("channeld_tpu/sim/authority.py", r"^(pump|commit|_attach)$"),
    # The supervised step wraps the per-tick device readbacks; its ONE
    # designed batched fetch (worker-thread _step_body) carries reasoned
    # disables, everything else in the guard must stay transfer-free.
    ("channeld_tpu/core/device_guard.py",
     r"^(run_step|_step_body|_sentinel|_dispatch)$"),
    ("channeld_tpu/spatial/grid.py", r"^_orchestrate"),
    ("channeld_tpu/spatial/controller.py", r"^tick$"),
    ("channeld_tpu/core/channel.py",
     r"^(tick_once|_tick_messages|_tick_connections|"
     r"_tick_recoverable_subscriptions)$"),
    ("channeld_tpu/federation/trunk.py",
     r"^(send|_dispatch|_read_loop|_heartbeat_loop|_on_heartbeat)$"),
    ("channeld_tpu/federation/plane.py",
     r"^(initiate_handover|_handle_|_on_|_commit_batch|_abort_batch|"
     r"_dst_fanout|_send_src_fanout|_reoffer_parked|_purge_local_placement)"),
    ("channeld_tpu/federation/control.py",
     r"^(_epoch_tick|_on_|_process_death|_begin_|_advance_|_finalize_|"
     r"_kick_drain|_census_advance|_restore_unclaimed|_evacuate_|"
     r"_sweep_stale_rows|_replicate|_build_vector)"),
    # WAL append surface (doc/persistence.md): journal hooks run inside
    # ticks and must never force a device sync (or any I/O — fsync
    # lives on the off-thread writer, which is out of scope by design).
    ("channeld_tpu/core/wal.py",
     r"^(append|note_dirty|on_global_tick|log_)"),
    # Fleet health plane (PR 13): the per-tick SLO hooks and the
    # staleness sample run inside the GLOBAL tick (the 24µs hot-path
    # budget doc/observability.md pins); the digest build/attach runs
    # on the control epoch inside the tick too. The ops handlers are
    # off-loop but still must not touch engine arrays — an /introspect
    # that syncs the device would stall the worker's dispatch queue.
    ("channeld_tpu/core/slo.py",
     r"^(on_global_tick|_evaluate|_feed|record_delivery|observe|"
     r"_sample_staleness|_rebuild_sample_ring)$"),
    ("channeld_tpu/core/opshttp.py",
     r"^(do_GET|readiness|introspect|_shard_ready|_device_ready|"
     r"_wal_ready|_trunk_ready)$"),
    ("channeld_tpu/federation/obs.py",
     r"^(build_local_digest|attach_digest|store_peer|refresh_local|"
     r"merged|merge_digests|render_)"),
)

# Calls that force a device->host transfer for ONE row/scalar.
_SINGLE_ROW_CALLS = {"interested_cells"}
# numpy entry points that materialize a device array on host.
_NP_MATERIALIZE = {"asarray", "array", "unpackbits", "copy"}


def _is_engine_chain(node: ast.AST) -> bool:
    """True for attribute chains rooted in an engine reference
    (``self.engine.X`` / ``engine.X``)."""
    name = dotted(node)
    return name is not None and (".engine." in f".{name}.")


class HotPathReadbackRule(Rule):
    name = "hot-readback"
    description = (
        "no implicit device->host syncs (.item(), np.asarray/np.array "
        "on engine arrays, scalar indexing, single-row interested_cells) "
        "in tick-path functions outside allowlisted batched helpers"
    )

    def check_module(self, mod: ModuleInfo, repo: RepoContext) -> list[Finding]:
        hot = [fn for fn in iter_functions(mod.tree)
               if match_scope(mod.rel, fn.name, HOT_PATHS)]
        if not hot:
            return []
        aliases = import_aliases(mod.tree)
        np_names = {local for local, target in aliases.items()
                    if target.lstrip(".") == "numpy"}
        findings: list[Finding] = []

        def flag(node: ast.AST, scope: str, detector: str, msg: str) -> None:
            # Hot functions can lexically contain one another's scan
            # roots (a nested def that itself matches the scope table):
            # dedupe by site so one expression flags once.
            if (node.lineno, detector) in seen:
                return
            seen.add((node.lineno, detector))
            findings.append(Finding(
                rule=self.name, path=mod.rel, line=node.lineno,
                message=msg, detector=detector, scope=scope,
            ))

        seen: set[tuple[int, str]] = set()

        for fn in hot:
            # Full walk INCLUDING nested defs/lambdas: a helper defined
            # inside tick() and called per connection performs its
            # readback on the hot path all the same (the async-blocking
            # rule covers nesting via FuncInfo.in_async; here the scope
            # is the hot function itself).
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Call):
                    func = node.func
                    if isinstance(func, ast.Attribute):
                        if func.attr == "item" and not node.args:
                            flag(node, fn.qualname, ".item()",
                                 ".item() forces a device->host sync per "
                                 "call")
                        elif func.attr in _SINGLE_ROW_CALLS:
                            flag(node, fn.qualname, f".{func.attr}()",
                                 f"single-row {func.attr}() reads back one "
                                 "device row per connection; use "
                                 "interested_cells_batch (ONE transfer "
                                 "per pass)")
                        elif (
                            func.attr in _NP_MATERIALIZE
                            and isinstance(func.value, ast.Name)
                            and func.value.id in np_names
                        ):
                            flag(node, fn.qualname, f"np.{func.attr}",
                                 f"np.{func.attr}() on a device array is "
                                 "an implicit device->host transfer")
                    elif (
                        isinstance(func, ast.Name)
                        and func.id in ("float", "int")
                        and node.args
                        and isinstance(node.args[0], ast.Subscript)
                    ):
                        flag(node, fn.qualname, f"{func.id}(subscript)",
                             f"{func.id}(arr[i]) over a device array reads "
                             "back one scalar per call; batch the transfer")
                elif isinstance(node, ast.Subscript):
                    if _is_engine_chain(node.value):
                        flag(node, fn.qualname, "engine-subscript",
                             "scalar indexing of an engine array syncs "
                             "device->host per element; fetch the batch "
                             "once")
        return findings

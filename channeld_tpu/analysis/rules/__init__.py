"""tpulint rule registry (doc/analysis.md#adding-a-rule)."""

from .accounting import DoubleEntryRule
from .affinity import (
    FenceDisciplineRule,
    LiveIterRule,
    OffLoopAsyncioRule,
    SharedStateRule,
    ThreadModelRule,
)
from .async_blocking import AsyncBlockingRule
from .excepts import ExceptHygieneRule
from .proto_drift import ProtoDriftRule
from .readback import HotPathReadbackRule
from .units import HistogramUnitsRule

ALL_RULES = (
    ProtoDriftRule,
    AsyncBlockingRule,
    HotPathReadbackRule,
    DoubleEntryRule,
    ExceptHygieneRule,
    HistogramUnitsRule,
    ThreadModelRule,
    SharedStateRule,
    OffLoopAsyncioRule,
    FenceDisciplineRule,
    LiveIterRule,
)


def make_rules(names: list[str] | None = None):
    rules = [cls() for cls in ALL_RULES]
    if names:
        wanted = set(names)
        unknown = wanted - {r.name for r in rules}
        if unknown:
            raise ValueError(f"unknown rule(s): {sorted(unknown)}")
        rules = [r for r in rules if r.name in wanted]
    return rules

"""except-hygiene: no silently swallowed broad excepts in hot paths.

A ``except Exception: pass`` in a tick, trunk or adoption path turns a
real failure (an undecodable frame, a half-applied handover, a device
error) into an invisible one — the soak's accounting then disagrees
with reality with nothing on the record.  In scope paths a broad
except must leave a trace: re-raise, bump a metric, log at warning+
(warn+ records feed the ``logs`` metric), or open a flight-recorder
span/event.  ``logger.debug`` does not count — it is off the record at
default levels.
"""

from __future__ import annotations

import ast

from ..astutil import dotted, iter_functions
from ..engine import Finding, ModuleInfo, RepoContext, Rule, match_scope

# Same shape as readback's HOT_PATHS, broadened to every trunk/adoption
# handler plus channel tick internals: the paths where accounting
# exactness is soak-asserted.
SCOPE: tuple[tuple[str, str], ...] = (
    ("channeld_tpu/spatial/tpu_controller.py",
     r"^(tick|_apply_follow_interests|_publish_due|_reap_followers|"
     r"_recenter_followers|collapse_micro_cells)$"),
    # Standing-query plane (doc/query_engine.md): the consume/apply pass
    # runs inside the GLOBAL tick and its ledgers are double-entry — a
    # swallowed failure desynchronizes ledger from metric and the soak's
    # exactness assertion lies.
    ("channeld_tpu/spatial/queryplane.py",
     r"^(pump|_consume|_apply_pending|reap_closed|deregister|_install|"
     r"_journal|restore_rows)$"),
    # Simulation plane (doc/simulation.md): cadence/census hooks run
    # inside the GLOBAL tick with double-entry ledgers — a swallowed
    # failure desynchronizes ledger from metric and the sim soak's
    # exactness assertion lies.
    ("channeld_tpu/sim/plane.py",
     r"^(pre_step|on_result|activate|on_agents_adopted|"
     r"on_agents_departed)$"),
    ("channeld_tpu/sim/authority.py", r"^(pump|commit)$"),
    ("channeld_tpu/spatial/grid.py", r"^_orchestrate"),
    ("channeld_tpu/spatial/controller.py", r"^tick$"),
    ("channeld_tpu/core/channel.py",
     r"^(tick_once|_tick_messages|_tick_connections|"
     r"_tick_recoverable_subscriptions|_deliver_forward_batch)$"),
    ("channeld_tpu/federation/trunk.py",
     r"^(send|_dispatch|_read_loop|_heartbeat_loop|_on_heartbeat)$"),
    ("channeld_tpu/federation/plane.py",
     r"^(initiate_handover|_handle_|_on_|_commit_batch|_abort_batch|"
     r"_dst_fanout|_send_src_fanout|_reoffer_parked|_flush_abort_notices)"),
    ("channeld_tpu/federation/control.py",
     r"^(_epoch_tick|_on_|_process_death|_begin_|_advance_|_finalize_|"
     r"_kick_drain|_census_advance|_restore_unclaimed|_evacuate_|"
     r"_replicate|_check_|_announce_resurrection|_yield_shard)"),
    # WAL hook surface (doc/persistence.md): these run on the tick path
    # — a swallowed failure here silently un-journals a transition and
    # the crash soak's exactness evaporates. The writer thread
    # (_writer_loop/_rewrite) is out of scope by design: it owns its
    # I/O error handling and never runs on the tick path.
    ("channeld_tpu/core/wal.py",
     r"^(append|note_dirty|on_global_tick|log_|_count_)"),
    # Fleet health plane (PR 13, doc/observability.md): the SLO
    # evaluation + staleness sample run inside the GLOBAL tick and the
    # breach ledger is double-entry — a swallowed failure here makes
    # the soak's ledger==metric assertion lie. The ops probes
    # (core/opshttp.py readiness/introspect) are the matching runtime
    # surface: a component probe that swallows its error reports a
    # half-truth to the orchestrator.
    ("channeld_tpu/core/slo.py",
     r"^(on_global_tick|_evaluate|_feed|record_delivery|observe|"
     r"_sample_staleness|_rebuild_sample_ring|_count_breach)$"),
    ("channeld_tpu/core/opshttp.py",
     r"^(do_GET|readiness|introspect|_shard_ready|_device_ready|"
     r"_wal_ready|_trunk_ready)$"),
    ("channeld_tpu/federation/obs.py",
     r"^(attach_digest|store_peer|refresh_local|merged|render_)"),
    # Adversarial edge plane (PR 16, doc/edge_hardening.md): the receive
    # path and the edge ladder run uncaught on the event loop — a
    # swallowed failure here is precisely the "parse failure becomes
    # gateway-fatal" defect class the wire fuzzer hunts, so every broad
    # except must stay connection-fatal AND on the record.
    ("channeld_tpu/core/edge.py",
     r"^(note_egress|note_drain|note_frames|edge_tick|quarantine|"
     r"_trim_to_watermark|_structured_disconnect|mark_full_resync)$"),
    ("channeld_tpu/core/connection.py",
     r"^(on_bytes|receive_message|flush|flush_ingest|flush_pending)$"),
    ("channeld_tpu/core/ddos.py", r"^check_unauth_conns_once$"),
    # The fuzz harness's catches ARE its oracle: each one must file a
    # Violation (traceback.format_exc on the record) or log warning+.
    ("channeld_tpu/chaos/fuzz.py", r"^(_feed|_pump_sync|run_case)$"),
)

_LOG_OK = {"warning", "error", "exception", "critical"}
_ACCOUNT_CALLS = {"_count", "_note", "_event", "count_shed", "append_event",
                  "span", "event", "stage",
                  # Edge-plane double-entry ledgers (core/edge.py) and the
                  # fuzzer's violation record (the captured traceback IS
                  # the trace).
                  "count_quarantine", "count_malformed", "count_egress_drop",
                  "count_reap", "format_exc"}


def _absolved(handler: ast.ExceptHandler) -> bool:
    """True when the handler body leaves a trace (raise / metric /
    warn+ log / trace span / ledger call)."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func)
        if name is None:
            continue
        parts = name.split(".")
        tail = parts[-1]
        if tail in _LOG_OK:
            return True
        if "metrics" in parts[:-1] and tail in ("inc", "dec", "set",
                                                "observe", "labels"):
            return True
        if tail in ("inc", "dec", "observe") and "labels" in parts:
            return True
        if tail in _ACCOUNT_CALLS:
            return True
    return False


class ExceptHygieneRule(Rule):
    name = "except-hygiene"
    description = (
        "broad excepts in tick/trunk/adoption paths must re-raise, bump "
        "a metric, log at warning+, or record a trace span"
    )

    def check_module(self, mod: ModuleInfo, repo: RepoContext) -> list[Finding]:
        scoped = [fn for fn in iter_functions(mod.tree)
                  if match_scope(mod.rel, fn.name, SCOPE)]
        if not scoped:
            return []
        findings: list[Finding] = []
        for fn in scoped:
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                def _broad_name(t: ast.AST) -> bool:
                    return (isinstance(t, ast.Name)
                            and t.id in ("Exception", "BaseException"))

                broad = (
                    node.type is None
                    or _broad_name(node.type)
                    or (isinstance(node.type, ast.Tuple)
                        and any(_broad_name(e) for e in node.type.elts))
                )
                if not broad or _absolved(node):
                    continue
                findings.append(Finding(
                    rule=self.name,
                    path=mod.rel,
                    line=node.lineno,
                    message="broad except swallows the failure with no "
                            "metric, warn+ log, span, or re-raise on the "
                            "record",
                    detector="swallowed-broad-except",
                    scope=fn.qualname,
                ))
        return findings

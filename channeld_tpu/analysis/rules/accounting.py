"""double-entry: metric declarations are the registry; bumps pair with
ledgers.

Two checks:

1. **Ledger pairing.**  Every Prometheus Counter in ``core/metrics.py``
   whose help text names a python ledger ("ledger" appears in the help)
   is double-entry: soak invariant checkers assert the python-side
   ledger equals the metric exactly, so a bump without the paired
   ledger write silently breaks soak accounting.  The rule requires
   every ``.inc()`` of a ledgered counter to sit in a function that
   also performs a ledger write (a ``self.X[...] = / +=`` dict store,
   a ``self.X += n`` tally, or a ``self.X.append(...)``) — the
   project-wide ``_count()`` idiom.

2. **Declaration + label-set consistency.**  Every metric referenced
   anywhere (``metrics.name`` attribute or a direct import from
   ``core.metrics``) must be declared in ``core/metrics.py``, and every
   use must match the declared label set: ``.labels()`` keywords must
   equal the declared labelnames, a labeled family cannot be bumped
   without ``.labels()``, an unlabeled one cannot be given labels, and
   positional ``.labels`` args are rejected (kwargs only — positional
   labels silently reorder on a declaration change).
"""

from __future__ import annotations

import ast

from ..astutil import dotted, iter_functions, metrics_aliases
from ..engine import Finding, ModuleInfo, RepoContext, Rule

METRICS_REL = "channeld_tpu/core/metrics.py"
_METRIC_CTORS = {"Counter", "Gauge", "Histogram", "Summary"}
_BUMP_METHODS = {"inc", "dec", "set", "observe"}


class MetricDecl:
    def __init__(self, attr: str, ctor: str, prom_name: str,
                 help_text: str, labels: tuple[str, ...]):
        self.attr = attr
        self.ctor = ctor
        self.prom_name = prom_name
        self.help = help_text
        self.labels = labels

    @property
    def ledgered(self) -> bool:
        return self.ctor == "Counter" and "ledger" in self.help.lower()


def parse_metric_decls(mod: ModuleInfo) -> dict[str, MetricDecl]:
    """Metric declarations from core/metrics.py, by attribute name."""
    decls: dict[str, MetricDecl] = {}
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)):
            continue
        ctor = dotted(node.value.func)
        if ctor is None or ctor.split(".")[-1] not in _METRIC_CTORS:
            continue
        args = node.value.args
        prom_name = ""
        help_text = ""
        labels: tuple[str, ...] = ()
        if args and isinstance(args[0], ast.Constant) \
                and isinstance(args[0].value, str):
            prom_name = args[0].value
        if len(args) > 1 and isinstance(args[1], ast.Constant) \
                and isinstance(args[1].value, str):
            help_text = args[1].value
        for extra in args[2:]:
            if isinstance(extra, (ast.List, ast.Tuple)):
                labels = tuple(
                    e.value for e in extra.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                )
        for kw in node.value.keywords:
            if kw.arg == "labelnames" and isinstance(kw.value,
                                                    (ast.List, ast.Tuple)):
                labels = tuple(
                    e.value for e in kw.value.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                )
        decls[node.targets[0].id] = MetricDecl(
            node.targets[0].id, ctor.split(".")[-1], prom_name,
            help_text, labels,
        )
    return decls


def _has_ledger_write(func_node: ast.AST) -> bool:
    """A self-attribute dict store / tally / append anywhere in the
    function body — the python half of double-entry accounting."""
    for node in ast.walk(func_node):
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
        elif isinstance(node, ast.AugAssign):
            target = node.target
        if target is not None:
            if isinstance(target, ast.Subscript) and isinstance(
                    target.value, ast.Attribute):
                return True
            if isinstance(node, ast.AugAssign) and isinstance(
                    target, ast.Attribute):
                return True
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "append"
                and isinstance(node.func.value, ast.Attribute)):
            return True
    return False


class DoubleEntryRule(Rule):
    name = "double-entry"
    description = (
        "ledgered *_total counter bumps pair with a python ledger write "
        "in the same function; every metric use matches its declaration "
        "and label set in core/metrics.py"
    )

    def _decls(self, repo: RepoContext) -> dict[str, MetricDecl]:
        cached = getattr(repo, "_metric_decls", None)
        if cached is None:
            mod = repo.module(METRICS_REL)
            cached = parse_metric_decls(mod) if mod else {}
            repo._metric_decls = cached
        return cached

    def check_module(self, mod: ModuleInfo, repo: RepoContext) -> list[Finding]:
        if mod.rel == METRICS_REL:
            return []
        decls = self._decls(repo)
        if not decls:
            return []
        mod_names, obj_names = metrics_aliases(mod.tree)
        if not mod_names and not obj_names:
            return []
        findings: list[Finding] = []
        func_of: dict[int, ast.AST] = {}
        qual_of: dict[int, str] = {}
        for fn in iter_functions(mod.tree):
            for sub in ast.walk(fn.node):
                # innermost function wins (walk order is outer->inner)
                func_of[id(sub)] = fn.node
                qual_of[id(sub)] = fn.qualname

        def metric_attr(node: ast.AST) -> str | None:
            """metrics.<attr> or a direct-imported metric name."""
            if isinstance(node, ast.Attribute) and isinstance(
                    node.value, ast.Name) and node.value.id in mod_names:
                return node.attr
            if isinstance(node, ast.Name) and node.id in obj_names:
                return obj_names[node.id]
            return None

        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not isinstance(func, ast.Attribute):
                continue
            scope = qual_of.get(id(node), "")

            # metrics.X.labels(...) -----------------------------------
            if func.attr == "labels":
                attr = metric_attr(func.value)
                if attr is None:
                    continue
                decl = decls.get(attr)
                if decl is None:
                    findings.append(Finding(
                        rule=self.name, path=mod.rel, line=node.lineno,
                        message=f"metric {attr!r} is not declared in "
                                f"core/metrics.py",
                        detector=f"undeclared:{attr}", scope=scope))
                    continue
                if not decl.labels:
                    findings.append(Finding(
                        rule=self.name, path=mod.rel, line=node.lineno,
                        message=f"metric {attr!r} is declared without "
                                "labels but used with .labels()",
                        detector=f"labels-on-unlabeled:{attr}", scope=scope))
                    continue
                if node.args:
                    findings.append(Finding(
                        rule=self.name, path=mod.rel, line=node.lineno,
                        message=f"positional .labels() args on {attr!r}; "
                                "use keywords so a declaration reorder "
                                "cannot silently swap label values",
                        detector=f"positional-labels:{attr}", scope=scope))
                    continue
                used = {kw.arg for kw in node.keywords if kw.arg}
                if used != set(decl.labels):
                    findings.append(Finding(
                        rule=self.name, path=mod.rel, line=node.lineno,
                        message=f"label set {sorted(used)} on {attr!r} "
                                f"does not match declared "
                                f"{sorted(decl.labels)}",
                        detector=f"label-mismatch:{attr}", scope=scope))
                continue

            # metrics.X.inc()/set()/observe()/dec() -------------------
            if func.attr in _BUMP_METHODS:
                base = func.value
                attr = metric_attr(base)
                labeled_call = False
                if attr is None and isinstance(base, ast.Call) \
                        and isinstance(base.func, ast.Attribute) \
                        and base.func.attr == "labels":
                    attr = metric_attr(base.func.value)
                    labeled_call = True
                if attr is None:
                    continue
                decl = decls.get(attr)
                if decl is None:
                    findings.append(Finding(
                        rule=self.name, path=mod.rel, line=node.lineno,
                        message=f"metric {attr!r} is not declared in "
                                f"core/metrics.py",
                        detector=f"undeclared:{attr}", scope=scope))
                    continue
                if decl.labels and not labeled_call:
                    findings.append(Finding(
                        rule=self.name, path=mod.rel, line=node.lineno,
                        message=f"labeled metric {attr!r} bumped without "
                                f".labels() (declared labels: "
                                f"{sorted(decl.labels)})",
                        detector=f"missing-labels:{attr}", scope=scope))
                if decl.ledgered and func.attr == "inc":
                    owner = func_of.get(id(node))
                    if owner is None or not _has_ledger_write(owner):
                        findings.append(Finding(
                            rule=self.name, path=mod.rel, line=node.lineno,
                            message=f"ledgered counter {attr!r} bumped "
                                    "without a python ledger write in the "
                                    "same function (double-entry: soaks "
                                    "assert ledger == metric exactly)",
                            detector=f"unpaired:{attr}", scope=scope))
        return findings

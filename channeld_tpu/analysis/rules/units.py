"""histogram-units: one unit convention for every histogram family.

The metric surface accumulated two unit idioms — ``*_seconds``
families (the reference's prometheus-idiomatic convention) and
``*_ms`` families (the soak/bench artifacts' readability convention).
Both are fine; an *unlabeled* family or a family whose bucket edges
were authored in the other unit is not (a dashboard reading
``trunk_rtt`` as seconds is off by 1000x and nothing fails). The
convention (doc/observability.md#metric-unit-conventions):

- Every ``Histogram`` declared in ``core/metrics.py`` must end in
  ``_ms``, ``_seconds`` or ``_bytes``.
- Bucket edges must be plausible for the suffix: ``_seconds`` edges
  live in [1e-6, 600] (nothing the gateway times takes ten minutes);
  ``_ms`` edges live in [1e-3, 600000] AND the largest edge is at
  least 0.5 (an _ms family whose edges top out below half a
  millisecond was almost certainly authored in seconds); ``_bytes``
  edges are positive.
- A histogram with no explicit ``buckets=`` uses prometheus' default
  edges, which are seconds-scale — so the name must end ``_seconds``.

Grandfathered families (reference-parity names that predate the
convention) are baselined with reasons in ``analysis_baseline.json``.
"""

from __future__ import annotations

import ast

from ..engine import Finding, ModuleInfo, RepoContext, Rule

METRICS_REL = "channeld_tpu/core/metrics.py"

# suffix -> (min edge, max edge) plausibility band.
_EDGE_BANDS = {
    "_seconds": (1e-6, 600.0),
    "_ms": (1e-3, 600000.0),
    "_bytes": (1.0, float("inf")),
}


def _const_edges(node: ast.AST) -> list[float] | None:
    """Numeric bucket edges from a literal tuple/list; None when the
    expression is not a literal sequence of numbers."""
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    edges: list[float] = []
    for e in node.elts:
        if isinstance(e, ast.Constant) and isinstance(e.value, (int, float)):
            edges.append(float(e.value))
        else:
            return None
    return edges


class HistogramUnitsRule(Rule):
    name = "histogram-units"
    description = (
        "histogram families in core/metrics.py end in _ms/_seconds/"
        "_bytes and their bucket edges match the suffix"
    )

    def check_module(self, mod: ModuleInfo, repo: RepoContext) -> list[Finding]:
        if mod.rel != METRICS_REL:
            return []
        # Module-level literal-tuple constants (shared bucket tables
        # like DELIVERY_LATENCY_BUCKETS): a buckets= referencing one
        # resolves to its edges instead of escaping the check.
        consts: dict[str, list[float]] = {}
        for node in ast.walk(mod.tree):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                edges = _const_edges(node.value)
                if edges is not None:
                    consts[node.targets[0].id] = edges
        findings: list[Finding] = []
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)):
                continue
            func = node.value.func
            ctor = func.attr if isinstance(func, ast.Attribute) else (
                func.id if isinstance(func, ast.Name) else "")
            if ctor != "Histogram":
                continue
            attr = node.targets[0].id
            args = node.value.args
            prom_name = ""
            if args and isinstance(args[0], ast.Constant) \
                    and isinstance(args[0].value, str):
                prom_name = args[0].value
            suffix = next(
                (s for s in _EDGE_BANDS if prom_name.endswith(s)), None)
            if suffix is None:
                findings.append(Finding(
                    rule=self.name, path=mod.rel, line=node.lineno,
                    message=(
                        f"histogram {prom_name!r} has no unit suffix; "
                        "families must end in _ms/_seconds/_bytes "
                        "(doc/observability.md#metric-unit-conventions)"
                    ),
                    detector=f"suffix:{attr}", scope="",
                ))
                continue
            buckets_node = next(
                (kw.value for kw in node.value.keywords
                 if kw.arg == "buckets"), None)
            if buckets_node is None:
                if suffix != "_seconds":
                    findings.append(Finding(
                        rule=self.name, path=mod.rel, line=node.lineno,
                        message=(
                            f"histogram {prom_name!r} uses the prometheus "
                            "default buckets, which are seconds-scale, "
                            f"but is named {suffix}"
                        ),
                        detector=f"edges:{attr}", scope="",
                    ))
                continue
            edges = _const_edges(buckets_node)
            if edges is None and isinstance(buckets_node, ast.Name):
                edges = consts.get(buckets_node.id)
            if edges is None or not edges:
                continue  # computed edges: out of static reach
            lo, hi = _EDGE_BANDS[suffix]
            bad = [e for e in edges if not (lo <= e <= hi)]
            if bad:
                findings.append(Finding(
                    rule=self.name, path=mod.rel, line=node.lineno,
                    message=(
                        f"histogram {prom_name!r} ({suffix}) has bucket "
                        f"edges {bad} outside the plausible "
                        f"[{lo}, {hi}] band for its unit"
                    ),
                    detector=f"edges:{attr}", scope="",
                ))
            elif suffix == "_ms" and max(edges) < 0.5:
                findings.append(Finding(
                    rule=self.name, path=mod.rel, line=node.lineno,
                    message=(
                        f"histogram {prom_name!r} is named _ms but every "
                        f"bucket edge is under 0.5 (max {max(edges)}) — "
                        "edges authored in seconds?"
                    ),
                    detector=f"edges:{attr}", scope="",
                ))
        return findings

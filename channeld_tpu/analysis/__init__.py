"""tpulint: the project-invariant static-analysis suite.

Turns the conventions seven PRs of soak-proven machinery rely on into
mechanical, tier-1-gated checks: proto/pb2 drift + one global msgType
registry, no blocking calls in async paths, no per-connection
device->host readbacks in tick paths, double-entry counter/ledger
accounting, and exception hygiene in the hot paths.

Driver: ``scripts/analyze.py`` (``--changed`` for pre-commit).
Docs: ``doc/analysis.md``.  Gate: ``tests/test_analysis.py``.
"""

from .engine import (  # noqa: F401
    BASELINE_FILE,
    Baseline,
    Finding,
    ModuleInfo,
    RepoContext,
    Report,
    Rule,
    load_repo,
    run_analysis,
)
from .rules import ALL_RULES, make_rules  # noqa: F401

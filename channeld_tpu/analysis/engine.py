"""tpulint rule engine: findings, suppressions, baseline, runner.

The analysis suite turns the project's review-enforced conventions into
mechanical checks (doc/analysis.md).  Design points:

- **Pure static**: rules work on ``ast`` trees and file text only; no
  project module is imported (the proto-drift rule reads pb2 *source*,
  so it can inspect a drifted descriptor without executing it).
- **Findings fail the build** (tier-1 runs the full suite via
  ``tests/test_analysis.py``) unless suppressed with a *reason*, either
  inline (``# tpulint: disable=rule -- reason``) or in the committed
  baseline file ``analysis_baseline.json``.  A suppression without a
  reason is itself a finding; a baseline entry nothing matches is
  reported as stale so suppressions cannot outlive their target.
- **Stable keys**: baseline entries key on
  ``rule:path:scope:detector`` — no line numbers, so unrelated edits
  don't rot the baseline.
"""

from __future__ import annotations

import ast
import fnmatch
import json
import os
import re
from dataclasses import dataclass, field

BASELINE_FILE = "analysis_baseline.json"

# Directories scanned for python modules (relative to repo root).
PY_SCAN_DIRS = ("channeld_tpu", "scripts")
_SKIP_PARTS = ("__pycache__",)
_SKIP_SUFFIXES = ("_pb2.py",)


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # repo-relative, forward slashes
    line: int
    message: str
    detector: str      # stable tag for the baseline key (no line numbers)
    scope: str = ""    # enclosing symbol (function/class/message), if any

    @property
    def key(self) -> str:
        return f"{self.rule}:{self.path}:{self.scope}:{self.detector}"

    def render(self) -> str:
        where = f"{self.path}:{self.line}"
        scope = f" [{self.scope}]" if self.scope else ""
        return f"{where}: {self.rule}{scope}: {self.message}"


@dataclass
class ModuleInfo:
    """One python module under analysis."""

    path: str          # absolute
    rel: str           # repo-relative, forward slashes
    text: str
    tree: ast.AST
    lines: list[str] = field(default_factory=list)

    @classmethod
    def load(cls, path: str, repo: str) -> "ModuleInfo | SyntaxError":
        rel = os.path.relpath(path, repo).replace(os.sep, "/")
        with open(path, encoding="utf-8") as fh:
            text = fh.read()
        try:
            tree = ast.parse(text, filename=rel)
        except SyntaxError as e:
            return e
        return cls(path=path, rel=rel, text=text, tree=tree,
                   lines=text.split("\n"))


@dataclass
class RepoContext:
    """Everything a rule may look at."""

    root: str
    modules: list[ModuleInfo]
    # None = analyze everything (full run); a set of repo-relative paths
    # = only report findings attributable to those files (--changed).
    changed: set[str] | None = None
    # (repo-relative path, error text) for files ast could not parse —
    # surfaced as findings so an unparseable module can never silently
    # evade every rule.
    parse_failures: list[tuple[str, str]] = field(default_factory=list)

    def module(self, rel: str) -> ModuleInfo | None:
        for m in self.modules:
            if m.rel == rel:
                return m
        return None

    def read(self, rel: str) -> str | None:
        path = os.path.join(self.root, rel)
        if not os.path.exists(path):
            return None
        with open(path, encoding="utf-8") as fh:
            return fh.read()


class Rule:
    """Base rule.  Subclasses set ``name``/``description`` and override
    one or both hooks."""

    name = ""
    description = ""
    # Repo-wide rules attribute findings to files OTHER than the one
    # that changed (a .proto edit flags the stale pb2): their findings
    # survive the --changed filter whenever the rule runs at all (the
    # driver gates WHETHER it runs on the changed set).
    repo_wide = False

    def check_module(self, mod: ModuleInfo, repo: RepoContext) -> list[Finding]:
        return []

    def check_repo(self, repo: RepoContext) -> list[Finding]:
        return []


# ---------------------------------------------------------------------------
# inline suppressions
# ---------------------------------------------------------------------------

_DISABLE_RE = re.compile(
    r"#\s*tpulint:\s*disable=([a-z0-9_,-]+)(?:\s+--\s+(.*\S))?"
)


def inline_suppressions(
    mod: ModuleInfo,
) -> tuple[dict[int, set[str]], list[Finding]]:
    """{line number: {rule names}} suppressed inline, plus findings for
    suppressions missing the mandatory ``-- reason``.

    A directive covers its own line and, when it is a comment-only
    line, the next line.
    """
    by_line: dict[int, set[str]] = {}
    findings: list[Finding] = []
    for i, line in enumerate(mod.lines, start=1):
        m = _DISABLE_RE.search(line)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        if not m.group(2):
            findings.append(Finding(
                rule="tpulint",
                path=mod.rel,
                line=i,
                message="tpulint disable comment without a '-- reason'",
                detector="disable-without-reason",
                scope="",
            ))
            continue
        by_line.setdefault(i, set()).update(rules)
        if line.strip().startswith("#"):
            by_line.setdefault(i + 1, set()).update(rules)
    return by_line, findings


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

@dataclass
class Baseline:
    entries: dict[str, str]  # key -> reason

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls(entries={})
        with open(path) as fh:
            doc = json.load(fh)
        entries: dict[str, str] = {}
        for item in doc.get("suppressions", []):
            key = item.get("key", "")
            reason = (item.get("reason") or "").strip()
            if key:
                entries[key] = reason
        return cls(entries=entries)


@dataclass
class Report:
    findings: list[Finding]               # unsuppressed — these fail
    suppressed: list[tuple[Finding, str]]  # (finding, reason)
    stale_baseline: list[str]             # baseline keys nothing matched
    unreasoned_baseline: list[str]        # baseline keys without a reason

    @property
    def ok(self) -> bool:
        return not self.findings and not self.unreasoned_baseline


def _iter_py_files(repo: str) -> list[str]:
    out: list[str] = []
    for top in PY_SCAN_DIRS:
        base = os.path.join(repo, top)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d not in _SKIP_PARTS]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                if any(fn.endswith(sfx) for sfx in _SKIP_SUFFIXES):
                    continue
                out.append(os.path.join(dirpath, fn))
    return out


def load_repo(
    repo: str, changed: set[str] | None = None
) -> RepoContext:
    modules = []
    failures: list[tuple[str, str]] = []
    for path in _iter_py_files(repo):
        mod = ModuleInfo.load(path, repo)
        if isinstance(mod, ModuleInfo):
            modules.append(mod)
        else:
            rel = os.path.relpath(path, repo).replace(os.sep, "/")
            failures.append((rel, str(mod)))
    return RepoContext(root=repo, modules=modules, changed=changed,
                       parse_failures=failures)


def run_analysis(
    repo: RepoContext,
    rules: list[Rule],
    baseline: Baseline | None = None,
) -> Report:
    baseline = baseline or Baseline(entries={})
    raw: list[Finding] = []
    for rel, err in repo.parse_failures:
        raw.append(Finding(
            rule="tpulint", path=rel, line=1,
            message=f"module does not parse ({err}); it is invisible to "
                    "every rule",
            detector="syntax-error",
        ))
    sup_map: dict[str, dict[int, set[str]]] = {}
    for mod in repo.modules:
        by_line, meta = inline_suppressions(mod)
        sup_map[mod.rel] = by_line
        raw.extend(meta)
        for rule in rules:
            raw.extend(rule.check_module(mod, repo))
    for rule in rules:
        raw.extend(rule.check_repo(repo))

    if repo.changed is not None:
        repo_wide = {r.name for r in rules if r.repo_wide}
        raw = [f for f in raw
               if f.path in repo.changed or f.rule in repo_wide]

    findings: list[Finding] = []
    suppressed: list[tuple[Finding, str]] = []
    used_keys: set[str] = set()
    for f in raw:
        inline = sup_map.get(f.path, {}).get(f.line, set())
        if f.rule in inline:
            suppressed.append((f, "inline"))
            continue
        if f.key in baseline.entries:
            used_keys.add(f.key)
            suppressed.append((f, baseline.entries[f.key]))
            continue
        findings.append(f)

    stale = []
    if repo.changed is None:
        # Only a full run can prove a baseline entry stale, and only for
        # the rules that actually ran.
        ran = {r.name for r in rules}
        stale = sorted(
            key for key in set(baseline.entries) - used_keys
            if key.split(":", 1)[0] in ran
        )
    # A reason is mandatory for EVERY committed entry, matched or stale
    # — a reasonless entry whose finding has since disappeared must
    # still fail, or it silently outlives its justification.
    unreasoned = sorted(
        key for key, reason in baseline.entries.items() if not reason
    )
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return Report(
        findings=findings,
        suppressed=suppressed,
        stale_baseline=stale,
        unreasoned_baseline=unreasoned,
    )


def match_scope(rel: str, name: str,
                spec: tuple[tuple[str, str], ...]) -> bool:
    """True when (module path, function name) matches one (glob, regex)
    row of a scope spec."""
    for glob, name_re in spec:
        if fnmatch.fnmatch(rel, glob) and re.match(name_re, name):
            return True
    return False

"""A pure-python ``.proto`` -> ``FileDescriptorProto`` compiler.

There is no protoc in the image: the committed ``*_pb2.py`` modules are
regenerated *by hand* (historically by editing the serialized descriptor
blob in place — see doc/analysis.md).  That convention is exactly the
kind that silently breaks wire compatibility, so this module gives the
repo a checkable source of truth: it parses the subset of proto3 the
project's schemas use and builds a real
``google.protobuf.descriptor_pb2.FileDescriptorProto`` — byte-for-byte
what protoc would serialize for these files (field-number-ordered
serialization, synthetic oneofs for ``optional`` fields, no json_name
for derivable names).

Consumers:

- ``analysis/rules/proto_drift.py`` diffs the parsed schema against the
  committed pb2 descriptor (drift rule, gated in tier-1).
- ``scripts/regen_pb2.py`` regenerates a pb2 module from the ``.proto``
  (the descriptor-rewrite regen path), round-trip-tested in
  ``tests/test_analysis.py``.

Supported subset (everything under ``channeld_tpu/protocol/``): proto3
syntax, packages, imports, messages (nested), enums (nested and top
level), scalar/message/enum fields, ``repeated`` and proto3
``optional``.  Unsupported constructs (maps, real oneofs, services,
options, extensions) raise ``ProtoParseError`` — extend the parser when
a schema first needs them rather than silently mis-compiling.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field

from google.protobuf import descriptor_pb2


class ProtoParseError(Exception):
    pass


# FieldDescriptorProto.Type values for scalar type names.
SCALAR_TYPES = {
    "double": 1, "float": 2, "int64": 3, "uint64": 4, "int32": 5,
    "fixed64": 6, "fixed32": 7, "bool": 8, "string": 9, "bytes": 12,
    "uint32": 13, "sfixed32": 15, "sfixed64": 16, "sint32": 17,
    "sint64": 18,
}
TYPE_MESSAGE = 11
TYPE_ENUM = 14
LABEL_OPTIONAL = 1
LABEL_REPEATED = 3

# Well-known imports we cannot parse from disk (the runtime ships them
# pre-compiled): import path -> {symbol full name: is_message}.
WELL_KNOWN = {
    "google/protobuf/any.proto": {".google.protobuf.Any": True},
}

_TOKEN_RE = re.compile(
    r'"(?:[^"\\]|\\.)*"'      # string literal
    r"|[A-Za-z_][A-Za-z0-9_.]*"  # identifier / dotted reference
    r"|-?\d+"                  # integer
    r"|[{}=;<>,\[\]()]",       # punctuation
)

# ``msgType N`` claims in the comment block attached to a message: the
# project documents every extension message's wire msgType this way, and
# the drift rule cross-checks the claims against the python registries.
# (\b after "msgType" keeps the plural "msgTypes 30-37" range prose from
# matching — 's' is a word char, so there is no boundary.)
_MSGTYPE_CLAIM_RE = re.compile(r"\bmsgType\s+(\d+)\b")


@dataclass
class ParsedField:
    name: str
    number: int
    label: int
    type: int            # 0 until resolved for named types
    type_ref: str | None  # unresolved reference text, None for scalars
    type_name: str = ""   # resolved full name (".chtpu.X")
    proto3_optional: bool = False
    oneof_index: int | None = None


@dataclass
class ParsedEnum:
    name: str
    full_name: str
    values: list[tuple[str, int]] = field(default_factory=list)


@dataclass
class ParsedMessage:
    name: str
    full_name: str
    fields: list[ParsedField] = field(default_factory=list)
    nested: list["ParsedMessage"] = field(default_factory=list)
    enums: list[ParsedEnum] = field(default_factory=list)
    oneofs: list[str] = field(default_factory=list)
    # msgType numbers claimed by the doc comment attached to this message.
    msgtype_claims: list[int] = field(default_factory=list)


@dataclass
class ParsedFile:
    path: str            # import path, e.g. channeld_tpu/protocol/wire.proto
    package: str
    syntax: str
    imports: list[str] = field(default_factory=list)
    messages: list[ParsedMessage] = field(default_factory=list)
    enums: list[ParsedEnum] = field(default_factory=list)


class _Tokens:
    def __init__(self, text: str, path: str):
        self.toks = _TOKEN_RE.findall(text)
        self.i = 0
        self.path = path

    def peek(self) -> str | None:
        return self.toks[self.i] if self.i < len(self.toks) else None

    def next(self) -> str:
        tok = self.peek()
        if tok is None:
            raise ProtoParseError(f"{self.path}: unexpected end of file")
        self.i += 1
        return tok

    def expect(self, want: str) -> str:
        tok = self.next()
        if tok != want:
            raise ProtoParseError(
                f"{self.path}: expected {want!r}, got {tok!r}"
            )
        return tok


def _strip_comments(text: str) -> tuple[str, dict[str, str]]:
    """Remove comments; return (code, {message name: attached comment}).

    The attached comment of a message is the contiguous ``//`` block
    immediately above its ``message X {`` line — where the project
    documents msgType claims.  A blank line detaches a block (section
    banners above a message keep their own claims to themselves).
    """
    comments: dict[str, str] = {}
    lines = text.split("\n")
    block: list[str] = []
    for line in lines:
        stripped = line.strip()
        if stripped.startswith("//"):
            block.append(stripped[2:].strip())
            continue
        m = re.match(r"\s*message\s+([A-Za-z_][A-Za-z0-9_]*)", line)
        if m and block:
            comments[m.group(1)] = " ".join(block)
        block = []
    code = re.sub(r"//[^\n]*", "", text)
    code = re.sub(r"/\*.*?\*/", "", code, flags=re.S)
    return code, comments


def _parse_enum(toks: _Tokens, scope: str) -> ParsedEnum:
    name = toks.next()
    enum = ParsedEnum(name=name, full_name=f"{scope}.{name}")
    toks.expect("{")
    while toks.peek() != "}":
        vname = toks.next()
        if vname == "option":
            raise ProtoParseError(
                f"{toks.path}: enum options are not supported "
                f"(enum {name})"
            )
        toks.expect("=")
        vnum = int(toks.next())
        toks.expect(";")
        enum.values.append((vname, vnum))
    toks.expect("}")
    return enum


def _parse_message(
    toks: _Tokens, scope: str, comments: dict[str, str]
) -> ParsedMessage:
    name = toks.next()
    msg = ParsedMessage(name=name, full_name=f"{scope}.{name}")
    comment = comments.get(name, "")
    msg.msgtype_claims = sorted(
        {int(n) for n in _MSGTYPE_CLAIM_RE.findall(comment)}
    )
    toks.expect("{")
    while toks.peek() != "}":
        tok = toks.next()
        if tok == "message":
            msg.nested.append(_parse_message(toks, msg.full_name, comments))
            continue
        if tok == "enum":
            msg.enums.append(_parse_enum(toks, msg.full_name))
            continue
        if tok in ("oneof", "map", "option", "extensions", "reserved",
                   "extend", "group", "required"):
            raise ProtoParseError(
                f"{toks.path}: {tok!r} is not supported "
                f"(message {msg.full_name})"
            )
        label = LABEL_OPTIONAL
        proto3_optional = False
        if tok == "repeated":
            label = LABEL_REPEATED
            tok = toks.next()
        elif tok == "optional":
            proto3_optional = True
            tok = toks.next()
        ftype = tok
        fname = toks.next()
        toks.expect("=")
        fnum = int(toks.next())
        nxt = toks.next()
        if nxt == "[":
            raise ProtoParseError(
                f"{toks.path}: field options are not supported "
                f"({msg.full_name}.{fname})"
            )
        if nxt != ";":
            raise ProtoParseError(
                f"{toks.path}: expected ';' after field "
                f"{msg.full_name}.{fname}, got {nxt!r}"
            )
        if ftype in SCALAR_TYPES:
            f = ParsedField(fname, fnum, label, SCALAR_TYPES[ftype], None)
        else:
            f = ParsedField(fname, fnum, label, 0, ftype)
        f.proto3_optional = proto3_optional
        msg.fields.append(f)
    toks.expect("}")
    # Synthetic oneofs for proto3 optional fields, in declaration order
    # (protoc appends them after any real oneofs; this subset has none).
    for f in msg.fields:
        if f.proto3_optional:
            f.oneof_index = len(msg.oneofs)
            msg.oneofs.append(f"_{f.name}")
    return msg


def parse_proto_text(text: str, import_path: str) -> ParsedFile:
    code, comments = _strip_comments(text)
    toks = _Tokens(code, import_path)
    pf = ParsedFile(path=import_path, package="", syntax="proto2")
    while toks.peek() is not None:
        tok = toks.next()
        if tok == "syntax":
            toks.expect("=")
            pf.syntax = toks.next().strip('"')
            toks.expect(";")
        elif tok == "package":
            pf.package = toks.next()
            toks.expect(";")
        elif tok == "import":
            pf.imports.append(toks.next().strip('"'))
            toks.expect(";")
        elif tok == "message":
            pf.messages.append(
                _parse_message(toks, f".{pf.package}", comments)
            )
        elif tok == "enum":
            pf.enums.append(_parse_enum(toks, f".{pf.package}"))
        elif tok == "option":
            raise ProtoParseError(
                f"{import_path}: file options are not supported"
            )
        elif tok == "service":
            raise ProtoParseError(
                f"{import_path}: services are not supported"
            )
        else:
            raise ProtoParseError(
                f"{import_path}: unexpected top-level token {tok!r}"
            )
    return pf


# ---------------------------------------------------------------------------
# symbol resolution
# ---------------------------------------------------------------------------

def _symbols_of(pf: ParsedFile) -> dict[str, bool]:
    """{full name: is_message} declared by one parsed file."""
    syms: dict[str, bool] = {}

    def walk(msg: ParsedMessage) -> None:
        syms[msg.full_name] = True
        for e in msg.enums:
            syms[e.full_name] = False
        for n in msg.nested:
            walk(n)

    for m in pf.messages:
        walk(m)
    for e in pf.enums:
        syms[e.full_name] = False
    return syms


def _resolve_file(pf: ParsedFile, symbols: dict[str, bool]) -> None:
    """Resolve named field types against ``symbols`` using protoc's
    innermost-scope-outward rule."""

    def resolve(ref: str, scopes: list[str], where: str) -> tuple[str, bool]:
        if ref.startswith("."):
            if ref in symbols:
                return ref, symbols[ref]
            raise ProtoParseError(f"{pf.path}: unknown type {ref} ({where})")
        for scope in scopes:
            cand = f"{scope}.{ref}" if scope else f".{ref}"
            if cand in symbols:
                return cand, symbols[cand]
        raise ProtoParseError(f"{pf.path}: unresolved type {ref} ({where})")

    def walk(msg: ParsedMessage, scopes: list[str]) -> None:
        inner = [msg.full_name] + scopes
        for f in msg.fields:
            if f.type_ref is not None:
                full, is_msg = resolve(
                    f.type_ref, inner, f"{msg.full_name}.{f.name}"
                )
                f.type_name = full
                f.type = TYPE_MESSAGE if is_msg else TYPE_ENUM
        for n in msg.nested:
            walk(n, inner)

    pkg_scopes = [f".{pf.package}", ""]
    for m in pf.messages:
        walk(m, pkg_scopes)


def parse_proto_file(
    path: str, repo_root: str, _cache: dict | None = None
) -> ParsedFile:
    """Parse ``path`` (filesystem) and resolve type references using its
    transitive imports (resolved relative to ``repo_root``)."""
    cache = _cache if _cache is not None else {}
    import_path = os.path.relpath(path, repo_root).replace(os.sep, "/")

    def load(ipath: str) -> ParsedFile | None:
        if ipath in cache:
            return cache[ipath]
        if ipath in WELL_KNOWN:
            cache[ipath] = None
            return None
        fs_path = os.path.join(repo_root, ipath)
        try:
            with open(fs_path) as fh:
                pf = parse_proto_text(fh.read(), ipath)
        except OSError as e:
            raise ProtoParseError(f"{ipath}: unreadable ({e})")
        cache[ipath] = pf
        try:
            for dep in pf.imports:
                load(dep)
        except ProtoParseError:
            # Never leave a partially-loaded entry in a SHARED cache: a
            # later call would skip dependency loading and crash in
            # gather() instead of re-raising the real parse error.
            del cache[ipath]
            raise
        return pf

    pf = load(import_path)
    assert pf is not None
    symbols: dict[str, bool] = {}
    seen: set[str] = set()

    def gather(ipath: str) -> None:
        if ipath in seen:
            return
        seen.add(ipath)
        if ipath in WELL_KNOWN:
            symbols.update(WELL_KNOWN[ipath])
            return
        dep = cache[ipath]
        symbols.update(_symbols_of(dep))
        for sub in dep.imports:
            gather(sub)

    gather(import_path)
    for ipath in seen:
        if ipath not in WELL_KNOWN and cache[ipath] is not None:
            _resolve_file(cache[ipath], symbols)
    return pf


# ---------------------------------------------------------------------------
# FileDescriptorProto construction
# ---------------------------------------------------------------------------

def build_file_descriptor(
    pf: ParsedFile,
) -> descriptor_pb2.FileDescriptorProto:
    fdp = descriptor_pb2.FileDescriptorProto()
    fdp.name = pf.path
    fdp.package = pf.package
    for dep in pf.imports:
        fdp.dependency.append(dep)

    def fill_enum(dst, enum: ParsedEnum) -> None:
        dst.name = enum.name
        for vname, vnum in enum.values:
            v = dst.value.add()
            v.name = vname
            v.number = vnum

    def fill_message(dst, msg: ParsedMessage) -> None:
        dst.name = msg.name
        for f in msg.fields:
            fd = dst.field.add()
            fd.name = f.name
            fd.number = f.number
            fd.label = f.label
            fd.type = f.type
            if f.type_name:
                fd.type_name = f.type_name
            if f.oneof_index is not None:
                fd.oneof_index = f.oneof_index
            if f.proto3_optional:
                fd.proto3_optional = True
        for n in msg.nested:
            fill_message(dst.nested_type.add(), n)
        for e in msg.enums:
            fill_enum(dst.enum_type.add(), e)
        for oname in msg.oneofs:
            dst.oneof_decl.add().name = oname

    for m in pf.messages:
        fill_message(fdp.message_type.add(), m)
    for e in pf.enums:
        fill_enum(fdp.enum_type.add(), e)
    if pf.syntax != "proto2":
        fdp.syntax = pf.syntax
    return fdp


def msgtype_claims(pf: ParsedFile) -> dict[str, list[int]]:
    """{message name: [claimed msgType numbers]} for one parsed file."""
    claims: dict[str, list[int]] = {}

    def walk(msg: ParsedMessage) -> None:
        if msg.msgtype_claims:
            claims[msg.name] = list(msg.msgtype_claims)
        for n in msg.nested:
            walk(n)

    for m in pf.messages:
        walk(m)
    return claims

"""The gateway's declarative thread model (doc/concurrency.md).

The gateway stopped being single-threaded several PRs ago: the asyncio
loop carries the GLOBAL tick, trunk I/O and every channel mutation, but
the WAL writer (core/wal.py), the device-guard worker pool
(core/device_guard.py), the flight recorder's anomaly dump thread
(core/tracing.py), the ops HTTP server (core/opshttp.py) and the gRPC
sidecar executor (ops/service.py) all run off-loop.  Every one of those
threads has a *discipline* — what it may touch, how state crosses the
boundary — that was previously enforced only by review.  This module is
the machine-readable form of that discipline:

- **Execution domains** (:data:`DOMAINS`): the named contexts code runs
  in.  Loop-thread domains (``tick-loop``, ``trunk-reader``,
  ``boot-loop``) share one OS thread; own-thread domains (wal-writer,
  device-worker, trace-dumper, ops-http, grpc-pool, loop-offload) each
  have their own.  ``steady`` marks the domains where blocking stalls
  live traffic (boot/shutdown on the loop may block; a tick may not).
- **Entry-point inference**: ``threading.Thread(target=...)``,
  ``executor.submit(fn, ...)``, ``asyncio.to_thread(fn, ...)`` and
  ``loop.run_in_executor(_, fn, ...)`` sites are scanned; every thread
  entry point must be claimed by a domain's ``seeds`` (or the creation
  site by its ``spawn_sites``) — an undeclared thread is a
  ``thread-model`` finding, so a new thread cannot appear without
  extending this spec.
- **A call-graph pass** assigns every function the set of domains it is
  reachable from.  Resolution is name-based and deliberately pragmatic:
  ``self.x()`` resolves within the enclosing module's classes, bare
  names within the module (nested defs included) and via from-imports,
  and attribute calls through the :data:`INSTANCES` table of the
  project's module-level singletons (``wal`` -> WriteAheadLog, ``guard``
  -> DeviceGuard, ...).  Calls into an ``async def`` propagate only when
  awaited — ``ensure_future(coro())`` schedules a new task in the
  callee's own domain, it does not run the body in the caller's.

The affinity rules (analysis/rules/affinity.py) and the extended
async-blocking rule consume the model; ``core/affinity.py`` is its
runtime twin (the same domain names compile to thread-ident assertions
armed in tier-1), and ``tests/test_affinity.py`` pins that the two
agree.
"""

from __future__ import annotations

import ast
import fnmatch
import re
from dataclasses import dataclass, field

from .astutil import call_name, dotted, import_aliases, iter_functions
from .engine import ModuleInfo, RepoContext

# Modules the model covers: the planes that actually host or touch
# threads.  models/, compat/, replay/, client/, parallel/ and protocol/
# stay out of scope — they run in tests, sidecars or pure jax.
SCAN_GLOBS = (
    "channeld_tpu/core/*.py",
    "channeld_tpu/federation/*.py",
    "channeld_tpu/spatial/*.py",
    "channeld_tpu/ops/*.py",
    "channeld_tpu/chaos/*.py",
    "channeld_tpu/sim/*.py",
)

# Handoff mechanisms a ``# tpulint: shared=<mechanism>`` declaration may
# name (doc/concurrency.md#handoff-mechanisms).
SHARED_MECHANISMS = ("lock", "queue", "fence", "atomic", "cond", "event")


@dataclass(frozen=True)
class Domain:
    """One execution domain.  ``thread`` is ``"loop"`` (shares the
    asyncio loop's OS thread) or ``"own"``; ``steady`` marks the
    steady-state serving domains where a blocking call stalls live
    traffic (boot-loop blocks legitimately: listeners are not open)."""

    name: str
    thread: str
    steady: bool = False
    # ((module glob, qualname regex), ...): functions IN the domain —
    # thread bodies, handler methods, or the loop-side tick drivers.
    seeds: tuple = ()
    # Creation sites allowed to spawn this domain's threads even when
    # the target is not a project function (e.g. the ops server hands
    # the stdlib serve_forever to its thread).
    spawn_sites: tuple = ()
    doc: str = ""


DOMAINS: tuple[Domain, ...] = (
    Domain(
        "tick-loop", thread="loop", steady=True,
        seeds=(
            ("channeld_tpu/core/channel.py", r"^Channel\.tick_once$"),
            ("channeld_tpu/spatial/tpu_controller.py",
             r"^TPUSpatialController\.tick$"),
            # Standing-query plane (doc/query_engine.md): consume/apply
            # runs inside the controller tick; seeded explicitly because
            # the attribute hop (self.queryplane.pump) is not a
            # module-singleton call the propagator can resolve.
            ("channeld_tpu/spatial/queryplane.py",
             r"^QueryPlane\.(pump|reap_closed)$"),
            # Simulation plane (doc/simulation.md): cadence/absorb
            # hooks run inside the controller tick; seeded explicitly
            # for the same attribute-hop reason (self.simplane.pre_step
            # / on_result are plain instance fields).
            ("channeld_tpu/sim/plane.py",
             r"^SimPlane\.(pre_step|on_result|activate)$"),
            ("channeld_tpu/sim/authority.py",
             r"^SimAuthority\.(pump|commit|adopt)$"),
            ("channeld_tpu/spatial/grid.py",
             r"^StaticGrid2DSpatialController\.tick$"),
            ("channeld_tpu/core/connection.py", r"^Connection\.on_bytes$"),
            # asyncio transport/protocol callbacks are sync functions
            # the loop invokes directly — seed them or the ingest path
            # would be invisible to the model.
            ("channeld_tpu/core/*.py",
             r"\.(data_received|datagram_received|connection_made|"
             r"connection_lost|eof_received|error_received)$"),
            # Registry-dispatched message handlers (core/message.py
            # MESSAGE_MAP): invoked through a dict the call-graph pass
            # cannot follow, but they run inside the channel tick's
            # message drain all the same.
            ("channeld_tpu/core/message.py", r"^handle_"),
            # Control-plane work deferred INTO the GLOBAL tick via
            # _in_global_tick (callable queue — another registry hop).
            ("channeld_tpu/federation/control.py",
             r"^GlobalControlPlane\._epoch_tick$"),
        ),
        doc="the asyncio event loop's steady state: GLOBAL tick, channel "
            "ticks, message dispatch, fan-out, controller/device "
            "orchestration (every async def in scope defaults here)",
    ),
    Domain(
        "trunk-reader", thread="loop", steady=True,
        seeds=(
            ("channeld_tpu/federation/trunk.py",
             r"^(TrunkLink\._read_loop|TrunkLink\._heartbeat_loop|"
             r"TrunkManager\._dial_loop|TrunkManager\._on_accept)$"),
            # Trunk callbacks installed at construction (the link holds
            # them as fields, so the call-graph pass cannot follow the
            # dispatch): the federation plane's message/up/down hooks
            # and the control plane's trunk-facing handlers.
            ("channeld_tpu/federation/plane.py",
             r"^FederationPlane\._on_trunk_"),
            ("channeld_tpu/federation/control.py",
             r"^GlobalControlPlane\.(on_trunk_message|on_trunk_up|"
             r"on_peer_goodbye)$"),
        ),
        doc="trunk ingress/heartbeat tasks — same OS thread as the tick "
            "loop (asyncio tasks), named separately because their "
            "handlers are the federation hot path",
    ),
    Domain(
        "boot-loop", thread="loop", steady=False,
        seeds=(
            ("channeld_tpu/core/server.py",
             r"^(run_server|drain_gateway)$"),
            # The SIGTERM drain task and its closures: shutdown code on
            # the loop, not steady serving.
            ("channeld_tpu/core/server.py", r"^install_sigterm_drain\."),
        ),
        doc="gateway boot and SIGTERM drain on the loop thread before/"
            "after steady serving — blocking I/O is acceptable here "
            "(listeners are closed), so the blocking rules exempt it",
    ),
    Domain(
        "wal-writer", thread="own", steady=False,
        seeds=(
            ("channeld_tpu/core/wal.py",
             r"^WriteAheadLog\._writer_loop$"),
        ),
        doc="the journal's dedicated writer thread: frames, writes and "
            "fsyncs record batches (doc/persistence.md)",
    ),
    Domain(
        "device-worker", thread="own", steady=False,
        seeds=(
            ("channeld_tpu/core/device_guard.py",
             r"^DeviceGuard\._(step_body|rebuild_body)$"),
        ),
        doc="the device guard's watchdogged worker: the engine step, "
            "its batched readbacks, and the in-process rebuild "
            "(doc/device_recovery.md)",
    ),
    Domain(
        "trace-dumper", thread="own", steady=False,
        seeds=(
            ("channeld_tpu/core/tracing.py",
             r"^FlightRecorder\.note_anomaly\._write$"),
        ),
        doc="anomaly-dump writer threads: Perfetto JSON formatting and "
            "disk I/O off the tick that tripped the anomaly",
    ),
    Domain(
        "ops-http", thread="own", steady=False,
        seeds=(
            ("channeld_tpu/core/opshttp.py", r"^_OpsHandler\."),
            ("channeld_tpu/core/opshttp.py",
             r"^(readiness|introspect|_shard_ready|_device_ready|"
             r"_wal_ready|_trunk_ready)$"),
        ),
        spawn_sites=(
            ("channeld_tpu/core/opshttp.py", r"^OpsServer\.__init__$"),
        ),
        doc="the threaded ops HTTP server (/metrics /healthz /readyz "
            "/introspect /fleet): handler threads take snapshot reads "
            "of loop-owned state, never mutate it",
    ),
    Domain(
        "grpc-pool", thread="own", steady=False,
        seeds=(
            ("channeld_tpu/ops/service.py",
             r"^SpatialDecisionServicer\."),
        ),
        spawn_sites=(
            ("channeld_tpu/ops/service.py", r"^create_server$"),
        ),
        doc="the gRPC sidecar executor pool (ops/service.py): servicer "
            "methods own a sidecar engine, not the gateway's",
    ),
    Domain(
        "loop-offload", thread="own", steady=False,
        doc="asyncio.to_thread / run_in_executor targets: blocking work "
            "the loop explicitly shipped to the default executor "
            "(membership is inferred, never declared)",
    ),
)

DOMAINS_BY_NAME = {d.name: d for d in DOMAINS}

# Module-level singletons: an attribute call through one of these names
# resolves to the owning class's method.  (name -> ((module rel suffix,
# class name or None for any class in the module), ...)).
INSTANCES: dict[str, tuple] = {
    "wal": (("core/wal.py", "WriteAheadLog"),),
    "guard": (("core/device_guard.py", "DeviceGuard"),),
    "recorder": (("core/tracing.py", "FlightRecorder"),),
    "slo": (("core/slo.py", "SloPlane"),),
    "governor": (("core/overload.py", "OverloadGovernor"),),
    "plane": (("federation/plane.py", "FederationPlane"),),
    "control": (("federation/control.py", "GlobalControlPlane"),),
    "directory": (("federation/directory.py", "ShardDirectory"),),
    "fleet": (("federation/obs.py", "FleetObs"),),
    "chaos": (("chaos/injector.py", "ChaosInjector"),),
    "balancer": (("spatial/balancer.py", "BalancerPlane"),),
    "partition": (("spatial/partition.py", "PartitionPlane"),),
    "engine": (("ops/engine.py", "SpatialEngine"),),
    # SLO per-second rings: not singletons, but the one non-singleton
    # hop that crosses threads (the WAL writer feeds wal_fsync events).
    "ring": (("core/slo.py", "_WindowRing"),),
    "controller": (
        ("spatial/tpu_controller.py", None),
        ("spatial/grid.py", None),
    ),
}


@dataclass
class ThreadSite:
    """One thread/executor entry-point creation site."""

    rel: str
    line: int
    kind: str            # "thread" | "submit" | "to_thread" | "executor"
    site: str            # qualname of the function containing the call
    target_repr: str     # source-ish description of the target
    targets: list        # resolved (rel, qualname) keys (may be empty)
    declared: bool = False


@dataclass
class ThreadModel:
    # (rel, qualname) -> frozenset of domain names the function is
    # reachable from (empty set == unreached: tests/scripts only).
    fn_domains: dict = field(default_factory=dict)
    functions: dict = field(default_factory=dict)   # key -> FuncInfo
    sites: list = field(default_factory=list)       # [ThreadSite]
    stale_seeds: list = field(default_factory=list)  # [(domain, glob, re)]

    def domains_of(self, rel: str, qualname: str) -> frozenset:
        return self.fn_domains.get((rel, qualname), frozenset())

    def is_steady_loop(self, domains) -> bool:
        return any(
            DOMAINS_BY_NAME[d].thread == "loop" and DOMAINS_BY_NAME[d].steady
            for d in domains
        )

    def off_loop(self, domains):
        """The own-thread domains in ``domains`` (sorted)."""
        return sorted(
            d for d in domains if DOMAINS_BY_NAME[d].thread == "own"
        )

    def threads_of(self, domains) -> set:
        """Distinct OS threads for a domain set: loop domains collapse
        onto one thread; each own-thread domain is its own."""
        return {
            "loop" if DOMAINS_BY_NAME[d].thread == "loop" else d
            for d in domains
        }

    def stats(self) -> dict:
        """Per-domain reachable-function counts (the --json payload and
        the doc/concurrency.md drift gate)."""
        counts = {d.name: 0 for d in DOMAINS}
        for domains in self.fn_domains.values():
            for d in domains:
                counts[d] += 1
        return counts


def in_scope(rel: str) -> bool:
    return any(fnmatch.fnmatch(rel, g) for g in SCAN_GLOBS)


def _seed_domains(rel: str, qualname: str) -> set:
    out = set()
    for dom in DOMAINS:
        for glob, pattern in dom.seeds:
            if fnmatch.fnmatch(rel, glob) and re.search(pattern, qualname):
                out.add(dom.name)
    return out


def _spawn_site_ok(rel: str, qualname: str) -> bool:
    for dom in DOMAINS:
        for glob, pattern in dom.spawn_sites:
            if fnmatch.fnmatch(rel, glob) and re.search(pattern, qualname):
                return True
    return False


class _ModuleIndex:
    """Per-module lookup tables for call resolution."""

    def __init__(self, mod: ModuleInfo):
        self.mod = mod
        self.aliases = import_aliases(mod.tree)
        self.functions: dict[str, object] = {}   # qualname -> FuncInfo
        self.classes: set[str] = {
            n.name for n in ast.walk(mod.tree) if isinstance(n, ast.ClassDef)
        }
        self.methods: dict[str, list[str]] = {}  # method name -> [qualname]
        self.toplevel: set[str] = set()
        for fn in iter_functions(mod.tree):
            self.functions[fn.qualname] = fn
            parts = fn.qualname.split(".")
            if len(parts) == 1:
                self.toplevel.add(fn.qualname)
            elif len(parts) == 2 and parts[0] in self.classes:
                self.methods.setdefault(parts[1], []).append(fn.qualname)


def _build_indices(repo: RepoContext) -> dict[str, _ModuleIndex]:
    return {
        m.rel: _ModuleIndex(m) for m in repo.modules if in_scope(m.rel)
    }


def _module_by_suffix(indices: dict, suffix: str):
    for rel, idx in indices.items():
        if rel.endswith(suffix):
            return rel, idx
    return None, None


def _module_by_name(indices: dict, name: str):
    """The scanned module whose filename is ``<name>.py``."""
    return _module_by_suffix(indices, f"/{name}.py")


def _resolve_call(
    canonical: str | None,
    raw: str | None,
    caller_qual: str,
    rel: str,
    idx: _ModuleIndex,
    indices: dict,
) -> list:
    """Resolve one call to candidate (rel, qualname) keys."""
    out: list = []
    name = canonical or raw
    if not name:
        return out
    parts = name.lstrip(".").split(".")
    # self.meth() / cls.meth(): any same-module class method (base-class
    # methods live in the same module for every class this model cares
    # about; over-approximation is safe — domains only widen).
    if raw is not None and raw.split(".")[0] in ("self", "cls") \
            and len(raw.split(".")) == 2:
        meth = raw.split(".")[1]
        for qual in idx.methods.get(meth, ()):
            out.append((rel, qual))
        if out:
            return out
    if len(parts) == 1:
        # Bare name: nested def of the caller, then enclosing scopes,
        # then module level.
        scopes = caller_qual.split(".")
        for depth in range(len(scopes), -1, -1):
            prefix = ".".join(scopes[:depth])
            qual = f"{prefix}.{parts[0]}" if prefix else parts[0]
            if qual in idx.functions:
                return [(rel, qual)]
        return out
    owner, meth = parts[-2], parts[-1]
    # A singleton instance (wal.append, self.engine.tick, _slo.observe
    # via its canonical module path).
    if owner in INSTANCES:
        for suffix, cls in INSTANCES[owner]:
            target_rel, target_idx = _module_by_suffix(indices, suffix)
            if target_idx is None:
                continue
            if cls is None:
                for qual in target_idx.methods.get(meth, ()):
                    out.append((target_rel, qual))
            elif f"{cls}.{meth}" in target_idx.functions:
                out.append((target_rel, f"{cls}.{meth}"))
        if out:
            return out
    # Module-level function of a scanned module (``snapshot.write_...``
    # or from-import canonical "..core.snapshot.write_snapshot").
    target_rel, target_idx = _module_by_name(indices, owner)
    if target_idx is not None and meth in target_idx.toplevel:
        return [(target_rel, meth)]
    # Same-module class attribute (ClassName.method) references.
    if owner in idx.classes and f"{owner}.{meth}" in idx.functions:
        return [(rel, f"{owner}.{meth}")]
    return out


def _call_targets_in(fn_node: ast.AST):
    """(call node, awaited) pairs lexically inside ``fn_node`` but not
    inside a nested def (lambdas run inline and are included)."""
    awaited_ids = set()
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Await) and isinstance(node.value, ast.Call):
            awaited_ids.add(id(node.value))

    out = []

    def walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue
            if isinstance(child, ast.Call):
                out.append((child, id(child) in awaited_ids))
            walk(child)

    walk(fn_node)
    return out


def _target_keys(node: ast.AST, caller_qual: str, rel: str,
                 idx: _ModuleIndex, indices: dict) -> list:
    """Resolve a callable REFERENCE (Thread target, submit arg)."""
    name = dotted(node)
    if name is None:
        return []
    head = name.split(".")[0]
    if head in ("self", "cls") or head not in idx.aliases:
        canonical = name if head not in ("self", "cls") else None
        return _resolve_call(canonical, name, caller_qual, rel, idx, indices)
    canonical = idx.aliases.get(head)
    rest = name.split(".", 1)[1] if "." in name else ""
    full = f"{canonical.lstrip('.')}.{rest}" if rest else canonical.lstrip(".")
    return _resolve_call(full, name, caller_qual, rel, idx, indices)


def _scan_thread_sites(rel: str, idx: _ModuleIndex, indices: dict) -> list:
    """Thread/executor entry-point creation sites in one module."""
    sites: list[ThreadSite] = []
    enclosing: dict[int, str] = {}
    for fn in iter_functions(idx.mod.tree):
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Call):
                enclosing.setdefault(id(node), fn.qualname)
    for node in ast.walk(idx.mod.tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node, idx.aliases) or ""
        attr = node.func.attr if isinstance(node.func, ast.Attribute) else ""
        site_fn = enclosing.get(id(node), "<module>")
        kind = target = None
        if name == "threading.Thread" or name == "Thread":
            kind = "thread"
            for kw in node.keywords:
                if kw.arg == "target":
                    target = kw.value
        elif attr == "submit" and node.args:
            kind = "submit"
            target = node.args[0]
        elif name == "asyncio.to_thread" and node.args:
            kind = "to_thread"
            target = node.args[0]
        elif attr == "run_in_executor" and len(node.args) >= 2:
            kind = "executor"
            target = node.args[1]
        if kind is None:
            continue
        targets = (
            _target_keys(target, site_fn, rel, idx, indices)
            if target is not None else []
        )
        sites.append(ThreadSite(
            rel=rel, line=node.lineno, kind=kind, site=site_fn,
            target_repr=(dotted(target) or "<expr>")
            if target is not None else "<none>",
            targets=targets,
        ))
    return sites


def build_model(repo: RepoContext) -> ThreadModel:
    """Build (and cache on ``repo``) the thread model."""
    cached = getattr(repo, "_thread_model", None)
    if cached is not None:
        return cached
    indices = _build_indices(repo)
    model = ThreadModel()

    # ---- seeds -----------------------------------------------------------
    seeds: dict[tuple, set] = {}
    for rel, idx in indices.items():
        for qual, fn in idx.functions.items():
            key = (rel, qual)
            model.functions[key] = fn
            doms = _seed_domains(rel, qual)
            if not doms and fn.is_async:
                # Every unclaimed coroutine in scope runs as a loop
                # task: the tick-loop default.
                doms = {"tick-loop"}
            if doms:
                seeds[key] = doms

    # Stale spec entries: a seed whose module is present but matches no
    # function would silently hollow out the model (a rename rots the
    # discipline) — surfaced as findings by the thread-model rule.
    for dom in DOMAINS:
        for glob, pattern in dom.seeds:
            matched_mod = False
            matched_fn = False
            for rel, idx in indices.items():
                if not fnmatch.fnmatch(rel, glob):
                    continue
                matched_mod = True
                if any(re.search(pattern, q) for q in idx.functions):
                    matched_fn = True
                    break
            if matched_mod and not matched_fn:
                model.stale_seeds.append((dom.name, glob, pattern))

    # ---- thread-site scan + inferred offload membership ------------------
    for rel, idx in indices.items():
        model.sites.extend(_scan_thread_sites(rel, idx, indices))
    for site in model.sites:
        if site.kind in ("to_thread", "executor"):
            site.declared = True
            for key in site.targets:
                seeds.setdefault(key, set()).add("loop-offload")
            continue
        declared = _spawn_site_ok(site.rel, site.site)
        for key in site.targets:
            if _seed_domains(*key):
                declared = True
        site.declared = declared

    # ---- call edges ------------------------------------------------------
    edges: dict[tuple, list] = {}
    for rel, idx in indices.items():
        for qual, fn in idx.functions.items():
            targets: list = []
            for call, awaited in _call_targets_in(fn.node):
                canonical = call_name(call, idx.aliases)
                raw = dotted(call.func)
                for key in _resolve_call(canonical, raw, qual, rel, idx,
                                         indices):
                    callee = model.functions.get(key)
                    if callee is None:
                        continue
                    if callee.is_async and not awaited:
                        # ensure_future(coro()) / create_task(coro()):
                        # a NEW task in the callee's own domain — the
                        # caller's domain does not follow the call.
                        continue
                    targets.append(key)
            if targets:
                edges[(rel, qual)] = targets

    # ---- propagation -----------------------------------------------------
    fn_domains: dict[tuple, set] = {k: set(v) for k, v in seeds.items()}
    work = [(k, set(v)) for k, v in fn_domains.items()]
    while work:
        key, doms = work.pop()
        for callee in edges.get(key, ()):
            have = fn_domains.setdefault(callee, set())
            new = doms - have
            if new:
                have |= new
                work.append((callee, new))
    model.fn_domains = {
        k: frozenset(v) for k, v in fn_domains.items() if v
    }
    repo._thread_model = model
    return model

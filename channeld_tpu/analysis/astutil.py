"""Small AST helpers shared by the tpulint rules."""

from __future__ import annotations

import ast
from dataclasses import dataclass


def dotted(node: ast.AST) -> str | None:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclass
class FuncInfo:
    qualname: str        # e.g. "TPUSpatialController.tick"
    name: str
    node: ast.AST        # FunctionDef | AsyncFunctionDef
    is_async: bool
    in_async: bool       # lexically inside an async def (closures included)


def iter_functions(tree: ast.AST) -> list[FuncInfo]:
    """Every function definition with its class-qualified name and
    whether it executes in an async context (being async itself, or a
    closure defined inside an async def — such closures run inline on
    the event loop)."""
    out: list[FuncInfo] = []

    def walk(node: ast.AST, prefix: str, in_async: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                walk(child, f"{prefix}{child.name}.", in_async)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                is_async = isinstance(child, ast.AsyncFunctionDef)
                qual = f"{prefix}{child.name}"
                out.append(FuncInfo(
                    qualname=qual, name=child.name, node=child,
                    is_async=is_async, in_async=in_async or is_async,
                ))
                walk(child, f"{qual}.", in_async or is_async)
            else:
                walk(child, prefix, in_async)

    walk(tree, "", False)
    return out


def direct_body_nodes(func: ast.AST) -> list[ast.AST]:
    """All AST nodes lexically inside ``func`` but NOT inside a nested
    function/class definition.  Lambdas are NOT a boundary: a lambda
    handed to ``call_soon``/``sorted`` from an async context runs
    inline, so its body belongs to the enclosing function for
    blocking/readback purposes."""
    out: list[ast.AST] = []

    def walk(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue
            out.append(child)
            walk(child)

    walk(func)
    return out


def import_aliases(tree: ast.AST) -> dict[str, str]:
    """{local name: canonical dotted name} for module imports and
    from-imports (``import time as _time`` -> {"_time": "time"};
    ``from time import sleep`` -> {"sleep": "time.sleep"};
    ``from ..core import metrics`` -> {"metrics": "..core.metrics"}).
    Relative imports keep their leading dots."""
    out: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.asname:
                    out[alias.asname] = alias.name
                else:
                    # ``import a.b`` binds the ROOT package name ``a``
                    # locally; mapping it to ``a.b`` would mis-resolve
                    # every ``a.x`` call.
                    root = alias.name.split(".")[0]
                    out[root] = root
        elif isinstance(node, ast.ImportFrom):
            base = "." * node.level + (node.module or "")
            for alias in node.names:
                if alias.name == "*":
                    continue
                out[alias.asname or alias.name] = f"{base}.{alias.name}"
    return out


def metrics_aliases(tree: ast.AST) -> tuple[set[str], dict[str, str]]:
    """Names bound to the core metrics MODULE, and {local name: metric
    attr} for names imported from it directly."""
    modules: set[str] = set()
    objects: dict[str, str] = {}
    for local, target in import_aliases(tree).items():
        norm = target.lstrip(".")
        if norm in ("metrics", "core.metrics", "channeld_tpu.core.metrics"):
            modules.add(local)
        elif norm.startswith(("metrics.", "core.metrics.",
                              "channeld_tpu.core.metrics.")):
            objects[local] = norm.rsplit(".", 1)[1]
    return modules, objects


def call_name(node: ast.Call, aliases: dict[str, str]) -> str | None:
    """Canonical dotted name of a call target, resolving the leading
    module alias (``_time.sleep(...)`` -> ``time.sleep``)."""
    name = dotted(node.func)
    if name is None:
        return None
    head, _, rest = name.partition(".")
    canonical = aliases.get(head)
    if canonical is None:
        return name
    canonical = canonical.lstrip(".")
    return f"{canonical}.{rest}" if rest else canonical

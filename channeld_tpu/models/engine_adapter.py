"""Engine integration adapter: spawn/destroy routing over the core.

Capability parity with the reference's engine-side package
(ref: pkg/unreal/message.go, handover.go, recovery.go) — the proof that
the core is engine-agnostic: everything here uses only public core APIs.

- SPAWN (user-space 103): rewrites the message's spatial channel from the
  object's location, inserts the entity into the spatial channel data,
  sets the entity channel's object ref, records the spawn for recovery,
  then forwards server->clients.
- DESTROY (user-space 104): removes the entity from the spatial data,
  removes its entity channel, forwards.
- check_entity_handover: the position-delta test feeding the spatial
  notifier (the reference swaps UE's Z-up to Y-up; the sim family is
  already Y-up so the swap is optional).
- RecoverableChannelDataExtension: spawned-object table shipped in
  ChannelDataRecoveryMessage.recoveryData.
"""

from __future__ import annotations

from typing import Optional

from ..core.channel import get_channel
from ..core.data import set_channel_data_extension
from ..core.message import (
    MessageContext,
    handle_server_to_client_user_message,
    register_message_handler,
)
from ..core.types import ChannelType, MessageType
from ..protocol import wire_pb2
from ..spatial.controller import SpatialInfo, get_spatial_controller
from ..utils.logger import get_logger
from . import sim_pb2

logger = get_logger("models.engine")

# User-space message types (ref: pkg/unrealpb/unreal_common.proto:25-29).
MSG_SPAWN = 103
MSG_DESTROY = 104


class RecoverableChannelDataExtension:
    """(ref: pkg/unreal/recovery.go:10-40)."""

    def __init__(self):
        self.spawned_objs: dict[int, sim_pb2.ObjectRef] = {}

    def init(self, channel) -> None:
        self.spawned_objs = {}

    def get_recovery_data_message(self):
        data = sim_pb2.EngineRecoveryData()
        for net_id, obj in self.spawned_objs.items():
            data.spawnedObjects[net_id].CopyFrom(obj)
        return data

    def on_spawn(self, obj: sim_pb2.ObjectRef) -> None:
        self.spawned_objs[obj.netId] = obj

    def on_destroy(self, net_id: int) -> None:
        self.spawned_objs.pop(net_id, None)


def init_message_handlers() -> None:
    """(ref: pkg/unreal/message.go:12-17)."""
    from ..core import events

    register_message_handler(
        MSG_SPAWN, wire_pb2.ServerForwardMessage, handle_spawn_object
    )
    register_message_handler(
        MSG_DESTROY, wire_pb2.ServerForwardMessage, handle_destroy_object
    )
    set_channel_data_extension(ChannelType.GLOBAL, RecoverableChannelDataExtension)
    set_channel_data_extension(ChannelType.SUBWORLD, RecoverableChannelDataExtension)
    events.entity_channel_spatially_owned.listen(
        handle_entity_channel_spatially_owned
    )


def handle_entity_channel_spatially_owned(data) -> None:
    """An entity channel just became owned by a spatial server: insert the
    entity into that spatial channel's entity table, or handover cannot
    see it (ref: pkg/unreal/message.go:205-215
    handleEntityChannelSpatiallyOwned)."""
    entity_data = data.entity_channel.get_data_message()
    if entity_data is None or not hasattr(entity_data, "state"):
        logger.error(
            "spatially-owned entity channel %d has no usable data",
            data.entity_channel.id,
        )
        return
    state = entity_data.state
    # The entity channel id IS the netId (channel.go:229-241); the data's
    # state.entityId may legitimately still be unset at this point (it is
    # filled by the SPAWN path).
    entity_id = data.entity_channel.id

    def _add(ch) -> None:
        data_msg = ch.get_data_message()
        adder = getattr(data_msg, "add_entity", None)
        if adder is not None:
            adder(entity_id, state)

    data.spatial_channel.execute(_add)


def _add_spatial_entity(channel, obj: sim_pb2.ObjectRef, location) -> None:
    """Insert the entity into the spatial channel data so handover can see
    it (ref: message.go addSpatialEntity)."""
    data_msg = channel.get_data_message()
    adder = getattr(data_msg, "add_entity", None)
    if adder is None:
        return
    state = sim_pb2.EntityState(entityId=obj.netId, owningConnId=obj.owningConnId)
    if location is not None:
        state.transform.position.CopyFrom(location)
    adder(obj.netId, state)


def _record_spawn(channel, obj: sim_pb2.ObjectRef) -> None:
    ext = channel.data.extension if channel.data else None
    if isinstance(ext, RecoverableChannelDataExtension):
        ext.on_spawn(obj)


def handle_spawn_object(ctx: MessageContext) -> None:
    """(ref: message.go:20-128)."""
    msg = ctx.msg
    if not isinstance(msg, wire_pb2.ServerForwardMessage):
        logger.error("SPAWN payload is not a ServerForwardMessage")
        return
    spawn = sim_pb2.SpawnObjectMessage()
    try:
        spawn.ParseFromString(msg.payload)
    except Exception:
        logger.exception("failed to unmarshal SpawnObjectMessage")
        return
    if not spawn.HasField("obj") or spawn.obj.netId == 0:
        logger.error("invalid ObjectRef in SpawnObjectMessage")
        return

    controller = get_spatial_controller()
    if spawn.HasField("location") and controller is not None:
        loc = spawn.location
        try:
            spatial_ch_id = controller.get_channel_id(SpatialInfo(loc.x, loc.y, loc.z))
        except ValueError as e:
            logger.warning("failed to map spawn location: %s", e)
            return
        old_ch_id = spawn.channelId
        spawn.channelId = spatial_ch_id
        if spatial_ch_id != old_ch_id:
            # Re-route to the correct spatial channel and let it handle the
            # forward inside its own execution context.
            ctx.msg = wire_pb2.ServerForwardMessage(
                clientConnId=msg.clientConnId, payload=spawn.SerializeToString()
            )
            target = get_channel(spatial_ch_id)
            if target is None:
                logger.error("spawn target channel %d missing", spatial_ch_id)
                return
            ctx.channel = target
            ctx.channel_id = spatial_ch_id
            target.execute(lambda ch: _add_spatial_entity(ch, spawn.obj, loc))
            target.put_message_context(ctx, handle_server_to_client_user_message)
        else:
            _add_spatial_entity(ctx.channel, spawn.obj, loc)
            handle_server_to_client_user_message(ctx)
    else:
        if ctx.channel.channel_type in (ChannelType.GLOBAL, ChannelType.SUBWORLD):
            _record_spawn(ctx.channel, spawn.obj)
        elif ctx.channel.channel_type == ChannelType.SPATIAL:
            _add_spatial_entity(
                ctx.channel, spawn.obj,
                spawn.location if spawn.HasField("location") else None,
            )
        handle_server_to_client_user_message(ctx)

    # Wire the object ref into the entity channel's data, if it exists.
    entity_channel = get_channel(spawn.obj.netId)
    if entity_channel is None:
        return

    def _set_ref(ch) -> None:
        data_msg = ch.get_data_message()
        if isinstance(data_msg, sim_pb2.SimEntityChannelData):
            data_msg.state.entityId = spawn.obj.netId
            data_msg.state.owningConnId = spawn.obj.owningConnId

    entity_channel.execute(_set_ref)


def handle_destroy_object(ctx: MessageContext) -> None:
    """(ref: message.go:165-196)."""
    from ..core.channel import remove_channel

    msg = ctx.msg
    if not isinstance(msg, wire_pb2.ServerForwardMessage):
        return
    destroy = sim_pb2.DestroyObjectMessage()
    try:
        destroy.ParseFromString(msg.payload)
    except Exception:
        logger.exception("failed to unmarshal DestroyObjectMessage")
        return

    data_msg = ctx.channel.get_data_message()
    remover = getattr(data_msg, "remove_entity", None)
    if remover is not None:
        remover(destroy.netId)
    ext = ctx.channel.data.extension if ctx.channel.data else None
    if isinstance(ext, RecoverableChannelDataExtension):
        ext.on_destroy(destroy.netId)

    entity_channel = get_channel(destroy.netId)
    if entity_channel is not None and not entity_channel.is_removing():
        remove_channel(entity_channel)

    handle_server_to_client_user_message(ctx)


def check_entity_handover(
    net_id: int, new_loc, old_loc, swap_yz: bool = False
) -> tuple[bool, Optional[SpatialInfo], Optional[SpatialInfo]]:
    """Position-delta handover test (ref: pkg/unreal/handover.go:8-47).

    Axis-presence aware when the locations are sim ``Vec3`` protos: an
    absent axis in ``new_loc`` falls back to the OLD value (the engine
    replicated only the axes that changed — exactly the reference's
    ``newLoc.X != nil`` ladder). ``swap_yz=True`` applies the UE Z-up ->
    Y-up axis swap.
    """
    def axis(loc, name, fallback):
        has_field = getattr(loc, "HasField", None)
        if has_field is not None:
            try:
                if not has_field(name):
                    return fallback
            except ValueError:
                pass  # non-optional field: plain read below
        return getattr(loc, name)

    ox, oy, oz = old_loc.x, old_loc.y, old_loc.z
    nx = axis(new_loc, "x", ox)
    ny = axis(new_loc, "y", oy)
    nz = axis(new_loc, "z", oz)
    if (nx, ny, nz) == (ox, oy, oz):
        return False, None, None
    if swap_yz:
        return True, SpatialInfo(ox, oz, oy), SpatialInfo(nx, nz, ny)
    return True, SpatialInfo(ox, oy, oz), SpatialInfo(nx, ny, nz)

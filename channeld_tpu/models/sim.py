"""Behavior extensions for the sim channel-data family.

The reference implements these as methods on generated Go types
(ref: pkg/unrealpb/extension.go:10-94, examples/channeld-ue-tps/tpspb/data.go):
custom merges, the handover trigger inside EntityChannelData.Merge, the
SpatialChannelEntityUpdater (AddEntity/RemoveEntity), and HandoverDataMerger
(MergeTo). Python protobuf classes accept attribute assignment, so the
hooks attach directly to the generated classes.
"""

from __future__ import annotations

from typing import Optional

from ..spatial.controller import SpatialInfo
from ..core.data import IncompatibleUpdateError
from ..utils.logger import get_logger
from . import sim_pb2

logger = get_logger("models.sim")

SimSpatialChannelData = sim_pb2.SimSpatialChannelData
SimEntityChannelData = sim_pb2.SimEntityChannelData
SimGlobalChannelData = sim_pb2.SimGlobalChannelData
EntityState = sim_pb2.EntityState


# ---- SimSpatialChannelData: entity table maintenance ----------------------


def _spatial_add_entity(self, entity_id: int, entity_data) -> None:
    """(ref: unrealpb/extension.go SpatialChannelData.AddEntity)."""
    if isinstance(entity_data, SimEntityChannelData):
        self.entities[entity_id].CopyFrom(entity_data.state)
    elif isinstance(entity_data, EntityState):
        self.entities[entity_id].CopyFrom(entity_data)
    else:
        raise IncompatibleUpdateError(
            f"cannot add entity from {type(entity_data).__name__}")
    self.entities[entity_id].entityId = entity_id


def _spatial_remove_entity(self, entity_id: int) -> None:
    if entity_id in self.entities:
        del self.entities[entity_id]


def _spatial_merge(self, src, options, spatial_notifier) -> None:
    """Entity-table merge: update/insert by id, honoring removed flags
    (ref: unrealpb/extension.go SpatialChannelData.Merge)."""
    if not isinstance(src, SimSpatialChannelData):
        raise IncompatibleUpdateError("src is not a SimSpatialChannelData")
    for entity_id, state in src.entities.items():
        if state.removed:
            self.entities.pop(entity_id, None)
        else:
            self.entities[entity_id].MergeFrom(state)


SimSpatialChannelData.add_entity = _spatial_add_entity
SimSpatialChannelData.remove_entity = _spatial_remove_entity
SimSpatialChannelData.merge = _spatial_merge


# ---- SimEntityChannelData: handover trigger + data merger -----------------


def _position_info(data: "SimEntityChannelData") -> Optional[SpatialInfo]:
    if not data.HasField("state") or not data.state.HasField("transform"):
        return None
    p = data.state.transform.position
    return SpatialInfo(p.x, p.y, p.z)


def _entity_get_spatial_info(self) -> Optional[SpatialInfo]:
    """(ref: spatial.go EntityChannelDataWithSpatialInfo)."""
    return _position_info(self)


def _entity_merge(self, src, options, spatial_notifier) -> None:
    """Merge an update and fire the handover notification when the entity
    MOVED (ref: tpspb/data.go:227-320 + pkg/unreal/handover.go:8-47):
    Vec3 axes carry presence, so a partial position update (only the
    changed axes replicated) merges over the old coordinates instead of
    zeroing them, and the notification fires only on an actual delta."""
    if not isinstance(src, SimEntityChannelData):
        raise IncompatibleUpdateError("src is not a SimEntityChannelData")
    old_info = _position_info(self)
    self.MergeFrom(src)
    # Post-merge position = partial update resolved against old values
    # (absent axes fell back), exactly CheckEntityHandover's fallback.
    new_info = _position_info(self)
    if spatial_notifier is None or old_info is None or new_info is None:
        return
    entity_id = self.state.entityId
    if entity_id == 0:
        return
    provider = lambda src_ch, dst_ch: entity_id
    if (old_info.x, old_info.y, old_info.z) == (new_info.x, new_info.y, new_info.z):
        # No movement -> no handover check (handover.go:31). The device
        # controller still needs to SEE stationary entities (its tracking
        # and follow-interest centering come from updates), so offer the
        # observation without the handover path.
        observe = getattr(spatial_notifier, "observe_entity", None)
        if observe is not None:
            observe(entity_id, new_info, provider)
        return
    spatial_notifier.notify(
        old_info,
        new_info,
        provider,
    )


def _entity_merge_to(self, spatial_data, full_data: bool) -> None:
    """(ref: tpspb/data.go MergeTo). Identifier-only unless ``full_data``."""
    if not isinstance(spatial_data, SimSpatialChannelData):
        raise IncompatibleUpdateError("target is not a SimSpatialChannelData")
    entity_id = self.state.entityId
    if full_data:
        spatial_data.entities[entity_id].CopyFrom(self.state)
    else:
        spatial_data.entities[entity_id].entityId = entity_id


SimEntityChannelData.get_spatial_info = _entity_get_spatial_info
SimEntityChannelData.merge = _entity_merge
SimEntityChannelData.merge_to = _entity_merge_to


# ---- SimHandoverData: the HandoverDataWithPayload seam --------------------


def _handover_clear_payload(self) -> None:
    """Strip the bulk payload for connections without interest
    (ref: spatial.go:594-597 HandoverDataWithPayload +
    unrealpb/extension.go HandoverData.ClearPayload — identity context
    stays, channel data goes)."""
    self.ClearField("channelData")


sim_pb2.SimHandoverData.clear_payload = _handover_clear_payload


def register_sim_types() -> None:
    """Install the sim family as the channel-data types (the reference does
    this via DataMsgFullName in the channel settings or explicit calls in
    example mains)."""
    from ..core.data import register_channel_data_type
    from ..core.types import ChannelType

    register_channel_data_type(ChannelType.SPATIAL, SimSpatialChannelData())
    register_channel_data_type(ChannelType.ENTITY, SimEntityChannelData())
    register_channel_data_type(ChannelType.GLOBAL, SimGlobalChannelData())
    register_channel_data_type(ChannelType.SUBWORLD, SimGlobalChannelData())


# -imports hook (see core.channel.init_channels)
register_channel_data_types = register_sim_types

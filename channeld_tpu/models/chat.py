"""Chat channel-data behavior: custom list merge with time-span truncation
(ref: examples/chat-rooms/chatpb/merge.go:14-49).

When the merged message list exceeds listSizeLimit with truncateTop, the
head is trimmed — but messages younger than TIME_SPAN_LIMIT survive even
beyond the limit, so a burst of fresh chat is never cut mid-conversation.
"""

from __future__ import annotations

import time

from ..core.data import IncompatibleUpdateError

from . import chat_pb2

ChatMessage = chat_pb2.ChatMessage
ChatChannelData = chat_pb2.ChatChannelData

# Messages newer than this always survive a top-truncation (seconds).
# Module-level so deployments can match the reference examples (chat-rooms
# main.go sets 60s at boot; merge.go's own default is 10s).
TIME_SPAN_LIMIT = 10.0


def set_time_span_limit(seconds: float) -> None:
    global TIME_SPAN_LIMIT
    TIME_SPAN_LIMIT = seconds


def _chat_merge(self, src, options, spatial_notifier) -> None:
    # The same merge serves the chtpu-native family and the
    # reference-package-compatible one (compat/chatpb.proto). A
    # cross-family update (same field numbers, different descriptor pool)
    # is converted via serialize/parse BEFORE any mutation — mutating
    # first and failing on extend would wipe existing history when
    # shouldReplaceList is set.
    if type(src) is not type(self):
        if not hasattr(src, "chatMessages"):
            raise IncompatibleUpdateError("src is not a chat channel data message")
        converted = type(self)()
        converted.ParseFromString(src.SerializeToString())
        src = converted
    if options is not None and options.shouldReplaceList:
        del self.chatMessages[:]
    self.chatMessages.extend(src.chatMessages)

    if options is None:
        return
    limit = options.listSizeLimit
    n = len(self.chatMessages)
    if limit > 0 and n > limit:
        if options.truncateTop:
            start = n - limit
            if TIME_SPAN_LIMIT > 0:
                available_ms = (time.time() - TIME_SPAN_LIMIT) * 1000
                while start > 0 and self.chatMessages[start - 1].sendTime >= available_ms:
                    start -= 1
            del self.chatMessages[:start]
        else:
            del self.chatMessages[limit:]


def attach_chat_merge(cls) -> None:
    """Attach the reference chat merge to a ChatChannelData-shaped class."""
    cls.merge = _chat_merge


attach_chat_merge(ChatChannelData)


def register_chat_types() -> None:
    from ..core.data import register_channel_data_type
    from ..core.types import ChannelType

    register_channel_data_type(ChannelType.GLOBAL, ChatChannelData())
    register_channel_data_type(ChannelType.SUBWORLD, ChatChannelData())
    register_channel_data_type(ChannelType.PRIVATE, ChatChannelData())


# -imports hook (see core.channel.init_channels)
register_channel_data_types = register_chat_types

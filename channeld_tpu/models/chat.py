"""Chat channel-data behavior: custom list merge with time-span truncation
(ref: examples/chat-rooms/chatpb/merge.go:14-49).

When the merged message list exceeds listSizeLimit with truncateTop, the
head is trimmed — but messages younger than TIME_SPAN_LIMIT survive even
beyond the limit, so a burst of fresh chat is never cut mid-conversation.
"""

from __future__ import annotations

import time

from . import chat_pb2

ChatMessage = chat_pb2.ChatMessage
ChatChannelData = chat_pb2.ChatChannelData

# Messages newer than this always survive a top-truncation (seconds).
TIME_SPAN_LIMIT = 10.0


def _chat_merge(self, src, options, spatial_notifier) -> None:
    if not isinstance(src, ChatChannelData):
        raise TypeError("src is not a ChatChannelData")
    if options is not None and options.shouldReplaceList:
        del self.chatMessages[:]
    self.chatMessages.extend(src.chatMessages)

    if options is None:
        return
    limit = options.listSizeLimit
    n = len(self.chatMessages)
    if limit > 0 and n > limit:
        if options.truncateTop:
            start = n - limit
            if TIME_SPAN_LIMIT > 0:
                available_ms = (time.time() - TIME_SPAN_LIMIT) * 1000
                while start > 0 and self.chatMessages[start - 1].sendTime >= available_ms:
                    start -= 1
            del self.chatMessages[:start]
        else:
            del self.chatMessages[limit:]


ChatChannelData.merge = _chat_merge


def register_chat_types() -> None:
    from ..core.data import register_channel_data_type
    from ..core.types import ChannelType

    register_channel_data_type(ChannelType.GLOBAL, ChatChannelData())
    register_channel_data_type(ChannelType.SUBWORLD, ChatChannelData())
    register_channel_data_type(ChannelType.PRIVATE, ChatChannelData())


# -imports hook (see core.channel.init_channels)
register_channel_data_types = register_chat_types

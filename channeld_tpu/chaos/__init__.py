"""Deterministic fault-injection layer (chaos engineering for the gateway).

Injection points are threaded through the transport reactors, the
connection/channel backpressure machinery, the KCP wire ARQ, and the
device decision plane; a seeded :class:`Scenario` schedules which faults
fire and when, and every fire is journaled so failures replay exactly.
See doc/chaos.md for the catalog and the soak driver
(scripts/chaos_soak.py) that proves the degradation paths live.

The wire-protocol fuzzer (:mod:`channeld_tpu.chaos.fuzz`,
doc/edge_hardening.md) is the adversarial complement: seeded hostile
byte streams against a real in-process gateway, with minimized violating
inputs committed to tests/corpus/wire/ and replayed in tier-1.
"""

from .injector import POINTS, ChaosInjector, arm, arm_from_file, chaos, disarm
from .invariants import InvariantChecker
from .scenario import FaultRule, Scenario

__all__ = [
    "POINTS",
    "ChaosInjector",
    "InvariantChecker",
    "FaultRule",
    "Scenario",
    "arm",
    "arm_from_file",
    "chaos",
    "disarm",
]

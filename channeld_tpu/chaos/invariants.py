"""Invariant checking over the gateway's own metrics.

The chaos soak's pass/fail story: after (and during) a fault-laden run,
the gateway must still satisfy hard invariants — no entity lost, exact
counter accounting, recovery inside its deadline, tick p99 bounded. The
checker reads the process metrics registry directly (the same numbers
/metrics serves) so the assertions are about what an operator would
actually observe.

Counters are process-cumulative, so a soak embedded in a longer-lived
process (the pytest smoke) snapshots a baseline with :func:`scrape` at
start and evaluates on the :func:`delta` — histogram buckets are
cumulative counters too, so quantiles computed from a delta reflect only
the soak's own observations.
"""

from __future__ import annotations

from typing import Optional


def scrape(registry=None) -> dict:
    """{(sample_name, (sorted label items)): value} for every sample in
    the metrics registry (defaults to the gateway registry)."""
    if registry is None:
        from ..core import metrics

        registry = metrics.registry
    out: dict = {}
    for family in registry.collect():
        for sample in family.samples:
            key = (sample.name, tuple(sorted(sample.labels.items())))
            out[key] = sample.value
    return out


def delta(now: dict, base: dict) -> dict:
    """Per-sample ``now - base`` (samples absent from base count from 0).
    Meaningful for counters and histogram buckets; gauges keep their
    ``now`` reading by passing ``base={}``."""
    return {k: v - base.get(k, 0.0) for k, v in now.items()}


def sample_total(samples: Optional[dict], name: str, **label_filter) -> float:
    """Sum of every sample called ``name`` whose labels include
    ``label_filter`` (Counter samples end in ``_total``). ``samples``
    None scrapes the live registry."""
    if samples is None:
        samples = scrape()
    want = set(label_filter.items())
    total = 0.0
    for (sname, labels), value in samples.items():
        if sname == name and want.issubset(set(labels)):
            total += value
    return total


def histogram_quantile(
    samples: Optional[dict], name: str, q: float, **label_filter
) -> Optional[float]:
    """Estimate the q-quantile of a prometheus Histogram from its
    cumulative buckets (linear interpolation inside the bucket — the
    same estimate PromQL's histogram_quantile gives). None with no
    observations."""
    if samples is None:
        samples = scrape()
    want = set(label_filter.items())
    buckets: list[tuple[float, float]] = []
    for (sname, labels), value in samples.items():
        if sname != f"{name}_bucket":
            continue
        ld = dict(labels)
        le = ld.pop("le", None)
        if le is None or not want.issubset(set(ld.items())):
            continue
        buckets.append((float("inf") if le == "+Inf" else float(le), value))
    if not buckets:
        return None
    buckets.sort()
    total = buckets[-1][1]
    if total <= 0:
        return None
    target = q * total
    prev_le, prev_count = 0.0, 0.0
    for le, count in buckets:
        if count >= target:
            if le == float("inf"):
                return prev_le  # everything above the last finite bucket
            span = count - prev_count
            frac = (target - prev_count) / span if span > 0 else 1.0
            return prev_le + (le - prev_le) * frac
        prev_le, prev_count = le, count
    return buckets[-1][0]


class InvariantChecker:
    """Accumulates named pass/fail checks into a report dict."""

    def __init__(self):
        self.results: list[dict] = []

    def check(self, name: str, ok: bool, detail: str = "") -> bool:
        self.results.append({"name": name, "ok": bool(ok), "detail": detail})
        return bool(ok)

    def expect_equal(self, name: str, got, want, detail: str = "") -> bool:
        return self.check(
            name, got == want,
            f"got={got} want={want}" + (f" ({detail})" if detail else ""),
        )

    def expect_le(self, name: str, got, bound, detail: str = "") -> bool:
        return self.check(
            name, got is not None and got <= bound,
            f"got={got} bound={bound}" + (f" ({detail})" if detail else ""),
        )

    def expect_gt(self, name: str, got, floor, detail: str = "") -> bool:
        return self.check(
            name, got is not None and got > floor,
            f"got={got} floor={floor}" + (f" ({detail})" if detail else ""),
        )

    @property
    def ok(self) -> bool:
        return all(r["ok"] for r in self.results)

    def summary(self) -> dict:
        return {"ok": self.ok, "checks": self.results}

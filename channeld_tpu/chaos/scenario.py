"""Chaos scenario spec: which faults fire, where, and on what schedule.

A scenario is a seed plus a list of fault rules. Each rule targets one
injection point (a dotted name like ``transport.reset``; the catalog of
points threaded through the stack lives in ``injector.POINTS``) and
fires on a deterministic schedule:

- ``every_n``: fire on every Nth *call* of the point (per-point call
  counters, so the schedule replays exactly for a given inbound
  sequence regardless of how unrelated points interleave).
- ``rate``: fire with probability ``rate`` per call, drawn from a
  per-point ``random.Random`` seeded from ``seed ^ crc32(point)`` —
  identical call sequences produce identical fault sequences.
- ``burst``: once triggered, keep firing for ``burst`` consecutive
  calls (models a sustained outage rather than a blip).
- ``start_at_s`` / ``stop_at_s``: wall-clock gates relative to arming,
  for live soaks (omit them in replay-exact unit scenarios).
- ``max_fires``: hard cap on total fires for the rule.
- ``stall_ms``: for stall-type points, how long the injected stall is.

JSON schema (see doc/chaos.md)::

    {
      "seed": 42,
      "config_overrides": {"CellBucket": 2},
      "faults": [
        {"point": "transport.reset", "every_n": 400, "max_fires": 6},
        {"point": "kcp.loss", "rate": 0.05},
        {"point": "channel.tick_budget", "every_n": 50, "stall_ms": 15}
      ]
    }

``config_overrides`` is not an injection rule: the soak driver merges it
into the spatial controller's ``Config`` (e.g. undersizing ``CellBucket``
to force the cells-plane overflow shed + re-offer path).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class FaultRule:
    point: str
    every_n: int = 0  # 0 = not call-scheduled
    rate: float = 0.0  # 0 = not probability-scheduled
    burst: int = 1  # consecutive calls per trigger
    start_at_s: float = 0.0
    stop_at_s: float = float("inf")
    max_fires: Optional[int] = None
    stall_ms: float = 0.0

    def __post_init__(self):
        if not self.point:
            raise ValueError("fault rule needs a point name")
        if self.every_n < 0 or self.burst < 1:
            raise ValueError(f"bad schedule for {self.point}")
        if not (0.0 <= self.rate <= 1.0):
            raise ValueError(f"rate out of [0,1] for {self.point}")
        if self.every_n == 0 and self.rate == 0.0:
            raise ValueError(
                f"rule for {self.point} needs every_n or rate to ever fire"
            )

    @classmethod
    def from_dict(cls, d: dict) -> "FaultRule":
        # None is accepted wherever to_dict emits it (stop_at_s has no
        # JSON spelling for inf; max_fires None = uncapped), so a
        # SOAK_*.json artifact's embedded scenario replays as-is.
        stop = d.get("stop_at_s")
        max_fires = d.get("max_fires")
        return cls(
            point=d.get("point", ""),
            every_n=int(d.get("every_n", 0)),
            rate=float(d.get("rate", 0.0)),
            burst=int(d.get("burst", 1)),
            start_at_s=float(d.get("start_at_s", 0.0)),
            stop_at_s=float(stop) if stop is not None else float("inf"),
            max_fires=int(max_fires) if max_fires is not None else None,
            stall_ms=float(d.get("stall_ms", 0.0)),
        )


@dataclass
class Scenario:
    seed: int = 0
    faults: list[FaultRule] = field(default_factory=list)
    # Merged into the spatial controller Config by the soak driver
    # (e.g. {"CellBucket": 2} to force the overflow shed path).
    config_overrides: dict = field(default_factory=dict)
    name: str = ""

    @classmethod
    def from_dict(cls, d: dict) -> "Scenario":
        return cls(
            seed=int(d.get("seed", 0)),
            faults=[FaultRule.from_dict(f) for f in d.get("faults", [])],
            config_overrides=dict(d.get("config_overrides", {})),
            name=str(d.get("name", "")),
        )

    @classmethod
    def load(cls, path: str) -> "Scenario":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "seed": self.seed,
            "config_overrides": self.config_overrides,
            "faults": [
                {
                    "point": r.point,
                    "every_n": r.every_n,
                    "rate": r.rate,
                    "burst": r.burst,
                    "start_at_s": r.start_at_s,
                    "stop_at_s": (
                        r.stop_at_s if r.stop_at_s != float("inf") else None
                    ),
                    "max_fires": r.max_fires,
                    "stall_ms": r.stall_ms,
                }
                for r in self.faults
            ],
        }

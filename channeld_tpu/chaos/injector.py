"""Deterministic fault injector: the process-wide chaos singleton.

Hooks threaded through the stack call ``chaos.fire(point)`` /
``chaos.stall_s(point)`` at their injection point; when the injector is
disarmed (the default, and the only state production code ever runs in)
the hooks cost one attribute load. When armed with a
:class:`~channeld_tpu.chaos.scenario.Scenario`, each point keeps its own
call counter and its own seeded RNG, so a fault schedule replays exactly
for a given per-point call sequence — the interleaving of *other* points
cannot shift it. Every fire is journaled (point, call index, fire
ordinal, relative time) so a soak artifact records precisely which
faults hit and a failing run can be replayed.

This module imports only the standard library (plus a lazy metrics
import at fire time), so any layer of the stack can hook it without
import cycles.
"""

from __future__ import annotations

import time
import zlib
from random import Random
from typing import Optional

from .scenario import FaultRule, Scenario

# Catalog of injection points threaded through the stack. Hook sites
# pass these exact names; scenarios referencing an unknown point fail
# at arm time (a typo'd rule that silently never fires would make a
# "passing" chaos run meaningless).
POINTS = {
    # transport plane (core/server.py reactors)
    "transport.reset": "abort the socket before processing the read",
    "transport.truncate": "feed a partial read, then reset (peer died mid-frame)",
    "transport.corrupt": "flip a header byte (exercises the fatal framing path)",
    # connection plane (core/server.py + core/channel.py)
    "connection.eof_race": "close right after a read (EOF races deferred ingest)",
    "connection.queue_full": "report the target channel queue full (backpressure stash)",
    # channel runtime (core/channel.py)
    "channel.tick_budget": "stall inside message handling (tick-budget exhaustion)",
    # KCP wire ARQ (core/kcp.py)
    "kcp.loss": "drop an outbound datagram",
    "kcp.reorder": "hold an outbound datagram until after the next one",
    "kcp.dup": "duplicate an outbound datagram",
    # device plane (spatial/tpu_controller.py + core/device_guard.py)
    "device.dispatch_stall": "stall before the engine step (slow device dispatch)",
    "device.step_error": "raise a transient XLA-style error from the guarded step",
    "device.step_hang": "stall INSIDE the guarded step past the watchdog deadline",
    "device.nan": "corrupt device state (NaN positions + garbage cell baselines)",
    "device.rebuild_fail": "fail the in-process engine rebuild attempt",
    # simulation plane (channeld_tpu/sim/plane.py)
    "sim.step_nan": "rot the agent rows on device (NaN kinematics + "
                    "garbage cell baselines; the sentinel-triggered "
                    "rebuild must heal the population exactly)",
    "sim.stampede": "herd every agent toward one cell (deterministic "
                    "handover/density burst: exercises partition "
                    "splits and overload shedding from the sim plane)",
    # federation trunk plane (federation/trunk.py)
    "trunk.egress_drop": "drop an outbound trunk frame (lossy inter-gateway link)",
    "trunk.sever": "abort the trunk socket before the write (link partition)",
    # durable persistence plane (core/wal.py)
    "wal.torn_write": "write only a prefix of a WAL record (power loss "
                      "mid-append; replay must truncate at the bad CRC)",
    "wal.fsync_stall": "stall the off-thread writer before fsync (slow "
                       "disk; the tick path must stay unaffected)",
}


class _PointState:
    __slots__ = ("rule", "rng", "calls", "fires", "burst_left")

    def __init__(self, rule: FaultRule, seed: int):
        self.rule = rule
        self.rng = Random(seed ^ zlib.crc32(rule.point.encode()))
        self.calls = 0
        self.fires = 0
        self.burst_left = 0


class ChaosInjector:
    """Armed/disarmed fault gate. One instance per process (``chaos``)."""

    def __init__(self):
        self.armed = False
        self._points: dict[str, _PointState] = {}
        self._armed_at = 0.0
        self.scenario: Optional[Scenario] = None
        # Fired from every domain (tick-loop, WAL writer, device
        # worker): one GIL-atomic list append per event, read only by
        # soak teardown (doc/concurrency.md).
        self.journal: list[dict] = []  # tpulint: shared=atomic

    # ---- lifecycle -------------------------------------------------------

    def arm(self, scenario: Scenario) -> None:
        unknown = [r.point for r in scenario.faults if r.point not in POINTS]
        if unknown:
            raise ValueError(f"unknown chaos points: {unknown}")
        self._points = {
            r.point: _PointState(r, scenario.seed) for r in scenario.faults
        }
        self.scenario = scenario
        self.journal = []
        self._armed_at = time.monotonic()
        self.armed = True

    def disarm(self) -> None:
        self.armed = False
        self._points = {}
        self.scenario = None

    # ---- fault gates -----------------------------------------------------

    def fire(self, point: str) -> bool:
        """Count one call of ``point``; True when the fault fires."""
        st = self._points.get(point)
        if st is None:
            return False
        st.calls += 1
        rule = st.rule
        if rule.max_fires is not None and st.fires >= rule.max_fires:
            st.burst_left = 0  # the cap is hard; a burst never exceeds it
            return False
        if st.burst_left > 0:
            st.burst_left -= 1
            self._record(st, point)
            return True
        if rule.start_at_s > 0.0 or rule.stop_at_s != float("inf"):
            t = time.monotonic() - self._armed_at
            if not (rule.start_at_s <= t <= rule.stop_at_s):
                return False
        triggered = False
        if rule.every_n and st.calls % rule.every_n == 0:
            triggered = True
        elif rule.rate and st.rng.random() < rule.rate:
            triggered = True
        if not triggered:
            return False
        st.burst_left = rule.burst - 1
        self._record(st, point)
        return True

    def stall_s(self, point: str) -> float:
        """Stall duration in seconds when the point fires, else 0."""
        if not self.fire(point):
            return 0.0
        st = self._points[point]
        return st.rule.stall_ms / 1000.0

    def _record(self, st: _PointState, point: str) -> None:
        st.fires += 1
        self.journal.append({
            "point": point,
            "call": st.calls,
            "fire": st.fires,
            "t": round(time.monotonic() - self._armed_at, 4),
        })
        try:  # lazy: metrics must not be a hard dependency of the injector
            from ..core import metrics

            metrics.chaos_faults.labels(point=point).inc()
        except Exception:
            pass

    # ---- reporting -------------------------------------------------------

    def fire_counts(self) -> dict[str, int]:
        return {p: st.fires for p, st in self._points.items()}

    def report(self) -> dict:
        """Journal + per-point counts, for soak artifacts."""
        return {
            "scenario": self.scenario.to_dict() if self.scenario else None,
            "fire_counts": self.fire_counts(),
            "call_counts": {p: st.calls for p, st in self._points.items()},
            "journal": list(self.journal),
        }


# The process-wide injector. Hook sites hold a module reference and check
# ``chaos.armed`` inline; tests and the soak driver arm/disarm it.
chaos = ChaosInjector()


def arm(scenario_or_dict) -> None:
    if isinstance(scenario_or_dict, dict):
        scenario_or_dict = Scenario.from_dict(scenario_or_dict)
    chaos.arm(scenario_or_dict)


def arm_from_file(path: str) -> None:
    chaos.arm(Scenario.load(path))


def disarm() -> None:
    chaos.disarm()

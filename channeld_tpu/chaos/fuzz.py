"""Deterministic, seeded wire-protocol fuzzer (doc/edge_hardening.md).

The adversarial complement to the scenario-driven chaos plane: instead of
replaying *plausible* faults (loss, reorder, partitions), this module throws
*implausible* bytes — truncated and oversized length prefixes, torn frames,
bit-flipped protobuf bodies, valid protos in the wrong FSM state, replayed
auth, mid-handshake closes — at a real in-process gateway and checks three
invariants after every input:

  1. **No uncaught exception reaches the event loop.** The TCP receive path
     (``_TcpServerProtocol.data_received`` -> ``Connection.on_bytes``) runs
     uncaught on the loop; anything a hostile peer can make escape there is
     gateway-fatal, not connection-fatal, and is exactly the defect class
     the edge plane exists to make impossible.
  2. **No per-connection resource leaves its envelope.** Every connection's
     send queue stays within ``-edge-queue-msgs`` / ``-edge-queue-bytes``
     (core/edge.py) no matter what the peer did.
  3. **The honest census stays exact.** A well-behaved authenticated client
     and the GLOBAL owner survive every hostile input — open, authenticated,
     owner intact — and a periodic user-space round-trip still delivers.

Determinism: every case derives from ``master_seed ^ iteration`` through
``random.Random`` only; no wall-clock feeds case generation, and channel
time is advanced synthetically by the pump. Replaying a saved case byte
stream is therefore exact at the decode/dispatch layer (ladder *timing* —
quarantine grace windows — still reads the monotonic clock, which is fine:
the oracle checks bounds, not schedules).

Corpus discipline: a violating input is shrunk by a bounded ddmin-lite pass
(drop ops, then halve byte ranges) and written as JSON to the regression
corpus (tests/corpus/wire/). tests/test_edge.py replays every corpus file
in tier-1, so a fixed defect stays fixed.

Thread model: everything here runs on the event-loop thread of the harness'
``asyncio.run``; the harness owns every registry it touches (it boots a
private gateway per run).
"""

from __future__ import annotations

import asyncio
import json
import os
import struct
import traceback
from dataclasses import dataclass, field
from random import Random
from typing import Callable, Optional

from ..utils.logger import get_logger

logger = get_logger("fuzz")

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))
CORPUS_DIR = os.path.join(REPO, "tests", "corpus", "wire")

# An op is one step of a hostile session:
#   ("data", <bytes>)  -> one data_received() call
#   ("pump",)          -> one gateway pump (tick + flush + edge tick)
#   ("close",)         -> connection_lost() (peer vanished mid-anything)
Op = tuple


@dataclass
class FuzzCase:
    """One hostile session: an op list against a fresh peer socket."""

    kind: str
    seed: int
    ops: list
    auth_first: bool = False  # complete a real handshake before the ops

    def to_json(self) -> dict:
        return {
            "kind": self.kind,
            "seed": self.seed,
            "auth_first": self.auth_first,
            "ops": [
                ["data", op[1].hex()] if op[0] == "data" else [op[0]]
                for op in self.ops
            ],
        }

    @classmethod
    def from_json(cls, obj: dict) -> "FuzzCase":
        ops = []
        for op in obj["ops"]:
            if op[0] == "data":
                ops.append(("data", bytes.fromhex(op[1])))
            else:
                ops.append((op[0],))
        return cls(
            kind=obj["kind"],
            seed=int(obj.get("seed", 0)),
            ops=ops,
            auth_first=bool(obj.get("auth_first", False)),
        )


@dataclass
class Violation:
    """One oracle breach, with enough context to reproduce it."""

    oracle: str  # event_loop_exception | envelope | census | roundtrip
    detail: str
    case: Optional[FuzzCase] = None


# ---------------------------------------------------------------------------
# frame builders
# ---------------------------------------------------------------------------


def _frame(msg_type: int, body: bytes, channel_id: int = 0) -> bytes:
    from ..protocol import encode_packet, wire_pb2

    return encode_packet(
        wire_pb2.Packet(
            messages=[
                wire_pb2.MessagePack(
                    channelId=channel_id, msgType=msg_type, msgBody=body
                )
            ]
        )
    )


def _auth_frame(pit: str) -> bytes:
    from ..core.types import MessageType
    from ..protocol import control_pb2

    return _frame(
        MessageType.AUTH,
        control_pb2.AuthMessage(
            playerIdentifierToken=pit, loginToken="fuzz"
        ).SerializeToString(),
    )


def _valid_frames(rng: Random) -> list:
    """A pool of well-formed frames to mutate — every system body the
    client FSM can reach, plus user-space forwards."""
    from ..core.types import MessageType
    from ..protocol import control_pb2

    return [
        _auth_frame("fuzz-pit-%d" % rng.randrange(1 << 16)),
        _frame(
            MessageType.SUB_TO_CHANNEL,
            control_pb2.SubscribedToChannelMessage(
                connId=rng.randrange(1 << 10)
            ).SerializeToString(),
        ),
        _frame(
            MessageType.CREATE_CHANNEL,
            control_pb2.CreateChannelMessage(
                channelType=rng.choice([0, 1, 2, 3, 7]),
                metadata="fuzz",
            ).SerializeToString(),
        ),
        _frame(
            MessageType.REMOVE_CHANNEL,
            control_pb2.RemoveChannelMessage(
                channelId=rng.randrange(1 << 8)
            ).SerializeToString(),
        ),
        _frame(
            MessageType.DISCONNECT,
            control_pb2.DisconnectMessage(
                connId=rng.randrange(1 << 10)
            ).SerializeToString(),
        ),
        _frame(100 + rng.randrange(8), rng.randbytes(rng.randrange(1, 64))),
    ]


def _bitflip(data: bytes, rng: Random, flips: int) -> bytes:
    buf = bytearray(data)
    for _ in range(flips):
        i = rng.randrange(len(buf))
        buf[i] ^= 1 << rng.randrange(8)
    return bytes(buf)


def _tear(data: bytes, rng: Random) -> list:
    """Split one byte stream into 2..5 data ops with pumps between —
    the decoder must reassemble across reads."""
    cuts = sorted(rng.sample(range(1, len(data)), min(len(data) - 1, rng.randrange(1, 5))))
    ops = []
    prev = 0
    for cut in cuts + [len(data)]:
        ops.append(("data", data[prev:cut]))
        if rng.random() < 0.5:
            ops.append(("pump",))
        prev = cut
    return ops


# ---------------------------------------------------------------------------
# case generators — one per hostile input family
# ---------------------------------------------------------------------------


def _gen_garbage(rng: Random) -> list:
    return [
        ("data", rng.randbytes(rng.randrange(1, 512)))
        for _ in range(rng.randrange(1, 4))
    ]


def _gen_bitflip_valid(rng: Random) -> list:
    frame = rng.choice(_valid_frames(rng))
    return [("data", _bitflip(frame, rng, rng.randrange(1, 9)))]


def _gen_truncate(rng: Random) -> list:
    frame = rng.choice(_valid_frames(rng))
    cut = rng.randrange(1, len(frame))
    ops = [("data", frame[:cut]), ("pump",)]
    if rng.random() < 0.5:
        ops.append(("data", rng.randbytes(rng.randrange(1, 64))))
    else:
        ops.append(("close",))
    return ops


def _gen_torn(rng: Random) -> list:
    frame = rng.choice(_valid_frames(rng))
    return _tear(frame, rng)


def _gen_oversize_prefix(rng: Random) -> list:
    # Header claims up to MAX_PACKET_SIZE; the body never (or partially)
    # arrives. The decoder must hold bounded state and teardown cleanly.
    size = rng.choice([0xFFFF, 0xFFFE, 0x8000, rng.randrange(1024, 0xFFFF)])
    header = b"CH" + struct.pack(">H", size) + bytes([rng.randrange(2)])
    ops = [("data", header), ("pump",)]
    if rng.random() < 0.5:
        ops.append(("data", rng.randbytes(rng.randrange(1, size))))
    ops.append(("close",) if rng.random() < 0.5 else ("pump",))
    return ops


def _gen_bad_header(rng: Random) -> list:
    choice = rng.randrange(3)
    if choice == 0:  # zero-size frame
        data = b"CH\x00\x00\x00"
    elif choice == 1:  # bad magic
        data = rng.randbytes(2) + struct.pack(">H", rng.randrange(64)) + b"\x00"
    else:  # snappy tag over garbage
        body = rng.randbytes(rng.randrange(1, 128))
        data = b"CH" + struct.pack(">H", len(body)) + b"\x01" + body
    return [("data", data)]


def _gen_wrong_state(rng: Random) -> list:
    # Valid protos the FSM must refuse in the current state (INIT unless
    # auth_first): subs, updates, forwards before auth; double auth after.
    frames = _valid_frames(rng)
    picks = rng.sample(frames, rng.randrange(1, min(4, len(frames))))
    ops = []
    for f in picks:
        ops.append(("data", f))
        ops.append(("pump",))
    return ops


def _gen_replay_auth(rng: Random) -> list:
    frame = _auth_frame("replay-%d" % rng.randrange(1 << 12))
    return [("data", frame), ("pump",), ("data", frame), ("pump",)]


def _gen_mid_handshake_close(rng: Random) -> list:
    frame = _auth_frame("gone-%d" % rng.randrange(1 << 12))
    cut = rng.randrange(1, len(frame))
    return [("data", frame[:cut]), ("close",)]


def _gen_hostile_fields(rng: Random) -> list:
    # Structurally valid wire packet, adversarial field values: system
    # msgTypes the client should never speak, huge channel ids, junk
    # bodies under a real type tag.
    from ..protocol import encode_packet, wire_pb2

    packs = []
    for _ in range(rng.randrange(1, 6)):
        packs.append(
            wire_pb2.MessagePack(
                channelId=rng.choice([0, 1, 0xFFFF, (1 << 31) - 1]),
                msgType=rng.choice(
                    [0, 2, 9, 13, 19, 22, 24, 27, 30, 38, 50, 99, 100, 65535]
                ),
                msgBody=rng.randbytes(rng.randrange(64)),
                stubId=rng.choice([0, 1, 0xFFFFFFFF]),
                broadcast=rng.choice([0, 1, 3, 0xFF]),
            )
        )
    data = encode_packet(wire_pb2.Packet(messages=packs))
    return [("data", data), ("pump",)]


def _gen_splice(rng: Random) -> list:
    frames = _valid_frames(rng)
    a, b = rng.choice(frames), rng.choice(frames)
    glue = rng.randbytes(rng.randrange(0, 16))
    return _tear(a + glue + b, rng)


def _gen_spatial_probe(rng: Random) -> list:
    # The client FSM whitelists 15-65535, which includes the whole
    # spatial/entity plane — probe those handlers with valid-ish and
    # garbage bodies against a gateway with NO spatial controller.
    from ..core.types import MessageType
    from ..protocol import spatial_pb2

    builders = [
        lambda: (
            MessageType.QUERY_SPATIAL_CHANNEL,
            spatial_pb2.QuerySpatialChannelMessage().SerializeToString(),
        ),
        lambda: (
            MessageType.UPDATE_SPATIAL_INTEREST,
            spatial_pb2.UpdateSpatialInterestMessage(
                connId=rng.randrange(1 << 10)
            ).SerializeToString(),
        ),
        lambda: (
            MessageType.CREATE_ENTITY_CHANNEL,
            spatial_pb2.CreateEntityChannelMessage(
                entityId=rng.randrange(1 << 31)
            ).SerializeToString(),
        ),
        lambda: (
            MessageType.ENTITY_GROUP_ADD,
            rng.randbytes(rng.randrange(32)),
        ),
        lambda: (
            MessageType.ENTITY_GROUP_REMOVE,
            rng.randbytes(rng.randrange(32)),
        ),
        lambda: (
            MessageType.CHANNEL_DATA_HANDOVER,
            rng.randbytes(rng.randrange(64)),
        ),
        lambda: (MessageType.SPATIAL_CHANNELS_READY, b""),
    ]
    ops = []
    for _ in range(rng.randrange(1, 4)):
        mt, body = rng.choice(builders)()
        ops.append(("data", _frame(mt, body, rng.choice([0, 1, 0xFFFF]))))
        ops.append(("pump",))
    return ops


def _gen_query_probe(rng: Random) -> list:
    # Hostile standing-query registrations (spatial/messages.py
    # _validate_interest_query): NaN/inf centers, negative radii/angles,
    # spot lists past the queryplane_max_spots cap. The handler must
    # reject-and-count every one (query_malformed_total) without letting
    # a non-finite float near the device query table.
    from ..core.types import MessageType
    from ..protocol import spatial_pb2

    nan, inf = float("nan"), float("inf")

    def _msg():
        return spatial_pb2.UpdateSpatialInterestMessage(
            connId=rng.randrange(1 << 10)
        )

    def _bad_sphere():
        m = _msg()
        m.query.sphereAOI.center.x = rng.choice([nan, inf, -inf, 0.0])
        m.query.sphereAOI.center.z = rng.choice([nan, 1e308, 0.0])
        m.query.sphereAOI.radius = rng.choice([nan, inf, -1.0, -1e30, 50.0])
        return m

    def _bad_box():
        m = _msg()
        m.query.boxAOI.center.x = rng.choice([nan, inf, 0.0])
        m.query.boxAOI.extent.x = rng.choice([nan, -inf, -4.0, 100.0])
        m.query.boxAOI.extent.z = rng.choice([inf, -1.0, 100.0])
        return m

    def _bad_cone():
        m = _msg()
        m.query.coneAOI.center.z = rng.choice([nan, -inf, 0.0])
        m.query.coneAOI.direction.x = rng.choice([nan, inf, 1.0])
        m.query.coneAOI.angle = rng.choice([nan, -0.5, inf, 0.7])
        m.query.coneAOI.radius = rng.choice([-inf, nan, -2.0, 80.0])
        return m

    def _oversize_spots():
        m = _msg()
        for i in range(rng.randrange(257, 400)):
            s = m.query.spotsAOI.spots.add()
            s.x, s.z = float(i), float(i)
        return m

    def _nan_spots():
        m = _msg()
        for _ in range(rng.randrange(1, 8)):
            s = m.query.spotsAOI.spots.add()
            s.x = rng.choice([nan, inf, -inf, 1.0])
            s.z = rng.choice([nan, 3.0])
        return m

    builders = [_bad_sphere, _bad_box, _bad_cone, _oversize_spots,
                _nan_spots]
    ops = []
    for _ in range(rng.randrange(1, 4)):
        body = rng.choice(builders)().SerializeToString()
        ops.append(("data", _frame(MessageType.UPDATE_SPATIAL_INTEREST,
                                   body, rng.choice([0, 1, 0xFFFF]))))
        ops.append(("pump",))
    return ops


def _gen_acl_spoof(rng: Random) -> list:
    # Sub/unsub with ANOTHER conn's id (1 = GLOBAL owner, 2 = the honest
    # client in this harness): the ACL must refuse the cross-conn op and
    # the census oracle must see the honest world untouched.
    from ..core.types import MessageType
    from ..protocol import control_pb2

    target = rng.choice([1, 2])
    ops = []
    for _ in range(rng.randrange(1, 3)):
        if rng.random() < 0.5:
            body = control_pb2.UnsubscribedFromChannelMessage(
                connId=target
            ).SerializeToString()
            ops.append(("data", _frame(MessageType.UNSUB_FROM_CHANNEL, body)))
        else:
            body = control_pb2.SubscribedToChannelMessage(
                connId=target
            ).SerializeToString()
            ops.append(("data", _frame(MessageType.SUB_TO_CHANNEL, body)))
        ops.append(("pump",))
    return ops


def _gen_recovery_probe(rng: Random) -> list:
    # Gateway->peer recovery/failover control types, reflected back by a
    # hostile client (20-27 sit inside the client whitelist).
    from ..core.types import MessageType

    types = [
        MessageType.RECOVERY_CHANNEL_DATA,
        MessageType.RECOVERY_END,
        MessageType.CHANNEL_OWNER_LOST,
        MessageType.CHANNEL_OWNER_RECOVERED,
        MessageType.CELL_REHOSTED,
        MessageType.CELL_MIGRATED,
        MessageType.CLIENT_REDIRECT,
    ]
    ops = []
    for _ in range(rng.randrange(1, 4)):
        ops.append(
            ("data", _frame(rng.choice(types), rng.randbytes(rng.randrange(48))))
        )
        ops.append(("pump",))
    return ops


def _gen_data_update(rng: Random) -> list:
    # CHANNEL_DATA_UPDATE with a hostile Any: garbage type_url, wrong
    # payload under a real url, or random bytes where the Any should be.
    from ..core.types import MessageType
    from ..protocol import control_pb2

    choice = rng.randrange(3)
    if choice == 0:
        msg = control_pb2.ChannelDataUpdateMessage()
        msg.data.type_url = "type.googleapis.com/" + "".join(
            chr(rng.randrange(33, 127)) for _ in range(rng.randrange(1, 40))
        )
        msg.data.value = rng.randbytes(rng.randrange(128))
        body = msg.SerializeToString()
    elif choice == 1:
        msg = control_pb2.ChannelDataUpdateMessage()
        msg.data.type_url = "type.googleapis.com/channeld.SpatialChannelDataMessage"
        msg.data.value = rng.randbytes(rng.randrange(128))
        body = msg.SerializeToString()
    else:
        body = rng.randbytes(rng.randrange(1, 96))
    return [("data", _frame(MessageType.CHANNEL_DATA_UPDATE, body)), ("pump",)]


def _gen_oversize_forward(rng: Random) -> list:
    # A user-space forward near the 64KB frame cap: the egress wrap adds
    # bytes, so re-encode must split or drop WITHOUT killing the pump.
    from ..protocol.framing import FramingError

    mt = 100 + rng.randrange(4)
    overhead = len(_frame(mt, b"")) - 5 + 8  # proto wrap + grown varints
    size = 0xFFFF - overhead - rng.randrange(4)
    body = rng.randbytes(size)
    while True:  # creep up against the exact cap
        try:
            frame = _frame(mt, body)
        except FramingError:
            body = body[:-4]
            continue
        break
    return [("data", frame), ("pump",), ("pump",)]


def _gen_frame_flood(rng: Random) -> list:
    # Hundreds of valid frames in single reads: drives the ingress
    # token bucket into strikes -> quarantine -> structured disconnect,
    # all under the envelope/census oracle.
    frame = _frame(100 + rng.randrange(4), rng.randbytes(rng.randrange(4, 32)))
    ops = []
    for _ in range(rng.randrange(2, 5)):
        ops.append(("data", frame * rng.randrange(50, 300)))
        if rng.random() < 0.5:
            ops.append(("pump",))
    ops.append(("pump",))
    return ops


GENERATORS: dict[str, Callable[[Random], list]] = {
    "garbage": _gen_garbage,
    "bitflip_valid": _gen_bitflip_valid,
    "truncate": _gen_truncate,
    "torn": _gen_torn,
    "oversize_prefix": _gen_oversize_prefix,
    "bad_header": _gen_bad_header,
    "wrong_state": _gen_wrong_state,
    "replay_auth": _gen_replay_auth,
    "mid_handshake_close": _gen_mid_handshake_close,
    "hostile_fields": _gen_hostile_fields,
    "splice": _gen_splice,
    "spatial_probe": _gen_spatial_probe,
    "query_probe": _gen_query_probe,
    "acl_spoof": _gen_acl_spoof,
    "recovery_probe": _gen_recovery_probe,
    "data_update": _gen_data_update,
    "oversize_forward": _gen_oversize_forward,
    "frame_flood": _gen_frame_flood,
}

# Families that exercise the authenticated FSM state get a handshake first
# half the time (always, where unauthenticated sends would just be FSM
# noise); pure framing attacks don't need one.
_AUTH_ELIGIBLE = {
    "bitflip_valid",
    "wrong_state",
    "hostile_fields",
    "splice",
    "garbage",
}
_AUTH_ALWAYS = {
    "spatial_probe",
    "query_probe",
    "acl_spoof",
    "recovery_probe",
    "data_update",
    "oversize_forward",
    "frame_flood",
}


def make_case(master_seed: int, iteration: int) -> FuzzCase:
    seed = (master_seed ^ (iteration * 0x9E3779B1)) & 0xFFFFFFFF
    rng = Random(seed)
    kind = rng.choice(sorted(GENERATORS))
    auth_first = kind in _AUTH_ALWAYS or (
        kind in _AUTH_ELIGIBLE and rng.random() < 0.5
    )
    ops = GENERATORS[kind](rng)
    return FuzzCase(kind=kind, seed=seed, ops=ops, auth_first=auth_first)


# ---------------------------------------------------------------------------
# the in-process gateway harness
# ---------------------------------------------------------------------------


class _FuzzSocket:
    """asyncio.Transport stand-in: captures writes, honors pause/close,
    never touches a real socket."""

    def __init__(self, peer: tuple):
        self._peer = peer
        self._closing = False
        self.paused = False
        self.written: list = []

    def get_extra_info(self, name, default=None):
        if name == "peername":
            return self._peer
        return default

    def set_write_buffer_limits(self, high=None, low=None):
        pass

    def get_write_buffer_size(self) -> int:
        return 0

    def write(self, data: bytes) -> None:
        if not self._closing:
            self.written.append(data)

    def is_closing(self) -> bool:
        return self._closing

    def close(self) -> None:
        self._closing = True

    def abort(self) -> None:
        self._closing = True

    def pause_reading(self) -> None:
        self.paused = True

    def resume_reading(self) -> None:
        self.paused = False


class GatewayHarness:
    """A private, fully-booted gateway the fuzzer can hammer.

    Real everything: registries, FSMs, the GLOBAL channel with a SERVER
    owner, an honest authenticated client — only the sockets are fake.
    Bans are disabled (``max_failed_auth_attempts = max_fsm_disallowed =
    0``) because every fuzz peer would otherwise blacklist its synthetic
    /16 and turn the rest of the run into a no-op.
    """

    def __init__(self):
        self.violations: list[Violation] = []
        self._peer_serial = 0
        self.now_ns = 0
        self.mono = 0.0
        self._honest_written = 0

    # -- boot --------------------------------------------------------------

    def boot(self) -> None:
        from ..core import channel as channel_mod
        from ..core import connection as connection_mod
        from ..core import data as data_mod
        from ..core import ddos as ddos_mod
        from ..core import connection_recovery as recovery_mod
        from ..core import events
        from ..core.channel import init_channels
        from ..core.connection import init_connections
        from ..core.ddos import init_anti_ddos
        from ..core.overload import reset_overload
        from ..core.settings import (
            ChannelSettings,
            global_settings,
            reset_global_settings,
        )
        from ..core.tracing import recorder
        from ..core.types import ChannelType, ConnectionType
        from ..federation import reset_federation
        from ..spatial.controller import reset_spatial_controller

        channel_mod.reset_channels()
        connection_mod.reset_connections()
        data_mod.reset_registries()
        ddos_mod.reset_ddos()
        recovery_mod.reset_recovery()
        reset_spatial_controller()
        reset_global_settings()
        reset_overload()
        reset_federation()
        events.reset_all()

        global_settings.development = True
        global_settings.trace_enabled = False
        global_settings.slo_enabled = False
        global_settings.device_guard_enabled = False
        global_settings.balancer_enabled = False
        global_settings.federation_config = ""
        global_settings.max_failed_auth_attempts = 0
        global_settings.max_fsm_disallowed = 0
        global_settings.channel_settings = {
            ChannelType.GLOBAL: ChannelSettings(
                tick_interval_ms=10, default_fanout_interval_ms=20
            ),
        }
        recorder.configure(enabled=False)

        init_connections(
            os.path.join(REPO, "config", "server_authoritative_fsm.json"),
            os.path.join(REPO, "config", "client_authoritative_fsm.json"),
        )
        init_channels()
        init_anti_ddos()

        self._connection_mod = connection_mod
        self._settings = global_settings
        self.gch = channel_mod.get_global_channel()
        self.now_ns = 0
        self.mono = 0.0

        # GLOBAL owner: a SERVER conn fed through the real protocol path.
        self.master_proto, self.master_sock = self._open(
            ConnectionType.SERVER, ("10.255.255.1", 7777)
        )
        self.master = self.master_proto.conn
        self._feed(self.master_proto, _auth_frame("fuzz-master"))
        # Honest client: authenticates through the wire like any player.
        self.honest_proto, self.honest_sock = self._open(
            ConnectionType.CLIENT, ("10.255.255.2", 7778)
        )
        self.honest = self.honest_proto.conn
        self._feed(self.honest_proto, _auth_frame("fuzz-honest"))
        self._pump_sync()
        self.gch.set_owner(self.master)
        # Honest client subscribes to GLOBAL like a real player; the
        # census then also proves no hostile input can unsubscribe it.
        from ..protocol import control_pb2
        from ..core.types import MessageType

        self._feed(
            self.honest_proto,
            _frame(
                MessageType.SUB_TO_CHANNEL,
                control_pb2.SubscribedToChannelMessage(
                    connId=self.honest.id
                ).SerializeToString(),
            ),
        )
        self._pump_sync()
        assert self.honest in self.gch.subscribed_connections, (
            "harness boot failed: honest client not subscribed to GLOBAL"
        )
        self._honest_written = len(self.honest_sock.written)

    # -- plumbing ----------------------------------------------------------

    def _open(self, conn_type, peer):
        from ..core.server import _TcpServerProtocol

        proto = _TcpServerProtocol(conn_type)
        sock = _FuzzSocket(peer)
        proto.connection_made(sock)
        return proto, sock

    def open_peer(self):
        """A fresh hostile CLIENT socket with a unique synthetic address
        (unique so an IP ban from one case can never mute the next)."""
        from ..core.types import ConnectionType

        self._peer_serial += 1
        n = self._peer_serial
        peer = ("10.%d.%d.%d" % ((n >> 16) & 0xFF, (n >> 8) & 0xFF, n & 0xFF), 40000)
        return self._open(ConnectionType.CLIENT, peer)

    def _feed(self, proto, data: bytes, case: Optional[FuzzCase] = None) -> None:
        """One data_received() call; an escaping exception IS the defect —
        on a live gateway it would reach the event loop."""
        if proto.transport.is_closing():
            return
        try:
            proto.data_received(data)
        except Exception:
            self.violations.append(
                Violation(
                    oracle="event_loop_exception",
                    detail=traceback.format_exc(limit=12),
                    case=case,
                )
            )
            # The socket is poisoned; a real loop would have died. Tear it
            # down so the rest of the run measures fresh state.
            try:
                proto.connection_lost(None)
            except Exception:
                logger.warning("teardown after violation failed", exc_info=True)

    def _pump_sync(self, case: Optional[FuzzCase] = None) -> None:
        """One deterministic gateway cycle: channel tick (drains ingest),
        fair flush pump, edge ladder tick. Escapes here are equally
        gateway-fatal — these run as bare loop tasks in production."""
        from ..core.edge import edge_tick

        self.now_ns += 10_000_000
        self.mono += 0.010
        try:
            self.gch.tick_once(self.now_ns)
            for conn in self._connection_mod.drain_pending_flush():
                conn.flush(fair=True)
                if conn.send_queue:
                    self._connection_mod.requeue_flush(conn)
            edge_tick()
        except Exception:
            self.violations.append(
                Violation(
                    oracle="event_loop_exception",
                    detail=traceback.format_exc(limit=12),
                    case=case,
                )
            )

    async def pump(self, case: Optional[FuzzCase] = None) -> None:
        self._pump_sync(case)
        # Let protocol _drain tasks (spawned under backpressure) run.
        await asyncio.sleep(0)

    # -- oracle ------------------------------------------------------------

    def check_envelopes(self, case: Optional[FuzzCase] = None) -> None:
        gs = self._settings
        for conn in list(self._connection_mod._all_connections.values()):
            q_len = len(conn.send_queue)
            q_bytes = conn.envelope.queue_bytes
            if q_len > gs.edge_send_queue_max_msgs or (
                q_bytes > gs.edge_send_queue_max_bytes
            ):
                self.violations.append(
                    Violation(
                        oracle="envelope",
                        detail="conn %d: %d msgs / %d bytes exceeds envelope"
                        % (conn.id, q_len, q_bytes),
                        case=case,
                    )
                )

    def check_census(self, case: Optional[FuzzCase] = None) -> None:
        from ..core.types import ConnectionState

        problems = []
        if self.master.is_closing():
            problems.append("GLOBAL owner closed")
        if self.honest.is_closing():
            problems.append("honest client closed")
        elif self.honest.state != ConnectionState.AUTHENTICATED:
            problems.append("honest client lost AUTHENTICATED state")
        elif self.honest not in self.gch.subscribed_connections:
            problems.append("honest client unsubscribed from GLOBAL")
        if self.gch.get_owner() is not self.master:
            problems.append("GLOBAL owner reassigned")
        for p in problems:
            self.violations.append(
                Violation(oracle="census", detail=p, case=case)
            )

    async def honest_roundtrip(self, case: Optional[FuzzCase] = None) -> None:
        """The honest client sends a user-space forward; it must reach the
        GLOBAL owner's socket — delivery intact under whatever abuse the
        current window applied."""
        before = len(self.master_sock.written)
        self._feed(self.honest_proto, _frame(100, b"fuzz-roundtrip"), case)
        for _ in range(4):
            await self.pump(case)
        if len(self.master_sock.written) <= before and not self.master.is_closing():
            self.violations.append(
                Violation(
                    oracle="roundtrip",
                    detail="honest user-space forward never reached the "
                    "GLOBAL owner",
                    case=case,
                )
            )

    # -- case driver -------------------------------------------------------

    async def run_case(self, case: FuzzCase) -> int:
        """Apply one hostile session; returns the number of NEW violations."""
        before = len(self.violations)
        proto, sock = self.open_peer()
        if proto.conn is None:  # admission refused (overload) — still legal
            return 0
        if case.auth_first:
            self._feed(proto, _auth_frame("fuzz-%d" % case.seed), case)
            await self.pump(case)
        for op in case.ops:
            if op[0] == "data":
                self._feed(proto, op[1], case)
            elif op[0] == "pump":
                await self.pump(case)
            elif op[0] == "close":
                try:
                    proto.connection_lost(None)
                except Exception:
                    self.violations.append(
                        Violation(
                            oracle="event_loop_exception",
                            detail=traceback.format_exc(limit=12),
                            case=case,
                        )
                    )
                break
        await self.pump(case)
        self.check_envelopes(case)
        self.check_census(case)
        # Hostile peer leaves; teardown must be clean too.
        if not sock.is_closing():
            try:
                proto.connection_lost(None)
            except Exception:
                self.violations.append(
                    Violation(
                        oracle="event_loop_exception",
                        detail=traceback.format_exc(limit=12),
                        case=case,
                    )
                )
        return len(self.violations) - before


# ---------------------------------------------------------------------------
# minimization + corpus
# ---------------------------------------------------------------------------


async def _still_fails(case: FuzzCase) -> bool:
    """Replay ``case`` against a FRESH gateway; True if any oracle trips."""
    h = GatewayHarness()
    h.boot()
    new = await h.run_case(case)
    return new > 0


async def minimize(case: FuzzCase, budget: int = 120) -> FuzzCase:
    """ddmin-lite: drop whole ops, then halve data payloads, keeping every
    step that still reproduces. Bounded by ``budget`` replays — corpus
    entries should be small, not provably minimal."""
    best = case
    runs = 0

    # Pass 1: remove ops one at a time (repeat until fixpoint).
    changed = True
    while changed and runs < budget:
        changed = False
        for i in range(len(best.ops) - 1, -1, -1):
            if len(best.ops) == 1:
                break
            trial = FuzzCase(
                kind=best.kind,
                seed=best.seed,
                ops=best.ops[:i] + best.ops[i + 1 :],
                auth_first=best.auth_first,
            )
            runs += 1
            if await _still_fails(trial):
                best = trial
                changed = True
            if runs >= budget:
                break

    # Pass 2: shrink each data op by halving from either end.
    for i, op in enumerate(best.ops):
        if op[0] != "data" or runs >= budget:
            continue
        data = op[1]
        step = len(data) // 2
        while step > 0 and runs >= 0 and runs < budget:
            shrunk = False
            for trial_data in (data[step:], data[:-step]):
                if not trial_data:
                    continue
                ops = list(best.ops)
                ops[i] = ("data", trial_data)
                trial = FuzzCase(
                    kind=best.kind, seed=best.seed, ops=ops,
                    auth_first=best.auth_first,
                )
                runs += 1
                if await _still_fails(trial):
                    best = trial
                    data = trial_data
                    shrunk = True
                    break
                if runs >= budget:
                    break
            if not shrunk:
                step //= 2
    return best


def save_case(case: FuzzCase, violation: Violation, corpus_dir: str = CORPUS_DIR) -> str:
    os.makedirs(corpus_dir, exist_ok=True)
    name = "%s_%s_%08x.json" % (violation.oracle, case.kind, case.seed)
    path = os.path.join(corpus_dir, name)
    obj = case.to_json()
    obj["oracle"] = violation.oracle
    obj["detail"] = violation.detail.strip().splitlines()[-1][:200]
    with open(path, "w") as f:  # tpulint: disable=async-blocking -- corpus files are tiny JSON and the fuzz harness owns its private loop; no gateway traffic rides it
        json.dump(obj, f, indent=1)
    return path


def load_corpus(corpus_dir: str = CORPUS_DIR) -> list:
    """(filename, FuzzCase) pairs, sorted for deterministic replay order."""
    if not os.path.isdir(corpus_dir):
        return []
    out = []
    for name in sorted(os.listdir(corpus_dir)):
        if not name.endswith(".json"):
            continue
        with open(os.path.join(corpus_dir, name)) as f:  # tpulint: disable=async-blocking -- tiny JSON reads on the harness's private loop
            out.append((name, FuzzCase.from_json(json.load(f))))
    return out


def write_pinned_corpus(corpus_dir: str = CORPUS_DIR) -> list:
    """Write one canonical case per hostile family from fixed seeds.

    The committed corpus has two kinds of entry: *minimized defects* (from
    run_fuzz finding a real violation — the file records the oracle it
    tripped) and these *pinned sentinels* — inputs the gateway currently
    survives and must keep surviving. Both replay identically in tier-1:
    zero violations or the build is red. Regenerate with
    ``python -c "from channeld_tpu.chaos.fuzz import write_pinned_corpus;
    write_pinned_corpus()"`` after adding a family."""
    os.makedirs(corpus_dir, exist_ok=True)
    paths = []
    for kind in sorted(GENERATORS):
        # A fixed per-family seed keeps files byte-stable across runs.
        seed = int.from_bytes(kind.encode()[:4].ljust(4, b"\0"), "big")
        rng = Random(seed)
        case = FuzzCase(
            kind=kind,
            seed=seed,
            ops=GENERATORS[kind](rng),
            auth_first=kind in _AUTH_ALWAYS or kind in _AUTH_ELIGIBLE,
        )
        obj = case.to_json()
        obj["oracle"] = "pinned"
        obj["detail"] = "sentinel: the gateway survives this family today"
        path = os.path.join(corpus_dir, "pinned_%s.json" % kind)
        with open(path, "w") as f:  # tpulint: disable=async-blocking -- tiny JSON writes on the harness's private loop
            json.dump(obj, f, indent=1)
        paths.append(path)
    return paths


async def replay_corpus(corpus_dir: str = CORPUS_DIR) -> dict:
    """Replay every committed corpus case against a fresh gateway each —
    the tier-1 regression gate. Returns {file: n_violations}; all zeros
    means every past defect is still fixed."""
    results = {}
    for name, case in load_corpus(corpus_dir):
        h = GatewayHarness()
        h.boot()
        results[name] = await h.run_case(case)
    return results


# ---------------------------------------------------------------------------
# the main fuzz loop
# ---------------------------------------------------------------------------


async def run_fuzz(
    iterations: int,
    seed: int = 0,
    corpus_dir: Optional[str] = None,
    do_minimize: bool = True,
    roundtrip_every: int = 512,
    progress: Optional[Callable[[int, int], None]] = None,
) -> dict:
    """Drive ``iterations`` seeded hostile sessions against one live
    gateway; returns a JSON-able report. The gateway is rebooted after any
    violation (its state is suspect) and otherwise lives across the whole
    run — leaks and cross-connection corruption only show up that way."""
    h = GatewayHarness()
    h.boot()
    report = {
        "iterations": iterations,
        "seed": seed,
        "kinds": {},
        "violations": [],
        "corpus_files": [],
    }
    for i in range(iterations):
        case = make_case(seed, i)
        report["kinds"][case.kind] = report["kinds"].get(case.kind, 0) + 1
        new = await h.run_case(case)
        if i % roundtrip_every == roundtrip_every - 1 and not new:
            await h.honest_roundtrip(case)
            new = len([v for v in h.violations if v.case is case])
        if new:
            fresh = h.violations[-1]
            min_case = case
            if do_minimize and await _still_fails(case):
                min_case = await minimize(case)
            report["violations"].append(
                {
                    "iteration": i,
                    "oracle": fresh.oracle,
                    "kind": case.kind,
                    "seed": case.seed,
                    "detail": fresh.detail.strip().splitlines()[-1][:300],
                    "ops": len(min_case.ops),
                }
            )
            if corpus_dir is not None:
                report["corpus_files"].append(
                    save_case(min_case, fresh, corpus_dir)
                )
            h = GatewayHarness()  # suspect state: start clean
            h.boot()
        if progress is not None and (i + 1) % 1000 == 0:
            progress(i + 1, len(report["violations"]))
    report["total_violations"] = len(report["violations"])
    return report

"""On-device world simulation (doc/simulation.md).

A server-driven NPC population stepped on the accelerator INSIDE the
same guarded spatial tick: agents occupy ordinary entity slots in the
engine's arrays, so crossings, handover, adaptive partitioning,
standing queries and device fan-out see them exactly like human-driven
entities — with zero additional device<->host transfers per tick (the
sim pass is device->device; the only readback is the census-cadence
batched fetch that rides the guarded step's existing prefetch window).

Authority flows through an internal server connection
(:mod:`.authority`): the sim plane registers as an ordinary spatial
server peer and commits census batches through the ordinary channel
path, never by poking channel state directly.
"""

from .plane import SimPlane, reset_sim, restore_census  # noqa: F401

__all__ = ["SimPlane", "reset_sim", "restore_census"]

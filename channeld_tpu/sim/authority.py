"""Sim authority: the population's internal server peer (doc/simulation.md).

Agents are OWNED like any server-spawned entity: the plane registers one
internal SERVER connection (a real :class:`~channeld_tpu.core.connection.
Connection` over a null transport — no socket, no reactor) and gives up
to ``sim_channel_agents`` agents real entity channels owned by it, added
to their cell channel's entity table through the ordinary Execute path.
Census commits then flow through ``ChannelData.on_update`` exactly like
a remote server's movement updates — the handover trigger, fan-out and
placement ledger all see agents through the same seam as humans.

Agents beyond the cap are engine-only: device-tracked entities with no
channel data anywhere, so their crossings need no orchestration (the
controller skips them). That mode exists for engine-direct benches at
100K+ agents; a live channel world should keep ``sim_agents`` at or
under ``sim_channel_agents``.

Threading (doc/concurrency.md): all methods run on the GLOBAL tick loop.
"""

from __future__ import annotations

from typing import Optional

from ..core.settings import global_settings
from ..utils.logger import get_logger

logger = get_logger("sim.authority")


class _NullTransport:
    """Byte sink for the internal connection: frames fanned out TO the
    authority (its own subscriptions echo back) are counted and
    dropped — there is no remote process to deliver them to."""

    def __init__(self):
        self.bytes_dropped = 0

    def write(self, data: bytes) -> None:
        self.bytes_dropped += len(data)

    def close(self) -> None:
        pass

    def remote_addr(self):
        return None  # in-process: no addr, no ban check, no accounting


class SimAuthority:
    """Owns the agents' entity channels via an internal server conn."""

    def __init__(self, controller):
        self.controller = controller
        self.conn = None
        self.transport: Optional[_NullTransport] = None
        self._backed: set[int] = set()    # agents with live entity channels
        self._pending: list[tuple[int, float, float]] = []  # awaiting attach
        self.ledgers: dict[str, int] = {}

    # ---- internal connection --------------------------------------------

    def ensure_connection(self):
        """The internal peer, created on first use: a real SERVER-type
        connection authenticated immediately (the unauthenticated reaper
        must never harvest it) with no socket behind it."""
        if self.conn is not None and not self.conn.is_closing():
            return self.conn
        from ..core.connection import add_connection
        from ..core.types import ConnectionType

        self.transport = _NullTransport()
        conn = add_connection(self.transport, ConnectionType.SERVER)
        conn.on_authenticated("sim-authority")
        self.conn = conn
        self._count("connections", 1)
        logger.info("sim authority connected as server conn %d", conn.id)
        return conn

    # ---- population attach ----------------------------------------------

    def adopt(self, ids) -> None:
        """Queue agents for channel attachment (bounded per tick by
        ``sim_attach_per_tick``; retried while the world boots). Agents
        past the ``sim_channel_agents`` cap stay engine-only."""
        from ..core.channel import get_channel

        ctl = self.controller
        cap = int(global_settings.sim_channel_agents)
        for eid in ids:
            eid = int(eid)
            if get_channel(eid) is not None:
                # WAL/snapshot restore already rebuilt the channel.
                self._backed.add(eid)
                continue
            if len(self._backed) + len(self._pending) >= cap:
                self._count("engine_only", 1)
                continue
            info = ctl._last_positions.get(eid)
            if info is None:
                continue
            self._pending.append((eid, float(info.x), float(info.z)))

    def pump(self) -> None:
        """One bounded attach pass (called from the plane's pre_step):
        attach pending agents whose cell channel exists; cells still
        booting go back on the queue."""
        if not self._pending:
            return
        budget = max(1, int(global_settings.sim_attach_per_tick))
        retry: list[tuple[int, float, float]] = []
        taken = self._pending[:budget]
        rest = self._pending[budget:]
        for eid, x, z in taken:
            done = self._attach(eid, x, z)
            if done is None:
                retry.append((eid, x, z))
        self._pending = retry + rest

    def _attach(self, eid: int, x: float, z: float) -> Optional[bool]:
        """Create the agent's entity channel + cell-table row through the
        ordinary channel path. True = attached, False = dropped (outside
        the world), None = retry later (cell channel not up yet)."""
        from ..core.channel import create_entity_channel, get_channel
        from ..core.subscription import subscribe_to_channel
        from ..models import sim_pb2
        from ..spatial.controller import SpatialInfo

        ctl = self.controller
        try:
            cell_id = ctl.get_channel_id(SpatialInfo(x, 0.0, z))
        except ValueError:
            self._count("attach_dropped", 1)
            return False
        cell_ch = get_channel(cell_id)
        if cell_ch is None or cell_ch.is_removing():
            return None
        if get_channel(eid) is not None:
            self._backed.add(eid)
            return True
        conn = self.ensure_connection()
        try:
            ch = create_entity_channel(eid, conn)
        except Exception as e:  # ChannelFullError / id races: engine-only
            logger.warning("sim agent %d channel attach failed: %s", eid, e)
            self._count("attach_dropped", 1)
            return False
        d = sim_pb2.SimEntityChannelData()
        d.state.entityId = eid
        d.state.transform.position.x = x
        d.state.transform.position.z = z
        ch.init_data(d, None)
        ch.spatial_notifier = ctl
        subscribe_to_channel(conn, ch, None)
        cell_ch.execute(
            lambda c, e=eid, dd=d: c.get_data_message().add_entity(e, dd)
        )
        self._backed.add(eid)
        self._count("attached", 1)
        return True

    # ---- census commit ---------------------------------------------------

    def is_backed(self, eid: int) -> bool:
        return eid in self._backed

    def pending_count(self) -> int:
        return len(self._pending)

    def commit(self, ids, positions) -> int:
        """Commit one census batch through the ordinary channel path:
        each channel-backed agent's entity channel merges a position
        update via ``on_update`` — the same seam a remote server's
        movement updates flow through, so handover triggers, fan-out and
        the placement ledger behave identically for agents and humans.
        ``positions`` is a host list of [x, y, z] rows (the plane
        converts the census before calling). Returns the number of
        updates committed."""
        from ..core.channel import get_channel
        from ..models import sim_pb2

        if not self._backed:
            return 0
        ctl = self.controller
        n = 0
        for i, eid in enumerate(ids):
            eid = int(eid)
            if eid not in self._backed:
                continue
            ch = get_channel(eid)
            if ch is None or ch.is_removing():
                self._backed.discard(eid)
                continue
            upd = sim_pb2.SimEntityChannelData()
            upd.state.entityId = eid
            upd.state.transform.position.x = positions[i][0]
            upd.state.transform.position.z = positions[i][2]

            def _apply(c, u=upd):
                owner = c.get_owner()
                c.data.on_update(
                    u, c.get_time(),
                    owner.id if owner is not None else 0, ctl,
                )

            ch.execute(_apply)
            n += 1
        self._count("commits", 1)
        self._count("updates", n)
        return n

    # ---- accounting ------------------------------------------------------

    def _count(self, key: str, n: int) -> None:
        self.ledgers[key] = self.ledgers.get(key, 0) + n

    def report(self) -> dict:
        return {
            "ledgers": dict(self.ledgers),
            "channel_backed": len(self._backed),
            "pending_attach": len(self._pending),
            "bytes_dropped": (
                self.transport.bytes_dropped if self.transport else 0
            ),
        }

"""Simulation plane orchestration (doc/simulation.md).

One :class:`SimPlane` per spatial controller. The plane owns the HOST
side of the simulated population: spawn/restore at activation, per-tick
cadence decisions (including the overload ladder's L2 cadence halving),
chaos injection, the census-cadence absorb/journal/commit pass, and the
danger-zone sensor that drives the FLEE behavior from the standing-query
plane. The DEVICE side — steering, behavior FSM, integration — lives in
:func:`channeld_tpu.ops.spatial_ops.sim_step` and runs inside the
engine's guarded tick; the plane never reads device arrays outside the
census cadence.

Threading (doc/concurrency.md): every method except the module-level
WAL-replay rendezvous runs on the GLOBAL tick loop, the same domain as
the controller that calls it.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from ..chaos.injector import chaos as _chaos
from ..core import metrics
from ..core.overload import governor as _governor
from ..core.settings import global_settings
from ..core.wal import wal as _wal
from ..ops.spatial_ops import SimParams
from ..spatial.controller import SpatialInfo
from ..utils.logger import get_logger
from .authority import SimAuthority

logger = get_logger("sim.plane")

# Agent entity ids live far above the interactive entity range so a
# spawned population can never collide with client-created entities
# (ids are uint32 channel ids; 4M of headroom each way).
AGENT_ID_OFFSET = 1 << 22

# WAL-replay rendezvous: boot replay runs BEFORE the spatial controller
# loads, so a replayed census is staged here and consumed by
# ``SimPlane.activate()``. Written by the boot thread before the tick
# loop exists, read once at controller load — never concurrent.
_pending_census: Optional[dict] = None


def restore_census(rec, source: str = "wal replay") -> int:
    """Stage a journaled census (a ``sim_census`` WalRecord) for the
    plane to consume at activation. Returns the agent count staged (0 =
    empty record, nothing staged). Last record wins — replay calls this
    once with the final census."""
    global _pending_census
    n = len(rec.simAgentIds)
    if n == 0:
        return 0
    _pending_census = {
        "tick": int(rec.simTick),
        "seed": int(rec.simSeed),
        "ids": np.asarray(rec.simAgentIds, np.uint32),
        "pos": np.asarray(rec.simAgentPos, np.float32).reshape(n, 3),
        "vel": np.asarray(rec.simAgentVel, np.float32).reshape(n, 3),
        "state": np.asarray(rec.simAgentState, np.int32),
        "target": np.asarray(rec.simAgentTarget, np.float32).reshape(n, 3),
        "source": source,
    }
    logger.info(
        "sim census staged from %s: %d agents at sim tick %d",
        source, n, _pending_census["tick"],
    )
    return n


def consume_pending_census() -> Optional[dict]:
    global _pending_census
    c = _pending_census
    _pending_census = None
    return c


def reset_sim() -> None:
    """Test isolation hook (tests/conftest.py): drop any staged census."""
    global _pending_census
    _pending_census = None


def _params_from_settings() -> SimParams:
    s = global_settings
    return SimParams(
        dt=float(s.sim_step_dt),
        max_speed=float(s.sim_max_speed),
        accel=float(s.sim_accel),
        separation=float(s.sim_separation),
        cohesion=float(s.sim_cohesion),
        arrive_radius=float(s.sim_arrive_radius),
        crowd=int(s.sim_crowd),
        p_wander=float(s.sim_p_wander),
        p_seek=float(s.sim_p_seek),
        p_idle=float(s.sim_p_idle),
    )


class SimPlane:
    """Host orchestration for the on-device agent population."""

    def __init__(self, controller, engine):
        self.controller = controller
        self.engine = engine
        self.authority = SimAuthority(controller)
        self._tick = 0            # controller ticks seen (cadence base)
        self._since_census = 0    # scheduled sim passes since last census
        self._sim_skip = False    # L2+ cadence-halving flip-flop
        self._last_sim_tick = 0   # for the committed-pass counter
        self._danger_key: Optional[int] = None
        # Double-entry ledgers (scripts/sim_soak.py asserts these match
        # the prometheus side exactly).
        self.ledgers: dict[str, int] = {}

    # ---- lifecycle -------------------------------------------------------

    def activate(self) -> None:
        """Spawn the population (or restore a WAL-replayed census) and
        pre-compile the sim kernel. Called once from the controller's
        ``load_config``, after the engine exists, before listeners open."""
        eng = self.engine
        params = _params_from_settings()
        pending = consume_pending_census()
        if pending is not None:
            entries = [
                (int(eid), float(p[0]), float(p[1]), float(p[2]))
                for eid, p in zip(pending["ids"], pending["pos"])
            ]
            eng.seed_agents(
                entries, pending["seed"], params,
                vels=pending["vel"], states=pending["state"],
                targets=pending["target"],
            )
            eng.sim_tick = pending["tick"]
            self._last_sim_tick = pending["tick"]
            self._count("agents_restored", len(entries))
            logger.info(
                "sim population restored from %s: %d agents, resuming at "
                "sim tick %d (seed %d)", pending["source"], len(entries),
                pending["tick"], pending["seed"],
            )
        else:
            entries = self._fresh_entries()
            eng.seed_agents(entries, global_settings.sim_seed, params)
            self._count("agents_spawned", len(entries))
            logger.info(
                "sim population spawned: %d agents (seed %d)",
                len(entries), global_settings.sim_seed,
            )
        # Controller bookkeeping: placement ledger + last-position rows
        # so rebuild seeding and partition-split sorting see agents like
        # any tracked entity. (track_entity's add_entity is an upsert
        # onto the slot seed_agents already claimed.)
        for eid, x, y, z in entries:
            self.controller.track_entity(eid, SpatialInfo(x, y, z))
        self.authority.adopt(eid for eid, *_ in entries)
        eng.sim_warmup()  # compile OUTSIDE the guarded window (watchdog)
        metrics.sim_agents_num.set(eng.agent_count())

    def _fresh_entries(self) -> list[tuple[int, float, float, float]]:
        """Seeded-uniform spawn positions over the world interior. Host
        numpy RNG, distinct from the device's counter-based stream —
        spawn layout replays from sim_seed alone."""
        ctl = self.controller
        rng = np.random.default_rng(global_settings.sim_seed)
        n = int(global_settings.sim_agents)
        x0 = ctl.world_offset_x + 1.0
        z0 = ctl.world_offset_z + 1.0
        x1 = ctl.world_offset_x + ctl.grid_width * ctl.grid_cols - 1.0
        z1 = ctl.world_offset_z + ctl.grid_height * ctl.grid_rows - 1.0
        xs = rng.uniform(x0, x1, n)
        zs = rng.uniform(z0, z1, n)
        base = global_settings.entity_channel_id_start + AGENT_ID_OFFSET
        return [
            (base + i, float(xs[i]), 0.0, float(zs[i])) for i in range(n)
        ]

    # ---- per-tick hooks (GLOBAL tick loop) -------------------------------

    def pre_step(self) -> None:
        """Cadence + chaos decisions for the tick about to run. Sets the
        engine's ``run_sim_pass`` / ``sim_census_due`` flags; the device
        work itself happens inside the guarded step."""
        eng = self.engine
        if not eng.sim_enabled:
            return
        if _chaos.armed:
            if _chaos.fire("sim.step_nan"):
                eng.corrupt_sim_state_for_chaos()
                self._count("chaos_nan", 1)
            if _chaos.fire("sim.stampede"):
                g = eng.grid
                cell = (g.rows // 2) * g.cols + g.cols // 2
                eng.sim_stampede(cell)
                self._count("chaos_stampede", 1)
        self.authority.pump()
        self._tick += 1
        run = self._tick % max(1, global_settings.sim_step_every_ticks) == 0
        if run and _governor.level >= 2:
            # L2+: the population holds still every other scheduled pass
            # — sim cadence halves BEFORE human traffic degrades
            # (doc/overload.md ladder; same alternating-flag shape as
            # the query plane's apply deferral).
            if not self._sim_skip:
                self._sim_skip = True
                n = eng.agent_count()
                if n:
                    # An empty population sheds nothing — a zero count
                    # would still create the ledger key and break the
                    # soaks' exact shed accounting.
                    _governor.count_shed("sim_cadence_defer", n)
                run = False
            else:
                self._sim_skip = False
        elif _governor.level < 2:
            self._sim_skip = False
        if run:
            self._since_census += 1
        eng.run_sim_pass = run
        eng.sim_census_due = (
            run and self._since_census
            >= max(1, global_settings.sim_census_every_ticks)
        )

    def on_result(self, result: dict) -> None:
        """Post-step absorb: count committed passes; on a census tick,
        fold the fetched kinematic columns into the host shadow, journal
        them, and commit through the authority's channel path. The
        census arrays arrive as numpy under the device guard (prefetched
        inside the supervised window) or as device arrays from a bare
        ``engine.tick()``."""
        eng = self.engine
        if not eng.sim_enabled:
            return
        advanced = eng.sim_tick - self._last_sim_tick
        if advanced > 0:
            metrics.sim_ticks.inc(advanced)
            self._count("sim_passes", advanced)
        self._last_sim_tick = eng.sim_tick
        census = result.get("sim_census")
        if census is None:
            return
        t0 = time.monotonic()
        pos, vel, state, target = (
            np.asarray(a)  # tpulint: disable=hot-readback -- census-cadence batched fetch (the sim plane's ONLY readback, doc/simulation.md); a no-op under the guard, which already prefetched numpy inside the supervised window
            for a in census
        )
        slots = eng.agent_slots()
        eng.absorb_census(slots, pos, vel, state, target)
        ids = eng.agent_ids(slots)
        self._since_census = 0
        metrics.sim_census_transfers.inc()
        self._count("census_transfers", 1)
        sim_tick = int(result.get("sim_tick", eng.sim_tick))
        if _wal.enabled:
            _wal.log_sim_census(
                sim_tick, eng.sim_seed, ids, pos[slots], vel[slots],
                state[slots], target[slots],
            )
            self._count("censuses_journaled", 1)
        # Refresh last-known positions for EVERY agent (engine-only
        # agents have no channel path to do it); the authority commit
        # below re-walks channel-backed ones through the ordinary
        # update path, which keeps the same rows authoritative. The
        # arrays are host numpy at this point — tolist() shapes, it
        # does not transfer.
        ctl = self.controller
        agent_pos = pos[slots].tolist()
        for i, eid in enumerate(ids):
            px, py, pz = agent_pos[i]
            ctl._last_positions[int(eid)] = SpatialInfo(px, py, pz)
        committed = self.authority.commit(ids, agent_pos)
        self._count("census_commits", committed)
        metrics.sim_agents_num.set(eng.agent_count())
        metrics.sim_pass_ms.observe((time.monotonic() - t0) * 1000.0)

    # ---- federation ride-along (federation/plane.py) ---------------------

    def on_agents_adopted(self, ids) -> int:
        """Agents adopted from a peer shard rejoin THIS gateway's
        population: ids in the reserved agent range are re-flagged as
        agents on their already-tracked slots. Kinematics are not
        shipped in the handover payload — adopted agents restart IDLE
        at their adopted position and the local counter-based stream
        takes over (doc/simulation.md)."""
        eng = self.engine
        if not eng.sim_enabled or eng.sim_params is None:
            return 0
        base = global_settings.entity_channel_id_start + AGENT_ID_OFFSET
        entries = []
        for eid in ids:
            eid = int(eid)
            if eid < base or eng.is_agent(eid):
                continue
            info = self.controller._last_positions.get(eid)
            if info is None:
                continue
            entries.append((eid, float(info.x), float(info.y),
                            float(info.z)))
        if not entries:
            return 0
        eng.seed_agents(entries, eng.sim_seed, eng.sim_params)
        for eid, *_ in entries:
            self.authority._backed.add(eid)
        self._count("agents_adopted", len(entries))
        metrics.sim_agents_num.set(eng.agent_count())
        return len(entries)

    def on_agents_departed(self, ids) -> int:
        """Agents committed to a peer shard leave the population (the
        channel teardown untracks them; the agent flag clears with the
        slot) — this hook only keeps the double-entry census ledgers
        and the population gauge exact."""
        eng = self.engine
        n = sum(1 for eid in ids if eng.is_agent(int(eid)))
        if n:
            self._count("agents_departed", n)
            for eid in ids:
                self.authority._backed.discard(int(eid))
        metrics.sim_agents_num.set(max(0, eng.agent_count() - n))
        return n

    # ---- danger zone: FLEE driven by the standing-query plane ------------

    def set_danger_zone(self, center, radius: float) -> Optional[int]:
        """Register a standing danger sensor; agents FLEE any cell the
        sensor's interest set covers. Returns the sensor key, or None
        when the query plane is off/full (no danger = no fleeing)."""
        if self._danger_key is not None:
            self.clear_danger_zone()
        key = self.controller.register_sensor(
            "sim.danger", center=tuple(center),
            extent=(float(radius), float(radius)),
            callback=self._on_danger_cells,
        )
        self._danger_key = key
        if key is not None:
            self._count("danger_zones", 1)
        return key

    def clear_danger_zone(self) -> None:
        qp = self.controller.queryplane
        if self._danger_key is not None and qp is not None:
            qp.deregister(self._danger_key)
        self._danger_key = None
        self.engine.set_flee_cells(())

    def _on_danger_cells(self, key: int, cells: dict) -> None:
        """Sensor callback ({leaf_channel: dist}): rasterize the hit
        leaves to micro cells and install the FLEE mask."""
        self.engine.set_flee_cells(self._micro_cells(cells))

    def _micro_cells(self, cells: dict) -> list[int]:
        ctl = self.controller
        hit = set(cells)
        if ctl._micro_leaf is None:
            start = global_settings.spatial_channel_id_start
            return [ch - start for ch in hit]
        return [m for m, leaf in enumerate(ctl._micro_leaf) if leaf in hit]

    def on_geometry(self) -> None:
        """A geometry epoch committed: the leaf->micro mapping changed
        (even at unchanged micro dims), so the FLEE mask must be
        re-rasterized from the sensor's current interest set."""
        if self._danger_key is None:
            return
        qp = self.controller.queryplane
        cells = qp.sensor_cells(self._danger_key) if qp is not None else {}
        self.engine.set_flee_cells(self._micro_cells(cells))

    # ---- accounting ------------------------------------------------------

    def _count(self, key: str, n: int) -> None:
        self.ledgers[key] = self.ledgers.get(key, 0) + n

    def report(self) -> dict:
        """Soak/bench artifact block (double-entry vs prometheus)."""
        return {
            "ledgers": dict(self.ledgers),
            "agents": self.engine.agent_count(),
            "sim_tick": self.engine.sim_tick,
            "rebuilds": dict(self.engine.sim_rebuild_counts),
            "authority": self.authority.report(),
        }

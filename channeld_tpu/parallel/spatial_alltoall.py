"""Cell-sharded spatial decision plane: space partitioned over devices.

`parallel/mesh.py` shards the ENTITY axis (every device sees every cell);
this module shards SPACE itself — each device owns a contiguous block of
grid rows, exactly like the reference gives each spatial server an
authority block of cells with a subscribed interest border
(ref: spatial.go:89-124, :481-590). It is the 2D-world instance of the
two standard long-context parallelism patterns:

- **all-to-all redistribution** (the Ulysses/sequence-alltoall shape):
  entities land on whichever shard ingested them; each tick computes
  their cell, packs them into fixed-capacity per-destination buckets,
  and one `all_to_all` over ICI delivers every entity (id + position)
  to the shard that OWNS its cell block. Bucket overflow is never
  silent: the per-entity ``undelivered`` mask identifies exactly which
  ingest-shard slots did not fit, so the caller keeps them queued and
  re-offers them next tick (the same explicit-overflow contract as
  handover compaction).
- **ring halo exchange** (the ring-attention shape): per-cell occupancy
  of the first/last owned grid rows is exchanged with ring neighbors via
  `ppermute`, giving each shard its interest border — the data the
  reference's border subscriptions carry between adjacent servers —
  without any global collective.

Everything is shape-static and jit/shard_map-compatible; tests pin the
sharded results against the dense single-device computation on the
virtual 8-device CPU mesh.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.spatial_ops import GridSpec, assign_cells

AXIS = "space"


def make_space_mesh(devices=None) -> Mesh:
    from .mesh import make_mesh

    return make_mesh(devices, axis_name=AXIS)


def rows_per_shard(grid: GridSpec, n_shards: int) -> int:
    if grid.rows % n_shards != 0:
        raise ValueError(
            f"grid rows {grid.rows} must divide evenly over {n_shards} shards"
        )
    return grid.rows // n_shards


def build_cell_sharded_step(grid: GridSpec, mesh: Mesh, bucket: int):
    """Compile the cell-sharded tick.

    Inputs (sharded over AXIS): positions f32[N,3], valid bool[N],
    entity_ids i32[N] — N is the per-ingest-shard capacity x n_shards.

    Returns per-shard (all sharded over AXIS, leading dim = n_shards):
      owned_ids   i32[S, bucket*S]  entity ids now resident on their
                                    owner shard (-1 = empty slot)
      owned_cells i32[S, bucket*S]  the owned entities' global cell ids
      owned_xyz   f32[S, bucket*S, 3]  their positions
      counts      i32[S, rows_blk*cols]   occupancy of the OWNED block
      halo_lo     i32[S, cols]  occupancy of the previous shard's LAST
                                owned row (the south interest border)
      halo_hi     i32[S, cols]  occupancy of the next shard's FIRST
                                owned row (the north interest border)
      undelivered bool[S, n_local]  ingest-shard entity slots whose
                                destination bucket was full this tick;
                                the caller re-offers exactly these
      overflow    i32[S]        sum of undelivered (diagnostic)
    """
    n_shards = mesh.devices.size
    rows_blk = rows_per_shard(grid, n_shards)
    cells_blk = rows_blk * grid.cols

    def shard_fn(positions, valid, entity_ids):
        me = jax.lax.axis_index(AXIS)
        cell_of = assign_cells(grid, positions, valid)  # global cell ids
        row = cell_of // grid.cols
        dest = jnp.where(cell_of >= 0, row // rows_blk, -1)  # owner shard

        # Pack per-destination buckets (fixed shape [n_shards, bucket]).
        # rank within (dest == d) via cumulative counts, like handover
        # compaction; entities beyond a bucket overflow (reported).
        slot_ids = jnp.full((n_shards, bucket), -1, jnp.int32)
        slot_cells = jnp.full((n_shards, bucket), -1, jnp.int32)
        slot_xyz = jnp.zeros((n_shards, bucket, 3), jnp.float32)
        delivered = jnp.zeros_like(dest, dtype=bool)
        for d in range(n_shards):  # static, small (n_shards <= 16)
            mask = dest == d
            rank = jnp.cumsum(mask, dtype=jnp.int32) - 1
            fits = mask & (rank < bucket)
            delivered = delivered | fits
            (idx,) = jnp.nonzero(mask, size=bucket, fill_value=0)
            idx = idx.astype(jnp.int32)
            row_valid = jnp.arange(bucket) < jnp.sum(fits, dtype=jnp.int32)
            slot_ids = slot_ids.at[d].set(
                jnp.where(row_valid, entity_ids[idx], -1))
            slot_cells = slot_cells.at[d].set(
                jnp.where(row_valid, cell_of[idx], -1))
            slot_xyz = slot_xyz.at[d].set(
                jnp.where(row_valid[:, None], positions[idx], 0.0))
        undelivered = (dest >= 0) & ~delivered
        overflow = jnp.sum(undelivered, dtype=jnp.int32)

        # The Ulysses move: [n_shards, bucket] -> every shard receives its
        # own-destination bucket from every source.
        recv_ids = jax.lax.all_to_all(slot_ids, AXIS, 0, 0, tiled=False)
        recv_cells = jax.lax.all_to_all(slot_cells, AXIS, 0, 0, tiled=False)
        recv_xyz = jax.lax.all_to_all(slot_xyz, AXIS, 0, 0, tiled=False)
        owned_ids = recv_ids.reshape(-1)  # [n_shards * bucket]
        owned_cells = recv_cells.reshape(-1)
        owned_xyz = recv_xyz.reshape(-1, 3)

        # Owned-block occupancy: local cell index = global - block start.
        block_start = me * cells_blk
        local = jnp.where(owned_cells >= 0, owned_cells - block_start, 0)
        present = owned_cells >= 0
        counts = jnp.zeros(cells_blk, jnp.int32).at[local].add(
            present.astype(jnp.int32))

        # Ring halo: the interest border. ppermute moves each shard's last
        # owned row north (to me+1) and first owned row south (to me-1) —
        # one neighbor hop over ICI, never a global collective.
        last_row = counts[-grid.cols:]
        first_row = counts[: grid.cols]
        halo_lo = jax.lax.ppermute(  # from me-1's last row
            last_row, AXIS,
            [(i, (i + 1) % n_shards) for i in range(n_shards)])
        halo_hi = jax.lax.ppermute(  # from me+1's first row
            first_row, AXIS,
            [(i, (i - 1) % n_shards) for i in range(n_shards)])
        # World edges have no neighbor: zero the wrapped halos.
        halo_lo = jnp.where(me == 0, jnp.zeros_like(halo_lo), halo_lo)
        halo_hi = jnp.where(me == n_shards - 1, jnp.zeros_like(halo_hi),
                            halo_hi)
        return (owned_ids[None], owned_cells[None], owned_xyz[None],
                counts[None], halo_lo[None], halo_hi[None],
                undelivered[None], overflow[None])

    sharded = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(AXIS), P(AXIS), P(AXIS)),
        out_specs=(P(AXIS),) * 8,
        check_vma=False,
    )
    return jax.jit(sharded)

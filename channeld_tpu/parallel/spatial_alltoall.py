"""Cell-sharded spatial decision plane: space partitioned over devices.

`parallel/mesh.py` shards the ENTITY axis (every device sees every cell);
this module shards SPACE itself — each device owns a contiguous block of
grid rows, exactly like the reference gives each spatial server an
authority block of cells with a subscribed interest border
(ref: spatial.go:89-124, :481-590). It is the 2D-world instance of the
two standard long-context parallelism patterns:

- **all-to-all redistribution** (the Ulysses/sequence-alltoall shape):
  entities land on whichever shard ingested them; each tick computes
  their cell, packs them into fixed-capacity per-destination buckets,
  and one `all_to_all` over ICI delivers every entity (id + position)
  to the shard that OWNS its cell block. Bucket overflow is never
  silent: the per-entity ``undelivered`` mask identifies exactly which
  ingest-shard slots did not fit, so the caller keeps them queued and
  re-offers them next tick (the same explicit-overflow contract as
  handover compaction).
- **ring halo exchange** (the ring-attention shape): per-cell occupancy
  of the first/last owned grid rows is exchanged with ring neighbors via
  `ppermute`, giving each shard its interest border — the data the
  reference's border subscriptions carry between adjacent servers —
  without any global collective.

Everything is shape-static and jit/shard_map-compatible; tests pin the
sharded results against the dense single-device computation on the
virtual 8-device CPU mesh.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from ._jax_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.spatial_ops import (
    GridSpec,
    QuerySet,
    aoi_masks_for_cells,
    assign_cells,
    compact_handovers,
    detect_handovers,
    fanout_due,
)

AXIS = "space"


def make_space_mesh(devices=None) -> Mesh:
    from .mesh import make_mesh

    return make_mesh(devices, axis_name=AXIS)


def rows_per_shard(grid: GridSpec, n_shards: int) -> int:
    if grid.rows % n_shards != 0:
        raise ValueError(
            f"grid rows {grid.rows} must divide evenly over {n_shards} shards"
        )
    return grid.rows // n_shards


def build_cell_sharded_step(grid: GridSpec, mesh: Mesh, bucket: int):
    """Compile the cell-sharded tick.

    Inputs (sharded over AXIS): positions f32[N,3], valid bool[N],
    entity_ids i32[N] — N is the per-ingest-shard capacity x n_shards.

    Returns per-shard (all sharded over AXIS, leading dim = n_shards):
      owned_ids   i32[S, bucket*S]  entity ids now resident on their
                                    owner shard (-1 = empty slot)
      owned_cells i32[S, bucket*S]  the owned entities' global cell ids
      owned_xyz   f32[S, bucket*S, 3]  their positions
      counts      i32[S, rows_blk*cols]   occupancy of the OWNED block
      halo_lo     i32[S, cols]  occupancy of the previous shard's LAST
                                owned row (the south interest border)
      halo_hi     i32[S, cols]  occupancy of the next shard's FIRST
                                owned row (the north interest border)
      undelivered bool[S, n_local]  ingest-shard entity slots whose
                                destination bucket was full this tick;
                                the caller re-offers exactly these
      overflow    i32[S]        sum of undelivered (diagnostic)
    """
    n_shards = mesh.devices.size
    rows_blk = rows_per_shard(grid, n_shards)
    cells_blk = rows_blk * grid.cols

    def shard_fn(positions, valid, entity_ids):
        me = jax.lax.axis_index(AXIS)
        cell_of = assign_cells(grid, positions, valid)  # global cell ids
        row = cell_of // grid.cols
        dest = jnp.where(cell_of >= 0, row // rows_blk, -1)  # owner shard

        # Pack per-destination buckets (fixed shape [n_shards, bucket]).
        # rank within (dest == d) via cumulative counts, like handover
        # compaction; entities beyond a bucket overflow (reported).
        slot_ids = jnp.full((n_shards, bucket), -1, jnp.int32)
        slot_cells = jnp.full((n_shards, bucket), -1, jnp.int32)
        slot_xyz = jnp.zeros((n_shards, bucket, 3), jnp.float32)
        delivered = jnp.zeros_like(dest, dtype=bool)
        for d in range(n_shards):  # static, small (n_shards <= 16)
            mask = dest == d
            rank = jnp.cumsum(mask, dtype=jnp.int32) - 1
            fits = mask & (rank < bucket)
            delivered = delivered | fits
            (idx,) = jnp.nonzero(mask, size=bucket, fill_value=0)
            idx = idx.astype(jnp.int32)
            row_valid = jnp.arange(bucket) < jnp.sum(fits, dtype=jnp.int32)
            slot_ids = slot_ids.at[d].set(
                jnp.where(row_valid, entity_ids[idx], -1))
            slot_cells = slot_cells.at[d].set(
                jnp.where(row_valid, cell_of[idx], -1))
            slot_xyz = slot_xyz.at[d].set(
                jnp.where(row_valid[:, None], positions[idx], 0.0))
        undelivered = (dest >= 0) & ~delivered
        overflow = jnp.sum(undelivered, dtype=jnp.int32)

        # The Ulysses move: [n_shards, bucket] -> every shard receives its
        # own-destination bucket from every source.
        recv_ids = jax.lax.all_to_all(slot_ids, AXIS, 0, 0, tiled=False)
        recv_cells = jax.lax.all_to_all(slot_cells, AXIS, 0, 0, tiled=False)
        recv_xyz = jax.lax.all_to_all(slot_xyz, AXIS, 0, 0, tiled=False)
        owned_ids = recv_ids.reshape(-1)  # [n_shards * bucket]
        owned_cells = recv_cells.reshape(-1)
        owned_xyz = recv_xyz.reshape(-1, 3)

        # Owned-block occupancy: local cell index = global - block start.
        block_start = me * cells_blk
        local = jnp.where(owned_cells >= 0, owned_cells - block_start, 0)
        present = owned_cells >= 0
        counts = jnp.zeros(cells_blk, jnp.int32).at[local].add(
            present.astype(jnp.int32))

        # Ring halo: the interest border. ppermute moves each shard's last
        # owned row north (to me+1) and first owned row south (to me-1) —
        # one neighbor hop over ICI, never a global collective.
        last_row = counts[-grid.cols:]
        first_row = counts[: grid.cols]
        halo_lo = jax.lax.ppermute(  # from me-1's last row
            last_row, AXIS,
            [(i, (i + 1) % n_shards) for i in range(n_shards)])
        halo_hi = jax.lax.ppermute(  # from me+1's first row
            first_row, AXIS,
            [(i, (i - 1) % n_shards) for i in range(n_shards)])
        # World edges have no neighbor: zero the wrapped halos.
        halo_lo = jnp.where(me == 0, jnp.zeros_like(halo_lo), halo_lo)
        halo_hi = jnp.where(me == n_shards - 1, jnp.zeros_like(halo_hi),
                            halo_hi)
        return (owned_ids[None], owned_cells[None], owned_xyz[None],
                counts[None], halo_lo[None], halo_hi[None],
                undelivered[None], overflow[None])

    sharded = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(AXIS), P(AXIS), P(AXIS)),
        out_specs=(P(AXIS),) * 8,
        check_vma=False,
    )
    return jax.jit(sharded)


def cells_per_shard(grid: GridSpec, n_shards: int) -> int:
    """Owned-block size for the serving step: contiguous cell ranges,
    padded so any grid divides over any shard count (cell range ==
    row block whenever rows % n_shards == 0)."""
    return -(-grid.num_cells // n_shards)


def build_cell_serving_step(grid: GridSpec, mesh: Mesh, bucket: int,
                            max_handovers_per_shard: int,
                            with_spots: bool = False):
    """The cell-sharded plane as a SERVING backend: same result contract
    as parallel.mesh.build_sharded_step (the engine normalizes either
    into one tick result), but space itself is partitioned —

    - each shard OWNS a contiguous block of ``cells_per_shard`` cells
      (the reference's per-server authority block, spatial.go:89-124);
    - per-tick entity (id, cell) pairs are bucket-packed per owner and
      delivered with ONE all_to_all over ICI; the owner accumulates its
      block's occupancy from what it received — never a global
      collective over the entity axis;
    - the [Q, C] AOI interest/dist planes are computed column-block-wise
      (each shard only its own cells via aoi_masks_for_cells) and
      all_gathered — the per-device AOI work scales 1/n_shards with
      world size, the axis on which worlds actually grow;
    - bucket overflow is never silent: ``undelivered`` (slot-sharded
      bool[N]) marks exactly the entities whose owner bucket was full —
      they stay in the ingest arrays and are re-offered next tick
      (redistribution is stateless per tick), and their occupancy is
      missing from this tick's counts until delivered. ``overflow``
      carries the per-shard sums for the controller's shed metric.

    Handover detection/compaction and the fan-out due scan are
    slot-local / replicated exactly as in the entity-sharded step —
    they don't depend on cell ownership.

    Inputs: entity arrays slot-sharded over the mesh's (single) axis;
    queries + sub state replicated. ``bucket`` = per-(source, dest)
    capacity of the redistribution; n_local (= N / n_shards) makes
    delivery exact.
    """
    if len(mesh.axis_names) != 1:
        raise ValueError(
            "cells sharding partitions space over one axis; got mesh axes "
            f"{mesh.axis_names} — use a 1D mesh (make_mesh)"
        )
    axis = mesh.axis_names[0]
    n_shards = int(mesh.devices.size)
    cells_blk = cells_per_shard(grid, n_shards)

    def shard_fn(positions, prev_cell, valid, q_kind, q_center, q_extent,
                 q_dir, q_angle, *rest):
        if with_spots:
            spot_dist, last_ms, interval_ms, active, now_ms = rest
        else:
            spot_dist = None
            last_ms, interval_ms, active, now_ms = rest
        queries = QuerySet(q_kind, q_center, q_extent, q_dir, q_angle,
                           spot_dist)
        me = jax.lax.axis_index(axis)
        cell_of = assign_cells(grid, positions, valid)

        # Handover plane: slot-local, identical to the entity-sharded step.
        handover_mask = detect_handovers(prev_cell, cell_of)
        ho_count, ho_rows, reported = compact_handovers(
            handover_mask, prev_cell, cell_of, max_handovers_per_shard
        )
        committed_prev = jnp.where(
            handover_mask & ~reported, prev_cell, cell_of)
        shard_size = positions.shape[0]
        offset = (me * shard_size).astype(jnp.int32)
        ho_rows = ho_rows.at[:, 0].set(
            jnp.where(ho_rows[:, 0] >= 0, ho_rows[:, 0] + offset, -1))
        all_counts = jax.lax.all_gather(ho_count, axis)
        all_rows = jax.lax.all_gather(ho_rows, axis)

        # Redistribution: deliver (global slot, cell) to the cell's owner.
        dest = jnp.where(cell_of >= 0, cell_of // cells_blk, -1)
        slot_ids = jnp.full((n_shards, bucket), -1, jnp.int32)
        slot_cells = jnp.full((n_shards, bucket), -1, jnp.int32)
        delivered = jnp.zeros_like(dest, dtype=bool)
        global_slots = offset + jnp.arange(shard_size, dtype=jnp.int32)
        for d in range(n_shards):  # static, small
            mask = dest == d
            rank = jnp.cumsum(mask, dtype=jnp.int32) - 1
            fits = mask & (rank < bucket)
            delivered = delivered | fits
            (idx,) = jnp.nonzero(mask, size=bucket, fill_value=0)
            idx = idx.astype(jnp.int32)
            row_valid = jnp.arange(bucket) < jnp.sum(fits, dtype=jnp.int32)
            slot_ids = slot_ids.at[d].set(
                jnp.where(row_valid, global_slots[idx], -1))
            slot_cells = slot_cells.at[d].set(
                jnp.where(row_valid, cell_of[idx], -1))
        undelivered = (dest >= 0) & ~delivered
        overflow = jnp.sum(undelivered, dtype=jnp.int32)
        recv_ids = jax.lax.all_to_all(slot_ids, axis, 0, 0, tiled=False)
        recv_cells = jax.lax.all_to_all(slot_cells, axis, 0, 0, tiled=False)
        owned_ids = recv_ids.reshape(-1)          # [n_shards * bucket]
        owned_cells = recv_cells.reshape(-1)

        # Owned-block occupancy from what the owner received.
        block_start = me * cells_blk
        local = jnp.where(owned_cells >= 0, owned_cells - block_start, 0)
        present = owned_cells >= 0
        blk_counts = jnp.zeros(cells_blk, jnp.int32).at[local].add(
            present.astype(jnp.int32))
        counts = jax.lax.all_gather(blk_counts, axis)  # [S, cells_blk]
        # (No ring-halo exchange here: nothing in the serving path consumes
        # it, and a row-width halo is only geometric on row-aligned blocks
        # — the ingest-plane step, build_cell_sharded_step, carries the
        # tested halo exchange for consumers that want borders.)

        # Column-block AOI: only my cells' columns, gathered to [Q, C_pad].
        blk_ids = block_start + jnp.arange(cells_blk, dtype=jnp.int32)
        spot_slice = None
        if spot_dist is not None:
            # The table arrives pre-padded to cells_blk * n_shards columns
            # (see cell_serving_spatial_step) so the last shard's slice
            # never clamps — a clamped start would misalign spot columns
            # against blk_ids and silently drop border-cell interest.
            spot_slice = jax.lax.dynamic_slice_in_dim(
                spot_dist, block_start, cells_blk, axis=1)
        blk_hit, blk_dist = aoi_masks_for_cells(
            grid, queries, blk_ids, spot_slice)
        interest = jax.lax.all_gather(blk_hit, axis, axis=1)   # [Q,S,blk]
        dist = jax.lax.all_gather(blk_dist, axis, axis=1)

        # Fan-out due: replicated, computed once per shard.
        due, new_last = fanout_due(now_ms, last_ms, interval_ms, active)
        return (cell_of, committed_prev, all_counts, all_rows, counts,
                interest, dist, due, new_last, undelivered,
                overflow[None], owned_ids[None])

    sharded = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(
            P(axis), P(axis), P(axis),
            P(), P(), P(), P(), P(),
            *((P(),) if with_spots else ()),
            P(), P(), P(),
            P(),
        ),
        out_specs=(
            P(axis), P(axis),      # cell_of, committed_prev
            P(), P(),              # handover counts/rows (gathered)
            P(), P(), P(),         # counts, interest, dist (gathered)
            P(), P(),              # due, new_last (replicated)
            P(axis),               # undelivered (slot-sharded)
            P(axis), P(axis),      # overflow, owned_ids
        ),
        check_vma=False,
    )

    def full(*args):
        (cell_of, committed_prev, all_counts, all_rows, counts, interest,
         dist, due, new_last, undelivered, overflow,
         owned_ids) = sharded(*args)
        c = grid.num_cells
        counts = counts.reshape(-1)[:c]
        interest = interest.reshape(interest.shape[0], -1)[:, :c]
        dist = dist.reshape(dist.shape[0], -1)[:, :c]
        due_packed = jnp.packbits(due)
        return (cell_of, committed_prev, all_counts, all_rows, counts,
                interest, dist, due, due_packed, new_last, undelivered,
                overflow, owned_ids)

    jitted = jax.jit(full, donate_argnums=(1,))

    def step(*args):
        return jitted(*args)

    step.with_spots = with_spots
    step.bucket = bucket
    step.cells_blk = cells_blk
    step.n_shards = n_shards
    return step


def cell_serving_spatial_step(step_fn, positions, prev_cell, valid,
                              queries: QuerySet, sub_state, now_ms):
    """Drive a build_cell_serving_step function; returns the engine's
    normalized tick-result dict (parallel.mesh.sharded_spatial_step's
    contract plus the cells-plane extras)."""
    last_ms, interval_ms, active = sub_state
    if queries.spot_dist is not None and not step_fn.with_spots:
        raise ValueError(
            "queries carry a spots table; build_cell_serving_step("
            "with_spots=True)")
    if queries.spot_dist is None and step_fn.with_spots:
        raise ValueError(
            "step compiled with_spots=True but queries have no spots table")
    spot_args = ()
    if step_fn.with_spots:
        # Pad to the sharded cell space (cells_blk * n_shards columns, -1 =
        # no interest) so every shard's block slice is in-bounds.
        c_pad = step_fn.cells_blk * step_fn.n_shards
        spot = queries.spot_dist
        if spot.shape[1] < c_pad:
            spot = jnp.pad(spot, ((0, 0), (0, c_pad - spot.shape[1])),
                           constant_values=-1)
        spot_args = (spot,)
    (cell_of, committed_prev, ho_counts, ho_rows, counts, interest, dist,
     due, due_packed, new_last, undelivered, overflow,
     owned_ids) = step_fn(
        positions, prev_cell, valid,
        queries.kind, queries.center, queries.extent, queries.direction,
        queries.angle, *spot_args, last_ms, interval_ms, active,
        jnp.int32(now_ms),
    )
    return {
        "cell_of": cell_of,
        "committed_prev": committed_prev,
        "handover_counts": ho_counts,
        "handovers": ho_rows,
        "cell_counts": counts,
        "interest": interest,
        "dist": dist,
        "due": due,
        "due_packed": due_packed,
        "new_last_fanout_ms": new_last,
        "undelivered": undelivered,
        "overflow": overflow,
        "owned_ids": owned_ids,
    }

"""Multi-chip sharding of the spatial decision step.

The reference scales by giving each spatial *server* a block of grid
cells plus an interest border (ref: spatial.go:387-590) — model-parallel
over space. On a TPU mesh the analogous scale-out is simpler and better
balanced: shard the entity slot arrays over the mesh's data axis, keep
the (small) query set and grid geometry replicated, and combine per-cell
aggregates with ``psum`` over ICI. Cell occupancy plays the role of the
halo: every device learns the global per-cell counts in one collective
instead of exchanging border entities.

All sharding is expressed with jax.sharding.Mesh + shard_map so the same
code runs on one chip (mesh of 1), a v5e-4 slice, or a multi-host mesh
over DCN.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from ._jax_compat import axis_size as _axis_size, shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.spatial_ops import (
    GridSpec,
    QuerySet,
    aoi_masks,
    assign_cells,
    cell_counts,
    compact_handovers,
    detect_handovers,
    fanout_due,
)

DATA_AXIS = "entities"
HOST_AXIS = "hosts"


def make_mesh(devices: Optional[list] = None,
              axis_name: str = DATA_AXIS) -> Mesh:
    import numpy as np

    devices = devices if devices is not None else jax.devices()
    return Mesh(np.array(devices, dtype=object).reshape(-1), (axis_name,))


def make_mesh_2d(n_hosts: int, devices: Optional[list] = None) -> Mesh:
    """Multi-host mesh: a (hosts, entities) grid where the host axis rides
    DCN and the entity axis rides ICI. Entity arrays shard over BOTH axes
    (each host's chips own a contiguous slot range); the occupancy psum
    reduces over ('hosts', 'entities'), so XLA emits the ICI all-reduce
    within each host and the DCN all-reduce across hosts — the same
    hierarchy the reference gets from spatial servers + gateway fan-in."""
    import numpy as np

    devices = devices if devices is not None else jax.devices()
    arr = np.array(devices, dtype=object).reshape(n_hosts, -1)
    return Mesh(arr, (HOST_AXIS, DATA_AXIS))


def mesh_from_config(n_devices: int, n_hosts: int = 1) -> Optional[Mesh]:
    """Mesh for the serving engine from config/flag values; None when
    n_devices is 0 (single-device step)."""
    if not n_devices:
        return None
    devices = jax.devices()
    if len(devices) < n_devices:
        raise ValueError(
            f"mesh wants {n_devices} devices but only {len(devices)} present"
        )
    devices = devices[:n_devices]
    if n_hosts > 1:
        return make_mesh_2d(n_hosts, devices)
    return make_mesh(devices)


def entity_sharding(mesh: Mesh) -> NamedSharding:
    """Joint sharding over every mesh axis — matches build_sharded_step's
    entity spec for both 1D and 2D meshes."""
    return NamedSharding(mesh, P(tuple(mesh.axis_names)))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def build_sharded_step(grid: GridSpec, mesh: Mesh, max_handovers_per_shard: int,
                       with_spots: bool = False):
    """Compile the per-tick decision step sharded over ``mesh``.

    Entity arrays (positions/prev_cell/valid) are sharded on the mesh's
    data axes (single-axis ICI mesh from ``make_mesh``, or the
    (hosts, entities) DCN x ICI mesh from ``make_mesh_2d``); queries and
    subscription state are replicated; outputs: cell_of sharded, handover
    rows per-shard (gathered), cell counts and AOI masks replicated.

    ``with_spots=True`` adds the replicated [Q,C] spots dist table to
    the signature (see QuerySet.spot_dist) — build with it when any
    query uses SpotsAOI.
    """
    axes = tuple(mesh.axis_names)  # ("entities",) or ("hosts", "entities")
    entity_spec = P(axes)  # shard jointly over every mesh axis

    def shard_fn(positions, prev_cell, valid, q_kind, q_center, q_extent,
                 q_dir, q_angle, *rest):
        if with_spots:
            spot_dist, last_ms, interval_ms, active, now_ms = rest
        else:
            spot_dist = None
            last_ms, interval_ms, active, now_ms = rest
        queries = QuerySet(q_kind, q_center, q_extent, q_dir, q_angle,
                           spot_dist)
        cell_of = assign_cells(grid, positions, valid)
        handover_mask = detect_handovers(prev_cell, cell_of)
        ho_count, ho_rows, reported = compact_handovers(
            handover_mask, prev_cell, cell_of, max_handovers_per_shard
        )
        # Crossings that overflowed this shard's row budget keep their old
        # cell as next tick's baseline so they are re-detected, not lost —
        # the same overflow contract as the single-device spatial_step.
        committed_prev = jnp.where(handover_mask & ~reported, prev_cell, cell_of)
        # Local slot indices -> global entity slots (row-major shard order).
        shard_index = jnp.int32(0)
        for axis in axes:
            shard_index = shard_index * _axis_size(axis) + jax.lax.axis_index(axis)
        shard_size = positions.shape[0]
        offset = (shard_index * shard_size).astype(jnp.int32)
        ho_rows = ho_rows.at[:, 0].set(
            jnp.where(ho_rows[:, 0] >= 0, ho_rows[:, 0] + offset, -1)
        )
        # Global per-cell occupancy: reduces over ICI within a host and
        # DCN across hosts — the collective that replaces the reference's
        # cross-server interest border.
        counts = jax.lax.psum(cell_counts(cell_of, grid.num_cells), axes)
        # Replicated decisions computed once per shard (identical inputs).
        interest, dist = aoi_masks(grid, queries)
        due, new_last = fanout_due(now_ms, last_ms, interval_ms, active)
        # Gather every shard's handover rows so the host reads one array.
        all_counts = jax.lax.all_gather(ho_count, axes)
        all_rows = jax.lax.all_gather(ho_rows, axes)
        return (cell_of, committed_prev, all_counts, all_rows, counts,
                interest, dist, due, new_last)

    sharded = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(
            entity_spec, entity_spec, entity_spec,  # positions, prev_cell, valid
            P(), P(), P(), P(), P(),  # query SoA (replicated)
            *((P(),) if with_spots else ()),  # spots dist table (replicated)
            P(), P(), P(),  # sub state (replicated)
            P(),  # now_ms
        ),
        out_specs=(
            entity_spec, entity_spec,  # cell_of, committed_prev
            P(), P(),  # handover counts/rows (gathered, replicated)
            P(), P(), P(), P(), P(),
        ),
        check_vma=False,
    )

    def full(*args):
        (cell_of, committed_prev, all_counts, all_rows, counts, interest,
         dist, due, new_last) = sharded(*args)
        # Bit-packed due mask: same D2H-thrift trick as spatial_step.
        due_packed = jnp.packbits(due)
        return (cell_of, committed_prev, all_counts, all_rows, counts,
                interest, dist, due, due_packed, new_last)

    jitted = jax.jit(full, donate_argnums=(1,))

    def step(*args):
        return jitted(*args)

    step.with_spots = with_spots
    return step


def sharded_spatial_step(step_fn, positions, prev_cell, valid, queries: QuerySet,
                         sub_state, now_ms):
    last_ms, interval_ms, active = sub_state
    if queries.spot_dist is not None and not getattr(step_fn, "with_spots", False):
        raise ValueError(
            "queries carry a spots table; build_sharded_step(with_spots=True)"
        )
    if queries.spot_dist is None and getattr(step_fn, "with_spots", False):
        raise ValueError(
            "step compiled with_spots=True but queries have no spots table"
        )
    spot_args = (
        (queries.spot_dist,) if getattr(step_fn, "with_spots", False) else ()
    )
    (cell_of, committed_prev, ho_counts, ho_rows, counts, interest, dist,
     due, due_packed, new_last) = step_fn(
        positions, prev_cell, valid,
        queries.kind, queries.center, queries.extent, queries.direction,
        queries.angle, *spot_args, last_ms, interval_ms, active,
        jnp.int32(now_ms),
    )
    return {
        "cell_of": cell_of,
        "committed_prev": committed_prev,
        "handover_counts": ho_counts,
        "handovers": ho_rows,
        "cell_counts": counts,
        "interest": interest,
        "dist": dist,
        "due": due,
        "due_packed": due_packed,
        "new_last_fanout_ms": new_last,
    }


def merge_handover_shards(ho_counts, ho_rows) -> "tuple[int, object]":
    """Flatten per-shard gathered handover rows into one (count, rows[K,3])
    array in shard order, dropping unused row slots. Host-side numpy."""
    import numpy as np

    counts = np.asarray(ho_counts).reshape(-1)
    rows = np.asarray(ho_rows)
    rows = rows.reshape(counts.shape[0], -1, 3)
    per_shard = rows.shape[1]
    merged = [rows[i, : min(int(counts[i]), per_shard)] for i in range(len(counts))]
    flat = (np.concatenate(merged, axis=0) if merged
            else np.zeros((0, 3), np.int32))
    return int(flat.shape[0]), flat

"""jax version compatibility shims for the sharded decision planes.

The image may carry jax 0.4.x (no top-level ``shard_map``, ``check_rep``
instead of ``check_vma``, no ``jax.lax.axis_size``) or >= 0.5. Both
mesh.py and spatial_alltoall.py import from here so the version sniffing
lives — and gets fixed — in exactly one place.
"""

from __future__ import annotations

import jax

try:  # jax >= 0.5 exports shard_map at top level
    from jax import shard_map
except ImportError:  # jax 0.4.x: experimental namespace; check_vma was
    # named check_rep there (same meaning: replication checking off).
    from functools import wraps as _wraps

    from jax.experimental.shard_map import shard_map as _shard_map_04

    @_wraps(_shard_map_04)
    def shard_map(*args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map_04(*args, **kwargs)


if not hasattr(jax.lax, "axis_size"):
    # jax 0.4.x: psum of ones over the axis is the canonical size idiom
    # (constant-folded under shard_map, so it costs no collective).
    def axis_size(axis_name):
        return jax.lax.psum(1, axis_name)
else:
    axis_size = jax.lax.axis_size

__all__ = ["axis_size", "shard_map"]

"""Client SDK: a synchronous socket client for game clients/servers.

Capability parity with the reference client library (ref: pkg/client/client.go):
message-handler registry, stub-id RPC callbacks, incoming/outgoing queues
pumped by ``tick()``, TCP and WebSocket dialing, default handlers that
track subscribed/created/listed channels. Blocking sockets + a tick pump
keep it embeddable in a game loop; an asyncio wrapper is trivial on top.
"""

from __future__ import annotations

import socket
import threading
from dataclasses import dataclass, field
from typing import Callable, Optional
from urllib.parse import urlparse

from ..protocol import (
    FrameDecoder,
    MAX_PACKET_SIZE,
    control_pb2,
    encode_frame,
    spatial_pb2,
    wire_pb2,
)
from ..core.types import BroadcastType, CompressionType, MessageType
from ..utils.logger import get_logger

logger = get_logger("client")

MessageHandler = Callable[["Client", int, object], None]
# (client, channel_id, message)


@dataclass
class _MessageEntry:
    template: type
    handlers: list[MessageHandler] = field(default_factory=list)


class Client:
    """(ref: ChanneldClient)."""

    def __init__(self, addr: str, connect_timeout: float = 5.0):
        self.id = 0
        self.compression_type = CompressionType.NO_COMPRESSION
        self.subscribed_channels: set[int] = set()
        self.created_channels: set[int] = set()
        self.listed_channels: set[int] = set()
        self.connected = False
        # Client-side decode accepts >64KB server packets via the 3-byte
        # size escape (ref: client.go:191-196; the server cap stays 64KB).
        self._decoder = FrameDecoder(extended_size=True)
        self._incoming: list = []  # (msg, channel_id, stub_id, handlers)
        self._outgoing: list[wire_pb2.MessagePack] = []
        self._lock = threading.Lock()
        self._message_map: dict[int, _MessageEntry] = {}
        self._stub_callbacks: dict[int, MessageHandler] = {0: lambda c, ch, m: None}
        self._next_stub = 1

        self._rudp = None
        if addr.startswith("ws"):
            import websockets.sync.client as ws_client

            self._ws = ws_client.connect(addr, max_size=1 << 20)
            self._sock = None
        elif addr.startswith(("rudp://", "kcp://")):
            netloc = urlparse(addr).netloc
            host, _, port = netloc.rpartition(":")
            if addr.startswith("kcp://"):
                # Real KCP wire protocol (kcp-go interop class).
                from ..core.kcp import KcpClient

                self._rudp = KcpClient(
                    host or "127.0.0.1", int(port), connect_timeout
                )
            else:
                from ..core.rudp import RudpClient

                self._rudp = RudpClient(
                    host or "127.0.0.1", int(port), connect_timeout
                )
            self._ws = None
            self._sock = None
        else:
            if "://" in addr:
                addr = urlparse(addr).netloc
            host, _, port = addr.rpartition(":")
            self._sock = socket.create_connection(
                (host or "127.0.0.1", int(port)), timeout=connect_timeout
            )
            self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._ws = None
        self.connected = True

        self.set_message_entry(
            MessageType.AUTH, control_pb2.AuthResultMessage, _handle_auth
        )
        self.set_message_entry(
            MessageType.CREATE_CHANNEL,
            control_pb2.CreateChannelResultMessage,
            _handle_create_channel,
        )
        self.set_message_entry(
            MessageType.REMOVE_CHANNEL,
            control_pb2.RemoveChannelMessage,
            _handle_remove_channel,
        )
        self.set_message_entry(
            MessageType.SUB_TO_CHANNEL,
            control_pb2.SubscribedToChannelResultMessage,
            _handle_sub,
        )
        self.set_message_entry(
            MessageType.UNSUB_FROM_CHANNEL,
            control_pb2.UnsubscribedFromChannelResultMessage,
            _handle_unsub,
        )
        self.set_message_entry(
            MessageType.LIST_CHANNEL, control_pb2.ListChannelResultMessage, _handle_list
        )
        self.set_message_entry(
            MessageType.CHANNEL_DATA_UPDATE, control_pb2.ChannelDataUpdateMessage
        )
        self.set_message_entry(
            MessageType.CREATE_SPATIAL_CHANNEL,
            spatial_pb2.CreateSpatialChannelsResultMessage,
        )
        self.set_message_entry(
            MessageType.CREATE_ENTITY_CHANNEL,
            control_pb2.CreateChannelResultMessage,
        )
        self.set_message_entry(
            MessageType.SPATIAL_CHANNELS_READY, spatial_pb2.SpatialChannelsReadyMessage
        )
        self.set_message_entry(
            MessageType.SPATIAL_REGIONS_UPDATE, spatial_pb2.SpatialRegionsUpdateMessage
        )
        self.set_message_entry(
            MessageType.QUERY_SPATIAL_CHANNEL,
            spatial_pb2.QuerySpatialChannelResultMessage,
        )
        self.set_message_entry(
            MessageType.CHANNEL_DATA_HANDOVER, spatial_pb2.ChannelDataHandoverMessage
        )
        self.set_message_entry(
            MessageType.RECOVERY_CHANNEL_DATA, control_pb2.ChannelDataRecoveryMessage
        )
        self.set_message_entry(MessageType.RECOVERY_END, control_pb2.EndRecoveryMessage)
        self.set_message_entry(
            MessageType.CHANNEL_OWNER_LOST, control_pb2.ChannelOwnerLostMessage
        )
        self.set_message_entry(
            MessageType.CHANNEL_OWNER_RECOVERED,
            control_pb2.ChannelOwnerRecoveredMessage,
        )


    # ---- registry ----------------------------------------------------------

    def set_message_entry(self, msg_type: int, template: type, *handlers) -> None:
        self._message_map[msg_type] = _MessageEntry(template, list(handlers))

    def add_message_handler(self, msg_type: int, *handlers) -> None:
        entry = self._message_map.get(msg_type)
        if entry is None:
            raise KeyError(f"no message entry for type {msg_type}")
        entry.handlers.extend(handlers)

    # ---- io ------------------------------------------------------------

    def auth(self, login_token: str = "", pit: str = "") -> None:
        self.send(
            0,
            BroadcastType.NO_BROADCAST,
            MessageType.AUTH,
            control_pb2.AuthMessage(playerIdentifierToken=pit, loginToken=login_token),
        )

    def send(
        self,
        channel_id: int,
        broadcast: int,
        msg_type: int,
        msg,
        callback: Optional[MessageHandler] = None,
    ) -> None:
        self.send_raw(channel_id, broadcast, msg_type, msg.SerializeToString(), callback)

    def send_raw(
        self,
        channel_id: int,
        broadcast: int,
        msg_type: int,
        msg_body: bytes,
        callback: Optional[MessageHandler] = None,
    ) -> None:
        stub_id = 0
        if callback is not None:
            stub_id = self._next_stub
            self._next_stub = self._next_stub % 0xFFFF + 1
            self._stub_callbacks[stub_id] = callback
        with self._lock:
            self._outgoing.append(
                wire_pb2.MessagePack(
                    channelId=channel_id,
                    broadcast=broadcast,
                    stubId=stub_id,
                    msgType=msg_type,
                    msgBody=msg_body,
                )
            )

    def receive(self, timeout: float = 0.0) -> None:
        """Read whatever is on the wire and queue decoded messages."""
        data = self._read(timeout)
        if not data:
            return
        for packet in self._decoder.decode_packets(data):
            for mp in packet.messages:
                entry = self._message_map.get(mp.msgType)
                if entry is None:
                    logger.warning("no message entry for incoming type %d", mp.msgType)
                    continue
                msg = entry.template()
                msg.ParseFromString(mp.msgBody)
                self._incoming.append((msg, mp.channelId, mp.stubId, entry.handlers))

    def tick(self, timeout: float = 0.0) -> None:
        """Pump receive + dispatch + flush (ref: client.go:246-276)."""
        self.receive(timeout)
        while self._incoming:
            msg, channel_id, stub_id, handlers = self._incoming.pop(0)
            for handler in handlers:
                handler(self, channel_id, msg)
            if stub_id:
                callback = self._stub_callbacks.pop(stub_id, None)
                if callback is not None:
                    callback(self, channel_id, msg)
        self.flush()

    def flush(self) -> None:
        from ..protocol import FramingError

        with self._lock:
            pending, self._outgoing = self._outgoing, []
        if not pending:
            return
        packet = wire_pb2.Packet()
        size = 0
        for mp in pending:
            msg_size = mp.ByteSize() + 6
            if msg_size > MAX_PACKET_SIZE:
                logger.warning(
                    "dropping oversized message (type %d, %d bytes)",
                    mp.msgType, msg_size,
                )
                continue
            size += msg_size
            if packet.messages and size > MAX_PACKET_SIZE:
                self._write_packet(packet)
                packet = wire_pb2.Packet()
                size = msg_size
            packet.messages.append(mp)
        if packet.messages:
            try:
                self._write_packet(packet)
            except FramingError:
                logger.exception("failed to flush packet")

    def _write_packet(self, packet: wire_pb2.Packet) -> None:
        frame = encode_frame(packet.SerializeToString(), int(self.compression_type))
        try:
            if self._rudp is not None:
                self._rudp.send(frame)
            elif self._ws is not None:
                self._ws.send(frame)
            else:
                self._sock.sendall(frame)
        except Exception:
            # BrokenPipe / ConnectionClosed / ICMP unreachable: peer is gone.
            self.connected = False

    def _read(self, timeout: float) -> bytes:
        if self._rudp is not None:
            data = self._rudp.recv(timeout)
            if self._rudp.session.closed:
                self.connected = False
            return data
        if self._ws is not None:
            try:
                msg = self._ws.recv(timeout=timeout)
            except TimeoutError:
                return b""
            except Exception:  # ConnectionClosed and friends
                self.connected = False
                return b""
            return msg if isinstance(msg, bytes) else msg.encode()
        self._sock.settimeout(timeout if timeout > 0 else 0.000001)
        try:
            data = self._sock.recv(1 << 17)
        except (socket.timeout, BlockingIOError):
            return b""
        except OSError:
            self.connected = False
            return b""
        if data == b"":
            # recv() returning empty without a timeout means peer EOF.
            self.connected = False
        return data

    def wait_for(self, msg_type: int, timeout: float = 5.0):
        """Convenience: tick until a message of ``msg_type`` arrives."""
        import time as _time

        box: list = []

        def _catch(client, channel_id, m):
            box.append((channel_id, m))

        self.add_message_handler(msg_type, _catch)
        try:
            end = _time.time() + timeout
            while not box and _time.time() < end:
                self.tick(timeout=0.05)
        finally:
            self._message_map[msg_type].handlers.remove(_catch)
        if not box:
            raise TimeoutError(f"no message of type {msg_type} within {timeout}s")
        return box[0]

    def disconnect(self) -> None:
        self.connected = False
        try:
            if self._rudp is not None:
                self._rudp.close()
            elif self._ws is not None:
                self._ws.close()
            else:
                self._sock.close()
        except OSError:
            pass

    def is_connected(self) -> bool:
        return self.connected


# ---- default handlers (ref: client.go handleAuth etc.) --------------------


def _handle_auth(client: Client, channel_id: int, msg) -> None:
    if msg.result == control_pb2.AuthResultMessage.SUCCESSFUL and client.id == 0:
        client.id = msg.connId
        client.compression_type = CompressionType(msg.compressionType)


def _handle_create_channel(client: Client, channel_id: int, msg) -> None:
    if msg.ownerConnId == client.id:
        client.created_channels.add(msg.channelId)


def _handle_remove_channel(client: Client, channel_id: int, msg) -> None:
    client.subscribed_channels.discard(msg.channelId)
    client.created_channels.discard(msg.channelId)
    client.listed_channels.discard(msg.channelId)


def _handle_sub(client: Client, channel_id: int, msg) -> None:
    if msg.connId == client.id:
        client.subscribed_channels.add(channel_id)


def _handle_unsub(client: Client, channel_id: int, msg) -> None:
    if msg.connId == client.id:
        client.subscribed_channels.discard(channel_id)


def _handle_list(client: Client, channel_id: int, msg) -> None:
    client.listed_channels = {info.channelId for info in msg.channels}

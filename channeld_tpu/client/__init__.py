from .client import Client, MessageHandler

__all__ = ["Client", "MessageHandler"]

"""Device-platform selection helpers shared by every process entrypoint."""

from __future__ import annotations

import os


def pin_cpu_if_virtual_devices() -> None:
    """When XLA_FLAGS requests forced host-platform devices (tests/CI on a
    virtual CPU mesh), pin the CPU backend before jax initializes — this
    harness ignores the JAX_PLATFORMS env var, so the config API is the
    only reliable switch. Harmless after backend init or without jax.

    Call sites: tests/conftest.py, __graft_entry__.dryrun_multichip, the
    gateway entrypoint (__main__), and the sidecar.
    """
    if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
        return
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass

from .ranges import RangeSet
from .idalloc import IdAllocator, hash_string
from .logger import get_logger, init_logs, security_logger

__all__ = [
    "RangeSet",
    "IdAllocator",
    "hash_string",
    "get_logger",
    "init_logs",
    "security_logger",
]

"""Integer range-set parsing for FSM message whitelists/blacklists.

Capability parity with the reference FSM config format
(ref: pkg/fsm/fsm.go:76-171), where allowed/blocked message types are
written as comma-separated entries like ``"1"`` or ``"2-65535"``.
"""

from __future__ import annotations

from bisect import bisect_right
from dataclasses import dataclass, field


@dataclass
class RangeSet:
    """A set of non-negative integers stored as sorted inclusive ranges."""

    ranges: list[tuple[int, int]] = field(default_factory=list)

    @classmethod
    def parse(cls, spec: str) -> "RangeSet":
        """Parse ``"1,5,10-99"`` style specs. Empty string -> empty set."""
        ranges: list[tuple[int, int]] = []
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            if "-" in part:
                lo_s, hi_s = part.split("-", 1)
                lo, hi = int(lo_s), int(hi_s)
                if hi < lo:
                    raise ValueError(f"invalid range: {part!r}")
            else:
                lo = hi = int(part)
            ranges.append((lo, hi))
        ranges.sort()
        # Coalesce overlapping/adjacent ranges so `contains` can bisect.
        merged: list[tuple[int, int]] = []
        for lo, hi in ranges:
            if merged and lo <= merged[-1][1] + 1:
                merged[-1] = (merged[-1][0], max(merged[-1][1], hi))
            else:
                merged.append((lo, hi))
        return cls(merged)

    def contains(self, value: int) -> bool:
        i = bisect_right(self.ranges, (value, float("inf")))
        return i > 0 and self.ranges[i - 1][1] >= value

    def __contains__(self, value: int) -> bool:
        return self.contains(value)

    def __bool__(self) -> bool:
        return bool(self.ranges)

"""FieldMask-style filtering of protobuf messages.

The reference uses fmutils.Filter to trim channel-data updates to each
subscriber's dataFieldMasks before fan-out (ref: pkg/channeld/data.go:293-318).
Semantics: an empty mask list means "send everything"; otherwise only the
named paths survive. Paths may be nested ("a.b.c"); for map fields a path
segment may name a map key ("players.alice").
"""

from __future__ import annotations

from google.protobuf.message import Message


def _build_tree(paths: list[str]) -> dict:
    tree: dict = {}
    for path in paths:
        node = tree
        for seg in path.split("."):
            node = node.setdefault(seg, {})
    return tree


def filter_fields(msg: Message, masks: list[str]) -> None:
    """Prune ``msg`` in place so only masked paths remain."""
    if not masks:
        return
    _filter_node(msg, _build_tree(masks))


def _filter_node(msg: Message, tree: dict) -> None:
    for fd in msg.DESCRIPTOR.fields:
        sub = tree.get(fd.name)
        if sub is None:
            msg.ClearField(fd.name)
        elif sub:
            # Descend only into singular sub-messages and maps; for maps the
            # next segments are keys to keep.
            if fd.type == fd.TYPE_MESSAGE:
                if fd.message_type.GetOptions().map_entry:
                    field_map = getattr(msg, fd.name)
                    keep = set(sub.keys())
                    for key in list(field_map.keys()):
                        if str(key) not in keep:
                            del field_map[key]
                elif not fd.is_repeated:
                    if msg.HasField(fd.name):
                        _filter_node(getattr(msg, fd.name), sub)
                # Repeated message fields: a mask naming the field keeps it
                # whole; deeper per-element masks aren't supported (same as
                # FieldMask semantics for repeated fields).

"""Logging with channeld-compatible verbosity levels.

The reference wraps zap with custom levels Verbose=-2, VeryVerbose=-3,
Trace=-4 below Debug=-1 (ref: pkg/channeld/logging.go:26-63), a separate
``security.log`` logger, and a warn+ counter metric. We map onto Python
logging: DEBUG=10 and three sub-debug levels below it.
"""

from __future__ import annotations

import logging
import sys
import time
from typing import Optional

VERBOSE = 8
VERY_VERBOSE = 6
TRACE = 4

logging.addLevelName(VERBOSE, "VERBOSE")
logging.addLevelName(VERY_VERBOSE, "VVERBOSE")
logging.addLevelName(TRACE, "TRACE")

_ROOT_NAME = "channeld_tpu"
_initialized = False
_active_format = "%(asctime)s %(levelname)-8s %(name)s: %(message)s"

# Incremented on warn+ records; mirrored into the Prometheus `logs` counter.
warn_counts: dict[str, int] = {}


class _WarnCountFilter(logging.Filter):
    """Counts warn+ records (ref: logging.go warn-hook -> `logs` metric).

    Attached to the *handler* (not the logger): records propagated from
    child loggers only pass through the parent's handlers, never the
    parent logger's own filters.
    """

    def filter(self, record: logging.LogRecord) -> bool:
        if record.levelno >= logging.WARNING:
            key = logging.getLevelName(record.levelno)
            warn_counts[key] = warn_counts.get(key, 0) + 1
            try:  # lazy: metrics pulls in prometheus_client
                from ..core.metrics import log_events

                log_events.labels(level=key).inc()
            except Exception:
                pass
        return True


def init_logs(
    level: int = logging.INFO,
    log_file: Optional[str] = None,
    development: bool = False,
) -> logging.Logger:
    """Initialize the root framework logger (ref: logging.go:66-100).

    ``log_file`` may contain a ``{time}`` placeholder replaced with a
    timestamp, matching the reference's log-file pattern.
    """
    global _initialized
    root = logging.getLogger(_ROOT_NAME)
    root.handlers.clear()
    root.setLevel(level)
    global _active_format
    fmt = _active_format = (
        "%(asctime)s %(levelname)-8s %(name)s: %(message)s"
        if development
        else '{"ts":"%(asctime)s","level":"%(levelname)s","logger":"%(name)s","msg":"%(message)s"}'
    )
    handler: logging.Handler
    if log_file:
        log_file = log_file.replace("{time}", time.strftime("%Y%m%d%H%M%S"))
        handler = logging.FileHandler(log_file)
    else:
        handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(logging.Formatter(fmt))
    handler.addFilter(_WarnCountFilter())
    root.addHandler(handler)
    root.propagate = False
    _initialized = True
    return root


def get_logger(name: str = "") -> logging.Logger:
    if not _initialized:
        init_logs()
    return logging.getLogger(f"{_ROOT_NAME}.{name}" if name else _ROOT_NAME)


def security_logger() -> logging.Logger:
    """Separate security event stream; gets its own file next to the main
    log when file logging is configured (ref: logging.go security.log)."""
    return get_logger("security")


def attach_security_log_file(main_log_file: str) -> None:
    """Route security events to ``security.log`` beside the main log.
    Re-init safe (replaces any prior file handler) and uses the same
    format init_logs chose, like the reference's shared zap config."""
    import os

    sec = get_logger("security")
    for h in [h for h in sec.handlers if isinstance(h, logging.FileHandler)]:
        sec.removeHandler(h)
        h.close()
    path = os.path.join(os.path.dirname(main_log_file) or ".", "security.log")
    handler = logging.FileHandler(path)
    handler.setFormatter(logging.Formatter(_active_format))
    sec.addHandler(handler)

"""google.protobuf.Any helpers (pack / resolve-and-unpack).

Mirrors the reference's anypb.New / Any.UnmarshalNew usage: the concrete
type is resolved from the process-wide descriptor pool, so game-defined
channel-data types just need their generated modules imported.
"""

from __future__ import annotations

from google.protobuf import any_pb2, symbol_database
from google.protobuf.message import Message

_sym_db = symbol_database.Default()


def pack_any(msg: Message) -> any_pb2.Any:
    a = any_pb2.Any()
    a.Pack(msg)
    return a


def unpack_any(a: any_pb2.Any) -> Message:
    """Resolve the concrete message type and unpack (ref: UnmarshalNew)."""
    type_name = a.type_url.split("/")[-1]
    cls = _sym_db.GetSymbol(type_name)
    msg = cls()
    if not a.Unpack(msg):
        raise ValueError(f"failed to unpack Any of type {type_name}")
    return msg

"""Id allocation with wraparound and occupancy checks.

Capability parity with the reference's generic id allocator
(ref: pkg/channeld/util.go:71-84 ``GetNextIdTyped``) and string hashing
(util.go ``HashString``).
"""

from __future__ import annotations

from typing import Callable, Optional


class IdAllocator:
    """Allocate the next free id in [lo, hi], scanning with wraparound.

    ``occupied`` is a predicate over candidate ids — the caller's live
    table is the source of truth, so no free-list drift is possible.
    """

    def __init__(self, lo: int, hi: int):
        if hi < lo:
            raise ValueError("hi < lo")
        self.lo = lo
        self.hi = hi
        self._next = lo

    def next_id(self, occupied: Callable[[int], bool]) -> Optional[int]:
        span = self.hi - self.lo + 1
        candidate = self._next
        for _ in range(span):
            if candidate > self.hi:
                candidate = self.lo
            if not occupied(candidate):
                self._next = candidate + 1
                return candidate
            candidate += 1
        return None


def hash_string(s: str) -> int:
    """FNV-1a 32-bit — a stable, dependency-free string hash for PIT keys."""
    h = 0x811C9DC5
    for b in s.encode("utf-8"):
        h ^= b
        h = (h * 0x01000193) & 0xFFFFFFFF
    return h


def difference(a: list, b: list) -> list:
    """Elements of ``a`` not present in ``b`` (ref: util.go ``Difference``)."""
    bs = set(b)
    return [x for x in a if x not in bs]

"""Replay tooling CLI: ``python -m channeld_tpu.replay <cmd>``.

    run <case.json>    replay recorded sessions against a live gateway
                       (the reference's load-test driver surface,
                       ref: pkg/replay/replay.go; same case-config JSON)
    dump <file.cpr>    inspect a recorded session: per-packet offset,
                       channel, msgType, body size — the quickest way to
                       see what a reference-recorded capture contains
"""

from __future__ import annotations

import json
import sys


def _dump(path: str) -> int:
    from ..core.types import MessageType
    from .session import ReplaySession

    session = ReplaySession.load(path)
    total_ns = 0
    counts: dict[int, int] = {}
    for i, rp in enumerate(session.proto.packets):
        total_ns += rp.offsetTime
        for pack in rp.packet.messages:
            counts[pack.msgType] = counts.get(pack.msgType, 0) + 1
            try:
                name = MessageType(pack.msgType).name
            except ValueError:
                name = f"USER_SPACE({pack.msgType})"
            print(f"{i:5d} +{rp.offsetTime / 1e6:9.2f}ms "
                  f"ch={pack.channelId:<8d} {name:<24s} "
                  f"{len(pack.msgBody)}B"
                  + (f" stub={pack.stubId}" if pack.stubId else "")
                  + (f" bcast={pack.broadcast}" if pack.broadcast else ""))
    print(f"-- {len(session.proto.packets)} packets, "
          f"{total_ns / 1e9:.2f}s span, msgType histogram: "
          f"{dict(sorted(counts.items()))}")
    return 0


def _run(path: str) -> int:
    from .harness import ReplayClient

    result = ReplayClient.from_config_file(path).run()
    print(json.dumps(result))
    return 0


def main(argv: list[str]) -> int:
    if len(argv) == 2 and argv[0] == "dump":
        return _dump(argv[1])
    if len(argv) == 2 and argv[0] == "run":
        return _run(argv[1])
    print(__doc__, file=sys.stderr)
    return 64


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

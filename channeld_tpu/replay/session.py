"""Recording of client packet sessions (ref: pkg/channeld/connection.go:768-821).

Client packets are timestamped relative to the previous packet and persisted
as ``.cpr`` files on connection close when ``-erp`` is enabled.
"""

from __future__ import annotations

import os
import time

from ..protocol import replay_pb2, wire_pb2
from ..utils.logger import get_logger

logger = get_logger("replay")


class ReplaySession:
    def __init__(self):
        self.proto = replay_pb2.ReplaySession()
        self._last_time_ns = 0

    def record(self, packet: wire_pb2.Packet) -> None:
        now = time.time_ns()
        offset = 0 if self._last_time_ns == 0 else now - self._last_time_ns
        self._last_time_ns = now
        rp = self.proto.packets.add(offsetTime=offset)
        rp.packet.CopyFrom(packet)

    def persist(self, directory: str, conn_id: int) -> str | None:
        if not self.proto.packets:
            return None
        directory = directory or "."
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(
            directory, f"session_{conn_id}_{time.strftime('%Y%m%d%H%M%S')}.cpr"
        )
        with open(path, "wb") as f:
            f.write(self.proto.SerializeToString())
        logger.info("persisted replay session to %s", path)
        return path

    @classmethod
    def load(cls, path: str) -> "ReplaySession":
        s = cls()
        with open(path, "rb") as f:
            s.proto.ParseFromString(f.read())
        return s

"""Replay load-test harness (ref: pkg/replay/replay.go).

Replays recorded ``.cpr`` packet sessions against a live gateway: N
connections per group, staggered connects, per-packet timing scaled by
an interval multiplier, optional auth-once and wait-for-auth, and hook
points to rewrite channel ids / messages before sending — the reference's
load-test driver surface.

Case config JSON (same keys as the reference):

    {"channeldAddr": "127.0.0.1:12108",
     "connectionGroups": [{"cprFilePath": ..., "connectionNumber": 8,
       "connectInterval": "20ms", "runningTime": "10s",
       "actionIntervalMultiplier": 1.0, "waitAuthSuccess": true,
       "authOnlyOnce": true, "sleepEndOfSession": "0s"}]}
"""

from __future__ import annotations

import json
import re
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..client import Client
from ..core.types import MessageType
from ..utils.logger import get_logger
from .session import ReplaySession

logger = get_logger("replay.harness")


def parse_duration(value) -> float:
    """Go-style durations ("20ms", "1.5s", "1m") or raw nanoseconds."""
    if isinstance(value, (int, float)):
        return float(value) / 1e9
    total = 0.0
    for num, unit in re.findall(r"([\d.]+)(ns|us|µs|ms|s|m|h)", value):
        total += float(num) * {
            "ns": 1e-9, "us": 1e-6, "µs": 1e-6, "ms": 1e-3,
            "s": 1.0, "m": 60.0, "h": 3600.0,
        }[unit]
    return total


@dataclass
class ConnectionGroupConfig:
    cpr_file_path: str = ""
    connection_number: int = 1
    connect_interval: float = 0.0
    running_time: float = 1.0
    sleep_end_of_session: float = 0.0
    action_interval_multiplier: float = 1.0
    wait_auth_success: bool = True
    auth_only_once: bool = True

    @classmethod
    def from_dict(cls, d: dict) -> "ConnectionGroupConfig":
        return cls(
            cpr_file_path=d.get("cprFilePath", ""),
            connection_number=d.get("connectionNumber", 1),
            connect_interval=parse_duration(d.get("connectInterval", 0)),
            running_time=parse_duration(d.get("runningTime", "1s")),
            sleep_end_of_session=parse_duration(d.get("sleepEndOfSession", 0)),
            action_interval_multiplier=d.get("actionIntervalMultiplier", 1.0),
            wait_auth_success=d.get("waitAuthSuccess", True),
            auth_only_once=d.get("authOnlyOnce", True),
        )


@dataclass
class CaseConfig:
    channeld_addr: str = "127.0.0.1:12108"
    connection_groups: list[ConnectionGroupConfig] = field(default_factory=list)


class ReplayClient:
    """(ref: replay.go ReplayClient)."""

    def __init__(self, case_config: CaseConfig):
        self.case_config = case_config
        self.sessions: list[ReplaySession] = [
            ReplaySession.load(g.cpr_file_path) for g in case_config.connection_groups
        ]
        # Hooks (ref: Set*Handler): rewrite or veto outgoing packs.
        self.alter_channel_id: Optional[Callable] = None
        # msg_type -> (template_cls, handler(msg, msg_pack, client) -> bool);
        # the recorded body is parsed into the template, the handler may
        # mutate it in place (e.g. rewrite connId to the replayer's own id)
        # or veto the send (ref: replay.go SetBeforeSendMessageEntry).
        self.before_send: dict[int, tuple] = {}
        self.stats_lock = threading.Lock()
        self.packets_sent = 0
        self.messages_received = 0

    @classmethod
    def from_config_file(cls, path: str) -> "ReplayClient":
        with open(path) as f:
            raw = json.load(f)
        cfg = CaseConfig(
            channeld_addr=raw.get("channeldAddr", "127.0.0.1:12108"),
            connection_groups=[
                ConnectionGroupConfig.from_dict(g)
                for g in raw.get("connectionGroups", [])
            ],
        )
        return cls(cfg)

    def run(self) -> dict:
        """Run every group to completion; returns aggregate stats."""
        threads = []
        for group, session in zip(self.case_config.connection_groups, self.sessions):
            for i in range(group.connection_number):
                t = threading.Thread(
                    target=self._run_connection, args=(group, session, i), daemon=True
                )
                threads.append(t)
                t.start()
                if group.connect_interval > 0:
                    time.sleep(group.connect_interval)
        for t in threads:
            t.join()
        return {
            "packets_sent": self.packets_sent,
            "messages_received": self.messages_received,
        }

    def _run_connection(self, group: ConnectionGroupConfig, session, index: int) -> None:
        try:
            client = Client(self.case_config.channeld_addr)
        except OSError as e:
            logger.error("replay connection %d failed to dial: %s", index, e)
            return
        received = [0]
        client.add_message_handler(
            MessageType.CHANNEL_DATA_UPDATE,
            lambda c, ch, m: received.__setitem__(0, received[0] + 1),
        )
        authed = [False]
        client.add_message_handler(
            MessageType.AUTH, lambda c, ch, m: authed.__setitem__(0, True)
        )

        deadline = time.time() + group.running_time
        first_pass = True
        try:
            while time.time() < deadline:
                for rp in session.proto.packets:
                    if time.time() >= deadline:
                        break
                    wait = rp.offsetTime / 1e9 * group.action_interval_multiplier
                    end = time.time() + wait
                    while time.time() < end:
                        client.tick(timeout=0.005)
                    for mp in rp.packet.messages:
                        if (
                            mp.msgType == MessageType.AUTH
                            and group.auth_only_once
                            and not first_pass
                        ):
                            continue
                        channel_id, send_it = mp.channelId, True
                        if self.alter_channel_id is not None:
                            channel_id, send_it = self.alter_channel_id(
                                mp.channelId, mp.msgType, mp, client
                            )
                        if not send_it:
                            continue
                        body = mp.msgBody
                        entry = self.before_send.get(mp.msgType)
                        if entry is not None:
                            template_cls, handler = entry
                            # A wrong template or corrupt recorded body must
                            # not kill the connection's whole remaining run
                            # (ref: replay.go:307-310 logs and skips).
                            try:
                                msg = template_cls()
                                msg.ParseFromString(body)
                                if not handler(msg, mp, client):
                                    continue
                                body = msg.SerializeToString()
                            except Exception:
                                # The hook exists because the recorded bytes
                                # are wrong as-is — skip rather than send them.
                                logger.exception(
                                    "before_send hook failed for msgType %d; "
                                    "skipping message", mp.msgType,
                                )
                                continue
                        client.send_raw(channel_id, mp.broadcast, mp.msgType, body)
                        with self.stats_lock:
                            self.packets_sent += 1
                    client.tick()
                    if first_pass and group.wait_auth_success:
                        end = time.time() + 3.0
                        while not authed[0] and time.time() < end:
                            client.tick(timeout=0.05)
                first_pass = False
                if group.sleep_end_of_session > 0:
                    time.sleep(group.sleep_end_of_session)
        finally:
            with self.stats_lock:
                self.messages_received += received[0]
            client.disconnect()

"""Spatial message handlers (ref: pkg/channeld/message_spatial.go)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..core.settings import global_settings
from ..core.types import ChannelType, ConnectionType, MessageType
from ..protocol import control_pb2, spatial_pb2
from ..utils.logger import get_logger
from .controller import SpatialInfo, get_spatial_controller

logger = get_logger("spatial.msg")


@dataclass
class SpatialDampingSettings:
    """Fan-out cadence + masks as a function of grid distance
    (ref: message_spatial.go:10-14)."""

    max_distance: int
    fanout_interval_ms: int
    data_field_masks: list[str] = field(default_factory=list)


# Near cells update fast and fully; far cells are damped
# (ref: message_spatial.go:16-29).
spatial_damping_settings: list[SpatialDampingSettings] = [
    SpatialDampingSettings(max_distance=0, fanout_interval_ms=20),
    SpatialDampingSettings(max_distance=1, fanout_interval_ms=50),
    SpatialDampingSettings(max_distance=2, fanout_interval_ms=100),
]


def get_spatial_damping_settings(dist: int) -> Optional[SpatialDampingSettings]:
    for s in spatial_damping_settings:
        if dist <= s.max_distance:
            return s
    return None


def sub_options_for_distance(dist: int) -> control_pb2.ChannelSubscriptionOptions:
    damp = get_spatial_damping_settings(dist)
    if damp is None:
        return control_pb2.ChannelSubscriptionOptions(
            fanOutIntervalMs=global_settings.get_channel_settings(
                ChannelType.SPATIAL
            ).default_fanout_interval_ms
        )
    return control_pb2.ChannelSubscriptionOptions(
        fanOutIntervalMs=damp.fanout_interval_ms,
        dataFieldMasks=damp.data_field_masks,
    )


def apply_interest_diff(conn, desired: dict, origin_channel=None,
                        origin_channel_id: int = 0, stub_id: int = 0) -> None:
    """Diff ``desired`` ({channel_id: grid_distance}) against the
    connection's current spatial subscriptions and enqueue sub/unsub into
    each target channel's own queue (ref: message_spatial.go:82-129).
    Desired channels are always (re)subscribed so distance-damped options
    refresh via the sub-merge."""
    from ..core.channel import get_channel
    from ..core.message import (
        MessageContext,
        handle_sub_to_channel,
        handle_unsub_from_channel,
    )

    to_unsub = set(conn.spatial_subscriptions.keys()) - set(desired.keys())
    for ch_id in to_unsub:
        target = get_channel(ch_id)
        if target is None:
            continue
        unsub_ctx = MessageContext(
            msg_type=MessageType.UNSUB_FROM_CHANNEL,
            msg=control_pb2.UnsubscribedFromChannelMessage(connId=conn.id),
            connection=conn,
            channel=target,
            channel_id=origin_channel_id or ch_id,
            stub_id=stub_id,
        )
        if target is origin_channel:
            handle_unsub_from_channel(unsub_ctx)
        else:
            target.put_message_context(unsub_ctx, handle_unsub_from_channel)

    for ch_id, dist in desired.items():
        target = get_channel(ch_id)
        if target is None:
            continue
        sub_ctx = MessageContext(
            msg_type=MessageType.SUB_TO_CHANNEL,
            msg=control_pb2.SubscribedToChannelMessage(
                connId=conn.id, subOptions=sub_options_for_distance(dist)
            ),
            connection=conn,
            channel=target,
            channel_id=origin_channel_id or ch_id,
        )
        if target is origin_channel:
            handle_sub_to_channel(sub_ctx)
        else:
            target.put_message_context(sub_ctx, handle_sub_to_channel)


def handle_update_spatial_interest(ctx) -> None:
    """Query -> desired sub set -> diff against current -> cross-channel
    sub/unsub (ref: message_spatial.go:41-129). Runs in a spatial channel."""
    from ..core.channel import get_channel
    from ..core.connection import get_connection
    from ..core.message import (
        MessageContext,
        handle_sub_to_channel,
        handle_unsub_from_channel,
    )

    msg = ctx.msg
    if not isinstance(msg, spatial_pb2.UpdateSpatialInterestMessage):
        return
    controller = get_spatial_controller()
    if controller is None:
        logger.error("cannot update spatial interest: no spatial controller")
        return
    client_conn = get_connection(msg.connId)
    if client_conn is None:
        logger.error("cannot update spatial interest: no connection %d", msg.connId)
        return

    # channeld-tpu extension: a followEntityId hands the query to the device
    # decision plane, which re-centers it on the entity and re-diffs the
    # subscriptions every batched tick. A plain query cancels any follow;
    # spots queries fall through to the host path below (absolute points
    # can't follow an entity — the engine itself serves spots via
    # set_spots_query for sidecar consumers).
    # Federation: a client following an entity is ANCHORED on it — if
    # that entity later commits a cross-gateway handover, the client is
    # redirected to the gateway now hosting it (doc/federation.md). The
    # anchor applies on the host path too (the follow itself needs the
    # device plane, but possession doesn't).
    from ..federation.directory import directory as _fed_directory

    if _fed_directory.active:
        from ..federation.plane import plane as _fed_plane

        if msg.followEntityId:
            _fed_plane.set_client_anchor(client_conn, msg.followEntityId)
        else:
            _fed_plane.clear_client_anchor(client_conn.id)

    bad_field = _validate_interest_query(msg.query)
    if bad_field is not None:
        # Hostile or broken query fields (NaN/inf centers, negative
        # radius/angle, oversize spot lists) are rejected BEFORE touching
        # any query table — host or device. Counted (the operator-visible
        # malformed finding) + throttled security log; the connection's
        # existing interest is left untouched.
        _count_malformed(bad_field, msg.connId)
        return

    register = getattr(controller, "register_follow_interest", None)
    unregister = getattr(controller, "unregister_follow_interest", None)
    if callable(register):
        params = _query_to_engine_params(msg.query) if msg.followEntityId else None
        if msg.followEntityId and params is not None:
            kind, extent, direction, angle = params
            register(client_conn, msg.followEntityId, kind, extent, direction, angle)
            return
        if callable(unregister):
            unregister(client_conn.id)

    try:
        spatial_ch_ids = controller.query_channel_ids(msg.query)
    except ValueError as e:
        logger.error("error querying spatial channel ids: %s", e)
        return

    apply_interest_diff(
        client_conn, dict(spatial_ch_ids),
        origin_channel=ctx.channel, origin_channel_id=ctx.channel_id,
        stub_id=ctx.stub_id,
    )

    # Standing-query plane (doc/query_engine.md): the synchronous host
    # apply above keeps the handler's semantics byte-identical; the
    # device row registered here keeps the interest LIVE — geometry
    # epochs, device rebuilds, and damping-distance drift re-apply it
    # with no further client messages.
    plane = getattr(controller, "queryplane", None)
    if plane is not None:
        _register_standing_query(plane, client_conn, msg.query)


_malformed_logged: dict[str, float] = {}  # field -> last log time


def _count_malformed(field: str, conn_id: int) -> None:
    """Operator-visible malformed-query finding: metric always, security
    log throttled per field (a hostile client repeats forever)."""
    import time as _time

    from ..core import metrics
    from ..utils.logger import security_logger

    metrics.query_malformed.labels(field=field).inc()
    now = _time.monotonic()
    if now - _malformed_logged.get(field, -1e9) >= 5.0:
        _malformed_logged[field] = now
        security_logger().warning(
            "malformed UpdateSpatialInterest rejected (%s) from conn %d "
            "(query_malformed_total counts every occurrence)",
            field, conn_id,
        )


def _validate_interest_query(
    query: spatial_pb2.SpatialInterestQuery,
) -> Optional[str]:
    """Reject hostile query fields before they touch any query table:
    the name of the offending field, or None when clean. NaN/inf
    coordinates would poison the device mask math (NaN comparisons are
    all-false — a silently empty interest) or wedge the host sampling
    loops; negative radius/angle invert shape tests; an unbounded spots
    list is an O(N) rasterization the sender controls."""
    import math

    def finite(*vals) -> bool:
        return all(math.isfinite(float(v)) for v in vals)

    if query.HasField("spotsAOI"):
        spots = query.spotsAOI.spots
        if len(spots) > global_settings.queryplane_max_spots:
            return "spots_oversize"
        if not all(finite(s.x, s.y, s.z) for s in spots):
            return "spots_not_finite"
    if query.HasField("boxAOI"):
        box = query.boxAOI
        if not finite(box.center.x, box.center.z, box.extent.x,
                      box.extent.z):
            return "box_not_finite"
        if box.extent.x < 0 or box.extent.z < 0:
            return "box_extent_negative"
    if query.HasField("sphereAOI"):
        sph = query.sphereAOI
        if not finite(sph.center.x, sph.center.z, sph.radius):
            return "sphere_not_finite"
        if sph.radius < 0:
            return "sphere_radius_negative"
    if query.HasField("coneAOI"):
        cone = query.coneAOI
        if not finite(cone.center.x, cone.center.z, cone.direction.x,
                      cone.direction.z, cone.angle, cone.radius):
            return "cone_not_finite"
        if cone.radius < 0:
            return "cone_radius_negative"
        if cone.angle < 0:
            return "cone_angle_negative"
    return None


def _register_standing_query(plane, conn, query) -> None:
    """Map a validated client query onto one standing device row
    (spatial/queryplane.py). An empty query (no AOI field) clears the
    standing registration — the host apply above already unsubscribed."""
    from ..ops.spatial_ops import AOI_BOX, AOI_CONE, AOI_SPHERE

    if query.HasField("spotsAOI"):
        plane.register_client_spots(
            conn,
            [(s.x, s.z) for s in query.spotsAOI.spots],
            list(query.spotsAOI.dists) or None,
        )
    elif query.HasField("sphereAOI"):
        sph = query.sphereAOI
        plane.register_client(
            conn, AOI_SPHERE, (sph.center.x, sph.center.z),
            (sph.radius, 0.0),
        )
    elif query.HasField("boxAOI"):
        box = query.boxAOI
        plane.register_client(
            conn, AOI_BOX, (box.center.x, box.center.z),
            (box.extent.x, box.extent.z),
        )
    elif query.HasField("coneAOI"):
        cone = query.coneAOI
        plane.register_client(
            conn, AOI_CONE, (cone.center.x, cone.center.z),
            (cone.radius, 0.0), (cone.direction.x, cone.direction.z),
            cone.angle,
        )
    else:
        plane.deregister(conn.id)


def _query_to_engine_params(query: spatial_pb2.SpatialInterestQuery):
    """Map a proto query shape onto the device query table's SoA row
    (ref: ops/spatial_ops.py QuerySet); spots have no follow semantics."""
    from ..ops.spatial_ops import AOI_BOX, AOI_CONE, AOI_SPHERE

    if query.HasField("sphereAOI"):
        return AOI_SPHERE, (query.sphereAOI.radius, 0.0), (1.0, 0.0), 0.0
    if query.HasField("boxAOI"):
        return AOI_BOX, (query.boxAOI.extent.x, query.boxAOI.extent.z), (1.0, 0.0), 0.0
    if query.HasField("coneAOI"):
        c = query.coneAOI
        return AOI_CONE, (c.radius, 0.0), (c.direction.x, c.direction.z), c.angle
    return None


def handle_create_spatial_channel(ctx, msg: control_pb2.CreateChannelMessage) -> None:
    """(ref: message_spatial.go:131-189). Called from handle_create_channel."""
    from ..core.channel import get_global_channel
    from ..core.subscription import subscribe_to_channel
    from ..core.subscription_messages import send_subscribed

    if ctx.connection.connection_type != ConnectionType.SERVER:
        logger.error("illegal attempt to create SPATIAL channel from a client")
        return
    controller = get_spatial_controller()
    if controller is None:
        logger.error("illegal attempt to create SPATIAL channel: no controller")
        return
    try:
        channels = controller.create_channels(ctx)
    except Exception as e:
        logger.error("failed to create spatial channels: %s", e)
        return

    resp = ctx.clone_for_send()
    resp.msg_type = MessageType.CREATE_SPATIAL_CHANNEL
    resp.msg = spatial_pb2.CreateSpatialChannelsResultMessage(
        spatialChannelId=[ch.id for ch in channels],
        metadata=msg.metadata,
        ownerConnId=ctx.connection.id,
    )
    ctx.connection.send(resp)
    gch = get_global_channel()
    owner = gch.get_owner() if gch is not None else None
    if owner is not None and owner is not ctx.connection and not owner.is_closing():
        mirror = resp.clone_for_send()
        mirror.stub_id = 0
        owner.send(mirror)

    for ch in channels:
        cs, _ = subscribe_to_channel(ctx.connection, ch, msg.subOptions)
        if cs is not None:
            send_subscribed(ctx.connection, ch, ctx.connection, 0, cs.options)

    logger.info(
        "created %d spatial channels for conn %d", len(channels), ctx.connection.id
    )

    # Push the region table so the server can map positions locally.
    regions_ctx = ctx.clone_for_send()
    regions_ctx.msg_type = MessageType.SPATIAL_REGIONS_UPDATE
    regions_ctx.msg = spatial_pb2.SpatialRegionsUpdateMessage(
        regions=controller.get_regions()
    )
    ctx.connection.send(regions_ctx)


def handle_create_entity_channel(ctx) -> None:
    """(ref: message_spatial.go:191-333)."""
    from ..core import events
    from ..core.channel import (
        create_channel_with_id,
        get_channel,
        get_global_channel,
    )
    from ..core.connection import all_connections
    from ..core.data import unwrap_update_any
    from ..core.message import MessageContext
    from ..core.subscription import subscribe_to_channel
    from ..core.subscription_messages import send_subscribed

    gch = get_global_channel()
    if ctx.channel is not gch and ctx.channel.channel_type != ChannelType.SPATIAL:
        logger.error(
            "illegal attempt to create entity channel outside GLOBAL/SPATIAL channels"
        )
        return
    msg = ctx.msg
    if not isinstance(msg, spatial_pb2.CreateEntityChannelMessage):
        return
    entity_ch_id = msg.entityId
    if entity_ch_id < global_settings.entity_channel_id_start:
        logger.error("invalid entityId %d for entity channel", entity_ch_id)
        return
    existing = get_channel(entity_ch_id)
    if existing is not None and not existing.is_removing():
        logger.warning("entity channel %d already exists", entity_ch_id)
        return

    new_channel = create_channel_with_id(entity_ch_id, ChannelType.ENTITY, ctx.connection)
    new_channel.metadata = msg.metadata

    controller = get_spatial_controller()
    if msg.HasField("data"):
        try:
            data_msg = unwrap_update_any(msg.data)
        except Exception:
            new_channel.logger.exception("failed to unmarshal entity channel data")
            data_msg = None
        if data_msg is not None:
            new_channel.init_data(data_msg, msg.mergeOptions)
            # Entity created by the master server but carrying a position:
            # ownership belongs to the spatial channel's server.
            get_info = getattr(data_msg, "get_spatial_info", None)
            info = get_info() if callable(get_info) else None
            if ctx.channel is gch and controller is not None and info is not None:
                _assign_spatial_owner(ctx, new_channel, info)
            # Device-backed controllers track positions from birth so the
            # batch tick has a previous cell to detect crossings against.
            track = getattr(controller, "track_entity", None)
            if callable(track) and info is not None:
                track(new_channel.id, info)
    else:
        new_channel.init_data(None, msg.mergeOptions)

    resp = ctx.clone_for_send()
    resp.msg = control_pb2.CreateChannelResultMessage(
        channelType=new_channel.channel_type,
        metadata=new_channel.metadata,
        ownerConnId=ctx.connection.id,
        channelId=new_channel.id,
    )
    ctx.connection.send(resp)

    if msg.isWellKnown:
        # Everyone sees well-known entities, regardless of AOI.
        for conn in list(all_connections().values()):
            if conn.connection_type == ConnectionType.SERVER:
                continue
            cs, should_send = subscribe_to_channel(conn, new_channel, None)
            if should_send:
                send_subscribed(conn, new_channel, conn, 0, cs.options)

        def _on_auth(data: events.AuthEventData) -> None:
            if data.connection.connection_type == ConnectionType.SERVER:
                return
            # Give the client time to handle the spawn message first.
            sub_options = control_pb2.ChannelSubscriptionOptions(fanOutDelayMs=1000)
            cs, should_send = subscribe_to_channel(
                data.connection, new_channel, sub_options
            )
            if should_send:
                send_subscribed(data.connection, new_channel, data.connection, 0, cs.options)

        events.auth_complete.listen_for(new_channel, _on_auth)

    cs, _ = subscribe_to_channel(ctx.connection, new_channel, msg.subOptions)
    if cs is not None:
        send_subscribed(ctx.connection, new_channel, ctx.connection, 0, cs.options)


def _assign_spatial_owner(ctx, entity_channel, info) -> None:
    """(ref: message_spatial.go:237-276)."""
    from ..core import events
    from ..core.channel import get_channel

    controller = get_spatial_controller()
    try:
        spatial_ch_id = controller.get_channel_id(
            SpatialInfo(info.x, info.y, info.z)
            if not isinstance(info, SpatialInfo)
            else info
        )
    except ValueError as e:
        logger.error("failed to map entity position to spatial channel: %s", e)
        return
    spatial_ch = get_channel(spatial_ch_id)
    if spatial_ch is None:
        entity_channel.logger.error(
            "owning spatial channel %d does not exist", spatial_ch_id
        )
        return
    owner = spatial_ch.get_owner()
    if owner is None or owner.is_closing():
        entity_channel.logger.warning(
            "owning spatial channel %d has no owner connection", spatial_ch_id
        )
        return
    entity_channel.set_owner(owner)
    events.entity_channel_spatially_owned.broadcast(
        events.SpatialOwnershipData(
            entity_channel=entity_channel, spatial_channel=spatial_ch
        )
    )
    # Route the result to the spatial owner instead of the master server.
    ctx.connection = owner
    ctx.channel_id = spatial_ch_id


def handle_query_spatial_channel(ctx) -> None:
    """(ref: message_spatial.go:335-370)."""
    from ..core.channel import get_global_channel

    if ctx.channel is not get_global_channel():
        logger.error("illegal attempt to query spatial channel outside GLOBAL")
        return
    msg = ctx.msg
    if not isinstance(msg, spatial_pb2.QuerySpatialChannelMessage):
        return
    controller = get_spatial_controller()
    if controller is None:
        logger.error("cannot query spatial channel: no controller")
        return
    channel_ids = []
    for info in msg.spatialInfo:
        try:
            channel_ids.append(
                controller.get_channel_id(SpatialInfo(info.x, info.y, info.z))
            )
        except ValueError:
            channel_ids.append(0)
    resp = ctx.clone_for_send()
    resp.msg = spatial_pb2.QuerySpatialChannelResultMessage(channelId=channel_ids)
    ctx.connection.send(resp)


def handle_debug_get_spatial_regions(ctx) -> None:
    """Dev-mode only (ref: message_debug.go:8-39)."""
    if not global_settings.development:
        logger.error("DebugGetSpatialRegions is only available in development mode")
        return
    controller = get_spatial_controller()
    if controller is None:
        return
    resp = ctx.clone_for_send()
    resp.msg_type = MessageType.SPATIAL_REGIONS_UPDATE
    resp.msg = spatial_pb2.SpatialRegionsUpdateMessage(regions=controller.get_regions())
    ctx.connection.send(resp)


def install_spatial_handlers() -> None:
    """Register the spatial/entity handlers into the message map
    (ref: message.go:52-59)."""
    from ..core.message import MESSAGE_MAP, MessageMapEntry
    from .entity import handle_add_entity_group, handle_remove_entity_group

    for msg_type, template, handler in [
        (
            MessageType.QUERY_SPATIAL_CHANNEL,
            spatial_pb2.QuerySpatialChannelMessage,
            handle_query_spatial_channel,
        ),
        (
            MessageType.UPDATE_SPATIAL_INTEREST,
            spatial_pb2.UpdateSpatialInterestMessage,
            handle_update_spatial_interest,
        ),
        (
            MessageType.CREATE_ENTITY_CHANNEL,
            spatial_pb2.CreateEntityChannelMessage,
            handle_create_entity_channel,
        ),
        (
            MessageType.ENTITY_GROUP_ADD,
            spatial_pb2.AddEntityGroupMessage,
            handle_add_entity_group,
        ),
        (
            MessageType.ENTITY_GROUP_REMOVE,
            spatial_pb2.RemoveEntityGroupMessage,
            handle_remove_entity_group,
        ),
        (
            MessageType.DEBUG_GET_SPATIAL_REGIONS,
            spatial_pb2.DebugGetSpatialRegionsMessage,
            handle_debug_get_spatial_regions,
        ),
    ]:
        MESSAGE_MAP[msg_type] = MessageMapEntry(template, handler)

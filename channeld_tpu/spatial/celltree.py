"""Versioned quadtree cell geometry (doc/partitioning.md).

The spatial grid's cell layout is no longer a boot-time constant: the
adaptive partitioning plane (spatial/partition.py) splits hot cells into
four children and merges cold sibling groups back, and every consumer of
cell geometry — channel-id math, adjacency, server placement, the device
mirror — consults the live :class:`CellTree` instead of hard-coding the
base-grid formula.

Geometry state is just ``(epoch, splits)``: a monotonic epoch counter
plus the set of cell ids that are split (interior nodes). An empty split
set reproduces the legacy static grid bit-for-bit — every depth-0 id,
adjacency set and server index is identical to the fixed-grid formulas
the geometry tests pin.

Cell-id arithmetic is closed-form so every gateway derives the SAME ids
with no allocation coordination: depth-``d`` cells occupy a contiguous
block above the base grid,

    block_base(d) = start + base_count * (4**d - 1) // 3
    id(d, gx, gz) = block_base(d) + gz * (cols << d) + gx

with ``base_count = cols * rows``. Depth 0 degenerates to the legacy
``start + gx + gz*cols``. The id space consumed by ``max_depth`` levels
must fit under ``entity_channel_id_start`` — validated at load.
"""

from __future__ import annotations

from typing import Iterable, Optional

from ..utils.logger import get_logger

logger = get_logger("spatial.celltree")


class CellTree:
    """Quadtree over the base grid; identity + geometry math.

    Immutable-by-convention: mutation happens through :meth:`apply`,
    which replaces ``(epoch, splits)`` wholesale (the form WAL replay,
    trunk sync and the partition plane all share). Planning helpers
    (:meth:`split_result` / :meth:`merge_result`) return the candidate
    split set without touching live state.
    """

    def __init__(self, start: int, cols: int, rows: int,
                 cell_w: float, cell_h: float,
                 offset_x: float, offset_z: float,
                 max_depth: int = 0) -> None:
        self.start = start
        self.cols = cols
        self.rows = rows
        self.cell_w = cell_w
        self.cell_h = cell_h
        self.offset_x = offset_x
        self.offset_z = offset_z
        self.max_depth = max_depth
        self.epoch = 0
        self.splits: frozenset[int] = frozenset()

    # ---- closed-form id arithmetic -----------------------------------

    @property
    def base_count(self) -> int:
        return self.cols * self.rows

    def block_base(self, depth: int) -> int:
        """First cell id of the depth-``depth`` block."""
        return self.start + self.base_count * ((4 ** depth) - 1) // 3

    def id_space_end(self) -> int:
        """One past the last id ``max_depth`` levels can ever use."""
        return self.block_base(self.max_depth + 1)

    def encode(self, depth: int, gx: int, gz: int) -> int:
        return self.block_base(depth) + gz * (self.cols << depth) + gx

    def decode(self, cell_id: int) -> tuple[int, int, int]:
        """cell id -> (depth, gx, gz); raises on out-of-space ids."""
        d = 0
        while cell_id >= self.block_base(d + 1):
            d += 1
            if d > self.max_depth + 1:
                raise ValueError(f"cell id {cell_id} beyond depth bound")
        idx = cell_id - self.block_base(d)
        w = self.cols << d
        return d, idx % w, idx // w

    def depth_of(self, cell_id: int) -> int:
        return self.decode(cell_id)[0]

    def parent(self, cell_id: int) -> Optional[int]:
        d, gx, gz = self.decode(cell_id)
        if d == 0:
            return None
        return self.encode(d - 1, gx >> 1, gz >> 1)

    def children(self, cell_id: int) -> list[int]:
        """The four depth+1 children, row-major (z then x)."""
        d, gx, gz = self.decode(cell_id)
        return [self.encode(d + 1, (gx << 1) + dx, (gz << 1) + dz)
                for dz in (0, 1) for dx in (0, 1)]

    def sibling_group(self, cell_id: int) -> list[int]:
        p = self.parent(cell_id)
        if p is None:
            return [cell_id]
        return self.children(p)

    def base_cell_of(self, cell_id: int) -> int:
        """Base-grid (depth-0) index containing this cell."""
        d, gx, gz = self.decode(cell_id)
        return (gx >> d) + (gz >> d) * self.cols

    # ---- tree membership ---------------------------------------------

    def exists(self, cell_id: int) -> bool:
        try:
            d, _, _ = self.decode(cell_id)
        except ValueError:
            return False
        if d == 0:
            return True
        p = self.parent(cell_id)
        return p is not None and p in self.splits and self.exists(p)

    def is_leaf(self, cell_id: int) -> bool:
        return self.exists(cell_id) and cell_id not in self.splits

    def leaves_under(self, cell_id: int) -> list[int]:
        """All leaf cells at or beneath ``cell_id`` (itself if leaf)."""
        if cell_id not in self.splits:
            return [cell_id]
        out: list[int] = []
        for c in self.children(cell_id):
            out.extend(self.leaves_under(c))
        return out

    def leaves(self) -> list[int]:
        """Every live leaf, base-grid order then depth-first."""
        out: list[int] = []
        for base in range(self.start, self.start + self.base_count):
            out.extend(self.leaves_under(base))
        return out

    def max_active_depth(self) -> int:
        d = 0
        for s in self.splits:
            d = max(d, self.depth_of(s) + 1)
        return d

    # ---- world-space geometry ----------------------------------------

    def rect(self, cell_id: int) -> tuple[float, float, float, float]:
        """(x0, z0, x1, z1) world-space bounds of the cell."""
        d, gx, gz = self.decode(cell_id)
        w = self.cell_w / (1 << d)
        h = self.cell_h / (1 << d)
        x0 = self.offset_x + gx * w
        z0 = self.offset_z + gz * h
        return x0, z0, x0 + w, z0 + h

    def center(self, cell_id: int) -> tuple[float, float]:
        x0, z0, x1, z1 = self.rect(cell_id)
        return (x0 + x1) / 2.0, (z0 + z1) / 2.0

    def leaf_at(self, x: float, z: float) -> Optional[int]:
        """Leaf cell containing world position (x, z); None if outside."""
        gx = int((x - self.offset_x) // self.cell_w)
        gz = int((z - self.offset_z) // self.cell_h)
        if not (0 <= gx < self.cols and 0 <= gz < self.rows):
            return None
        cell = self.encode(0, gx, gz)
        d = 0
        while cell in self.splits:
            d += 1
            w = self.cell_w / (1 << d)
            h = self.cell_h / (1 << d)
            gx = int((x - self.offset_x) // w)
            gz = int((z - self.offset_z) // h)
            # Clamp against float edge cases at the far border.
            gx = min(gx, (self.cols << d) - 1)
            gz = min(gz, (self.rows << d) - 1)
            cell = self.encode(d, gx, gz)
        return cell

    def leaves_in_rect(self, x0: float, z0: float,
                       x1: float, z1: float) -> list[int]:
        """Leaves whose rect intersects [x0,x1) x [z0,z1)."""
        eps = 1e-9
        bx0 = max(0, int((x0 - self.offset_x) // self.cell_w))
        bz0 = max(0, int((z0 - self.offset_z) // self.cell_h))
        bx1 = min(self.cols - 1,
                  int((x1 - eps - self.offset_x) // self.cell_w))
        bz1 = min(self.rows - 1,
                  int((z1 - eps - self.offset_z) // self.cell_h))
        out: list[int] = []
        for gz in range(bz0, bz1 + 1):
            for gx in range(bx0, bx1 + 1):
                for leaf in self.leaves_under(self.encode(0, gx, gz)):
                    lx0, lz0, lx1, lz1 = self.rect(leaf)
                    if lx0 < x1 and lx1 > x0 and lz0 < z1 and lz1 > z0:
                        out.append(leaf)
        return out

    def neighbor_leaves(self, cell_id: int) -> list[int]:
        """Leaves within one BASE cell of ``cell_id`` (excl. itself).

        With no splits this is exactly the legacy 3x3 neighborhood;
        with splits it is every leaf intersecting the same border band.
        """
        x0, z0, x1, z1 = self.rect(cell_id)
        out = self.leaves_in_rect(x0 - self.cell_w, z0 - self.cell_h,
                                  x1 + self.cell_w, z1 + self.cell_h)
        return [c for c in out if c != cell_id]

    def server_index_of(self, cell_id: int, sgc: int, sgr: int,
                        server_cols: int) -> int:
        """Owning server index — children inherit the base cell's."""
        base = self.base_cell_of(cell_id)
        gx, gz = base % self.cols, base // self.cols
        return (gx // sgc) + (gz // sgr) * server_cols

    # ---- uniform micro grid (device mirror) --------------------------

    def micro_spec(self) -> tuple[int, int, int, float, float]:
        """(depth, micro_cols, micro_rows, micro_w, micro_h).

        The finest uniform grid that resolves every live leaf: the
        device engine runs on this grid and the host maps micro cells
        back to leaf channel ids via :meth:`micro_to_leaf`.
        """
        d = self.max_active_depth()
        return (d, self.cols << d, self.rows << d,
                self.cell_w / (1 << d), self.cell_h / (1 << d))

    def micro_to_leaf(self) -> list[int]:
        """Row-major micro-cell index -> leaf channel id."""
        d, mcols, mrows, _, _ = self.micro_spec()
        out = [0] * (mcols * mrows)
        for leaf in self.leaves():
            ld, gx, gz = self.decode(leaf)
            span = 1 << (d - ld)
            for dz in range(span):
                row = (gz * span + dz) * mcols
                for dx in range(span):
                    out[row + gx * span + dx] = leaf
        return out

    # ---- mutation ----------------------------------------------------

    def validate_splits(self, splits: Iterable[int]) -> Optional[str]:
        """None if ``splits`` forms a well-formed tree, else a reason."""
        s = frozenset(splits)
        for cell in s:
            try:
                d, gx, gz = self.decode(cell)
            except ValueError:
                return f"cell {cell} outside the id space"
            if d >= self.max_depth:
                return f"cell {cell} split past max depth {self.max_depth}"
            if not (0 <= gx < (self.cols << d)
                    and 0 <= gz < (self.rows << d)):
                return f"cell {cell} outside the grid"
            if d > 0:
                p = self.encode(d - 1, gx >> 1, gz >> 1)
                if p not in s:
                    return f"cell {cell} split but parent {p} is not"
        return None

    def apply(self, epoch: int, splits: Iterable[int]) -> None:
        """Replace geometry wholesale (partition commit / sync / replay)."""
        err = self.validate_splits(splits)
        if err is not None:
            raise ValueError(f"invalid geometry at epoch {epoch}: {err}")
        self.epoch = epoch
        self.splits = frozenset(splits)

    def split_result(self, cell_id: int) -> frozenset[int]:
        """Split set after splitting leaf ``cell_id`` (validated)."""
        if not self.is_leaf(cell_id):
            raise ValueError(f"cell {cell_id} is not a live leaf")
        if self.depth_of(cell_id) >= self.max_depth:
            raise ValueError(f"cell {cell_id} at max depth")
        return self.splits | {cell_id}

    def merge_result(self, parent_id: int) -> frozenset[int]:
        """Split set after merging ``parent_id``'s children back."""
        if parent_id not in self.splits:
            raise ValueError(f"cell {parent_id} is not split")
        for c in self.children(parent_id):
            if c in self.splits:
                raise ValueError(
                    f"child {c} of {parent_id} is itself split")
        return self.splits - {parent_id}

"""SpatialController: the pluggable spatial-partition boundary.

Capability parity with the reference (ref: pkg/channeld/spatial.go:17-74).
One process-wide controller instance is selected from a JSON config; the
static-grid host implementation lives in ``grid.py`` and the TPU-backed
implementation in ``tpu_controller.py`` — both plug in behind this seam
without touching the protocol path.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Callable, Optional, Protocol

from ..utils.logger import get_logger

logger = get_logger("spatial")


@dataclass
class SpatialInfo:
    """World position, left-handed Y-up (ref: channeld.proto SpatialInfo)."""

    x: float = 0.0
    y: float = 0.0
    z: float = 0.0


HandoverDataProvider = Callable[[int, int], Optional[dict]]
# (src_channel_id, dst_channel_id) -> {entityId: data message}


class SpatialController(Protocol):
    """(ref: spatial.go:17-35)."""

    def load_config(self, config: dict) -> None: ...
    def get_channel_id(self, info: SpatialInfo) -> int: ...
    def get_regions(self) -> list: ...
    def get_adjacent_channels(self, channel_id: int) -> list[int]: ...
    def query_channel_ids(self, query) -> dict[int, int]: ...
    def get_channel_id_with_offset(self, info: SpatialInfo, dx: float, dy: float, dz: float) -> int: ...
    def create_channels(self, ctx) -> list: ...
    def tick(self) -> None: ...
    def notify(self, old_info: SpatialInfo, new_info: SpatialInfo, handover_data_provider) -> None: ...


_spatial_controller: Optional[SpatialController] = None

# Name -> class, for config-selected controllers
# (ref: spatial.go:65-69 type switch on SpatialControllerType).
_controller_registry: dict[str, type] = {}


def register_spatial_controller_type(name: str, cls: type) -> None:
    _controller_registry[name] = cls


def get_spatial_controller() -> Optional[SpatialController]:
    return _spatial_controller


def set_spatial_controller(controller: Optional[SpatialController]) -> None:
    global _spatial_controller
    _spatial_controller = controller


def init_spatial_controller(config_path: Optional[str] = None) -> None:
    """Load the controller named in the config JSON
    (ref: spatial.go:40-74). No config -> no spatial features."""
    global _spatial_controller
    if config_path is None:
        from ..core.settings import global_settings

        config_path = global_settings.spatial_controller_config
    if not config_path:
        return
    with open(config_path) as f:
        spec = json.load(f)
    type_name = spec.get("SpatialControllerType", "")
    cls = _controller_registry.get(type_name)
    if cls is None:
        raise ValueError(f"unknown SpatialControllerType: {type_name}")
    controller = cls()
    controller.load_config(spec.get("Config", {}))
    _spatial_controller = controller
    logger.info("initialized spatial controller %s", type_name)


def reset_spatial_controller() -> None:
    """Test hook."""
    global _spatial_controller
    _spatial_controller = None

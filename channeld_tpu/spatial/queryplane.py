"""Device-native standing-query plane (doc/query_engine.md).

Every standing interest a gateway serves — entity-follow AOI, client
``UpdateSpatialInterestMessage`` queries, and the server-facing sensor
API — becomes ONE row in the engine's device query table. Per tick the
engine evaluates every row's cell-interest mask in the existing batched
AOI pass, diffs against the committed baseline ON DEVICE
(ops/spatial_ops.diff_query_masks) and compacts the delta to changed
``(query_row, cell, dist)`` rows; the host consumes them in ONE
transfer and drives the existing sub/unsub machinery through
``apply_interest_diff`` — host work is O(changed rows), never
O(standing queries).

The plane keeps a host MIRROR per engine row ({micro_cell: dist},
reconstructed purely from changed rows) so an apply pass always hands
``apply_interest_diff`` the query's FULL desired set — the diff against
``conn.spatial_subscriptions`` then yields exactly the sub/unsub delta,
and a full-resync (engine query epoch moved: device-guard rebuild or
geometry epoch threw the diff baseline away) is just "clear mirrors,
mark everything pending" with the device re-emitting every row against
its empty baseline.

Registrations journal to the WAL (``query`` records) and ride the
snapshot + the federation epoch replica next to staged handles: sensor
rows survive kill -9 and shard adoption; connection-scoped rows
(follow/client) are bound to sockets that did not survive, so replay
drops them with an exact count.

Double-entry discipline: every metric this plane increments has a
python-side ledger entry (``QueryPlane.ledgers``) that must match —
soak/bench invariant gates compare the two.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from ..core import metrics
from ..core.settings import global_settings
from ..ops.spatial_ops import AOI_NONE, AOI_SPHERE, AOI_SPOTS
from ..utils.logger import get_logger

logger = get_logger("spatial.queryplane")

# Sensor keys live far above any real connection id (conn ids are dense
# small ints): the engine query table is keyed by "conn id", and sensors
# are server-side rows with no connection.
SENSOR_KEY_BASE = 1 << 30

_SCOPES = ("follow", "client", "sensor")


def pack_params(center, extent, direction, angle, spots=None) -> list:
    """Flatten one registration's geometry for WAL/snapshot/replica
    transport: [cx, cz, ex, ez, dx, dz, angle, spot0x, spot0z, ...]."""
    params = [
        float(center[0]), float(center[1]),
        float(extent[0]), float(extent[1]),
        float(direction[0]), float(direction[1]),
        float(angle),
    ]
    for s in spots or []:
        params.extend((float(s[0]), float(s[1])))
    return params


def unpack_params(params) -> tuple:
    """Inverse of pack_params: (center, extent, direction, angle, spots)."""
    p = list(params) + [0.0] * max(0, 7 - len(params))
    spots = [(p[i], p[i + 1]) for i in range(7, len(p) - 1, 2)]
    return (p[0], p[1]), (p[2], p[3]), (p[4], p[5]), p[6], spots


class QueryPlane:
    """Registry + changed-rows consumer over one SpatialEngine."""

    def __init__(self, controller, engine):
        self.controller = controller
        self.engine = engine
        engine.query_rows_max = global_settings.queryplane_rows_max
        engine.track_query_changes = True
        # key -> registration entry. Keys are connection ids for
        # follow/client scopes (one engine row per connection — a plain
        # query replaces a follow and vice versa, the reference's
        # semantics) and synthetic ids >= SENSOR_KEY_BASE for sensors.
        self._entries: dict[int, dict] = {}
        # engine row -> key (the changed rows cite engine rows).
        self._key_of_row: dict[int, int] = {}
        # engine row -> {micro_cell: dist}: the host mirror of the
        # device's committed interest, rebuilt purely from changed rows.
        self._mirror: dict[int, dict[int, int]] = {}
        # Keys whose mirror changed since their last apply pass.
        self._pending: set[int] = set()
        self._epoch_seen = engine.query_epoch
        self._sensor_next = SENSOR_KEY_BASE
        # Double-entry ledgers; each must equal its metric exactly.
        self.ledgers = {
            "rows_changed": 0,    # == query_rows_changed_total
            "transfers": 0,       # == query_plane_transfers_total
            "full_resyncs": 0,    # == query_full_resyncs_total
            "applies": 0,         # apply passes run (no metric; bench)
            "reaped": 0,          # rows reaped on connection churn
            "replay_dropped": 0,  # conn-scoped rows dropped at replay
        }

    # ---- registry --------------------------------------------------------

    def count(self) -> int:
        return len(self._entries)

    def _scope_gauges(self) -> None:
        counts = dict.fromkeys(_SCOPES, 0)
        for e in self._entries.values():
            counts[e["scope"]] += 1
        for scope, n in counts.items():
            metrics.standing_queries.labels(scope=scope).set(n)

    def _install(self, key: int, entry: dict, journal: bool) -> None:
        row = self.engine.query_row_of_conn(key)
        if row is None:  # engine rejected the row (shouldn't happen here)
            return
        old_key = self._key_of_row.get(row)
        if old_key is not None and old_key != key:
            # Freed row reused: the engine zeroed its diff baseline
            # (_q_prev_reset_rows), so the mirror restarts empty too —
            # the next tick full-emits the new query's cells.
            self._mirror.pop(row, None)
        self._key_of_row[row] = key
        entry["row"] = row
        self._entries[key] = entry
        self._pending.add(key)
        self._scope_gauges()
        if journal:
            self._journal(key, entry, op="set")

    def _journal(self, key: int, entry: dict, op: str) -> None:
        from ..core.wal import wal

        wal.log_query(
            op=op, key=key, scope=entry["scope"],
            name=entry.get("name", ""), kind=entry.get("kind", AOI_NONE),
            params=pack_params(
                entry.get("center", (0.0, 0.0)),
                entry.get("extent", (0.0, 0.0)),
                entry.get("direction", (1.0, 0.0)),
                entry.get("angle", 0.0),
                entry.get("spots"),
            ),
            spot_dists=entry.get("dists") or [],
        )

    def bind_follow(self, conn, entity_id: int, kind: int, center, extent,
                    direction, angle) -> None:
        """Adopt a follow row the controller just wrote into the engine
        (register_follow_interest stays the single writer for follows —
        it owns re-centering and the shed policy)."""
        self._install(conn.id, {
            "scope": "follow", "conn": conn, "entity": entity_id,
            "kind": kind, "center": tuple(center), "extent": tuple(extent),
            "direction": tuple(direction), "angle": float(angle),
        }, journal=True)

    def register_client(self, conn, kind: int, center, extent=(0.0, 0.0),
                        direction=(1.0, 0.0), angle: float = 0.0) -> bool:
        """A client's geometric standing query: the host path already
        applied the initial interest synchronously (handler semantics
        unchanged); this row keeps it live — geometry epochs, rebuilds
        and damping-distance drift re-apply with no client round trip."""
        try:
            self.engine.set_query(conn.id, kind, tuple(center),
                                  tuple(extent), tuple(direction),
                                  float(angle))
        except RuntimeError:
            self.controller._shed("query", f"conn {conn.id} client query")
            return False
        self._install(conn.id, {
            "scope": "client", "conn": conn, "kind": kind,
            "center": tuple(center), "extent": tuple(extent),
            "direction": tuple(direction), "angle": float(angle),
        }, journal=True)
        return True

    def register_client_spots(self, conn, spots, dists) -> bool:
        try:
            self.engine.set_spots_query(conn.id, list(spots),
                                        list(dists) if dists else None)
        except RuntimeError:
            self.controller._shed("query", f"conn {conn.id} spots query")
            return False
        self._install(conn.id, {
            "scope": "client", "conn": conn, "kind": AOI_SPOTS,
            "spots": [tuple(s) for s in spots],
            "dists": list(dists) if dists else None,
        }, journal=True)
        return True

    def register_sensor(
        self,
        name: str,
        kind: int = AOI_SPHERE,
        center=(0.0, 0.0),
        extent=(0.0, 0.0),
        direction=(1.0, 0.0),
        angle: float = 0.0,
        spots=None,
        dists=None,
        callback: Optional[Callable[[int, dict], None]] = None,
        key: Optional[int] = None,
        journal: bool = True,
    ) -> Optional[int]:
        """Server-facing standing sensor: a named query row with no
        connection. Its interest set ({leaf_channel: dist}) refreshes
        from changed rows like any other query; consumers either poll
        ``sensor_cells(key)`` or get ``callback(key, cells)`` on every
        change. Returns the sensor key, or None when the table is full
        (shed, never raise — same policy as follows)."""
        if key is None:
            key = self._sensor_next
            self._sensor_next += 1
        else:
            self._sensor_next = max(self._sensor_next, key + 1)
        try:
            if spots is not None:
                self.engine.set_spots_query(key, list(spots),
                                            list(dists) if dists else None)
            else:
                self.engine.set_query(key, kind, tuple(center),
                                      tuple(extent), tuple(direction),
                                      float(angle))
        except RuntimeError:
            self.controller._shed("query", f"sensor {name!r}")
            return None
        entry = {
            "scope": "sensor", "conn": None, "name": name, "kind": kind,
            "center": tuple(center), "extent": tuple(extent),
            "direction": tuple(direction), "angle": float(angle),
            "callback": callback, "cells": {},
        }
        if spots is not None:
            entry["kind"] = AOI_SPOTS
            entry["spots"] = [tuple(s) for s in spots]
            entry["dists"] = list(dists) if dists else None
        self._install(key, entry, journal=journal)
        return key

    def deregister(self, key: int, reaped: bool = False) -> bool:
        """Drop a standing query: free the engine row (its diff baseline
        is zeroed, so the row emits nothing for its next owner) and
        synchronously unsubscribe a still-open connection — the mirror
        dies with the row, so there is no async removal stream to wait
        for."""
        entry = self._entries.pop(key, None)
        if entry is None:
            return False
        row = self.engine.query_row_of_conn(key)
        self.engine.remove_query(key)
        if row is not None:
            self._mirror.pop(row, None)
            if self._key_of_row.get(row) == key:
                del self._key_of_row[row]
        self._pending.discard(key)
        conn = entry.get("conn")
        if conn is not None and not conn.is_closing():
            from .messages import apply_interest_diff

            apply_interest_diff(conn, {})
        if reaped:
            self.ledgers["reaped"] += 1
        self._scope_gauges()
        self._journal(key, entry, op="remove")
        return True

    def reap_closed(self) -> None:
        """Connection-churn discipline (bounded registry): a closed
        connection's standing rows must not stay in the device pass
        forever. Follow rows are reaped by the controller's follower
        walk; this covers client-scope rows."""
        for key, entry in list(self._entries.items()):
            conn = entry.get("conn")
            if conn is not None and conn.is_closing():
                self.deregister(key, reaped=True)

    def sensor_cells(self, key: int) -> dict[int, int]:
        """Last-applied {leaf_channel_id: grid_distance} for a sensor."""
        entry = self._entries.get(key)
        return dict(entry.get("cells", {})) if entry else {}

    # ---- the per-tick pass ----------------------------------------------

    def pump(self, result: dict, apply: bool = True) -> None:
        """Consume this tick's changed rows and (unless deferred by the
        overload ladder) run the apply pass. Consume ALWAYS drains: the
        device committed its new baseline when the tick ran, so a blob
        left unconsumed is a permanently lost delta."""
        t0 = time.monotonic()
        self._consume(result)
        if apply:
            self._apply_pending()
        metrics.query_pass_ms.observe((time.monotonic() - t0) * 1000.0)

    def _consume(self, result: dict) -> None:
        epoch = result.get("query_epoch", self.engine.query_epoch)
        if epoch != self._epoch_seen:
            # The engine threw its diff baseline away (device-guard
            # rebuild / geometry epoch): the delta stream no longer
            # connects to our mirrors. Restart them empty — this very
            # result's rows are the device's full re-emission against
            # its fresh baseline — and re-apply every registration
            # (after a geometry epoch the micro->leaf collapse changed
            # even for cells whose micro mask did not).
            self._epoch_seen = epoch
            self._mirror.clear()
            self._pending.update(self._entries.keys())
            self.ledgers["full_resyncs"] += 1
            metrics.query_full_resyncs.inc()
        count, rows = self.engine.query_changed_rows(result)
        self.ledgers["transfers"] += 1
        metrics.query_plane_transfers.inc()
        consumed = 0
        for q, c, d in rows[: min(count, len(rows))].tolist():
            if q < 0:
                continue  # compaction discard lane
            mirror = self._mirror.setdefault(q, {})
            if d < 0:
                mirror.pop(c, None)
            else:
                mirror[c] = d
            consumed += 1
            key = self._key_of_row.get(q)
            if key is not None:
                self._pending.add(key)
        if consumed:
            self.ledgers["rows_changed"] += consumed
            metrics.query_rows_changed.inc(consumed)

    def _apply_pending(self) -> None:
        from .messages import apply_interest_diff

        while self._pending:
            key = self._pending.pop()
            entry = self._entries.get(key)
            if entry is None:
                continue
            row = self.engine.query_row_of_conn(key)
            desired = self._mirror.get(row, {}) if row is not None else {}
            wanted = self.controller.collapse_micro_cells(desired)
            self.ledgers["applies"] += 1
            if entry["scope"] == "sensor":
                entry["cells"] = wanted
                cb = entry.get("callback")
                if cb is not None:
                    try:
                        cb(key, dict(wanted))
                    except Exception:
                        logger.exception(
                            "sensor %r callback failed", entry.get("name")
                        )
            else:
                conn = entry.get("conn")
                if conn is None or conn.is_closing():
                    continue  # reap will free the row
                apply_interest_diff(conn, wanted)

    # ---- persistence / replication --------------------------------------

    def snapshot_rows(self) -> list[tuple]:
        """Every registration as (key, scope, name, kind, params,
        spot_dists) — the WAL/snapshot/replica transport shape."""
        out = []
        for key, e in self._entries.items():
            out.append((
                key, e["scope"], e.get("name", ""),
                int(e.get("kind", AOI_NONE)),
                pack_params(
                    e.get("center", (0.0, 0.0)), e.get("extent", (0.0, 0.0)),
                    e.get("direction", (1.0, 0.0)), e.get("angle", 0.0),
                    e.get("spots"),
                ),
                list(e.get("dists") or []),
            ))
        return out

    def restore_rows(self, rows, source: str) -> tuple[int, int]:
        """Re-register persisted/adopted rows (WAL replay, snapshot
        restore, shard adoption). Sensor rows re-register (no callback —
        consumers poll ``sensor_cells`` or re-attach one); follow/client
        rows are bound to connections that did not survive the restart,
        so they drop with an exact count. Returns (restored, dropped)."""
        restored = dropped = 0
        for key, scope, name, kind, params, spot_dists in rows:
            if scope != "sensor":
                dropped += 1
                continue
            center, extent, direction, angle, spots = unpack_params(params)
            got = self.register_sensor(
                name=name, kind=int(kind), center=center, extent=extent,
                direction=direction, angle=angle,
                spots=spots if int(kind) == AOI_SPOTS else None,
                dists=list(spot_dists) if spot_dists else None,
                key=int(key), journal=False,
            )
            if got is not None:
                restored += 1
        self.ledgers["replay_dropped"] += dropped
        if restored or dropped:
            logger.info(
                "query plane %s: %d sensor registrations restored, "
                "%d connection-scoped rows dropped", source, restored,
                dropped,
            )
        return restored, dropped


def restore_registrations(rows, source: str = "wal") -> tuple[int, int]:
    """Module-level restore hook for boot replay: find the live TPU
    controller's plane and hand it the persisted rows. (0, 0) when the
    gateway runs the host backend or the plane is disabled — the rows
    are simply not re-registered, never an error."""
    from .controller import get_spatial_controller

    controller = get_spatial_controller()
    plane = getattr(controller, "queryplane", None)
    if plane is None:
        return 0, 0
    return plane.restore_rows(rows, source)

"""Entity channels: handover/lock groups (ref: pkg/channeld/entity.go).

Groups are *shared instances* cascaded across member entity channels: when
entity A adds B to its handover group, B's controller adopts the same group
object, so later members join everyone's group at once. A LOCK group beats
HANDOVER — if any member of the handover group is locked, no handover
happens at all.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Optional

from ..utils.logger import get_logger
from ..core.types import EntityGroupType

if TYPE_CHECKING:
    from ..core.channel import Channel

logger = get_logger("entity")


class EntityGroup:
    def __init__(self):
        self.entity_ids: set[int] = set()

    def add_group(self, other: Optional["EntityGroup"]) -> None:
        if other is not None:
            self.entity_ids |= other.entity_ids


class FlatEntityGroupController:
    """Single-layer handover/lock grouping (ref: entity.go:58-224)."""

    def __init__(self):
        self.entity_id = 0
        self.handover_group: Optional[EntityGroup] = None
        self.lock_group: Optional[EntityGroup] = None

    def initialize(self, ch: "Channel") -> None:
        self.entity_id = ch.id

    def uninitialize(self, ch: "Channel") -> None:
        from ..core.types import ChannelType

        if ch.channel_type != ChannelType.ENTITY:
            return
        # Drop this entity from groups it may share with other channels.
        for t in (EntityGroupType.HANDOVER, EntityGroupType.LOCK):
            try:
                self.remove_from_group(t, [self.entity_id])
            except ValueError:
                pass

    def cascade_group(self, t: EntityGroupType, group: EntityGroup) -> None:
        """Adopt a shared group instance (ref: entity.go:83-104)."""
        if self.lock_group is not None and self.lock_group.entity_ids:
            return  # locked entities don't cascade
        if t == EntityGroupType.HANDOVER:
            group.add_group(self.handover_group)
            self.handover_group = group
        elif t == EntityGroupType.LOCK:
            # LOCK outranks HANDOVER: absorb both.
            group.add_group(self.handover_group)
            group.add_group(self.lock_group)
            self.lock_group = group

    def add_to_group(self, t: EntityGroupType, entities_to_add: list[int]) -> None:
        from ..core.channel import get_channel

        if t == EntityGroupType.HANDOVER:
            if self.handover_group is None:
                self.handover_group = EntityGroup()
            group = self.handover_group
        else:
            if self.lock_group is None:
                self.lock_group = EntityGroup()
            group = self.lock_group

        for entity_id in entities_to_add:
            group.entity_ids.add(entity_id)
            ch = get_channel(entity_id)
            if ch is None:
                continue
            if ch.entity_controller is None:
                ch.logger.error("channel has no entity controller")
                continue
            # Every member shares this exact group instance.
            ch.entity_controller.cascade_group(t, group)

    def remove_from_group(self, t: EntityGroupType, entities_to_remove: list[int]) -> None:
        from ..core.channel import get_channel

        group = self.handover_group if t == EntityGroupType.HANDOVER else self.lock_group
        if group is None:
            raise ValueError(f"group {t} is nil, entityId: {self.entity_id}")
        for entity_id in entities_to_remove:
            group.entity_ids.discard(entity_id)
            # The removed entity gets a fresh empty group of its own.
            entity_ch = get_channel(entity_id)
            if entity_ch is not None and entity_ch.entity_controller is not None:
                fresh = EntityGroup()
                if t == EntityGroupType.HANDOVER:
                    entity_ch.entity_controller.handover_group = fresh
                else:
                    entity_ch.entity_controller.lock_group = fresh

    def get_handover_entities(self) -> list[int]:
        """Entities that migrate together; [] if any member is locked
        (ref: entity.go:197-224)."""
        if self.handover_group is None:
            return [self.entity_id]
        locked = self.lock_group.entity_ids if self.lock_group is not None else set()
        result = []
        for entity_id in self.handover_group.entity_ids:
            if entity_id in locked:
                return []
            result.append(entity_id)
        return result


def get_handover_entities(ch: "Channel", notifying_entity_id: int) -> Optional[dict]:
    """entityId -> channel data message for every co-migrating entity
    (ref: entity.go:226-244)."""
    from ..core.channel import get_channel

    if ch.entity_controller is None:
        ch.logger.error("channel has no entity controller")
        return None
    entities: dict[int, object] = {}
    for entity_id in ch.entity_controller.get_handover_entities():
        entity_channel = get_channel(entity_id)
        entities[entity_id] = (
            entity_channel.get_data_message() if entity_channel is not None else None
        )
    return entities


def handle_add_entity_group(ctx) -> None:
    """Owner-only (ref: entity.go:246-269)."""
    from ..protocol import spatial_pb2

    if ctx.connection is not ctx.channel.get_owner():
        logger.error("AddEntityGroupMessage only handled for the channel owner")
        return
    msg = ctx.msg
    if not isinstance(msg, spatial_pb2.AddEntityGroupMessage):
        return
    if ctx.channel.entity_controller is None:
        ctx.channel.logger.error("channel has no entity controller")
        return
    ctx.channel.entity_controller.add_to_group(
        EntityGroupType(msg.type), list(msg.EntitiesToAdd)
    )


def handle_remove_entity_group(ctx) -> None:
    """Owner-only (ref: entity.go:271-294)."""
    from ..protocol import spatial_pb2

    if ctx.connection is not ctx.channel.get_owner():
        logger.error("RemoveEntityGroupMessage only handled for the channel owner")
        return
    msg = ctx.msg
    if not isinstance(msg, spatial_pb2.RemoveEntityGroupMessage):
        return
    if ctx.channel.entity_controller is None:
        ctx.channel.logger.error("channel has no entity controller")
        return
    try:
        ctx.channel.entity_controller.remove_from_group(
            EntityGroupType(msg.type), list(msg.EntitiesToRemove)
        )
    except ValueError as e:
        ctx.channel.logger.error("failed to remove entities from group: %s", e)

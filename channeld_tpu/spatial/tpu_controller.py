"""TPUSpatialController: the device-backed spatial controller.

Config-selected exactly like the static host controller
(ref: spatial.go:65-69 — the SpatialController interface is the plugin
boundary), so ``spatial_static_*.json`` configs choose host vs TPU
without touching the protocol path:

    {"SpatialControllerType": "TPUSpatialController", "Config": {...}}

Inherits all control-plane behavior (channel creation, regions, border
subscriptions, AOI query host semantics) from StaticGrid2DSpatialController
and moves the per-tick *decision plane* onto the device:

- ``notify`` no longer compares cells per entity on the host; it records
  the entity's new position in the SpatialEngine slot arrays.
- Once per GLOBAL-channel tick, one batched device step recomputes cell
  assignment for every entity and compacts boundary crossings; each
  crossing then runs the exact same handover orchestration as the host
  path (owner swap -> entity-table move -> handover fan-out).
"""

from __future__ import annotations

from typing import Callable, Optional

from ..chaos.injector import chaos as _chaos
from ..core.device_guard import guard as _guard
from ..core.failover import journal as _journal
from ..core.overload import governor as _governor
from .balancer import balancer as _balancer
from ..core.settings import global_settings
from ..core.tracing import recorder as _trace
from ..utils.logger import get_logger
from .controller import SpatialInfo, register_spatial_controller_type
from .grid import StaticGrid2DSpatialController

logger = get_logger("spatial.tpu")


class TPUSpatialController(StaticGrid2DSpatialController):
    def __init__(self):
        super().__init__()
        self.engine = None
        # entity id -> provider returning the notifying entity id, captured
        # from the most recent position update (used at batch-detect time).
        self._providers: dict[int, Callable[[int, int], Optional[int]]] = {}
        self._last_positions: dict[int, SpatialInfo] = {}
        # Position before the latest update — the TRUE old position for
        # handover orchestration (logic like the reference's position-delta
        # check, pkg/unreal/handover.go:8-47, needs real coordinates, not
        # a synthetic cell center).
        self._prev_positions: dict[int, SpatialInfo] = {}
        # Auto-following interests (channeld-tpu extension): conn_id ->
        # (connection, follow_entity_id, kind, extent, direction, angle).
        self._followers: dict[int, tuple] = {}
        # Device fan-out plane (ref: data.go:175-291 — hot loop #2, now
        # batched). Due decisions are published into per-channel pending
        # queues (slot -> engine seq) so each spatial channel consumes
        # exactly its own due set — O(own due) per tick, and a decision a
        # channel hasn't consumed yet survives subsequent engine ticks
        # (the device advances the sub's window unconditionally, so a
        # dropped bit would silently slip that sub's fan-out a full
        # interval).
        self._due_seq = 0
        self._slot_channel: dict[int, int] = {}
        self._due_pending: dict[int, dict[int, int]] = {}  # ch_id -> {slot: seq}
        self._device_sub_count = 0
        self._shed_logged: dict[str, float] = {}  # table -> last log time
        self._overflow_logged = -1e9
        # Overload deferrals (doc/overload.md): crossings past the L2+
        # per-tick orchestration cap wait here, keyed by entity so a
        # chain of deferred moves collapses into ONE crossing from the
        # cell the entity's channel data actually lives in to its
        # current cell (bounded at one entry per entity, never stale:
        # old_info stays pinned to the last orchestrated cell while
        # new_info follows the entity). Follower-interest passes
        # alternate ticks at L2+.
        self._deferred_crossings: dict[int, tuple] = {}
        self._follow_skip = False
        # _data_cell: inherited — the placement ledger lives on the
        # base grid controller (host gateways need the same exactness).
        # Device micro grid (adaptive partitioning, doc/partitioning.md):
        # the engine always serves a UNIFORM grid — the cell tree's
        # micro grid at its deepest active split. Device cell indices
        # are micro indices; ``_micro_leaf`` maps each back to the leaf
        # channel that owns it. With no splits the micro grid IS the
        # base grid and the mapping is identity — the legacy path
        # bit-for-bit.
        self._mcols = 0
        self._mrows = 0
        self._mw = 0.0
        self._mh = 0.0
        self._micro_leaf: Optional[list[int]] = None
        # Standing-query plane (spatial/queryplane.py;
        # doc/query_engine.md): None = disabled, the legacy per-follower
        # batch-readback path serves follows and client queries stay
        # host-evaluated per message.
        self.queryplane = None
        # Simulation plane (channeld_tpu/sim; doc/simulation.md): None =
        # disabled, no agent population, every hook below is one None
        # check.
        self.simplane = None

    def load_config(self, config: dict) -> None:
        super().load_config(config)
        from ..ops.engine import SpatialEngine
        from ..ops.spatial_ops import GridSpec

        # channel_removed -> untrack_entity is registered by the base
        # grid controller's load_config (polymorphic: the device-side
        # cleanup in our untrack_entity override still runs).

        # Mesh selection: the controller Config's MeshDevices/MeshHosts keys
        # win over the -mesh-devices/-mesh-hosts flags. With a mesh, the
        # live serving engine runs the shard_map step over the device mesh
        # — the gateway-facing results are identical (pinned by
        # test_ops.py::test_engine_mesh_matches_single_device).
        from ..parallel.mesh import mesh_from_config

        mesh = mesh_from_config(
            int(config.get("MeshDevices", global_settings.tpu_mesh_devices)),
            int(config.get("MeshHosts", global_settings.tpu_mesh_hosts)),
        )
        if mesh is not None:
            logger.info("spatial engine meshed over %s", mesh)

        # Sharding selection: Config {"Sharding": "cells"} serves from the
        # space-partitioned plane (all_to_all redistribution + column-block
        # AOI + ring halos); default "entities" is the psum plane. Only
        # meaningful with a mesh.
        self._refresh_micro()
        self.engine = SpatialEngine(
            GridSpec(
                offset_x=self.world_offset_x,
                offset_z=self.world_offset_z,
                cell_w=self._mw,
                cell_h=self._mh,
                cols=self._mcols,
                rows=self._mrows,
            ),
            entity_capacity=global_settings.tpu_entity_capacity,
            query_capacity=global_settings.tpu_query_capacity,
            mesh=mesh,
            sharding=str(config.get("Sharding", "entities")),
            cell_bucket=int(config.get("CellBucket", 0)),
            query_rows_max=global_settings.queryplane_rows_max,
        )
        if global_settings.queryplane_enabled:
            from .queryplane import QueryPlane

            # Created BEFORE warmup so the warmup tick also compiles the
            # on-device diff/compaction step.
            self.queryplane = QueryPlane(self, self.engine)
        self.engine.warmup()  # compile before listeners open (see warmup)
        if global_settings.sim_enabled and mesh is None:
            # On-device world simulation (channeld_tpu/sim;
            # doc/simulation.md): spawn/restore the agent population and
            # pre-compile the sim kernel — after warmup so the spatial
            # step's compile cost is already paid, still before
            # listeners open. The sim kernel is single-device; a meshed
            # engine skips the plane (documented in doc/simulation.md).
            from ..sim.plane import SimPlane

            self.simplane = SimPlane(self, self.engine)
            self.simplane.activate()

    # ---- decision plane --------------------------------------------------

    def _shed(self, table: str, detail: str) -> None:
        """Capacity-overflow policy: degrade visibly, never raise into the
        channel tick (a full world must keep ticking). Metric always;
        security log throttled per table (the shed condition repeats
        every update while the table stays full)."""
        import time as _time

        from ..core import metrics
        from ..utils.logger import security_logger

        metrics.tpu_capacity_shed.labels(table=table).inc()
        now = _time.monotonic()
        if now - self._shed_logged.get(table, -1e9) >= 5.0:
            self._shed_logged[table] = now
            security_logger().warning(
                "device %s table full: %s (degraded to host path; "
                "tpu_capacity_shed counts every occurrence)", table, detail
            )

    def notify(self, old_info, new_info, handover_data_provider) -> None:
        """Record the movement; detection happens in the batched tick."""
        entity_id = handover_data_provider(-1, -1)
        if entity_id is None:
            return
        if self.engine.slot_of_entity(entity_id) is None:
            # No device slot — first sighting, OR a previously shed entity
            # being re-adopted after capacity freed. Either way the slot's
            # prev-cell must be seeded from the *old* position, or this
            # very crossing is undetectable (detect_handovers needs
            # old_cell >= 0).
            try:
                slot = self.engine.add_entity(
                    entity_id, new_info.x, new_info.y, new_info.z
                )
            except RuntimeError:
                # Entity table full: this entity's handovers run the host
                # orchestration per-notify (the reference's only path,
                # spatial.go:612-626) until slots free up.
                self._shed("entity", f"entity {entity_id}")
                StaticGrid2DSpatialController.notify(
                    self, old_info, new_info, handover_data_provider
                )
                return
            try:
                self.engine.seed_cell(slot, self._micro_index(old_info))
            except ValueError:
                pass  # old position outside the world: no baseline
        try:
            self.engine.update_entity(
                entity_id, new_info.x, new_info.y, new_info.z
            )
        except RuntimeError:
            # Tracked host-side but shed from the device table earlier
            # (track_entity at capacity): host orchestration per-notify.
            self._shed("entity", f"entity {entity_id}")
            StaticGrid2DSpatialController.notify(
                self, old_info, new_info, handover_data_provider
            )
            return
        prev = self._last_positions.get(entity_id)
        if prev is None and old_info is not None:
            prev = old_info  # first sighting: the caller's old position
        if prev is not None:
            self._prev_positions[entity_id] = prev
        if entity_id not in self._data_cell and old_info is not None:
            # Authoritative placement ledger: the entity's channel data
            # lives where it was before this move. Seeded here (and in
            # track_entity) so even the FIRST crossing orchestrates from
            # the true cell — under cells-plane bucket overflow the
            # engine can report a crossing with a stale src, and a
            # remove aimed at the wrong channel leaves a duplicate.
            try:
                self._data_cell[entity_id] = self.get_channel_id(old_info)
            except ValueError:
                pass
        self._last_positions[entity_id] = new_info
        self._providers[entity_id] = handover_data_provider

    def _seed_baseline_cell(self, entity_id: int, info: SpatialInfo) -> None:
        """Set the device prev-cell for a just-sighted entity so a crossing
        in the same tick window starts from a real baseline, not -1."""
        slot = self.engine.slot_of_entity(entity_id)
        if slot is None:
            return
        try:
            self.engine.seed_cell(slot, self._micro_index(info))
        except ValueError:
            pass  # outside the world: no baseline

    def observe_entity(self, entity_id: int, info: SpatialInfo,
                       handover_data_provider=None) -> None:
        """Register/update an entity WITHOUT the handover path — fired by
        entity merges whose position didn't change (the reference never
        Notifies on an unmoved update, but this controller's tracking and
        follow-interest centering are fed by updates, so a stationary
        entity must still be seen)."""
        # Slot-existence, not host tracking: a shed entity being re-adopted
        # after capacity freed needs its baseline seeded like a first
        # sighting (an unseeded prev-cell of -1 hides its next crossing).
        fresh_slot = self.engine.slot_of_entity(entity_id) is None
        try:
            self.engine.update_entity(entity_id, info.x, info.y, info.z)
        except RuntimeError:
            self._shed("entity", f"entity {entity_id}")
        else:
            if fresh_slot:
                self._seed_baseline_cell(entity_id, info)
        self._last_positions.setdefault(entity_id, info)
        if handover_data_provider is not None:
            self._providers.setdefault(entity_id, handover_data_provider)

    def track_entity(self, entity_id: int, info: SpatialInfo) -> None:
        try:
            self.engine.add_entity(entity_id, info.x, info.y, info.z)
        except RuntimeError:
            # Stays host-tracked: follow centering and handover still work
            # (notify degrades per-entity); the world keeps ticking.
            self._shed("entity", f"entity {entity_id}")
        try:
            self._data_cell.setdefault(entity_id, self.get_channel_id(info))
        except ValueError:
            pass  # outside the world: no authoritative placement yet
        self._last_positions[entity_id] = info

    def untrack_entity(self, entity_id: int) -> None:
        self.engine.remove_entity(entity_id)
        self._last_positions.pop(entity_id, None)
        self._prev_positions.pop(entity_id, None)
        self._providers.pop(entity_id, None)
        self._deferred_crossings.pop(entity_id, None)
        # Shared cleanup (placement ledger, journal, balancer freezes)
        # lives on the base grid controller.
        super().untrack_entity(entity_id)

    # on_cell_rehosted / _note_entity_data_moved: inherited — the
    # placement ledger lives on the base grid controller now (host
    # gateways need the same exactness; doc/global_control.md).

    def entity_position(self, entity_id: int):
        """Partition-plane hook: the split commit sorts residents into
        child quadrants by last known position (None -> deterministic
        center-child fallback)."""
        info = self._last_positions.get(entity_id)
        return (info.x, info.z) if info is not None else None

    # ---- device micro grid (adaptive partitioning) -----------------------

    def _refresh_micro(self) -> None:
        """Recompute the micro grid spec + micro->leaf map from the cell
        tree. Depth 0 (or no tree) degenerates to the base grid with an
        identity mapping."""
        tree = getattr(self, "tree", None)
        if tree is None:
            self._mcols, self._mrows = self.grid_cols, self.grid_rows
            self._mw, self._mh = self.grid_width, self.grid_height
            self._micro_leaf = None
            return
        _d, mcols, mrows, mw, mh = tree.micro_spec()
        self._mcols, self._mrows = mcols, mrows
        self._mw, self._mh = mw, mh
        self._micro_leaf = tree.micro_to_leaf() if tree.splits else None

    def _micro_index(self, info) -> int:
        """Device (micro) cell index of a world position; ValueError
        outside the grid. Divide-then-floor, matching the device's
        assign_cells exactly — these values feed device baselines."""
        import math

        col = math.floor((info.x - self.world_offset_x) / self._mw)
        row = math.floor((info.z - self.world_offset_z) / self._mh)
        if not (0 <= col < self._mcols and 0 <= row < self._mrows):
            raise ValueError("position outside the grid")
        return row * self._mcols + col

    def _leaf_of_cell(self, cell: int) -> int:
        """Leaf channel id owning one device micro cell."""
        if self._micro_leaf is not None and 0 <= cell < len(self._micro_leaf):
            return self._micro_leaf[cell]
        return global_settings.spatial_channel_id_start + cell

    def _micro_of_channel(self, ch_id: int, entity_id: int = None) -> int:
        """Device baseline micro cell for an entity whose data lives in
        ``ch_id``: the micro cell of its last position when that still
        lies inside the leaf, else the leaf's center micro cell."""
        tree = getattr(self, "tree", None)
        if tree is None or self._micro_leaf is None:
            return ch_id - global_settings.spatial_channel_id_start
        if entity_id is not None:
            info = self._last_positions.get(entity_id)
            if info is not None:
                try:
                    m = self._micro_index(info)
                    if self._leaf_of_cell(m) == ch_id:
                        return m
                except ValueError:
                    pass
        try:
            x, z = tree.center(ch_id)
        except ValueError:
            return -1
        return self._micro_index(SpatialInfo(x, 0, z))

    def _channel_center(self, ch_id: int) -> SpatialInfo:
        """World-space center of one spatial CHANNEL (any depth)."""
        tree = getattr(self, "tree", None)
        if tree is not None:
            x, z = tree.center(ch_id)
            return SpatialInfo(x, 0, z)
        return self._cell_center(
            ch_id - global_settings.spatial_channel_id_start
        )

    def on_geometry_changed(self) -> None:
        """A geometry epoch committed (spatial/partition.py apply path or
        WAL/snapshot restore): re-mirror the cell tree onto the device.
        A same-depth change only swaps the host-side micro->leaf map; a
        depth change rebuilds the device arrays onto the new micro grid
        through the supervised-rebuild machinery (generation-fenced
        against watchdog-abandoned steps) and verifies the rebuilt
        arrays bit-identical to the host shadow."""
        old = (self._mcols, self._mrows)
        self._refresh_micro()
        if self.engine is None:
            return
        if (self._mcols, self._mrows) == old:
            # Same micro grid; only the leaf mapping moved — but that
            # remap still invalidates the sim plane's FLEE mask (it is
            # keyed by micro index via leaf hits).
            if self.simplane is not None:
                self.simplane.on_geometry()
            return
        from ..core import metrics
        from ..ops.spatial_ops import GridSpec

        seeds = self.rebuild_seed_cells()
        self.engine.apply_grid(
            GridSpec(
                offset_x=self.world_offset_x,
                offset_z=self.world_offset_z,
                cell_w=self._mw,
                cell_h=self._mh,
                cols=self._mcols,
                rows=self._mrows,
            ),
            seeds,
        )
        if self.simplane is not None:
            # Depth change: the device arrays rebuilt onto the new micro
            # grid (agent rows re-uploaded from the host shadow by the
            # same path); re-rasterize the FLEE mask onto it.
            self.simplane.on_geometry()
        errors = self.engine.verify_device_state(seeds)
        metrics.partition_device_rebuilds.labels(
            result="verified" if not errors else "mismatch"
        ).inc()
        if errors:
            logger.error(
                "geometry epoch %d device rebuild NOT bit-identical: %s",
                self.geometry_epoch, "; ".join(errors),
            )
            if _trace.enabled:
                _trace.note_anomaly(
                    "geometry_rebuild_mismatch",
                    f"epoch {self.geometry_epoch}: " + "; ".join(errors),
                    force=True,
                )
        else:
            logger.info(
                "geometry epoch %d: device micro grid now %dx%d "
                "(%.3gx%.3g cells), rebuild verified bit-identical",
                self.geometry_epoch, self._mcols, self._mrows,
                self._mw, self._mh,
            )

    # ---- device supervision hooks (core/device_guard.py) -----------------

    def on_device_fatal(self, cause: str) -> None:
        """The engine just failed fatally. Deferred crossings came from
        a possibly-corrupt engine AND will be re-detected from the
        rebuilt baseline anyway (each entity's data stays in its last
        orchestrated cell; the reseed makes the next tick re-report any
        move since) — dropping them here is lossless and deterministic.
        In-flight journal transactions are host-side channel hops that
        complete on their own; the rebuild seeding honors them via
        ``pending_dst`` (doc/device_recovery.md)."""
        if self._deferred_crossings:
            logger.warning(
                "device %s: dropping %d deferred crossings (re-detected "
                "after rebuild)", cause, len(self._deferred_crossings),
            )
            self._deferred_crossings.clear()

    def rebuild_seed_cells(self) -> dict[int, int]:
        """{engine slot: cell index} baselines for the in-process engine
        rebuild — where each entity's channel data authoritatively
        lives right now. The failover journal's in-flight dst outranks
        the committed ``_data_cell`` ledger (mid-flight, the data is
        bound for the pending dst); entities with neither fall back to
        their last known position (first sighting that never
        orchestrated). The rebuilt engine re-detects any movement since
        from these baselines, so an outage never loses a crossing.

        Cell indices are MICRO-grid indices (identical to base-grid
        indices until a split is live; doc/partitioning.md)."""
        seeds: dict[int, int] = {}
        for entity_id, slot in self.engine.tracked_entities():
            ch_id = _journal.pending_dst(entity_id)
            if ch_id is None:
                ch_id = self._data_cell.get(entity_id)
            if ch_id is None:
                info = self._last_positions.get(entity_id)
                if info is not None:
                    try:
                        ch_id = self.get_channel_id(info)
                    except ValueError:
                        ch_id = None
            seeds[slot] = (
                self._micro_of_channel(ch_id, entity_id)
                if ch_id is not None else -1
            )
        return seeds

    # ---- device fan-out plane --------------------------------------------

    def device_sub_add(
        self, interval_ms: int, delay_ms: int, channel_id: int
    ) -> Optional[int]:
        """Register a spatial-channel subscription in the engine sub table;
        None when the engine isn't up or the table is full (the caller
        falls back to the host time check)."""
        if self.engine is None:
            return None
        try:
            now = self.engine.now_ms()
            slot = self.engine.add_subscription(
                interval_ms, first_due_ms=now + delay_ms
            )
        except RuntimeError:
            return None
        self._slot_channel[slot] = channel_id
        self._device_sub_count += 1
        return slot

    def device_sub_remove(self, slot: int) -> None:
        if self.engine is not None:
            self.engine.remove_subscription(slot)
            ch_id = self._slot_channel.pop(slot, None)
            if ch_id is not None:
                self._due_pending.get(ch_id, {}).pop(slot, None)
            self._device_sub_count -= 1

    def device_sub_set_interval(self, slot: int, interval_ms: int) -> None:
        if self.engine is not None:
            self.engine.set_sub_interval(slot, interval_ms)

    def device_sub_first_fanout(self, slot: int) -> None:
        if self.engine is not None:
            self.engine.reset_sub_clock(slot, self.engine.now_ms())

    def device_due(self, channel_id: int) -> Optional[tuple[int, dict]]:
        """(engine_tick_seq, pending {slot: seq}) for one channel; the
        caller pops entries as it serves them (single consumption). None
        before the first engine tick (host fallback)."""
        if self._due_seq == 0:
            return None
        return self._due_seq, self._due_pending.setdefault(channel_id, {})

    def _publish_due(self, result) -> None:
        import numpy as np

        self._due_seq += 1
        due = np.unpackbits(np.asarray(result["due_packed"]))
        for slot in np.nonzero(due)[0].tolist():
            ch_id = self._slot_channel.get(slot)
            if ch_id is not None:
                self._due_pending.setdefault(ch_id, {})[slot] = self._due_seq

    # ---- auto-following interest (channeld-tpu extension) ----------------

    def register_follow_interest(
        self, conn, follow_entity_id: int, kind: int,
        extent=(0.0, 0.0), direction=(1.0, 0.0), angle: float = 0.0,
    ) -> None:
        """The connection's AOI query tracks ``follow_entity_id`` on device:
        every batched tick re-centers the query on the entity's position
        and re-diffs the spatial subscriptions from the interest mask —
        no per-move UPDATE_SPATIAL_INTEREST messages needed."""
        info = self._last_positions.get(follow_entity_id)
        center = (info.x, info.z) if info is not None else (0.0, 0.0)
        try:
            self.engine.set_query(conn.id, kind, center, extent, direction,
                                  angle)
        except RuntimeError:
            # Query table full: shed the auto-follow — the client keeps
            # whatever explicit interest it has (UPDATE_SPATIAL_INTEREST
            # stays host-served) instead of crashing the handler.
            self._shed("query", f"conn {conn.id} follow {follow_entity_id}")
            return
        self._followers[conn.id] = {
            "conn": conn, "entity": follow_entity_id, "kind": kind,
            "extent": extent, "direction": direction, "angle": angle,
            "center": center,
        }
        if self.queryplane is not None:
            self.queryplane.bind_follow(conn, follow_entity_id, kind,
                                        center, extent, direction, angle)

    def unregister_follow_interest(self, conn_id: int) -> None:
        if self._followers.pop(conn_id, None) is not None:
            if self.queryplane is not None:
                # Frees the engine row AND zeroes its diff baseline —
                # no dead row stays in the batched pass, and a reused
                # row can't leak the old mask (bounded-registry
                # discipline; the row-reuse hazard is pinned by
                # tests/test_queryplane.py churn coverage).
                self.queryplane.deregister(conn_id)
            else:
                self.engine.remove_query(conn_id)

    def _reap_followers(self) -> None:
        from ..spatial.messages import apply_interest_diff

        for conn_id, entry in list(self._followers.items()):
            if entry["conn"].is_closing():
                self.unregister_follow_interest(conn_id)
                continue
            tracked = entry["entity"] in self._last_positions
            if tracked:
                entry["seen"] = True
            elif entry.get("seen"):
                # The followed entity WAS tracked and is now gone
                # (destroyed / untracked): a stale frozen center would
                # stream the wrong cells to the client forever. Drop the
                # interest entirely — the client re-queries (or
                # re-follows) on respawn. A follow registered before the
                # entity's first position update is NOT reaped (grace:
                # "seen" is only set once the entity appears).
                self.unregister_follow_interest(conn_id)
                apply_interest_diff(entry["conn"], {})
        if self.queryplane is not None:
            # Client-scope standing rows ride connections too: reap the
            # closed ones so the device pass stays bounded by LIVE
            # registrations under churn.
            self.queryplane.reap_closed()

    def collapse_micro_cells(self, desired: dict[int, int]) -> dict[int, int]:
        """{micro_cell: dist} -> {leaf_channel_id: dist}. Micro cells
        collapse onto leaf CHANNELS; several micro cells of one leaf ->
        keep the closest distance (interest priority is distance-ranked).
        Identity (+ id offset) while no split is live."""
        start = global_settings.spatial_channel_id_start
        if self._micro_leaf is None:
            return {start + cell: dist for cell, dist in desired.items()}
        wanted: dict[int, int] = {}
        for cell, dist in desired.items():
            ch = self._leaf_of_cell(cell)
            if ch not in wanted or dist < wanted[ch]:
                wanted[ch] = dist
        return wanted

    def _recenter_followers(self) -> None:
        """Re-center each follow query on its entity for the *next*
        tick; skips the query-table write when the entity hasn't moved
        (the table upload is O(capacity))."""
        for conn_id, entry in list(self._followers.items()):
            if entry["conn"].is_closing():
                continue  # _reap_followers owns removal
            info = self._last_positions.get(entry["entity"])
            if info is not None and (info.x, info.z) != entry["center"]:
                self.engine.set_query(
                    conn_id, entry["kind"], (info.x, info.z),
                    entry["extent"], entry["direction"], entry["angle"],
                )
                entry["center"] = (info.x, info.z)

    def register_sensor(self, name: str, **kwargs):
        """Server-facing standing sensor (spatial/queryplane.py): a named
        AOI query with no connection, evaluated in the same batched
        device pass as every follower and client query. Returns the
        sensor key, or None when the plane is disabled or the query
        table is full."""
        if self.queryplane is None:
            return None
        return self.queryplane.register_sensor(name, **kwargs)

    def _apply_follow_interests(self, result) -> None:
        import time as _time

        from ..core import metrics
        from ..spatial.messages import apply_interest_diff

        live: list[int] = []
        for conn_id, entry in list(self._followers.items()):
            conn = entry["conn"]
            if conn.is_closing():
                self.unregister_follow_interest(conn_id)
                continue
            live.append(conn_id)
        self._recenter_followers()
        if not live:
            return
        # ONE device->host transfer of the whole interest/dist tables for
        # every follower (ROADMAP item 1: the per-follower row readback
        # measured ~330us each — linear in followers, the single biggest
        # live-gateway host cost); the per-follower diff runs on host
        # slices. follower_readbacks now counts BATCHED transfers — one
        # per pass, not one per follower.
        rb0 = _time.monotonic_ns()
        desired_all = self.engine.interested_cells_batch(result, live)
        readback_ns = _time.monotonic_ns() - rb0
        metrics.follower_readbacks.inc()
        _trace.stage("readback", rb0, end_ns=rb0 + readback_ns)
        for conn_id in live:
            entry = self._followers.get(conn_id)
            if entry is None:
                continue
            wanted = self.collapse_micro_cells(desired_all.get(conn_id, {}))
            apply_interest_diff(entry["conn"], wanted)

    def tick(self) -> None:
        super().tick()  # reap closed server connections
        if self.engine is None:
            return
        self._reap_followers()  # even with no entities tracked
        # A tick is needed when entities move OR device-registered fan-out
        # subscriptions exist (due decisions come from the engine even for
        # an entity-less spatial world, e.g. pure chat-over-spatial) OR
        # standing queries are registered (a sensor over a static world
        # still needs its first evaluation + epoch re-applies).
        if (self.engine.entity_count() == 0 and self._device_sub_count == 0
                and (self.queryplane is None
                     or self.queryplane.count() == 0)):
            return
        from ..core import metrics

        import time as _time

        t0 = _time.monotonic()
        if _chaos.armed:
            # Chaos: a slow device dispatch (compilation hiccup, busy
            # chip, thermal step-down). The tick must absorb it —
            # degradation shows in tpu_step_latency / tick p99, never as
            # an exception into the channel tick.
            stall = _chaos.stall_s("device.dispatch_stall")
            if stall:
                _time.sleep(stall)  # tpulint: disable=async-blocking -- chaos-injected dispatch stall MODELS a busy chip stalling the tick (doc/chaos.md); blocking is the point
        if self.simplane is not None:
            # Sim cadence/chaos decisions for THIS tick (sets the
            # engine's run_sim_pass/sim_census_due flags; the agent step
            # itself runs inside the guarded device tick below).
            self.simplane.pre_step()
        if _guard.enabled:
            # Supervised step (doc/device_recovery.md): watchdog +
            # transient retry + sentinel + in-process rebuild. None =
            # the engine is down/held this tick — every device-
            # dependent stage below (due publish, crossing
            # orchestration, follower pass) waits; host-side work
            # (server reaping, follower registry upkeep) already ran.
            result = _guard.run_step(self)
            if result is None:
                return
        else:
            result = self.engine.tick()
        handovers = self.engine.handover_list(result)
        metrics.tpu_step_latency.observe(_time.monotonic() - t0)
        # Same window as tpu_step_latency: dispatch + device step + the
        # handover-list readback.
        _trace.stage("device_step", int(t0 * 1e9))
        metrics.tpu_entities.set(self.engine.entity_count())
        if "overflow" in result:
            # Cells-plane bucket overflow: the undelivered entities stay
            # in the ingest arrays and are re-offered next tick; surface
            # the shed so a sustained overflow is operator-visible.
            overflow = self.engine.last_overflow
            metrics.tpu_cell_overflow.set(overflow)
            if overflow:
                # Cumulative counter so a soak can assert the shed path
                # actually fired even when the final tick was clean.
                metrics.tpu_cell_overflow_total.inc(overflow)
            if overflow and _time.monotonic() - self._overflow_logged >= 5.0:
                self._overflow_logged = _time.monotonic()
                from ..utils.logger import security_logger

                security_logger().warning(
                    "cells-plane bucket overflow: %d entities undelivered "
                    "this tick (slots %s...), re-offered next tick",
                    overflow, self.engine.undelivered_slots(result)[:8],
                )
        if self.simplane is not None:
            # Census-cadence absorb/journal/commit (a no-op on every
            # non-census tick beyond one counter diff).
            self.simplane.on_result(result)
        self._publish_due(result)
        if handovers or self._deferred_crossings:
            # Batched orchestration: one owner-swap/remove-add/fan-out
            # pass per (src,dst) cell pair, not per crossing — the device
            # detects ~1.5K crossings per tick and per-crossing host
            # orchestration measured 3.9x slower than the detection rate
            # (scripts/bench_handover.py).
            pending = self._deferred_crossings
            for e, s, d in handovers:
                if (self._micro_leaf is not None
                        and self._leaf_of_cell(s) == self._leaf_of_cell(d)):
                    # Intra-leaf micro crossing: the device grid is finer
                    # than the channel geometry here (an unsplit neighbor
                    # pins the micro depth); no channel boundary crossed,
                    # nothing to orchestrate.
                    continue
                if (self.simplane is not None
                        and self.engine.is_agent(e)
                        and not self.simplane.authority.is_backed(e)):
                    # Engine-only agent (past the sim_channel_agents
                    # cap, or its cell channel is still booting): no
                    # channel data lives anywhere, so there is nothing
                    # to orchestrate — the device cell tracking alone is
                    # authoritative for it (doc/simulation.md).
                    continue
                prev = pending.get(e)
                if prev is not None:
                    # Chain: the entity's data still lives where the
                    # first deferred crossing left from; keep that
                    # origin, orchestrate straight to the newest
                    # destination (in-place update preserves the
                    # entry's FIFO position).
                    _, new_info, provider = self._build_crossing(e, s, d)
                    pending[e] = (prev[0], new_info, provider)
                    continue
                old_info, new_info, provider = self._build_crossing(e, s, d)
                # The transactional journal outranks the committed
                # ledger: mid-flight, the entity's data is bound for the
                # pending dst even though _data_cell still says src
                # (it only flips on commit, in the dst cell's tick).
                pend_dst = _journal.pending_dst(e)
                if pend_dst is not None:
                    if pend_dst == self._leaf_of_cell(d):
                        # Stale re-detection of the in-flight move.
                        continue
                    # Chained hop: orchestrate from where the in-flight
                    # txn will land (FIFO on that channel's queue puts
                    # the new remove after the pending add).
                    pending[e] = (
                        self._channel_center(pend_dst),
                        new_info, provider,
                    )
                    continue
                known = self._data_cell.get(e)
                if known is not None:
                    if known == self._leaf_of_cell(d):
                        # Stale re-detection (cells-plane re-offer): the
                        # data already lives in the destination.
                        continue
                    if known != self._leaf_of_cell(s):
                        old_info = self._channel_center(known)
                pending[e] = (old_info, new_info, provider)
            cap = _governor.handover_batch_cap()
            if cap is None and len(pending) > len(handovers):
                # De-escalation with a deferred backlog: drain it over a
                # few ticks instead of all at once — an unbounded drain
                # right after stepping down was measured re-spiking the
                # tick budget and bouncing the ladder back up.
                cap = max(
                    1, global_settings.overload_handover_batch_cap
                ) * 8
            if cap is not None and len(pending) > cap:
                # L2+: orchestrate the oldest ``cap`` entities, defer the
                # rest to next tick — lossless (each entity keeps exactly
                # one pending crossing; the channel data stays in its
                # last orchestrated cell meanwhile), and every deferral-
                # tick is counted.
                batch_keys = list(pending)[:cap]
                batch = [pending.pop(k) for k in batch_keys]
                _governor.count_shed("handover_defer", len(pending))
            else:
                batch = list(pending.values())
                pending.clear()
            t_ho = _time.monotonic()
            StaticGrid2DSpatialController.notify_crossings(self, batch)
            _governor.note_handover_cost(_time.monotonic() - t_ho)
            _trace.stage("handover", int(t_ho * 1e9))
        if self.queryplane is not None:
            # Standing-query plane (doc/query_engine.md): ONE changed-
            # rows consume per tick, apply O(changed). The CONSUME always
            # drains — the device already committed this tick's baseline,
            # so an unconsumed blob is a permanently lost delta; at L2+
            # only the APPLY pass (and follower re-centering) alternates
            # ticks, halving standing-query cadence exactly as the
            # legacy follower path halves.
            defer = _governor.level >= 2 and not self._follow_skip
            t_fi = _time.monotonic()
            if defer:
                self._follow_skip = True
                # An empty registry sheds nothing — a zero count would
                # still create the ledger key and break the soaks'
                # exact shed accounting.
                if self.queryplane.count():
                    _governor.count_shed(
                        "query_apply_defer", self.queryplane.count()
                    )
            else:
                self._follow_skip = False
                self._recenter_followers()
            self.queryplane.pump(result, apply=not defer)
            cost = _time.monotonic() - t_fi
            _trace.stage("query_plane", int(t_fi * 1e9))
            # Same pressure-signal input the legacy follower pass fed:
            # the plane's host cost is the follower cost now.
            metrics.follower_interest_ms.observe(cost * 1000.0)
            _governor.note_follower_cost(cost)
        elif self._followers:
            if _governor.level >= 2 and not self._follow_skip:
                # L2+: follower interests re-center every OTHER tick —
                # half the host cost, interest diffs lag one tick.
                self._follow_skip = True
                _governor.count_shed(
                    "follow_interest_defer", len(self._followers)
                )
            else:
                self._follow_skip = False
                t_fi = _time.monotonic()
                self._apply_follow_interests(result)
                cost = _time.monotonic() - t_fi
                _trace.stage("follow_interests", int(t_fi * 1e9))
                # The previously-unmeasured host cost inside the GLOBAL
                # tick budget (VERDICT weak #5): now a first-class
                # histogram and a pressure-signal input.
                metrics.follower_interest_ms.observe(cost * 1000.0)
                _governor.note_follower_cost(cost)

    def _build_crossing(self, entity_id: int, src_cell: int, dst_cell: int):
        """(old_info, new_info, provider) for one device-detected crossing."""
        provider = self._providers.get(entity_id)
        if provider is None:
            provider = lambda s, d: entity_id
        # Use the entity's TRUE previous position when it still maps to the
        # device-reported src cell (it can diverge when several moves
        # collapsed into one batched tick); the cell center is only the
        # consistency fallback. The orchestration recomputes src/dst from
        # the infos, so whichever is used must map back to src_cell.
        old_info = self._prev_positions.get(entity_id)
        if old_info is not None:
            try:
                mapped = self._micro_index(old_info)
            except ValueError:
                mapped = -1
            if mapped != src_cell:
                old_info = None
        if old_info is None:
            old_info = self._cell_center(src_cell)
        # Same consistency rule on the destination side: the host belief
        # can LAG the device for sim agents (their positions advance on
        # device every tick but _last_positions only refreshes at census
        # cadence), and a stale new_info that still maps to src would
        # collapse the crossing to s == d — dropped forever, since the
        # device baseline already committed to dst and never re-detects.
        new_info = self._last_positions.get(entity_id)
        if new_info is not None:
            try:
                mapped = self._micro_index(new_info)
            except ValueError:
                mapped = -1
            if mapped != dst_cell:
                new_info = None
        if new_info is None:
            new_info = self._cell_center(dst_cell)
        return old_info, new_info, provider

    def _run_handover(self, entity_id: int, src_cell: int, dst_cell: int) -> None:
        """Run the host orchestration for one device-detected crossing
        (kept for tests / tooling; the tick path batches via
        notify_crossings)."""
        old_info, new_info, provider = self._build_crossing(
            entity_id, src_cell, dst_cell
        )
        StaticGrid2DSpatialController.notify(self, old_info, new_info, provider)

    def _cell_center(self, cell: int) -> SpatialInfo:
        # MICRO-grid center (== base grid until a split is live).
        x = self.world_offset_x + (cell % self._mcols + 0.5) * self._mw
        z = self.world_offset_z + (cell // self._mcols + 0.5) * self._mh
        return SpatialInfo(x, 0, z)


register_spatial_controller_type("TPUSpatialController", TPUSpatialController)

"""StaticGrid2D spatial controller — host-semantics implementation.

Capability parity with the reference controller
(ref: pkg/channeld/spatial.go:89-902): the world is GridCols x GridRows
base cells on the XZ plane; each spatial server owns a ServerCols x
ServerRows block plus an interest border of cells it subscribes to; AOI
queries (spots/box/sphere/cone) sample cells at half-cell steps and
return {channelId: grid-distance}; ``notify`` orchestrates cross-cell
(and cross-server) entity handover.

Cell geometry is a runtime, versioned property (doc/partitioning.md):
all channel-id, adjacency and server-placement math consults the live
:class:`~.celltree.CellTree`, which the adaptive partitioning plane
(spatial/partition.py) mutates through transactional geometry epochs.
With no splits active the tree reproduces the legacy static formulas
bit-for-bit — geometry tests pin the INVARIANTS (position->leaf
containment, neighbor-band adjacency, server inheritance), not one
fixed layout.

This module is the *semantic reference* path. The TPU decision plane
(channeld_tpu.ops / tpu_controller.py) computes cell assignment, AOI
masks and handover detection as batched device arrays and must agree
with this implementation — the geometry tests pin both.
"""

from __future__ import annotations

import math
from typing import Callable, Optional

from ..core.overload import governor as _governor
from ..core.settings import global_settings
from ..federation.directory import directory as _shard_directory
from .balancer import balancer as _balancer
from .celltree import CellTree
from .partition import partition as _partition
from ..core.types import ChannelType, ConnectionType, MessageType
from ..protocol import control_pb2, spatial_pb2
from ..utils.anyutil import pack_any
from ..utils.logger import get_logger
from .controller import SpatialInfo, register_spatial_controller_type

logger = get_logger("spatial.grid")

# Y bounds of a region (the grid is 2D; regions span all heights)
# (ref: spatial.go MinY/MaxY).
MIN_Y = -3.40282347e38 / 2
MAX_Y = 3.40282347e38 / 2


def _dist_2d(ax: float, az: float, bx: float, bz: float) -> float:
    return math.hypot(ax - bx, az - bz)


class StaticGrid2DSpatialController:
    """(ref: spatial.go:93-124)."""

    def __init__(self):
        self.grid_width = 0.0
        self.grid_height = 0.0
        self.grid_cols = 0
        self.grid_rows = 0
        self.world_offset_x = 0.0
        self.world_offset_z = 0.0
        self.server_cols = 0
        self.server_rows = 0
        self.server_interest_border_size = 0
        self.server_connections: list = []
        self._grid_size = 0.0
        # Live cell geometry (doc/partitioning.md): built at load_config,
        # mutated only through apply_geometry (the partition plane's
        # commit, trunk geometry sync, and WAL replay).
        self.tree: Optional[CellTree] = None
        # Authoritative placement ledger: entity id -> the spatial cell
        # channel whose DATA currently holds the entity. Crossing
        # detection works from positions (host) or the device prev-cell
        # table (TPU) — both can disagree with where the data actually
        # sits (an entity applied into a cell by a trunked handover or
        # an adoption bootstrap hasn't been position-sighted yet), and a
        # remove aimed at the wrong src cell leaves the data duplicated
        # across two cells. Flipped only when a move is REAL: the
        # orchestration commit hook, the federation apply/restore paths,
        # and the failover re-host re-seed.
        self._data_cell: dict[int, int] = {}

    # ---- config ----------------------------------------------------------

    def load_config(self, config: dict) -> None:
        self.grid_width = float(config.get("GridWidth", 0))
        self.grid_height = float(config.get("GridHeight", 0))
        self.grid_cols = int(config.get("GridCols", 0))
        self.grid_rows = int(config.get("GridRows", 0))
        self.world_offset_x = float(config.get("WorldOffsetX", 0))
        self.world_offset_z = float(config.get("WorldOffsetZ", 0))
        self.server_cols = int(config.get("ServerCols", 0))
        self.server_rows = int(config.get("ServerRows", 0))
        self.server_interest_border_size = int(
            config.get("ServerInterestBorderSize", 0)
        )
        if self.grid_width <= 0 or self.grid_height <= 0:
            raise ValueError("GridWidth and GridHeight should be positive")
        if self.grid_cols <= 0 or self.grid_rows <= 0:
            raise ValueError("GridCols and GridRows should be positive")
        if self.server_cols <= 0 or self.server_rows <= 0:
            raise ValueError("ServerCols and ServerRows should be positive")
        st = global_settings
        self.tree = CellTree(
            st.spatial_channel_id_start, self.grid_cols, self.grid_rows,
            self.grid_width, self.grid_height,
            self.world_offset_x, self.world_offset_z,
            max_depth=st.partition_max_depth,
        )
        # Id-space guard: every depth's cell block must fit under the
        # entity channel id space, or a deep split would mint ids that
        # collide with entity channels.
        if self.tree.id_space_end() > st.entity_channel_id_start:
            raise ValueError(
                f"partition_max_depth={st.partition_max_depth} needs cell "
                f"ids up to {self.tree.id_space_end()}, past the entity "
                f"id start {st.entity_channel_id_start}"
            )
        from ..core import events

        def _on_channel_removed(channel_id: int) -> None:
            if channel_id >= global_settings.entity_channel_id_start:
                self.untrack_entity(channel_id)

        events.channel_removed.listen_for(self, _on_channel_removed)

    def untrack_entity(self, entity_id: int) -> None:
        """The entity's channel is gone: drop its placement-ledger row
        (a reused entity id must never inherit the old row — notify()
        would re-route the new entity's remove at a cell that holds no
        copy, stranding the real one as a duplicate), moot any in-flight
        journal transaction, and clear balancer freeze state. The TPU
        subclass adds device-side cleanup on top."""
        from ..core.failover import journal as _journal

        self._data_cell.pop(entity_id, None)
        _journal.forget_entity(entity_id)
        _balancer._frozen_crossings.pop(entity_id, None)

    # ---- geometry --------------------------------------------------------

    def world_width(self) -> float:
        return self.grid_width * self.grid_cols

    def world_height(self) -> float:
        return self.grid_height * self.grid_rows

    def grid_size(self) -> float:
        """Cell diagonal, the unit of AOI distance (ref: spatial.go:137-142)."""
        if self._grid_size == 0 and self.grid_width > 0 and self.grid_height > 0:
            self._grid_size = math.hypot(self.grid_width, self.grid_height)
        return self._grid_size

    def get_channel_id(self, info: SpatialInfo) -> int:
        return self.get_channel_id_with_offset(
            info, self.world_offset_x, self.world_offset_z
        )

    def get_channel_id_no_offset(self, info: SpatialInfo) -> int:
        return self.get_channel_id_with_offset(info, 0.0, 0.0)

    def get_channel_id_with_offset(
        self, info: SpatialInfo, offset_x: float, offset_z: float
    ) -> int:
        """Position -> LIVE LEAF cell id. Base cell by the legacy
        formula start + floor((x-ox)/w) + floor((z-oz)/h)*cols
        (ref: spatial.go:169-180), then descended through any active
        splits. Raises ValueError outside the world."""
        gx = math.floor((info.x - offset_x) / self.grid_width)
        if gx < 0 or gx >= self.grid_cols:
            raise ValueError(f"gridX={gx} out of [0,{self.grid_cols}) for X={info.x}")
        gz = math.floor((info.z - offset_z) / self.grid_height)
        if gz < 0 or gz >= self.grid_rows:
            raise ValueError(f"gridY={gz} out of [0,{self.grid_rows}) for Z={info.z}")
        cell = global_settings.spatial_channel_id_start + gx + gz * self.grid_cols
        tree = self.tree
        if tree is None or not tree.splits:
            return cell
        rx, rz = info.x - offset_x, info.z - offset_z
        d = 0
        while cell in tree.splits:
            d += 1
            w = self.grid_width / (1 << d)
            h = self.grid_height / (1 << d)
            cgx = min(int(rx // w), (self.grid_cols << d) - 1)
            cgz = min(int(rz // h), (self.grid_rows << d) - 1)
            cell = tree.encode(d, cgx, cgz)
        return cell

    def base_cell_id(self, gx: int, gz: int) -> int:
        """Depth-0 (base-grid) cell id; raises outside the grid."""
        if gx < 0 or gx >= self.grid_cols:
            raise ValueError(f"gridX={gx} out of [0,{self.grid_cols})")
        if gz < 0 or gz >= self.grid_rows:
            raise ValueError(f"gridY={gz} out of [0,{self.grid_rows})")
        return global_settings.spatial_channel_id_start + gx + gz * self.grid_cols

    # ---- AOI queries -----------------------------------------------------

    def _sample_cell_size(self) -> tuple[float, float]:
        """AOI sampling granularity: the finest live cell (the micro
        grid's), so a box/sphere sweep cannot step over a split child.
        Equals the base cell size when no splits are active."""
        tree = self.tree
        if tree is None or not tree.splits:
            return self.grid_width, self.grid_height
        d = tree.max_active_depth()
        return self.grid_width / (1 << d), self.grid_height / (1 << d)

    def query_channel_ids(self, query: spatial_pb2.SpatialInterestQuery) -> dict[int, int]:
        """{channelId: distance in grid-diagonal units}; 0 = nearest
        (ref: spatial.go:182-317)."""
        if query is None:
            raise ValueError("query is nil")
        result: dict[int, int] = {}
        samp_w, samp_h = self._sample_cell_size()

        if query.HasField("spotsAOI"):
            for i, spot in enumerate(query.spotsAOI.spots):
                try:
                    ch_id = self.get_channel_id(SpatialInfo(spot.x, spot.y, spot.z))
                except ValueError:
                    continue
                if i < len(query.spotsAOI.dists):
                    result[ch_id] = query.spotsAOI.dists[i]
                else:
                    result[ch_id] = 0

        if query.HasField("boxAOI"):
            box = query.boxAOI
            cx, cz = box.center.x, box.center.z
            step_z = min(box.extent.z, samp_h) * 0.5
            if step_z <= 0:
                raise ValueError(f"invalid box extentZ={box.extent.z}")
            step_x = min(box.extent.x, samp_w) * 0.5
            if step_x <= 0:
                raise ValueError(f"invalid box extentX={box.extent.x}")
            z = cz - box.extent.z
            while z <= cz + box.extent.z:
                x = cx - box.extent.x
                while x <= cx + box.extent.x:
                    self._add_sample(result, cx, cz, x, z)
                    x += step_x
                z += step_z
            result[self.get_channel_id(SpatialInfo(cx, 0, cz))] = 0

        if query.HasField("sphereAOI"):
            r = query.sphereAOI.radius
            cx, cz = query.sphereAOI.center.x, query.sphereAOI.center.z
            step_z = min(r, samp_h) * 0.5
            step_x = min(r, samp_w) * 0.5
            if step_z <= 0 or step_x <= 0:
                raise ValueError(f"invalid radius={r}")
            z = cz - r
            while z <= cz + r:
                x = cx - r
                while x <= cx + r:
                    if (x - cx) ** 2 + (z - cz) ** 2 <= r * r:
                        self._add_sample(result, cx, cz, x, z)
                    x += step_x
                z += step_z
            result[self.get_channel_id(SpatialInfo(cx, 0, cz))] = 0

        if query.HasField("coneAOI"):
            cone = query.coneAOI
            r = cone.radius
            cx, cz = cone.center.x, cone.center.z
            dx, dz = cone.direction.x, cone.direction.z
            dlen = math.hypot(dx, dz)
            if dlen > 0:
                dx, dz = dx / dlen, dz / dlen
            step_z = min(r, samp_h) * 0.5
            step_x = min(r, samp_w) * 0.5
            if step_z <= 0 or step_x <= 0:
                raise ValueError(f"invalid radius={r}")
            cos_angle = math.cos(cone.angle)
            z = max(self.world_offset_z, cz - r)
            z_end = min(self.world_offset_z + self.world_height(), cz + r)
            x_start = max(self.world_offset_x, cx - r)
            x_end = min(self.world_offset_x + self.world_width(), cx + r)
            while z <= z_end:
                x = x_start
                while x <= x_end:
                    if (x - cx) ** 2 + (z - cz) ** 2 <= r * r:
                        ex, ez = x - cx, z - cz
                        elen = math.hypot(ex, ez)
                        if elen > 0:
                            ex, ez = ex / elen, ez / elen
                        if ex * dx + ez * dz >= cos_angle:
                            self._add_sample(result, cx, cz, x, z)
                    x += step_x
                z += step_z
            result[self.get_channel_id(SpatialInfo(cx, 0, cz))] = 0

        return result

    def _add_sample(self, result: dict, cx: float, cz: float, x: float, z: float) -> None:
        try:
            ch_id = self.get_channel_id(SpatialInfo(x, 0, z))
        except ValueError:
            return
        result[ch_id] = int(math.ceil(_dist_2d(cx, cz, x, z) / self.grid_size()))

    # ---- regions / adjacency --------------------------------------------

    def _server_grid_cols(self) -> int:
        return -(-self.grid_cols // self.server_cols)  # ceil div

    def _server_grid_rows(self) -> int:
        return -(-self.grid_rows // self.server_rows)

    def get_regions(self) -> list[spatial_pb2.SpatialRegion]:
        """One region per LIVE LEAF cell (ref: spatial.go:319-356);
        identical to the legacy base-grid sweep when no splits are
        active (leaves come back in base row-major order)."""
        sgc, sgr = self._server_grid_cols(), self._server_grid_rows()
        tree = self.tree
        regions = []
        if tree is not None:
            for leaf in tree.leaves():
                x0, z0, x1, z1 = tree.rect(leaf)
                regions.append(
                    spatial_pb2.SpatialRegion(
                        min=spatial_pb2.SpatialInfo(x=x0, y=MIN_Y, z=z0),
                        max=spatial_pb2.SpatialInfo(x=x1, y=MAX_Y, z=z1),
                        channelId=leaf,
                        serverIndex=tree.server_index_of(
                            leaf, sgc, sgr, self.server_cols
                        ),
                    )
                )
            return regions
        for y in range(self.grid_rows):
            for x in range(self.grid_cols):
                index = x + y * self.grid_cols
                regions.append(
                    spatial_pb2.SpatialRegion(
                        min=spatial_pb2.SpatialInfo(
                            x=self.world_offset_x + self.grid_width * x,
                            y=MIN_Y,
                            z=self.world_offset_z + self.grid_height * y,
                        ),
                        max=spatial_pb2.SpatialInfo(
                            x=self.world_offset_x + self.grid_width * (x + 1),
                            y=MAX_Y,
                            z=self.world_offset_z + self.grid_height * (y + 1),
                        ),
                        channelId=global_settings.spatial_channel_id_start + index,
                        serverIndex=(x // sgc) + (y // sgr) * self.server_cols,
                    )
                )
        return regions

    def server_index_of_cell(self, spatial_channel_id: int) -> int:
        """The spatial-server index whose authority block contains the
        cell — the same geometric mapping get_regions stamps into
        ``SpatialRegion.serverIndex``. Child cells inherit their base
        cell's server (a split never moves authority across servers by
        itself). The shard directory (federation/directory.py) resolves
        cell->gateway through this. Raises ValueError outside the
        geometry's id space."""
        sgc, sgr = self._server_grid_cols(), self._server_grid_rows()
        tree = self.tree
        if tree is not None:
            try:
                return tree.server_index_of(
                    spatial_channel_id, sgc, sgr, self.server_cols
                )
            except ValueError:
                raise ValueError(
                    f"channel {spatial_channel_id} outside the grid"
                )
        index = spatial_channel_id - global_settings.spatial_channel_id_start
        if index < 0 or index >= self.grid_cols * self.grid_rows:
            raise ValueError(f"channel {spatial_channel_id} outside the grid")
        gx, gy = index % self.grid_cols, index // self.grid_cols
        return (gx // sgc) + (gy // sgr) * self.server_cols

    def get_adjacent_channels(self, spatial_channel_id: int) -> list[int]:
        """Live leaves within one BASE cell of the given cell, minus
        itself — exactly the legacy 3x3 neighborhood when no splits are
        active (ref: spatial.go:358-381)."""
        tree = self.tree
        if tree is not None:
            return tree.neighbor_leaves(spatial_channel_id)
        index = spatial_channel_id - global_settings.spatial_channel_id_start
        gx, gy = index % self.grid_cols, index // self.grid_cols
        out = []
        for y in range(gy - 1, gy + 2):
            if y < 0 or y >= self.grid_rows:
                continue
            for x in range(gx - 1, gx + 2):
                if x < 0 or x >= self.grid_cols or (x == gx and y == gy):
                    continue
                out.append(
                    global_settings.spatial_channel_id_start + x + y * self.grid_cols
                )
        return out

    # ---- server lifecycle ------------------------------------------------

    def _init_server_connections(self) -> None:
        if not self.server_connections:
            self.server_connections = [None] * (self.server_cols * self.server_rows)

    def _allowed_server_indices(self) -> list[int]:
        """Server indices THIS gateway may allocate: all of them in a
        self-contained world; only the shard directory's local block
        assignment in a federated one (remote blocks' cells live on
        other gateways and are never created here — doc/federation.md)."""
        n = self.server_cols * self.server_rows
        if _shard_directory.active:
            return [i for i in _shard_directory.local_server_indices()
                    if i < n]
        return list(range(n))

    def _next_server_index(self) -> int:
        for i in self._allowed_server_indices():
            conn = self.server_connections[i]
            if conn is None or conn.is_closing():
                return i
        return len(self.server_connections)

    def create_channels(self, ctx) -> list:
        """Allocate one server's authority block of spatial channels
        (ref: spatial.go:387-479)."""
        from ..core.channel import create_channel_with_id
        from ..core.channel import get_global_channel
        from ..core.data import unwrap_update_any
        from ..core.message import MessageContext

        self._init_server_connections()
        server_index = self._next_server_index()
        n_servers = self.server_cols * self.server_rows
        if server_index >= n_servers:
            raise RuntimeError(
                f"all {self.grid_cols * self.grid_rows} grids are already "
                f"allocated to {n_servers} servers"
            )
        msg = ctx.msg
        if not isinstance(msg, control_pb2.CreateChannelMessage):
            raise TypeError("ctx.msg is not a CreateChannelMessage")

        sgc, sgr = self._server_grid_cols(), self._server_grid_rows()
        sx, sy = server_index % self.server_cols, server_index // self.server_cols
        channel_ids = []
        for y in range(sgr):
            for x in range(sgc):
                base = self.base_cell_id(sx * sgc + x, sy * sgr + y)
                # A geometry restored BEFORE the servers registered (WAL
                # replay) may already have this base cell split: the
                # server's block is its live leaves, not the base ids.
                if self.tree is not None:
                    channel_ids.extend(self.tree.leaves_under(base))
                else:
                    channel_ids.append(base)

        from ..core.channel import get_channel

        channels = []
        for channel_id in channel_ids:
            # Boot replay can have restored the leaf channel (with its
            # authoritative data) ahead of the owning server's
            # registration — adopt it instead of re-creating.
            ch = get_channel(channel_id)
            if ch is None or ch.is_removing():
                ch = create_channel_with_id(
                    channel_id, ChannelType.SPATIAL, ctx.connection
                )
                if msg.HasField("data"):
                    ch.init_data(unwrap_update_any(msg.data), msg.mergeOptions)
                else:
                    ch.init_data(None, msg.mergeOptions)
            elif not ch.has_owner():
                ch.set_owner(ctx.connection)
            channels.append(ch)

        self.server_connections[server_index] = ctx.connection
        server_index = self._next_server_index()
        if server_index == n_servers:
            # Everyone (this gateway hosts) is in: wire the interest
            # borders, then tell all the local spatial servers (and the
            # master server) the world is ready. In a federated world the
            # remote shards' slots stay None here — their cells live on
            # other gateways (doc/federation.md).
            for i in range(n_servers):
                if self.server_connections[i] is None:
                    continue
                self._sub_to_adjacent_channels(i, sgc, sgr, msg.subOptions)
            ready = spatial_pb2.SpatialChannelsReadyMessage(
                serverIndex=server_index, serverCount=n_servers
            )
            for conn in self.server_connections:
                if conn is None:
                    continue
                conn.send(
                    MessageContext(
                        msg_type=MessageType.SPATIAL_CHANNELS_READY, msg=ready
                    )
                )
            gch = get_global_channel()
            if gch is not None and gch.get_owner() is not None:
                gch.get_owner().send(
                    MessageContext(
                        msg_type=MessageType.SPATIAL_CHANNELS_READY, msg=ready
                    )
                )
        return channels

    def _sub_to_adjacent_channels(
        self, server_index: int, sgc: int, sgr: int, sub_options
    ) -> None:
        """Subscribe a server to the interest border around its authority
        block (ref: spatial.go:481-590)."""
        if self.server_interest_border_size == 0:
            return
        from ..core.channel import get_channel
        from ..core.subscription import subscribe_to_channel
        from ..core.subscription_messages import send_subscribed

        conn = self.server_connections[server_index]
        sx, sy = server_index % self.server_cols, server_index // self.server_cols
        border = self.server_interest_border_size

        def sub_cell(grid_x_units: float, grid_z_units: float) -> None:
            base = self.base_cell_id(int(grid_x_units), int(grid_z_units))
            # Border interest covers every live leaf under the base
            # cell — a split border cell contributes all its children.
            leaves = (
                self.tree.leaves_under(base)
                if self.tree is not None else [base]
            )
            for channel_id in leaves:
                ch = get_channel(channel_id)
                if ch is None:
                    if not _shard_directory.is_local_cell(channel_id):
                        # Border cell in a remote shard: it has no local
                        # channel to subscribe to. Cross-gateway interest
                        # arrives as handover/redirect traffic instead.
                        continue
                    raise RuntimeError(
                        f"border channel {channel_id} doesn't exist"
                    )
                cs, should_send = subscribe_to_channel(conn, ch, sub_options)
                if should_send:
                    send_subscribed(conn, ch, conn, 0, cs.options)

        if sx > 0:  # cells to the left of the block
            for y in range(sgr):
                for x in range(1, border + 1):
                    sub_cell(sx * sgc - x, sy * sgr + y)
        if sx < self.server_cols - 1:  # right
            for y in range(sgr):
                for x in range(border):
                    sub_cell((sx + 1) * sgc + x, sy * sgr + y)
        if sy > 0:  # below
            for y in range(1, border + 1):
                for x in range(sgc):
                    sub_cell(sx * sgc + x, sy * sgr - y)
        if sy < self.server_rows - 1:  # above
            for y in range(border):
                for x in range(sgc):
                    sub_cell(sx * sgc + x, (sy + 1) * sgr + y)

    def tick(self) -> None:
        """Reap closed server connections (ref: spatial.go:884-893), then
        run the load-balancer update (doc/balancer.md) and the adaptive
        partitioning governor (doc/partitioning.md) — all inside the
        GLOBAL channel tick, the single-writer context every channel
        mutation here requires."""
        self._init_server_connections()
        for i, conn in enumerate(self.server_connections):
            if conn is not None and conn.is_closing():
                self.server_connections[i] = None
                logger.info("reset spatial server connection %d", i)
        _balancer.update(self)
        _partition.update(self)

    # ---- live geometry (doc/partitioning.md) -----------------------------

    @property
    def geometry_epoch(self) -> int:
        return self.tree.epoch if self.tree is not None else 0

    def geometry_splits(self) -> frozenset:
        return self.tree.splits if self.tree is not None else frozenset()

    def apply_geometry(self, epoch: int, splits) -> None:
        """Replace the live cell geometry wholesale. The ONLY mutation
        path — used by the partition plane's commit/abort, trunk
        geometry sync (federation/control.py) and WAL replay. Validates
        the split set, bumps the epoch gauge, refreshes the per-leaf
        depth gauges and invokes the device-rebuild hook."""
        if self.tree is None:
            raise RuntimeError("geometry applied before load_config")
        from ..core import metrics

        old_leaves = set(self.tree.leaves())
        self.tree.apply(epoch, splits)
        metrics.partition_geometry_epoch.set(epoch)
        new_leaves = set(self.tree.leaves())
        for cell in old_leaves - new_leaves:
            metrics.spatial_cell_depth.labels(cell=str(cell)).set(0)
        for cell in new_leaves:
            metrics.spatial_cell_depth.labels(cell=str(cell)).set(
                self.tree.depth_of(cell)
            )
        self.on_geometry_changed()

    def on_geometry_changed(self) -> None:
        """Hook for the device plane (tpu_controller overrides): rebuild
        interest masks and cell-id arrays for the new geometry epoch.
        The host-semantics controller needs nothing — every lookup
        already consults the live tree."""

    # ---- handover --------------------------------------------------------

    def notify(
        self,
        old_info: SpatialInfo,
        new_info: SpatialInfo,
        handover_data_provider: Callable[[int, int], Optional[int]],
    ) -> None:
        """Cross-cell entity migration (ref: spatial.go:612-858).

        ``handover_data_provider(src, dst)`` returns the id of the entity
        whose movement triggered the notification (the reference passes an
        out-pointer; we return it).
        """
        try:
            src_channel_id = self.get_channel_id(old_info)
            dst_channel_id = self.get_channel_id(new_info)
        except ValueError as e:
            logger.error("failed to compute handover channel ids: %s", e)
            return
        if src_channel_id == dst_channel_id:
            return
        # Position-derived src vs the authoritative placement ledger:
        # an entity applied here by a trunked handover / adoption
        # bootstrap has data in a cell its position history knows
        # nothing about — orchestrating from the position's src would
        # leave that data behind as a stale duplicate. Same discipline
        # as the TPU tick path (tpu_controller.tick): the in-flight
        # journal outranks the committed ledger.
        from ..core.failover import journal as _jrn

        eid = handover_data_provider(-1, -1)
        if eid is not None:
            if _jrn.remote_in_flight(eid):
                # Mid cross-gateway flight: commit removes the entity
                # here; abort restores and re-offers it. Orchestrating
                # this hop now would duplicate the data.
                return
            known = _jrn.pending_dst(eid)
            if known is None:
                known = self._data_cell.get(eid)
            if known is not None and known != src_channel_id:
                if known == dst_channel_id:
                    return  # stale re-detection: the data already moved
                # Chained hop: per-channel FIFO puts this remove after
                # the pending add on `known`.
                src_channel_id = known
        frozen = _balancer.frozen_cells
        if frozen or _balancer._frozen_crossings:
            # A live migration has a cell frozen: park crossings that
            # touch it (one pending move per entity; chains collapse) —
            # they replay through the batched orchestration on
            # unfreeze. An entity with an ALREADY-parked crossing keeps
            # chaining into it even off-freeze: its true origin is the
            # parked entry's. Checked BEFORE the remote-dst branch: a
            # federated handover out of a frozen src cell would mutate
            # the cell mid-migration (the packed-state bootstrap could
            # ship an entity the trunk just moved).
            if eid is not None and (
                src_channel_id in frozen
                or dst_channel_id in frozen
                or eid in _balancer._frozen_crossings
            ):
                _balancer.defer_crossing(
                    eid, old_info, new_info, handover_data_provider
                )
                return
        if not _shard_directory.is_local_cell(dst_channel_id):
            # The destination cell lives on another gateway: this
            # crossing is a cross-gateway handover — the transactional
            # journal extended over the trunk (federation/plane.py,
            # doc/federation.md). Never orchestrated locally.
            from ..federation.plane import plane as _fed_plane

            _fed_plane.initiate_handover(
                src_channel_id, dst_channel_id, [handover_data_provider]
            )
            return
        self._orchestrate_pair(src_channel_id, dst_channel_id,
                               [handover_data_provider])

    def entity_position(self, entity_id: int):
        """Last known world position of one tracked entity, or None when
        the controller keeps no position cache (host-semantics mode).
        The partition plane uses this to sort residents into child
        quadrants at split commit; with no position the entity
        bootstraps into the child containing the parent's center and
        re-sorts on its next movement."""
        return None

    def _note_entity_data_moved(self, entity_ids, dst_channel_id: int) -> None:
        """Placement-ledger callback: fires only when entity data
        ACTUALLY moved (a skipped orchestration — missing channel,
        locked group — must leave the ledger on the cell the data still
        lives in, or stale re-detections would be mis-suppressed and
        the data stranded). Called from the local orchestration's
        commit hook, the federation apply/restore paths, and the
        global-control adoption bootstrap."""
        for eid in entity_ids:
            self._data_cell[eid] = dst_channel_id
        from ..core.wal import wal as _wal

        if _wal.enabled:
            # Placement flips ride the WAL (doc/persistence.md): boot
            # replay re-seeds the ledger from the restored cell rows,
            # then overlays these so a mid-crossing entity re-baselines
            # to where its data is BOUND, not where a stale row says.
            _wal.log_flip(entity_ids, dst_channel_id)

    def on_cell_rehosted(self, cell_channel_id: int, new_owner) -> None:
        """Failover hook (core/failover.py): the cell's authority moved
        to ``new_owner``. What must stay exact is the placement ledger:
        re-seed a row for every entity actually resident in the cell's
        authoritative data (an entity shed/re-tracked during the outage
        can have lost its row, and a later crossing orchestrated from
        the wrong origin would leave its data duplicated across two
        cells)."""
        from ..core.channel import get_channel

        ch = get_channel(cell_channel_id)
        if ch is None:
            return
        entities = getattr(ch.get_data_message(), "entities", None)
        if entities is None:
            return
        for eid in entities:
            self._data_cell.setdefault(eid, cell_channel_id)

    def notify_crossings(self, crossings) -> None:
        """Batched migration: ``crossings`` is an iterable of
        (old_info, new_info, provider). Crossings sharing a
        (src, dst) channel pair are orchestrated together — one owner-swap
        pass, one remove/add Execute hop per channel, one fan-out message
        per recipient per pair — preserving the reference's per-pair
        ordering (owner swap -> remove/add -> fan-out,
        ref: spatial.go:612-858). The device detects crossings in batch
        (~1.5K per tick at the flagship load); per-crossing orchestration
        measured 87.8us each (11.4K/s, scripts/bench_handover.py) — far
        under the 44.5K/s detection rate, hence this path."""
        groups: dict = {}  # insertion-ordered: first-crossing pair order
        remote_groups: dict = {}  # (src, dst) -> providers, dst on a peer
        frozen = _balancer.frozen_cells
        for old_info, new_info, provider in crossings:
            try:
                s = self.get_channel_id(old_info)
                d = self.get_channel_id(new_info)
            except ValueError as e:
                logger.error("failed to compute handover channel ids: %s", e)
                continue
            if s == d:
                continue
            if frozen or _balancer._frozen_crossings:
                eid = provider(-1, -1)
                if eid is not None and (
                    s in frozen
                    or d in frozen
                    # An entity that ALREADY has a parked crossing must
                    # keep chaining into it even when this hop touches
                    # no frozen cell: its true origin is the parked
                    # entry's — orchestrating this hop now would move
                    # data from the wrong cell and the later replay
                    # would duplicate it.
                    or eid in _balancer._frozen_crossings
                ):
                    # Live migration in flight: park the crossing with
                    # the balancer (chains collapse per entity); it
                    # replays through this very path once the migration
                    # commits or aborts. Outranks the remote-dst branch:
                    # a federated handover out of a frozen src would
                    # mutate the cell mid-migration.
                    _balancer.defer_crossing(eid, old_info, new_info,
                                             provider)
                    continue
            if not _shard_directory.is_local_cell(d):
                # Remote destination: batched cross-gateway handover
                # (one trunk prepare per (src, dst) pair per tick).
                remote_groups.setdefault((s, d), []).append(provider)
                continue
            groups.setdefault((s, d), []).append(provider)
        for (s, d), providers in groups.items():
            self._orchestrate_pair(s, d, providers)
        if remote_groups:
            from ..federation.plane import plane as _fed_plane

            for (s, d), providers in remote_groups.items():
                _fed_plane.initiate_handover(s, d, providers)

    def _orchestrate_pair(
        self, src_channel_id: int, dst_channel_id: int, providers: list
    ) -> None:
        """Owner swap -> data remove/add -> handover fan-out for every
        crossing between one (src, dst) spatial channel pair."""
        from ..core.channel import get_channel
        from ..core.data import reflect_channel_data_message
        from ..core.failover import journal as _journal
        from ..core.message import MessageContext
        from ..core.subscription import subscribe_to_channel
        from ..core.subscription_messages import send_subscribed, send_unsubscribed
        from ..core.types import ChannelDataAccess
        from ..core.subscription import unsubscribe_from_channel

        src_channel = get_channel(src_channel_id)
        dst_channel = get_channel(dst_channel_id)
        if src_channel is None or dst_channel is None:
            logger.error(
                "handover impossible: channel missing (src=%s dst=%s)",
                src_channel_id, dst_channel_id,
            )
            return

        from ..core import metrics

        handover_entities: dict = {}
        contributing = 0
        for provider in providers:
            handover_entity_id = provider(src_channel_id, dst_channel_id)
            if handover_entity_id is None:
                continue
            if _journal.remote_in_flight(handover_entity_id):
                # Mid cross-gateway flight (a shard drain or a trunked
                # crossing): the remote batch already captured the
                # data. Commit removes the entity here; abort restores
                # and re-offers it — orchestrating this local hop now
                # would leave the data in two cells.
                continue
            entity_channel = get_channel(handover_entity_id)
            if entity_channel is None:
                logger.warning(
                    "handover skipped: entity channel %d doesn't exist",
                    handover_entity_id,
                )
                continue
            group = entity_channel.get_handover_entities(handover_entity_id)
            if not group:
                continue  # a member is locked, or nothing to move
            contributing += 1
            handover_entities.update(group)
        if not handover_entities:
            return
        metrics.handover_count.inc(contributing)
        # Per-cell crossing observability + the balancer's crossing-rate
        # signal (doc/balancer.md): one orchestration counts against
        # both ends of the pair.
        metrics.spatial_cell_crossings.labels(
            cell=str(src_channel_id), direction="out"
        ).inc(contributing)
        metrics.spatial_cell_crossings.labels(
            cell=str(dst_channel_id), direction="in"
        ).inc(contributing)
        _balancer.note_crossing(src_channel_id, dst_channel_id, contributing)
        from ..federation.control import control as _global_control

        _global_control.note_crossing(contributing)

        # Step 1: cross-server — swap entity-channel ownership first so the
        # src server's residual updates are ignored (prevents handover loops).
        if not src_channel.is_same_owner(dst_channel):
            for entity_id in handover_entities:
                entity_ch = get_channel(entity_id)
                if entity_ch is None:
                    continue
                owner = src_channel.get_owner()
                if (
                    owner is not None
                    and not owner.is_closing()
                    and not owner.has_interest_in(dst_channel_id)
                ):
                    try:
                        unsubscribe_from_channel(owner, entity_ch)
                        send_unsubscribed(owner, entity_ch, None, 0)
                    except KeyError:
                        pass
                entity_ch.set_owner(dst_channel.get_owner())

        # Step 2: move the entities between the spatial channels' data,
        # each inside its own channel's execution context — wrapped in a
        # transactional journal (core/failover.py): prepare here, the
        # remove marks the src hop done, the dst's add COMMITS. A crash
        # between the hops resolves deterministically to exactly one
        # owning cell (the failover pass aborts records whose dst can
        # never run and re-adds the data to src through the same FIFO
        # queue), and the authoritative placement ledger only flips on
        # commit — never on an optimistic queue.
        from ..core.failover import journal as _journal

        records = _journal.prepare(
            handover_entities, src_channel_id, dst_channel_id
        )
        moved_hook = getattr(self, "_note_entity_data_moved", None)

        def _remove(ch):
            data_msg = ch.get_data_message()
            remover = getattr(data_msg, "remove_entity", None)
            if remover is None:
                ch.logger.warning("spatial data can't remove entities")
                return
            for entity_id in handover_entities:
                remover(entity_id)
            _journal.note_removed(records)

        def _add(ch):
            data_msg = ch.get_data_message()
            adder = getattr(data_msg, "add_entity", None)
            if adder is None:
                ch.logger.warning("spatial data can't add entities")
                for rec in records:
                    _journal.abort(rec)
                return
            for entity_id, entity_data in handover_entities.items():
                if entity_data is not None:
                    adder(entity_id, entity_data)
            flips = _journal.commit(records)
            # Placement hook: the move is now REAL (the add ran in the
            # dst tick). Controllers keeping an authoritative placement
            # ledger (the TPU controller's _data_cell, which
            # de-duplicates stale engine re-detections) flip it here —
            # never on a skipped orchestration or an in-flight one, and
            # only for entities whose flip the journal granted (commits
            # land in channel-tick order; a chained hop may have
            # committed first).
            if moved_hook is not None and flips:
                moved_hook(flips, dst_channel_id)

        src_channel.execute(_remove)
        dst_channel.execute(_add)

        # Step 3: identifier-only handover payload for src-side connections.
        spatial_data_msg = reflect_channel_data_message(ChannelType.SPATIAL)
        if spatial_data_msg is None:
            logger.error("no SPATIAL channel data type registered for handover")
            return
        initializer = getattr(spatial_data_msg, "init_data", None)
        if callable(initializer):
            initializer()
        for entity_id, entity_data in handover_entities.items():
            if entity_data is None:
                continue
            merger = getattr(entity_data, "merge_to", None)
            if callable(merger):
                merger(spatial_data_msg, False)
            else:
                logger.warning("entity %d data has no merge_to()", entity_id)

        context_conn_id = src_channel.latest_data_update_conn_id
        base_msg = spatial_pb2.ChannelDataHandoverMessage(
            srcChannelId=src_channel_id,
            dstChannelId=dst_channel_id,
            contextConnId=context_conn_id,
            data=pack_any(spatial_data_msg),
        )

        src_conns = src_channel.get_all_connections()
        dst_conns = dst_channel.get_all_connections()
        # Overload L2+: only REDUNDANT handover payloads are shed — dst
        # clients already subscribed to every moved entity (their state
        # keeps flowing through the entity channels). The src-side
        # identifier-only message is load-bearing (it is the only signal
        # that the entity LEFT the cell; entity removal cannot ride a
        # map-merge delta) and, post-batching, one shared encode — it is
        # never withheld.
        defer_fanout = _governor.defer_handover_fanout()

        # Step 4-1: src-only connections get the identifier-only payload.
        # ONE context, encoded once, shared by every recipient (the
        # queued sender consumes fields into a tuple immediately) — the
        # per-recipient rebuild+re-encode was the dominant share of the
        # 21.8us/handover host cost at r5 load.
        src_only = src_conns - dst_conns
        if src_only:
            shared = MessageContext(
                msg_type=MessageType.CHANNEL_DATA_HANDOVER,
                msg=base_msg,
                channel_id=dst_channel_id,
            )
            shared.ensure_raw_body()
            for conn in src_only:
                conn.send(shared)

        # Step 4-2: dst connections are auto-subscribed to the entity
        # channels (WRITE for the new owner) and receive full entity data
        # when newly subscribed.
        # Hoisted: subscribe_to_channel only reads the options (MergeFrom
        # into the per-sub copy), so the two access variants can be shared
        # across every (conn x entity) subscription in the pair.
        _write_opts = control_pb2.ChannelSubscriptionOptions(
            skipSelfUpdateFanOut=True,
            # Entity data rides in the handover message itself.
            skipFirstFanOut=True,
            dataAccess=ChannelDataAccess.WRITE_ACCESS,
        )
        _read_opts = control_pb2.ChannelSubscriptionOptions(
            skipSelfUpdateFanOut=True,
            skipFirstFanOut=True,
            dataAccess=ChannelDataAccess.READ_ACCESS,
        )
        # Entity channel + merger resolved once per pair, not per conn.
        _targets = []
        for entity_id, entity_data in handover_entities.items():
            entity_ch = get_channel(entity_id)
            if entity_ch is None or entity_data is None:
                continue
            _targets.append(
                (entity_ch, getattr(entity_data, "merge_to", None))
            )
        # Grouped per connection: the subscription pass runs first (state
        # must stay exact even under overload deferral), then exactly one
        # handover message per conn — and conns whose subscription state
        # didn't change all carry the identical payload, so it is built
        # and encoded once and the context shared across them.
        dst_owner = dst_channel.get_owner()
        shared_ctx = None  # the no-new-subscription payload, lazily built
        for conn in dst_conns:
            if conn is None or conn.is_closing():
                # A mid-disconnect conn would subscribe to nothing and
                # build an EMPTY payload — which must never become the
                # cached shared_ctx served to healthy recipients.
                continue
            any_new = False
            merges = []
            for entity_ch, merger in _targets:
                sub_options = (
                    _write_opts if conn is entity_ch.get_owner() else _read_opts
                )
                cs, should_send = subscribe_to_channel(conn, entity_ch, sub_options)
                if cs is None:
                    continue
                if should_send:
                    send_subscribed(conn, entity_ch, conn, 0, cs.options)
                    any_new = True
                merges.append((merger, should_send))
            if (
                defer_fanout
                and not any_new
                and conn is not dst_owner
                and conn.connection_type == ConnectionType.CLIENT
            ):
                # Redundant for this recipient: it was already subscribed
                # to every moved entity (no new sub -> no full state in
                # the payload it would miss), and the entity channels'
                # own fan-out keeps carrying the state. A conn with ANY
                # new subscription still gets the message — it carries
                # that entity's full state (skipFirstFanOut skipped the
                # usual full-state send on purpose).
                _governor.count_shed("handover_fanout")
                continue
            if not any_new and shared_ctx is not None:
                conn.send(shared_ctx)
                continue
            handover_data_msg = type(spatial_data_msg)()
            initializer = getattr(handover_data_msg, "init_data", None)
            if callable(initializer):
                initializer()
            for merger, should_send in merges:
                if callable(merger):
                    # Full state for new subscribers.
                    merger(handover_data_msg, should_send)
            ctx_out = MessageContext(
                msg_type=MessageType.CHANNEL_DATA_HANDOVER,
                msg=spatial_pb2.ChannelDataHandoverMessage(
                    srcChannelId=src_channel_id,
                    dstChannelId=dst_channel_id,
                    contextConnId=context_conn_id,
                    data=pack_any(handover_data_msg),
                ),
                channel_id=dst_channel_id,
            )
            ctx_out.ensure_raw_body()
            # Cache only a payload that covered every entity in the pair
            # (a partial build — e.g. a subscription refused mid-loop —
            # must not be replayed to other recipients).
            if not any_new and len(merges) == len(_targets):
                shared_ctx = ctx_out
            conn.send(ctx_out)


register_spatial_controller_type(
    "Static2DSpatialController", StaticGrid2DSpatialController
)
register_spatial_controller_type(
    "StaticGrid2DSpatialController", StaticGrid2DSpatialController
)

"""Spatial layer: grid partitioning, AOI queries, entity channels, handover.

Reference counterpart: pkg/channeld/spatial.go, message_spatial.go, entity.go.
The decision-heavy paths (cell assignment, AOI masks, handover detection)
also have batched device implementations in channeld_tpu.ops, selected via
settings.spatial_backend.
"""

from .controller import (
    SpatialController,
    SpatialInfo,
    get_spatial_controller,
    init_spatial_controller,
    register_spatial_controller_type,
    set_spatial_controller,
)
from .entity import EntityGroup, FlatEntityGroupController
from .grid import StaticGrid2DSpatialController
from .tpu_controller import TPUSpatialController

__all__ = [
    "SpatialController",
    "SpatialInfo",
    "get_spatial_controller",
    "init_spatial_controller",
    "register_spatial_controller_type",
    "set_spatial_controller",
    "EntityGroup",
    "FlatEntityGroupController",
    "StaticGrid2DSpatialController",
    "TPUSpatialController",
]

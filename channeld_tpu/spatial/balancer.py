"""Live spatial load balancer: planned, zero-loss cell migration.

The grid assignment used to be static for a server's whole lifetime:
cells moved only when their owner DIED (core/failover.py re-host), and
the overload governor (core/overload.py) could only shed a hot server's
load, never move it to an idle peer — one crowded cell pinned one
server at L2/L3 while its neighbors idled. This plane makes the
multi-server grid *elastic*, in the continuous-repartitioning tradition
of streaming spatial systems (PAPERS.md: CheetahGIS's load-aware
partition re-balancing) using the planned, transactional state-movement
discipline of live-replica migration (Spider): cells migrate between
LIVE servers, on purpose, with zero entity loss.

Runs inside the GLOBAL channel tick (the same single-writer context as
handover orchestration and failover), once per tick:

1. **Load fold** — per server: resident entities per owned cell
   (authoritative channel data), crossing rate (fed by
   ``grid._orchestrate_pair``), fan-out bytes (fed by
   ``data.fan_out_data_update``) and the server's exported overload
   pressure (``governor.server_pressure_of``). Imbalance = max/mean.
2. **Hysteresis + budget + cooldown** — a migration is planned only
   after the imbalance held above the enter threshold for
   ``balancer_hold_ticks`` consecutive updates, at most
   ``balancer_budget_per_epoch`` commits per epoch, never for a cell
   inside its post-migration cooldown, and NEVER while the overload
   ladder sits at L2+ (shedding outranks rebalancing).
3. **The migration transaction** — hottest cell on the most loaded
   server, destination by the same entity-weighted
   ``placement_score()`` failover uses:

   * *prepare* — freeze crossings into/out of the cell (detected
     crossings defer, chains collapse to one pending move per entity);
   * *drain* — wait until no handover-journal record touches the cell
     (the journal serializes migration against in-flight handovers),
     bounded by ``balancer_drain_deadline_ticks``;
   * *flip* — atomically (within the GLOBAL tick) re-own the cell and
     its resident entity channels to the destination, bootstrap the new
     owner with packed authoritative state in a ``CellMigratedMessage``
     (msgType 26), re-seed the ``_data_cell`` placement ledger, force a
     full-state resync for every other subscriber;
   * *commit/abort* — commit unfreezes and replays deferred crossings;
     any failure before the flip (destination died, drain timeout,
     overload escalation, ownership changed under us) aborts with a
     deterministic rollback: the old owner simply keeps the cell,
     nothing moved, crossings unfreeze and replay.

Every terminal result is counted twice on purpose — the
``balancer_migrations_total{result}`` counter AND a python-side ledger
— so the skew soak (``scripts/balance_soak.py``) proves the accounting
exact. Operator knobs + the interaction matrix with overload/failover:
doc/balancer.md.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional

from ..core.overload import OverloadLevel, governor as _governor
from ..core.settings import global_settings
from ..core.types import ChannelDataAccess, ConnectionType, MessageType
from ..utils.logger import get_logger

logger = get_logger("balancer")

# Migration phases.
DRAINING = "draining"
COMMITTED = "committed"
ABORTED = "aborted"


@dataclass
class CellMigration:
    migration_id: int
    cell_id: int
    src_conn: object
    dst_conn: object
    planned_tick: int
    epoch: int
    state: str = DRAINING
    t0: float = field(default_factory=time.monotonic)


class BalancerPlane:
    """One instance (``balancer``); (re-)installed by ``init_channels``."""

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        self._tick = 0
        self._epoch = 0
        self._epoch_started = 0
        self._epoch_committed = 0
        self._hold = 0  # consecutive over-enter-threshold updates
        self._armed = False  # hysteresis latch (enter/exit are apart)
        self._migration: Optional[CellMigration] = None
        self._migration_seq = 0
        self.frozen_cells: frozenset = frozenset()
        # entity id -> (old_info, new_info, provider): crossings deferred
        # while their src/dst cell is frozen (host-notify path; the TPU
        # tick keeps frozen crossings in its own deferred map).
        self._frozen_crossings: dict[int, tuple] = {}
        # cell id -> tick until which it may not migrate again.
        self._cooldown: dict[int, int] = {}
        # Crossing/byte accumulators since the last update (cleared each
        # fold into the EWMAs below).
        self._crossings_acc: dict[int, int] = {}
        self._bytes_acc: dict[int, int] = {}
        self._cell_crossing_rate: dict[int, float] = {}
        self._cell_byte_rate: dict[int, float] = {}
        self.imbalance = 0.0
        # Python-side result ledger; must match balancer_migrations_total.
        self.ledger: dict[str, int] = {}
        self.events: list[dict] = []  # one record per terminal migration
        self._gauge_cells: set[int] = set()  # cells with a published gauge

    # ---- install ---------------------------------------------------------

    def install(self) -> None:
        """Listen for server registrations: a new spatial server adopts
        any permanently-ownerless cells (the cells_unrehostable orphans
        a total loss left behind) through the placement path."""
        from ..core import events

        events.auth_complete.unlisten_for(self)
        events.auth_complete.listen_for(self, self._on_server_registered)

    # ---- signal intake (hot paths; keep them cheap) ----------------------

    def note_crossing(self, src_channel_id: int, dst_channel_id: int,
                      n: int) -> None:
        if not global_settings.balancer_enabled:
            return  # nothing drains the accumulators while disabled
        acc = self._crossings_acc
        acc[src_channel_id] = acc.get(src_channel_id, 0) + n
        acc[dst_channel_id] = acc.get(dst_channel_id, 0) + n

    def note_fanout_bytes(self, channel_id: int, nbytes: int) -> None:
        if not global_settings.balancer_enabled:
            return
        acc = self._bytes_acc
        acc[channel_id] = acc.get(channel_id, 0) + nbytes

    # ---- crossing freeze (consulted by grid.notify / the TPU tick) -------

    def defer_crossing(self, entity_id: int, old_info, new_info,
                       provider) -> bool:
        """Host-notify path: park a crossing touching a frozen cell.
        Chained moves collapse to one pending entry per entity (old_info
        stays pinned to where the data lives; new_info follows)."""
        prev = self._frozen_crossings.get(entity_id)
        if prev is not None:
            self._frozen_crossings[entity_id] = (prev[0], new_info, provider)
        else:
            self._frozen_crossings[entity_id] = (old_info, new_info, provider)
        return True

    def _unfreeze(self, ctl) -> None:
        self.frozen_cells = frozenset()
        backlog = list(self._frozen_crossings.values())
        self._frozen_crossings.clear()
        if not backlog or ctl is None:
            return
        # Replay through the batched orchestration (chains already
        # collapsed per entity; the TPU tick's own deferred map replays
        # itself next tick once the freeze is lifted).
        from .grid import StaticGrid2DSpatialController

        StaticGrid2DSpatialController.notify_crossings(ctl, backlog)

    # ---- the per-GLOBAL-tick update --------------------------------------

    def update(self, ctl) -> None:
        self._tick += 1
        st = global_settings
        if self._tick - self._epoch_started >= st.balancer_epoch_ticks:
            self._epoch += 1
            self._epoch_started = self._tick
            self._epoch_committed = 0
        if self._migration is not None:
            self._advance(ctl)
            return
        if not st.balancer_enabled:
            # Drop any signal accumulated before the disable landed —
            # re-enabling must start from a clean fold, not replay a
            # backlog as one tick's "rate".
            if self._crossings_acc or self._bytes_acc:
                self._crossings_acc.clear()
                self._bytes_acc.clear()
            return
        loads, cell_stats = self._collect(ctl)
        if len(loads) < 2:
            self._hold = 0
            return
        entity_loads = [row[1] for row in loads.values()]
        if max(entity_loads) - min(entity_loads) < st.balancer_min_entity_delta:
            # World too small/even to be worth moving authority around.
            self._hold = 0
            self._armed = False
            return
        scores = {c: row[3] for c, row in loads.items()}
        mean = sum(scores.values()) / len(scores)
        self.imbalance = (max(scores.values()) / mean) if mean > 0 else 0.0
        from ..core import metrics

        metrics.balancer_imbalance.set(self.imbalance)
        if self._armed:
            if self.imbalance < st.balancer_imbalance_exit:
                self._armed = False
                self._hold = 0
                return
        elif self.imbalance >= st.balancer_imbalance_enter:
            self._hold += 1
            if self._hold >= st.balancer_hold_ticks:
                self._armed = True
        else:
            self._hold = 0
            return
        if not self._armed:
            return
        if self._epoch_committed >= st.balancer_budget_per_epoch:
            return  # budget spent; re-plan next epoch
        self._plan(ctl, loads, cell_stats)

    # ---- load fold -------------------------------------------------------

    def _collect(self, ctl):
        """(loads, cell_stats): loads = conn -> [cells, entities,
        pressure, score]; cell_stats = cell id -> (owner, entities,
        crossing_rate). Also publishes the per-cell entity gauge and
        folds the crossing/byte accumulators into their EWMAs."""
        from ..core import metrics
        from ..core.channel import all_channels
        from ..core.failover import entity_count_of

        st = global_settings
        alpha = st.overload_alpha
        cross_rate = self._cell_crossing_rate
        byte_rate = self._cell_byte_rate
        cacc, bacc = self._crossings_acc, self._bytes_acc
        lo = st.spatial_channel_id_start
        hi = st.entity_channel_id_start

        loads: dict = {}
        cell_stats: dict[int, tuple] = {}
        seen_cells: set[int] = set()
        for cid, ch in all_channels().items():
            if not (lo <= cid < hi) or ch.is_removing():
                continue
            seen_cells.add(cid)
            ents = entity_count_of(ch)
            cr = alpha * cacc.pop(cid, 0) + (1 - alpha) * cross_rate.get(cid, 0.0)
            br = alpha * bacc.pop(cid, 0) + (1 - alpha) * byte_rate.get(cid, 0.0)
            if cr > 1e-3:
                cross_rate[cid] = cr
            else:
                cross_rate.pop(cid, None)
            if br > 1.0:
                byte_rate[cid] = br
            else:
                byte_rate.pop(cid, None)
            metrics.spatial_cell_entities.labels(cell=str(cid)).set(ents)
            self._gauge_cells.add(cid)
            if not ch.has_owner():
                continue
            owner = ch.get_owner()
            cell_stats[cid] = (owner, ents, cr)
            row = loads.setdefault(owner, [0, 0, 0.0, 0.0])
            row[0] += 1
            row[1] += ents
            row[3] += (
                ents
                + cr * st.balancer_crossing_weight
                + (br / 1024.0) * st.balancer_bytes_weight
            )
        # Accumulator keys for vanished cells must not leak.
        cacc.clear()
        bacc.clear()
        for cid in self._gauge_cells - seen_cells:
            metrics.spatial_cell_entities.labels(cell=str(cid)).set(0)
        self._gauge_cells &= seen_cells
        for owner, row in loads.items():
            row[2] = _governor.server_pressure_of(owner.id)
            row[3] += row[2] * st.balancer_pressure_weight
        return loads, cell_stats

    # ---- planning --------------------------------------------------------

    def _plan(self, ctl, loads, cell_stats) -> None:
        st = global_settings
        if self.frozen_cells:
            # Another plane (the adaptive-partitioning transaction,
            # doc/partitioning.md) holds the crossing freeze: planning a
            # migration now would clobber its frozen set on commit.
            # Transient — re-plan once the geometry op resolves.
            return
        if _governor.level >= OverloadLevel.L2:
            # Never fight the overload ladder: shedding outranks
            # rebalancing, and a migration is extra load by definition.
            self._count("vetoed")
            self._hold = 0
            logger.warning(
                "migration vetoed: overload ladder at L%d", _governor.level
            )
            return
        hottest = max(loads, key=lambda c: loads[c][3])
        candidates = []
        for cid, (owner, ents, cr) in cell_stats.items():
            if owner is not hottest or ents <= 0:
                continue
            if self._cooldown.get(cid, 0) > self._tick:
                continue
            if loads[hottest][0] <= 1:
                continue  # never strip a server of its last cell
            candidates.append((ents + cr * st.balancer_crossing_weight, cid))
        if not candidates:
            return
        cell_score, cell_id = max(candidates)

        from ..core.failover import pick_placement

        dest_loads = {
            c: row[:2]
            for c, row in loads.items()
            if c is not hottest
            and not c.is_closing()
            and row[2] < st.balancer_dest_pressure_max
        }
        if not dest_loads:
            self._count("vetoed")
            self._hold = 0
            logger.warning(
                "migration of cell %d vetoed: every destination at/above "
                "pressure %.2f", cell_id, st.balancer_dest_pressure_max,
            )
            return
        dst = pick_placement(dest_loads)
        # The move must actually flatten the fold: if the post-move
        # worst of (shrunken src, grown dst) is no better than the src
        # today, migrating just relocates the hotspot (the classic
        # one-giant-cell case — no destination can absorb it).
        src_score = loads[hottest][3]
        if max(src_score - cell_score, loads[dst][3] + cell_score) >= src_score:
            return
        self._migration_seq += 1
        self._migration = CellMigration(
            migration_id=self._migration_seq,
            cell_id=cell_id,
            src_conn=hottest,
            dst_conn=dst,
            planned_tick=self._tick,
            epoch=self._epoch,
        )
        self.frozen_cells = frozenset((cell_id,))
        self._count("planned")
        logger.info(
            "migration %d planned: cell %d, server %d -> %d (imbalance "
            "%.2f); crossings frozen, draining journal",
            self._migration_seq, cell_id, hottest.id, dst.id, self.imbalance,
        )

    def plan_directed(self, cell_id: int, dst_conn, reason: str = "") -> bool:
        """Directed migration on behalf of another control plane — the
        adaptive-partitioning governor reuniting a cold sibling group's
        diverged owners before a merge (doc/partitioning.md). The SAME
        transaction (freeze -> drain -> flip, same ledger/metric) with
        the candidate/hysteresis/cooldown policy left to the caller;
        only the hard safety guards stay: one migration at a time, no
        clobbering a held crossing freeze, never at overload L2+, never
        to a dead or identical destination. Advances even while
        autonomous balancing is disabled (``update`` drains an in-flight
        migration before consulting ``balancer_enabled``)."""
        from ..core.channel import get_channel

        if self._migration is not None or self.frozen_cells:
            return False
        if _governor.level >= OverloadLevel.L2:
            return False
        ch = get_channel(cell_id)
        if ch is None or ch.is_removing() or not ch.has_owner():
            return False
        src = ch.get_owner()
        if dst_conn is None or dst_conn is src or dst_conn.is_closing():
            return False
        self._migration_seq += 1
        self._migration = CellMigration(
            migration_id=self._migration_seq,
            cell_id=cell_id,
            src_conn=src,
            dst_conn=dst_conn,
            planned_tick=self._tick,
            epoch=self._epoch,
        )
        self.frozen_cells = frozenset((cell_id,))
        self._count("planned")
        logger.info(
            "migration %d planned (directed%s): cell %d, server %d -> %d; "
            "crossings frozen, draining journal",
            self._migration_seq, f": {reason}" if reason else "",
            cell_id, src.id, dst_conn.id,
        )
        return True

    # ---- the in-flight transaction ---------------------------------------

    def _advance(self, ctl) -> None:
        from ..core.channel import get_channel
        from ..core.failover import journal

        st = global_settings
        mig = self._migration
        ch = get_channel(mig.cell_id)
        if ch is None or ch.is_removing():
            self._abort(ctl, mig, "cell_removed")
            return
        if ch.get_owner() is not mig.src_conn:
            # Failover (or anything else) re-owned the cell under us:
            # the world changed, the plan is void.
            self._abort(ctl, mig, "owner_changed")
            return
        if mig.dst_conn.is_closing():
            self._abort(ctl, mig, "dst_dead")
            return
        if _governor.level >= OverloadLevel.L2:
            self._abort(ctl, mig, "overload")
            return
        age = self._tick - mig.planned_tick
        if journal.in_flight_touching(mig.cell_id):
            if age > st.balancer_drain_deadline_ticks:
                self._abort(ctl, mig, "drain_timeout")
            return  # keep draining
        if age < st.balancer_freeze_min_ticks:
            return  # queued entity hops on the cell channel still run
        self._execute(ctl, mig, ch)

    def _abort(self, ctl, mig: CellMigration, reason: str) -> None:
        """Deterministic rollback: nothing has moved before the flip, so
        the old owner simply keeps the cell; unfreeze and replay."""
        mig.state = ABORTED
        self._migration = None
        self._unfreeze(ctl)
        # A short lockout so the same plan doesn't re-arm next tick into
        # the same failure.
        self._cooldown[mig.cell_id] = (
            self._tick + global_settings.balancer_hold_ticks * 4
        )
        self._count("aborted")
        elapsed_ms = (time.monotonic() - mig.t0) * 1000.0
        from ..core import metrics
        from ..core.channel import get_channel

        metrics.balancer_migration_ms.observe(elapsed_ms)
        ev = self._event(mig, reason, elapsed_ms)
        # The rollback property, captured AT resolution (the cell may
        # legitimately re-plan and move moments later — soaks must not
        # race that): the old owner still holds the cell.
        ch = get_channel(mig.cell_id)
        ev["owner_rolled_back"] = (
            ch is not None and ch.get_owner() is mig.src_conn
        )
        self.events.append(ev)
        from ..core.tracing import recorder as _trace

        if _trace.enabled:
            _trace.note_anomaly(
                "migration_abort",
                f"migration {mig.migration_id} cell {mig.cell_id}: {reason}",
            )
        logger.warning(
            "migration %d aborted (%s): cell %d stays with server %d",
            mig.migration_id, reason, mig.cell_id, mig.src_conn.id,
        )

    def _execute(self, ctl, mig: CellMigration, ch) -> None:
        """The flip: runs start-to-finish inside this GLOBAL tick."""
        from ..core import metrics
        from ..core.channel import get_channel
        from ..core.failover import plane as _failover_plane
        from ..core.subscription import subscribe_to_channel
        from ..core.subscription_messages import send_subscribed
        from ..protocol import control_pb2, spatial_pb2

        src, dst = mig.src_conn, mig.dst_conn
        prev_owner_id = src.id

        # New owner: WRITE subscription; the authoritative bootstrap
        # rides the CellMigratedMessage, so the usual first full-state
        # fan-out would be redundant bytes.
        ch.set_owner(dst)
        opts = control_pb2.ChannelSubscriptionOptions(
            dataAccess=ChannelDataAccess.WRITE_ACCESS,
            skipSelfUpdateFanOut=True,
            skipFirstFanOut=True,
        )
        cs, should_send = subscribe_to_channel(dst, ch, opts)
        if should_send and cs is not None:
            send_subscribed(dst, ch, dst, 0, cs.options)
        # Old owner: downgrade to observer (it usually keeps border
        # interest in the cell); authority checks key off get_owner().
        old_sub = ch.subscribed_connections.get(src)
        if old_sub is not None:
            old_sub.options.dataAccess = ChannelDataAccess.READ_ACCESS

        # Resident entity channels move authority with the cell.
        entity_ids = []
        ents = getattr(ch.get_data_message(), "entities", None)
        if ents is not None:
            for eid in sorted(ents):
                ech = get_channel(eid)
                if ech is None or ech.is_removing():
                    continue
                if ech.get_owner() is src or not ech.has_owner():
                    _failover_plane._repoint_entity(ech, dst)
                    entity_ids.append(eid)

        from ..core.failover import announce_authority_change

        announce_authority_change(
            ch, dst, MessageType.CELL_MIGRATED,
            lambda c, eids=list(entity_ids), mid=mig.migration_id:
                spatial_pb2.CellMigratedMessage(
                    channelId=c.id,
                    prevOwnerConnId=prev_owner_id,
                    newOwnerConnId=dst.id,
                    entityIds=eids,
                    migrationId=mid,
                ),
        )
        # Placement-ledger re-seed (same hook failover uses): entities
        # resident in the cell keep exactly one authoritative row.
        hook = getattr(ctl, "on_cell_rehosted", None)
        if hook is not None:
            hook(ch.id, dst)

        mig.state = COMMITTED
        self._migration = None
        self._unfreeze(ctl)
        self._cooldown[mig.cell_id] = (
            self._tick + global_settings.balancer_cooldown_ticks
        )
        self._epoch_committed += 1
        self._count("committed")
        elapsed_ms = (time.monotonic() - mig.t0) * 1000.0
        metrics.balancer_migration_ms.observe(elapsed_ms)
        ev = self._event(mig, "committed", elapsed_ms)
        ev["entities_repointed"] = len(entity_ids)
        self.events.append(ev)
        logger.info(
            "migration %d committed: cell %d, server %d -> %d (%d entity "
            "channels re-pointed, %.1fms)",
            mig.migration_id, mig.cell_id, prev_owner_id, dst.id,
            len(entity_ids), elapsed_ms,
        )

    # ---- orphan adoption on server registration --------------------------

    def _on_server_registered(self, data) -> None:
        """cells_unrehostable fix: a server registering AFTER a total
        loss adopts the permanently-ownerless cells through the same
        placement path migrations use."""
        conn = data.connection
        if conn.connection_type != ConnectionType.SERVER:
            return
        # auth_complete fires for every auth RESULT; only a connection
        # that actually authenticated may adopt authority (a failed-auth
        # server conn would otherwise own cells it can never serve).
        from ..core.types import ConnectionState

        if getattr(conn, "state", None) != ConnectionState.AUTHENTICATED:
            return
        if not global_settings.failover_enabled:
            return
        from ..core.channel import get_global_channel

        if not self._ownerless_cells():
            return
        gch = get_global_channel()
        if gch is None or gch.is_removing():
            self._adopt_orphans(conn)
        else:
            gch.execute(lambda _ch, c=conn: self._adopt_orphans(c))

    def _ownerless_cells(self) -> list[int]:
        """PERMANENTLY ownerless spatial cells: no live owner AND no
        stashed recoverable owner subscription (a cell whose owner is
        merely inside its recovery window must never be adopted out from
        under it — recovery restores that ownership)."""
        from ..core.channel import all_channels

        lo = global_settings.spatial_channel_id_start
        hi = global_settings.entity_channel_id_start
        out = []
        for cid, ch in all_channels().items():
            if not (lo <= cid < hi) or ch.is_removing() or ch.has_owner():
                continue
            if any(
                rs.is_owner for rs in ch.recoverable_subs.values()
            ):
                continue
            out.append(cid)
        return sorted(out)

    def _adopt_orphans(self, new_conn) -> None:
        from ..core.channel import all_channels, get_channel
        from ..core.failover import (
            collect_spatial_loads,
            entity_count_of,
            pick_placement,
            plane as _failover_plane,
        )

        if new_conn.is_closing():
            return
        orphans = self._ownerless_cells()
        if not orphans:
            return
        t0 = time.monotonic()
        loads = collect_spatial_loads()
        loads.setdefault(new_conn, [0, 0])
        st = global_settings
        hi = st.entity_channel_id_start
        assignments: dict[int, object] = {}
        for cid in orphans:
            target = pick_placement(loads)
            loads[target][0] += 1
            loads[target][1] += entity_count_of(get_channel(cid))
            assignments[cid] = target
        # Ownerless resident entity channels re-point with their cell.
        repointed: dict[int, list[int]] = {}
        for cid, target in assignments.items():
            ch = get_channel(cid)
            ents = getattr(ch.get_data_message(), "entities", None) or ()
            for eid in sorted(ents):
                ech = get_channel(eid)
                if ech is None or ech.is_removing() or ech.has_owner():
                    continue
                _failover_plane._repoint_entity(ech, target)
                _failover_plane.ledger["entities_repointed"] += 1
                repointed.setdefault(cid, []).append(eid)
        for cid, target in assignments.items():
            _failover_plane._rehost_cell(
                get_channel(cid), target, 0, repointed.get(cid, [])
            )
        elapsed_ms = (time.monotonic() - t0) * 1000.0
        # Keep the failover event stream's accounting exact (soaks check
        # rehost totals against the per-event sums).
        _failover_plane.events.append({
            "pit": getattr(new_conn, "pit", ""),
            "prev_conn_id": 0,
            "reason": "registration_adoption",
            "orphan_cells": orphans,
            "rehosted": {str(c): t.id for c, t in assignments.items()},
            "entities_repointed": sum(len(v) for v in repointed.values()),
            "handovers_aborted": 0,
            "duration_ms": round(elapsed_ms, 3),
        })
        logger.warning(
            "server %d registered with %d ownerless cells pending: "
            "adopted %s (%.1fms)",
            new_conn.id, len(orphans),
            {c: t.id for c, t in assignments.items()}, elapsed_ms,
        )

    # ---- accounting ------------------------------------------------------

    def _count(self, result: str) -> None:
        self.ledger[result] = self.ledger.get(result, 0) + 1
        from ..core import metrics

        metrics.balancer_migrations.labels(result=result).inc()

    def _event(self, mig: CellMigration, result: str,
               elapsed_ms: float) -> dict:
        return {
            "migration_id": mig.migration_id,
            "cell": mig.cell_id,
            "from": mig.src_conn.id,
            "to": mig.dst_conn.id,
            "result": result,
            "epoch": mig.epoch,
            "planned_tick": mig.planned_tick,
            "resolved_tick": self._tick,
            "imbalance": round(self.imbalance, 4),
            "duration_ms": round(elapsed_ms, 3),
        }

    def migration_in_flight(self) -> Optional[CellMigration]:
        return self._migration

    def report(self) -> dict:
        return {
            "ledger": dict(self.ledger),
            "events": list(self.events),
            "imbalance": round(self.imbalance, 4),
            "in_flight": self._migration is not None,
            "frozen_cells": sorted(self.frozen_cells),
            "cooldowns": dict(self._cooldown),
            "epoch": self._epoch,
        }


balancer = BalancerPlane()


def reset_balancer() -> None:
    """Test hook (also run by init_channels at world boot)."""
    balancer.reset()

"""Adaptive partitioning plane (doc/partitioning.md).

Live quadtree cell split/merge so extreme density degrades gracefully
instead of melting one server: a density governor — fed by the same
per-cell resident counts the balancer folds — plans splits of hot cells
and merges of cold sibling groups, executed as transactional geometry
epochs riding the existing machinery:

  freeze  -> crossings touching the cell park with the balancer's
             frozen-crossing map (grid.notify / the TPU tick defer);
  drain   -> the handover journal must stop touching the cell
             (``in_flight_touching``), bounded by a drain deadline;
  commit  -> one WAL geometry record (the commit point), the new
             geometry applied (device arrays rebuild generation-fenced),
             child/parent channels created with the same owner, resident
             entities repartitioned through the transactional handover
             journal, authority announced per new cell
             (CellGeometryUpdateMessage: packed-state bootstrap for the
             owner, identifier-only + forced resync for everyone else),
             and the stale cells removed;
  abort   -> nothing has mutated before the WAL record, so the old
             geometry simply stays; unfreeze and replay.

Guard discipline matches the balancer plane: two-sided density
hysteresis (split/merge thresholds kept apart), hold ticks, a per-epoch
budget, per-cell cooldown, a hard veto at overload L2+ (with a forced
``density_hotspot`` flight-recorder dump when a cell is hot but the
split is vetoed), never split past the depth bound, never merge a group
with in-flight residents. Every terminal result is double-entried:
``partition_ops_total{op,result}`` must equal the python ledger here.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional

from ..core.overload import OverloadLevel, governor as _governor
from ..core.settings import global_settings
from ..utils.logger import get_logger
from .balancer import balancer as _balancer

logger = get_logger("spatial.partition")

# GeometryOp.state values.
DRAINING = "draining"       # frozen; waiting for the journal to clear
COMMITTING = "committing"   # geometry written; moves/removals queued
COMMITTED = "committed"
ABORTED = "aborted"


@dataclass
class GeometryOp:
    """One in-flight split/merge transaction."""

    op_id: int
    op: str                  # "split" | "merge"
    target: int              # split: the leaf to split; merge: the parent
    cells: tuple             # channels frozen for the op's duration
    planned_tick: int
    epoch: int               # governor epoch the op charges its budget to
    state: str = DRAINING
    t0: float = field(default_factory=time.monotonic)
    committed_tick: int = 0
    moved: int = 0           # entities repartitioned at commit


class PartitionPlane:
    """One instance (``partition``); driven from the grid tick."""

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        self._tick = 0
        self._epoch = 0
        self._epoch_started = 0
        self._epoch_committed = 0
        self._op: Optional[GeometryOp] = None
        self._op_seq = 0
        # cell id -> consecutive hot/cold evaluations (two counters so a
        # cell oscillating across one threshold never arms the other).
        self._split_hold: dict[int, int] = {}
        self._merge_hold: dict[int, int] = {}
        # cell id -> tick until which it may not be re-operated on.
        self._cooldown: dict[int, int] = {}
        # Python-side result ledger; must match partition_ops_total.
        self.ledger: dict[str, int] = {}
        self.events: list[dict] = []  # one record per terminal op

    # ---- the per-GLOBAL-tick update --------------------------------------

    def update(self, ctl) -> None:
        self._tick += 1
        st = global_settings
        if self._tick - self._epoch_started >= st.partition_epoch_ticks:
            self._epoch += 1
            self._epoch_started = self._tick
            self._epoch_committed = 0
        if self._op is not None:
            self._advance(ctl)
            return
        if not st.partition_enabled:
            if self._split_hold or self._merge_hold:
                self._split_hold.clear()
                self._merge_hold.clear()
            return
        if getattr(ctl, "tree", None) is None:
            return
        if self._tick % max(1, st.partition_eval_ticks) != 0:
            return
        self._evaluate(ctl)

    # ---- governor evaluation ---------------------------------------------

    def _cell_counts(self, ctl) -> dict[int, int]:
        """Resident entities per live spatial channel (one sweep)."""
        from ..core.channel import all_channels
        from ..core.failover import entity_count_of

        st = global_settings
        lo = st.spatial_channel_id_start
        hi = st.entity_channel_id_start
        return {
            cid: entity_count_of(ch)
            for cid, ch in all_channels().items()
            if lo <= cid < hi and not ch.is_removing()
        }

    def _evaluate(self, ctl) -> None:
        from ..core import metrics
        from ..core.failover import journal as _journal
        from ..federation.directory import directory as _directory

        st = global_settings
        tree = ctl.tree
        counts = self._cell_counts(ctl)
        for cell in counts:
            if tree.is_leaf(cell):
                metrics.spatial_cell_depth.labels(cell=str(cell)).set(
                    tree.depth_of(cell)
                )

        # ---- split arming (hottest first) ----
        hot = sorted(
            (
                (n, cell) for cell, n in counts.items()
                if n >= st.partition_split_entities
                and tree.is_leaf(cell)
                and _directory.is_local_cell(cell)
            ),
            reverse=True,
        )
        armed_split: Optional[int] = None
        hot_cells = {cell for _, cell in hot}
        for cell in list(self._split_hold):
            if cell not in hot_cells:
                del self._split_hold[cell]
        for n, cell in hot:
            held = self._split_hold.get(cell, 0) + 1
            self._split_hold[cell] = held
            if held < st.partition_hold_ticks or armed_split is not None:
                continue
            veto = None
            if tree.depth_of(cell) >= st.partition_max_depth:
                veto = f"depth bound {st.partition_max_depth}"
            elif _governor.level >= OverloadLevel.L2:
                veto = f"overload ladder at L{_governor.level}"
            elif any(not _directory.is_local_cell(c)
                     for c in tree.children(cell)):
                # A directory override redirects the parent but not its
                # would-be children (overrides are per-cell-id): a split
                # would scatter the cell across gateways.
                veto = "children not locally mapped"
            if veto is not None:
                self._split_hold[cell] = 0
                self._count("split", "vetoed")
                self._hotspot(cell, n, veto)
                continue
            if self._cooldown.get(cell, 0) > self._tick:
                continue
            armed_split = cell

        if armed_split is not None and self._may_transact():
            self._plan_split(ctl, armed_split)
            return

        # ---- merge arming (coldest sibling group first) ----
        cold: list[tuple[int, int]] = []
        for parent in tree.splits:
            children = tree.children(parent)
            if any(c in tree.splits for c in children):
                continue  # only a fully-leaf sibling group merges
            if not all(_directory.is_local_cell(c) for c in children):
                continue
            if not _directory.is_local_cell(parent):
                continue
            total = sum(counts.get(c, 0) for c in children)
            if total <= st.partition_merge_entities:
                cold.append((total, parent))
        cold_parents = {p for _, p in cold}
        for parent in list(self._merge_hold):
            if parent not in cold_parents:
                del self._merge_hold[parent]
        armed_merge: Optional[int] = None
        for total, parent in sorted(cold):
            held = self._merge_hold.get(parent, 0) + 1
            self._merge_hold[parent] = held
            if held < st.partition_hold_ticks or armed_merge is not None:
                continue
            if _governor.level >= OverloadLevel.L2:
                self._merge_hold[parent] = 0
                self._count("merge", "vetoed")
                continue
            children = tree.children(parent)
            if any(self._cooldown.get(c, 0) > self._tick for c in children):
                continue
            if any(_journal.in_flight_touching(c) for c in children):
                # Never merge a group with in-flight residents: the
                # drain phase would begin with the group already dirty.
                continue
            by_owner: dict = {}
            for c in children:
                by_owner.setdefault(self._owner_of(c), []).append(c)
            if None in by_owner:
                continue  # a child is mid-rehost; failover owns this
            if len(by_owner) > 1:
                # Authority diverged (the balancer placed split granules
                # on different servers): the merge needs ONE owner, so
                # reunite the group first through the balancer's own
                # migration transaction — directed, one child per
                # evaluation, toward the group's majority owner. The
                # group stays cold and held, so evaluation re-arrives
                # here until authority converges and the merge arms.
                self._consolidate(parent, by_owner)
                continue
            armed_merge = parent

        if armed_merge is not None and self._may_transact():
            self._plan_merge(ctl, armed_merge)

    def _consolidate(self, parent: int, by_owner: dict) -> None:
        """Plan ONE directed migration moving an outlier child back to
        the cold sibling group's majority owner (ties break on the
        lowest conn id, so every gateway converges on the same home).
        Rides the balancer's full transaction + accounting; this plane
        only supplies the policy."""
        if _balancer.migration_in_flight() is not None:
            return
        if _balancer.frozen_cells:
            return
        home = max(by_owner, key=lambda o: (len(by_owner[o]), -o.id))
        if home is None or home.is_closing():
            return
        outliers = sorted(
            c for o, cs in by_owner.items() if o is not home for c in cs
        )
        for cell in outliers:
            if _balancer.plan_directed(
                cell, home, reason=f"reunite sibling group of {parent}"
            ):
                return

    def _may_transact(self) -> bool:
        """One geometry op at a time, never concurrent with a balancer
        migration (the two planes share the crossing freeze), and only
        within the epoch budget."""
        st = global_settings
        if self._epoch_committed >= st.partition_budget_per_epoch:
            return False
        if _balancer.migration_in_flight() is not None:
            return False
        if _balancer.frozen_cells:
            return False
        return True

    def _owner_of(self, cell_id: int):
        from ..core.channel import get_channel

        ch = get_channel(cell_id)
        return ch.get_owner() if ch is not None else None

    def _hotspot(self, cell: int, n: int, veto: str) -> None:
        """Flight-recorder anomaly: a cell is past the split threshold
        but the split is vetoed — the exact moment an operator needs a
        timeline (the density has no remedy until the veto lifts)."""
        from ..core.tracing import recorder as _trace

        if _trace.enabled:
            _trace.note_anomaly(
                "density_hotspot",
                f"cell {cell} at {n} entities >= split threshold "
                f"{global_settings.partition_split_entities} but split "
                f"vetoed ({veto})",
                force=True,
            )

    # ---- planning --------------------------------------------------------

    def _plan_split(self, ctl, cell: int) -> None:
        self._op_seq += 1
        self._op = GeometryOp(
            op_id=self._op_seq, op="split", target=cell,
            cells=(cell,), planned_tick=self._tick, epoch=self._epoch,
        )
        _balancer.frozen_cells = frozenset((cell,))
        self._count("split", "planned")
        self._split_hold.pop(cell, None)
        logger.info(
            "geometry op %d planned: split cell %d (depth %d); crossings "
            "frozen, draining journal",
            self._op_seq, cell, ctl.tree.depth_of(cell),
        )

    def _plan_merge(self, ctl, parent: int) -> None:
        children = tuple(ctl.tree.children(parent))
        self._op_seq += 1
        self._op = GeometryOp(
            op_id=self._op_seq, op="merge", target=parent,
            cells=children, planned_tick=self._tick, epoch=self._epoch,
        )
        _balancer.frozen_cells = frozenset(children)
        self._count("merge", "planned")
        self._merge_hold.pop(parent, None)
        logger.info(
            "geometry op %d planned: merge cells %s back into %d; "
            "crossings frozen, draining journal",
            self._op_seq, list(children), parent,
        )

    # ---- the in-flight transaction ---------------------------------------

    def _advance(self, ctl) -> None:
        from ..core.channel import get_channel
        from ..core.failover import journal as _journal

        st = global_settings
        op = self._op
        if op.state == COMMITTING:
            self._advance_commit(ctl, op)
            return
        # ---- draining ----
        live = [get_channel(c) for c in op.cells]
        if any(ch is None or ch.is_removing() for ch in live):
            self._abort(ctl, op, "cell_removed")
            return
        owners = {ch.get_owner() for ch in live}
        if len(owners) != 1:
            self._abort(ctl, op, "owner_diverged")
            return
        owner = next(iter(owners))
        if owner is not None and owner.is_closing():
            # The server that would own the new cells died mid-drain:
            # the packed-state bootstrap has no recipient. Failover will
            # re-host; re-plan against the new world.
            self._abort(ctl, op, "dst_dead")
            return
        if _governor.level >= OverloadLevel.L2:
            self._abort(ctl, op, "overload")
            return
        age = self._tick - op.planned_tick
        if any(_journal.in_flight_touching(c) for c in op.cells):
            if age > st.partition_drain_deadline_ticks:
                self._abort(ctl, op, "drain_timeout")
            return  # keep draining
        if age < st.partition_freeze_min_ticks:
            return  # queued entity hops on the frozen cells still run
        if op.op == "split":
            self._execute_split(ctl, op)
        else:
            self._execute_merge(ctl, op)

    def _advance_commit(self, ctl, op: GeometryOp) -> None:
        """Post-commit settling: the geometry IS committed (WAL record
        written, tree applied) — this only waits for the queued data
        moves and channel removals to run before unfreezing."""
        from ..core.channel import get_channel
        from ..core.failover import journal as _journal

        stale = op.cells if op.op == "split" else tuple(
            c for c in op.cells
        )
        settling = any(
            get_channel(c) is not None for c in stale
        ) or any(_journal.in_flight_touching(c) for c in op.cells)
        if settling and self._tick - op.committed_tick < 64:
            return
        if settling:
            logger.warning(
                "geometry op %d: stale cells still settling %d ticks "
                "after commit; unfreezing anyway",
                op.op_id, self._tick - op.committed_tick,
            )
        self._finalize(ctl, op, COMMITTED, "committed")

    def _abort(self, ctl, op: GeometryOp, reason: str) -> None:
        """Deterministic rollback: nothing has mutated before the WAL
        geometry record, so the old geometry simply stays."""
        self._finalize(ctl, op, ABORTED, reason)

    def _finalize(self, ctl, op: GeometryOp, state: str,
                  reason: str) -> None:
        op.state = state if state in (COMMITTED, ABORTED) else op.state
        self._op = None
        _balancer._unfreeze(ctl)
        st = global_settings
        lockout = (
            st.partition_cooldown_ticks if state == COMMITTED
            else st.partition_hold_ticks * 4
        )
        for c in (op.target,) + op.cells:
            self._cooldown[c] = self._tick + lockout
        if state == COMMITTED:
            self._epoch_committed += 1
        result = "committed" if state == COMMITTED else "aborted"
        self._count(op.op, result)
        elapsed_ms = (time.monotonic() - op.t0) * 1000.0
        ev = {
            "op_id": op.op_id, "op": op.op, "target": op.target,
            "cells": list(op.cells), "result": result, "reason": reason,
            "elapsed_ms": round(elapsed_ms, 3), "moved": op.moved,
            "epoch": ctl.geometry_epoch,
            "governor_epoch": op.epoch,
            "planned_tick": op.planned_tick,
            "resolved_tick": self._tick,
        }
        self.events.append(ev)
        if state == ABORTED:
            from ..core.tracing import recorder as _trace

            if _trace.enabled:
                _trace.note_anomaly(
                    "partition_abort",
                    f"geometry op {op.op_id} {op.op} {op.target}: {reason}",
                )
            logger.warning(
                "geometry op %d aborted (%s): %s of %d rolled back, "
                "geometry unchanged at epoch %d",
                op.op_id, reason, op.op, op.target, ctl.geometry_epoch,
            )
        else:
            logger.info(
                "geometry op %d committed: %s of %d -> epoch %d (%d "
                "entities repartitioned, %.1fms)",
                op.op_id, op.op, op.target, ctl.geometry_epoch,
                op.moved, elapsed_ms,
            )

    # ---- commit execution ------------------------------------------------

    def _execute_split(self, ctl, op: GeometryOp) -> None:
        from ..core.channel import get_channel
        from ..core.wal import wal as _wal

        tree = ctl.tree
        cell = op.target
        parent_ch = get_channel(cell)
        if parent_ch is None:
            self._abort(ctl, op, "cell_removed")
            return
        try:
            new_splits = tree.split_result(cell)
        except ValueError as e:
            self._abort(ctl, op, f"geometry_invalid:{e}")
            return
        children = tree.children(cell)
        epoch_next = tree.epoch + 1

        # Partition residents by last known position; unknown positions
        # bootstrap into the child containing the parent's center (the
        # same deterministic fallback WAL replay re-homes with) and
        # re-sort on their next movement.
        ents = getattr(parent_ch.get_data_message(), "entities", None) or {}
        cx, cz = tree.center(cell)
        per_child: dict[int, dict] = {c: {} for c in children}
        for eid, data in dict(ents).items():
            pos = ctl.entity_position(eid)
            if pos is None:
                idx = 3
            else:
                idx = (1 if pos[0] >= cx else 0) + (
                    2 if pos[1] >= cz else 0
                )
            per_child[children[idx]][eid] = data
        op.moved = sum(len(v) for v in per_child.values())

        # THE COMMIT POINT: the geometry record hits the WAL before any
        # mutation it implies — a torn tail either has the record (and
        # replay lands on the new geometry, re-homing whatever the lost
        # mutations left behind) or doesn't (and replay lands on the old
        # geometry with nothing moved): deterministic either way.
        if _wal.enabled:
            _wal.log_geometry(epoch_next, new_splits)
        ctl.apply_geometry(epoch_next, new_splits)

        owner = parent_ch.get_owner()
        for child in children:
            child_ch = self._create_cell_channel(child, parent_ch, owner)
            moved = per_child[child]
            if moved:
                self._move_entities(ctl, cell, child, moved)
            self._announce(ctl, child_ch, owner, op="split",
                           parent=cell, entity_ids=sorted(moved))
        self._retire_cell(parent_ch)
        op.state = COMMITTING
        op.committed_tick = self._tick
        logger.info(
            "geometry op %d: split of cell %d committed at epoch %d "
            "(%d residents -> %s)",
            op.op_id, cell, epoch_next, op.moved,
            {c: len(v) for c, v in per_child.items()},
        )

    def _execute_merge(self, ctl, op: GeometryOp) -> None:
        from ..core.channel import get_channel
        from ..core.wal import wal as _wal

        tree = ctl.tree
        parent = op.target
        child_chs = []
        for c in op.cells:
            ch = get_channel(c)
            if ch is None:
                self._abort(ctl, op, "cell_removed")
                return
            child_chs.append(ch)
        try:
            new_splits = tree.merge_result(parent)
        except ValueError as e:
            self._abort(ctl, op, f"geometry_invalid:{e}")
            return
        epoch_next = tree.epoch + 1
        owner = child_chs[0].get_owner()

        if _wal.enabled:
            _wal.log_geometry(epoch_next, new_splits)
        ctl.apply_geometry(epoch_next, new_splits)

        parent_ch = self._create_cell_channel(parent, child_chs[0], owner)
        moved_ids: list[int] = []
        for ch in child_chs:
            # Merge every child's subscriber set onto the parent (the
            # union is what border interest looked like pre-split).
            self._copy_subscriptions(ch, parent_ch)
            ents = dict(
                getattr(ch.get_data_message(), "entities", None) or {}
            )
            if ents:
                self._move_entities(ctl, ch.id, parent, ents)
                moved_ids.extend(ents)
        op.moved = len(moved_ids)
        self._announce(ctl, parent_ch, owner, op="merge",
                       parent=parent, entity_ids=sorted(moved_ids))
        for ch in child_chs:
            self._retire_cell(ch)
        op.state = COMMITTING
        op.committed_tick = self._tick
        logger.info(
            "geometry op %d: merge into cell %d committed at epoch %d "
            "(%d residents)",
            op.op_id, parent, epoch_next, op.moved,
        )

    # ---- commit plumbing -------------------------------------------------

    def _create_cell_channel(self, cell_id: int, template_ch, owner):
        """A new leaf channel cloned structurally from ``template_ch``
        (same data type + merge options, same subscribers), owned by the
        same server — geometry ops never move authority by themselves."""
        from ..core.channel import create_channel_with_id, get_channel
        from ..core.types import ChannelType

        ch = get_channel(cell_id)
        if ch is not None and not ch.is_removing():
            return ch  # settled already (replayed geometry)
        ch = create_channel_with_id(cell_id, ChannelType.SPATIAL, owner)
        template_data = template_ch.get_data_message()
        merge_options = getattr(template_ch.data, "merge_options", None)
        ch.init_data(
            type(template_data)() if template_data is not None else None,
            merge_options,
        )
        self._copy_subscriptions(template_ch, ch)
        return ch

    def _copy_subscriptions(self, src_ch, dst_ch) -> None:
        from ..core.subscription import subscribe_to_channel

        for conn, cs in list(src_ch.subscribed_connections.items()):
            if conn is None or conn.is_closing():
                continue
            subscribe_to_channel(conn, dst_ch, cs.options)

    def _move_entities(self, ctl, src_id: int, dst_id: int,
                       ents: dict) -> None:
        """The transactional repartition hop — the same journal
        discipline as grid._orchestrate_pair step 2: prepare -> the src
        remove marks, the dst add commits, the placement ledger flips
        only on commit. A crash between the hops replays to exactly one
        owning cell."""
        from ..core.channel import get_channel
        from ..core.failover import journal as _journal

        src_ch, dst_ch = get_channel(src_id), get_channel(dst_id)
        if src_ch is None or dst_ch is None:
            return
        records = _journal.prepare(ents, src_id, dst_id)
        moved_hook = getattr(ctl, "_note_entity_data_moved", None)

        def _remove(ch):
            remover = getattr(ch.get_data_message(), "remove_entity", None)
            if remover is None:
                ch.logger.warning("spatial data can't remove entities")
                return
            for eid in ents:
                remover(eid)
            _journal.note_removed(records)

        def _add(ch):
            adder = getattr(ch.get_data_message(), "add_entity", None)
            if adder is None:
                ch.logger.warning("spatial data can't add entities")
                for rec in records:
                    _journal.abort(rec)
                return
            for eid, data in ents.items():
                if data is not None:
                    adder(eid, data)
            flips = _journal.commit(records)
            if moved_hook is not None and flips:
                moved_hook(flips, dst_id)

        src_ch.execute(_remove)
        dst_ch.execute(_add)

    def _announce(self, ctl, ch, owner, op: str, parent: int,
                  entity_ids: list) -> None:
        """Authority announcement per new cell: the owner's copy carries
        the packed authoritative bootstrap, everyone else gets the
        identifier-only form + a forced full resync — the same fan-out
        discipline as failover re-hosts and balancer migrations."""
        if owner is None:
            return
        from ..core.failover import announce_authority_change
        from ..core.types import MessageType
        from ..protocol import spatial_pb2

        tree = ctl.tree
        build = (
            lambda c, eids=list(entity_ids), o=op, p=parent,
            epoch=tree.epoch, splits=sorted(tree.splits),
            oid=owner.id:
                spatial_pb2.CellGeometryUpdateMessage(
                    geometryEpoch=epoch,
                    splitCells=splits,
                    channelId=c.id,
                    parentChannelId=p,
                    prevOwnerConnId=oid,
                    newOwnerConnId=oid,
                    entityIds=eids,
                    op=o,
                )
        )
        # Queued on the new cell's OWN FIFO: the repartition adds were
        # queued there first, so the owner's packed-state bootstrap packs
        # the post-move data, not the empty just-created channel.
        ch.execute(
            lambda c: announce_authority_change(
                c, owner, MessageType.CELL_GEOMETRY_UPDATE, build
            )
        )

    def _retire_cell(self, ch) -> None:
        """Queue the stale cell's teardown behind its pending removes:
        unsubscribe every connection, then remove the channel (the WAL
        tombstone rides remove_channel)."""
        from ..core.channel import remove_channel
        from ..core.subscription import unsubscribe_from_channel
        from ..core.subscription_messages import send_unsubscribed

        def _teardown(c):
            for conn in list(c.subscribed_connections):
                if conn is None or conn.is_closing():
                    continue
                try:
                    unsubscribe_from_channel(conn, c)
                    send_unsubscribed(conn, c, None, 0)
                except KeyError:
                    pass
            remove_channel(c)

        ch.execute(_teardown)

    # ---- bookkeeping -----------------------------------------------------

    def _count(self, op: str, result: str) -> None:
        key = f"{op}_{result}"
        self.ledger[key] = self.ledger.get(key, 0) + 1
        from ..core import metrics

        metrics.partition_ops.labels(op=op, result=result).inc()

    def op_in_flight(self) -> Optional[GeometryOp]:
        return self._op

    def report(self) -> dict:
        """Ops/soak surface."""
        return {
            "tick": self._tick,
            "epoch": self._epoch,
            "in_flight": (
                {
                    "op_id": self._op.op_id, "op": self._op.op,
                    "target": self._op.target, "state": self._op.state,
                }
                if self._op is not None else None
            ),
            "ledger": dict(self.ledger),
            "events": list(self.events),
        }


partition = PartitionPlane()


def reset_partition() -> None:
    """Test hook."""
    partition.reset()

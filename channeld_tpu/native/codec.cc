// Native wire codec for channeld-tpu.
//
// The per-packet hot path — 5-byte tag framing plus snappy compression
// (wire spec: ref pkg/channeld/connection.go:445-541, :683-697) — as a
// CPython extension. The gateway handles every inbound/outbound byte
// through this codec; the Python implementation in protocol/framing.py
// stays as the semantic reference and fallback.
//
// Linked against the system libsnappy via its stable C ABI (snappy-c.h);
// prototypes are declared here because the image ships the library
// without headers.
//
// Build: scripts/build_native.sh  ->  channeld_tpu/native/_codec.*.so

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <string>

extern "C" {
// snappy-c.h stable ABI (status: 0 = OK, 1 = INVALID_INPUT, 2 = BUFFER_TOO_SMALL)
int snappy_compress(const char* input, size_t input_length, char* compressed,
                    size_t* compressed_length);
int snappy_uncompress(const char* compressed, size_t compressed_length,
                      char* uncompressed, size_t* uncompressed_length);
size_t snappy_max_compressed_length(size_t source_length);
int snappy_uncompressed_length(const char* compressed, size_t compressed_length,
                               size_t* result);
}

static const unsigned char MAGIC0 = 0x43;  // 'C'
static const unsigned char MAGIC1 = 0x48;  // 'H'
static const size_t HEADER_SIZE = 5;
static const size_t MAX_PACKET_SIZE = 0xFFFF;

static PyObject* CodecError;

// Core frame construction shared by encode_frame and encode_packets.
// The size cap applies to the uncompressed payload (matching the Python
// codec and the reference's pre-compression packet cap) so that the
// decoder's decompression cap never rejects an honestly-encoded frame.
static PyObject* build_frame(const char* payload, size_t payload_len,
                             int compression) {
  if (payload_len > MAX_PACKET_SIZE) {
    PyErr_Format(CodecError, "packet oversized: %zu", payload_len);
    return nullptr;
  }
  char* scratch = nullptr;
  if (compression == 1) {
    size_t max_len = snappy_max_compressed_length(payload_len);
    scratch = static_cast<char*>(PyMem_Malloc(max_len));
    if (!scratch) return PyErr_NoMemory();
    size_t compressed_len = max_len;
    if (snappy_compress(payload, payload_len, scratch, &compressed_len) == 0 &&
        compressed_len < payload_len) {
      payload = scratch;
      payload_len = compressed_len;
    } else {
      // Incompressible (or error): store raw, mirroring the Python codec.
      compression = 0;
    }
  }

  if (payload_len > MAX_PACKET_SIZE) {
    if (scratch) PyMem_Free(scratch);
    PyErr_Format(CodecError, "packet oversized: %zu", payload_len);
    return nullptr;
  }

  PyObject* out = PyBytes_FromStringAndSize(nullptr,
                                            (Py_ssize_t)(HEADER_SIZE + payload_len));
  if (out) {
    unsigned char* dst =
        reinterpret_cast<unsigned char*>(PyBytes_AS_STRING(out));
    dst[0] = MAGIC0;
    dst[1] = MAGIC1;
    dst[2] = (unsigned char)((payload_len >> 8) & 0xFF);
    dst[3] = (unsigned char)(payload_len & 0xFF);
    dst[4] = (unsigned char)compression;
    memcpy(dst + HEADER_SIZE, payload, payload_len);
  }
  if (scratch) PyMem_Free(scratch);
  return out;
}

// encode_frame(body: bytes, compression: int = 0) -> bytes
static PyObject* codec_encode_frame(PyObject* self, PyObject* args) {
  Py_buffer body;
  int compression = 0;
  if (!PyArg_ParseTuple(args, "y*|i", &body, &compression)) return nullptr;
  PyObject* out = build_frame(static_cast<const char*>(body.buf),
                              (size_t)body.len, compression);
  PyBuffer_Release(&body);
  return out;
}

// decode_frames(buf: bytes-like) -> (list[tuple[bytes, int]], consumed: int)
//
// Parses every complete frame in buf, decompressing snappy bodies.
// Raises CodecError on a bad magic or zero-size frame (connection-fatal).
static PyObject* codec_decode_frames(PyObject* self, PyObject* args) {
  Py_buffer buf;
  if (!PyArg_ParseTuple(args, "y*", &buf)) return nullptr;

  const unsigned char* data = static_cast<const unsigned char*>(buf.buf);
  size_t len = static_cast<size_t>(buf.len);
  size_t pos = 0;

  PyObject* frames = PyList_New(0);
  if (!frames) {
    PyBuffer_Release(&buf);
    return nullptr;
  }

  while (len - pos >= HEADER_SIZE) {
    const unsigned char* tag = data + pos;
    if (tag[0] != MAGIC0 || tag[1] != MAGIC1) {
      Py_DECREF(frames);
      PyBuffer_Release(&buf);
      PyErr_Format(CodecError, "invalid tag at offset %zu", pos);
      return nullptr;
    }
    size_t size = ((size_t)tag[2] << 8) | (size_t)tag[3];
    if (size == 0) {
      Py_DECREF(frames);
      PyBuffer_Release(&buf);
      PyErr_SetString(CodecError, "zero-size frame");
      return nullptr;
    }
    if (len - pos < HEADER_SIZE + size) break;  // incomplete frame
    int ct = tag[4];
    const char* body = reinterpret_cast<const char*>(tag + HEADER_SIZE);

    PyObject* payload = nullptr;
    if (ct == 1) {
      size_t out_len = 0;
      if (snappy_uncompressed_length(body, size, &out_len) != 0) {
        Py_DECREF(frames);
        PyBuffer_Release(&buf);
        PyErr_SetString(CodecError, "corrupt snappy length preamble");
        return nullptr;
      }
      // Frame bodies are capped at MAX_PACKET_SIZE pre-compression, so a
      // preamble claiming more than a small multiple of that is hostile;
      // allocating it would be a pre-auth memory amplification.
      if (out_len > 4 * MAX_PACKET_SIZE) {
        Py_DECREF(frames);
        PyBuffer_Release(&buf);
        PyErr_Format(CodecError, "snappy uncompressed length %zu exceeds cap",
                     out_len);
        return nullptr;
      }
      payload = PyBytes_FromStringAndSize(nullptr, (Py_ssize_t)out_len);
      if (payload &&
          snappy_uncompress(body, size, PyBytes_AS_STRING(payload), &out_len) != 0) {
        Py_DECREF(payload);
        Py_DECREF(frames);
        PyBuffer_Release(&buf);
        PyErr_SetString(CodecError, "corrupt snappy data");
        return nullptr;
      }
    } else {
      payload = PyBytes_FromStringAndSize(body, (Py_ssize_t)size);
    }
    if (!payload) {
      Py_DECREF(frames);
      PyBuffer_Release(&buf);
      return nullptr;
    }
    PyObject* item = Py_BuildValue("(Ni)", payload, ct);
    if (!item || PyList_Append(frames, item) < 0) {
      Py_XDECREF(item);
      Py_DECREF(frames);
      PyBuffer_Release(&buf);
      return nullptr;
    }
    Py_DECREF(item);
    pos += HEADER_SIZE + size;
  }

  PyBuffer_Release(&buf);
  return Py_BuildValue("(Nn)", frames, (Py_ssize_t)pos);
}

// ---- outbound packet building -------------------------------------------
//
// Hand-rolled protobuf wire encoding of chtpu.Packet:
//   Packet.messages    = field 1, length-delimited (tag 0x0A)
//   MessagePack fields = channelId(1)/broadcast(2)/stubId(3)/msgType(4)
//                        varint, msgBody(5) bytes; proto3 zero-omission.
// Byte-identical to the generated serializer (verified in tests).

static size_t varint_size(uint64_t v) {
  size_t n = 1;
  while (v >= 0x80) {
    v >>= 7;
    n++;
  }
  return n;
}

static void write_varint(std::string& out, uint64_t v) {
  while (v >= 0x80) {
    out.push_back((char)((v & 0x7F) | 0x80));
    v >>= 7;
  }
  out.push_back((char)v);
}

// encode_packets(msgs, compression) -> (list[bytes], list[int])
//
// msgs: sequence of (channelId, broadcast, stubId, msgType, msgBody).
// Batches message packs into framed packets, each body <= 64KB before
// compression (mirroring Connection.flush's batching + oversize skip);
// returns the ready-to-write frames plus the number of messages packed
// into each frame (for exact sent-metrics attribution).
static PyObject* codec_encode_packets(PyObject* self, PyObject* args) {
  PyObject* seq;
  int compression = 0;
  if (!PyArg_ParseTuple(args, "O|i", &seq, &compression)) return nullptr;
  PyObject* fast = PySequence_Fast(seq, "encode_packets expects a sequence");
  if (!fast) return nullptr;

  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  PyObject* frames = PyList_New(0);
  if (!frames) {
    Py_DECREF(fast);
    return nullptr;
  }

  PyObject* counts = PyList_New(0);
  if (!counts) {
    Py_DECREF(fast);
    Py_DECREF(frames);
    return nullptr;
  }

  std::string body;
  body.reserve(MAX_PACKET_SIZE + 64);
  long body_msgs = 0;

  auto flush_body = [&](void) -> bool {
    if (body.empty()) return true;
    PyObject* frame = build_frame(body.data(), body.size(), compression);
    if (!frame) return false;
    int rc = PyList_Append(frames, frame);
    Py_DECREF(frame);
    if (rc != 0) return false;
    PyObject* cnt = PyLong_FromLong(body_msgs);
    if (!cnt) return false;
    rc = PyList_Append(counts, cnt);
    Py_DECREF(cnt);
    body.clear();
    body_msgs = 0;
    return rc == 0;
  };

  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* item = PySequence_Fast_GET_ITEM(fast, i);
    unsigned long ch, bc, stub, mt;
    Py_buffer mb;
    if (!PyArg_ParseTuple(item, "kkkky*", &ch, &bc, &stub, &mt, &mb)) {
      Py_DECREF(fast);
      Py_DECREF(frames);
      Py_DECREF(counts);
      return nullptr;
    }
    // MessagePack submessage payload size.
    size_t pack_size = 0;
    if (ch) pack_size += 1 + varint_size(ch);
    if (bc) pack_size += 1 + varint_size(bc);
    if (stub) pack_size += 1 + varint_size(stub);
    if (mt) pack_size += 1 + varint_size(mt);
    if (mb.len) pack_size += 1 + varint_size((uint64_t)mb.len) + (size_t)mb.len;
    size_t entry_size = 1 + varint_size(pack_size) + pack_size;

    if (entry_size > MAX_PACKET_SIZE) {
      PyBuffer_Release(&mb);
      continue;  // oversized single message: skip (caller logs)
    }
    if (body.size() + entry_size > MAX_PACKET_SIZE) {
      if (!flush_body()) {
        PyBuffer_Release(&mb);
        Py_DECREF(fast);
        Py_DECREF(frames);
        Py_DECREF(counts);
        return nullptr;
      }
    }
    body.push_back((char)0x0A);  // Packet.messages tag
    write_varint(body, pack_size);
    if (ch) {
      body.push_back((char)0x08);
      write_varint(body, ch);
    }
    if (bc) {
      body.push_back((char)0x10);
      write_varint(body, bc);
    }
    if (stub) {
      body.push_back((char)0x18);
      write_varint(body, stub);
    }
    if (mt) {
      body.push_back((char)0x20);
      write_varint(body, mt);
    }
    if (mb.len) {
      body.push_back((char)0x2A);
      write_varint(body, (uint64_t)mb.len);
      body.append(static_cast<const char*>(mb.buf), (size_t)mb.len);
    }
    body_msgs++;
    PyBuffer_Release(&mb);
  }
  Py_DECREF(fast);
  if (!flush_body()) {
    Py_DECREF(frames);
    Py_DECREF(counts);
    return nullptr;
  }
  return Py_BuildValue("(NN)", frames, counts);
}

// ---- inbound forward fast path ------------------------------------------
//
// parse_forward(body, conn_id, expect_channel, min_user_type)
//   -> None | (entries, counts)
//
// Scans one serialized chtpu.Packet. When EVERY message in it is a plain
// user-space forward (msgType >= min_user_type, broadcast == 0,
// stubId == 0, channelId == expect_channel, payload small enough to
// re-pack), returns the owner-bound send-queue entries with the
// ServerForwardMessage{clientConnId, payload} wrapper already encoded:
//   entries: list[(channelId, 0, 0, msgType, sfm_bytes)]
//   counts:  dict[msgType, n]   (for metrics attribution)
// Any other content — system messages, unknown fields, malformed wire
// data — returns None and the caller takes the full protobuf path. This
// removes the per-message Packet/MessagePack/ServerForwardMessage
// object churn from the gateway's steady-state ingest
// (ref: the reference parses in Go and forwards via the channel
// goroutine, connection.go:547-615 + message.go:66-126; this is the
// same routing decision made in native code).

static bool read_varint(const uint8_t** pp, const uint8_t* end, uint64_t* out) {
  const uint8_t* p = *pp;
  uint64_t v = 0;
  int shift = 0;
  while (p < end && shift < 64) {
    uint8_t b = *p++;
    v |= (uint64_t)(b & 0x7F) << shift;
    if (!(b & 0x80)) {
      *pp = p;
      *out = v;
      return true;
    }
    shift += 7;
  }
  return false;
}

static PyObject* codec_parse_forward(PyObject* self, PyObject* args) {
  Py_buffer buf;
  unsigned long conn_id, expect_ch, min_user;
  if (!PyArg_ParseTuple(args, "y*kkk", &buf, &conn_id, &expect_ch, &min_user))
    return nullptr;

  const uint8_t* p = static_cast<const uint8_t*>(buf.buf);
  const uint8_t* end = p + buf.len;
  PyObject* entries = PyList_New(0);
  PyObject* counts = PyDict_New();
  if (!entries || !counts) {
    Py_XDECREF(entries);
    Py_XDECREF(counts);
    PyBuffer_Release(&buf);
    return nullptr;
  }
  bool slow = false, fail = false;
  std::string sfm;

  while (p < end && !slow && !fail) {
    if (*p != 0x0A) {  // not Packet.messages: unknown top-level field
      slow = true;
      break;
    }
    p++;
    uint64_t mlen = 0;
    if (!read_varint(&p, end, &mlen) || mlen > (uint64_t)(end - p)) {
      slow = true;
      break;
    }
    const uint8_t* mend = p + mlen;
    uint64_t ch = 0, bc = 0, stub = 0, mt = 0, plen = 0;
    const uint8_t* payload = nullptr;
    while (p < mend) {
      uint8_t tag = *p++;
      bool ok = true;
      switch (tag) {
        case 0x08: ok = read_varint(&p, mend, &ch); break;
        case 0x10: ok = read_varint(&p, mend, &bc); break;
        case 0x18: ok = read_varint(&p, mend, &stub); break;
        case 0x20: ok = read_varint(&p, mend, &mt); break;
        case 0x2A:
          ok = read_varint(&p, mend, &plen) && plen <= (uint64_t)(mend - p);
          if (ok) {
            payload = p;
            p += plen;
          }
          break;
        default:
          ok = false;
      }
      if (!ok) {
        slow = true;
        break;
      }
    }
    if (slow) break;
    if ((ch | bc | stub | mt) >> 32) {
      // Over-long varints: protobuf truncates these uint32 fields to 32
      // bits (a crafted msgType of 2^32+5 IS system message 5 there) —
      // defer to the protobuf path so both classify identically.
      slow = true;
      break;
    }
    if (p != mend || mt < min_user || bc || stub || ch != expect_ch ||
        plen + 96 > MAX_PACKET_SIZE) {
      // Not a plain forward (or would oversize the outbound pack once
      // wrapped): let the full path handle the whole packet.
      slow = true;
      break;
    }
    sfm.clear();
    if (conn_id) {
      sfm.push_back((char)0x08);
      write_varint(sfm, conn_id);
    }
    if (plen) {
      sfm.push_back((char)0x12);
      write_varint(sfm, plen);
      sfm.append(reinterpret_cast<const char*>(payload), (size_t)plen);
    }
    PyObject* entry = Py_BuildValue("(kkkky#)", expect_ch, 0UL, 0UL,
                                    (unsigned long)mt, sfm.data(),
                                    (Py_ssize_t)sfm.size());
    if (!entry || PyList_Append(entries, entry) < 0) {
      Py_XDECREF(entry);
      fail = true;
      break;
    }
    Py_DECREF(entry);
    PyObject* key = PyLong_FromUnsignedLong((unsigned long)mt);
    if (!key) {
      fail = true;
      break;
    }
    PyObject* prev = PyDict_GetItem(counts, key);  // borrowed
    PyObject* next = PyLong_FromLong(prev ? PyLong_AsLong(prev) + 1 : 1);
    if (!next || PyDict_SetItem(counts, key, next) < 0) {
      Py_DECREF(key);
      Py_XDECREF(next);
      fail = true;
      break;
    }
    Py_DECREF(key);
    Py_DECREF(next);
  }

  PyBuffer_Release(&buf);
  if (fail) {
    Py_DECREF(entries);
    Py_DECREF(counts);
    return nullptr;
  }
  if (slow) {
    Py_DECREF(entries);
    Py_DECREF(counts);
    Py_RETURN_NONE;
  }
  return Py_BuildValue("(NN)", entries, counts);
}

// compress(data: bytes) -> bytes ; uncompress(data: bytes) -> bytes
static PyObject* codec_compress(PyObject* self, PyObject* args) {
  Py_buffer in;
  if (!PyArg_ParseTuple(args, "y*", &in)) return nullptr;
  size_t max_len = snappy_max_compressed_length((size_t)in.len);
  PyObject* out = PyBytes_FromStringAndSize(nullptr, (Py_ssize_t)max_len);
  if (!out) {
    PyBuffer_Release(&in);
    return nullptr;
  }
  size_t out_len = max_len;
  int status = snappy_compress(static_cast<const char*>(in.buf), (size_t)in.len,
                               PyBytes_AS_STRING(out), &out_len);
  PyBuffer_Release(&in);
  if (status != 0) {
    Py_DECREF(out);
    PyErr_Format(CodecError, "snappy_compress failed: %d", status);
    return nullptr;
  }
  if (_PyBytes_Resize(&out, (Py_ssize_t)out_len) < 0) return nullptr;
  return out;
}

static PyObject* codec_uncompress(PyObject* self, PyObject* args) {
  Py_buffer in;
  if (!PyArg_ParseTuple(args, "y*", &in)) return nullptr;
  size_t out_len = 0;
  if (snappy_uncompressed_length(static_cast<const char*>(in.buf), (size_t)in.len,
                                 &out_len) != 0) {
    PyBuffer_Release(&in);
    PyErr_SetString(CodecError, "corrupt snappy length preamble");
    return nullptr;
  }
  if (out_len > 4 * MAX_PACKET_SIZE) {
    PyBuffer_Release(&in);
    PyErr_Format(CodecError, "snappy uncompressed length %zu exceeds cap",
                 out_len);
    return nullptr;
  }
  PyObject* out = PyBytes_FromStringAndSize(nullptr, (Py_ssize_t)out_len);
  if (!out) {
    PyBuffer_Release(&in);
    return nullptr;
  }
  int status = snappy_uncompress(static_cast<const char*>(in.buf), (size_t)in.len,
                                 PyBytes_AS_STRING(out), &out_len);
  PyBuffer_Release(&in);
  if (status != 0) {
    Py_DECREF(out);
    PyErr_SetString(CodecError, "corrupt snappy data");
    return nullptr;
  }
  return out;
}

static PyMethodDef codec_methods[] = {
    {"encode_frame", codec_encode_frame, METH_VARARGS,
     "encode_frame(body, compression=0) -> framed bytes"},
    {"decode_frames", codec_decode_frames, METH_VARARGS,
     "decode_frames(buf) -> ([(body, compression)], consumed)"},
    {"encode_packets", codec_encode_packets, METH_VARARGS,
     "encode_packets([(chId, bc, stub, mt, body)], compression) -> ([frames], [counts])"},
    {"parse_forward", codec_parse_forward, METH_VARARGS,
     "parse_forward(body, conn_id, expect_channel, min_user_type) -> "
     "None | (entries, counts)"},
    {"compress", codec_compress, METH_VARARGS, "snappy compress"},
    {"uncompress", codec_uncompress, METH_VARARGS, "snappy uncompress"},
    {nullptr, nullptr, 0, nullptr},
};

static struct PyModuleDef codec_module = {
    PyModuleDef_HEAD_INIT, "_codec",
    "Native wire codec (framing + snappy) for channeld-tpu.", -1,
    codec_methods,
};

PyMODINIT_FUNC PyInit__codec(void) {
  PyObject* m = PyModule_Create(&codec_module);
  if (!m) return nullptr;
  CodecError = PyErr_NewException("channeld_tpu.native._codec.CodecError",
                                  PyExc_ValueError, nullptr);
  Py_INCREF(CodecError);
  if (PyModule_AddObject(m, "CodecError", CodecError) < 0) {
    Py_DECREF(CodecError);
    Py_DECREF(m);
    return nullptr;
  }
  return m;
}

"""Native runtime components.

``_codec``: C++ framing + snappy codec (build with scripts/build_native.sh).
Import ``codec`` from here; it is None when the extension isn't built, and
callers fall back to the pure-Python path in protocol/framing.py.
"""

try:
    from . import _codec as codec  # type: ignore[attr-defined]
except ImportError:
    codec = None

__all__ = ["codec"]

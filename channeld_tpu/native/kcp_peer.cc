// Standalone KCP wire peer for differential interop testing.
//
// This is an INDEPENDENT second implementation of the KCP wire contract
// spoken by core/kcp.py (itself interop-class with the reference's
// kcp-go listener, ref: pkg/channeld/connection.go:207-216): 24-byte
// little-endian header (conv u32, cmd u8, frg u8, wnd u16, ts u32,
// sn u32, una u32, len u32), commands 81 PUSH / 82 ACK / 83 WASK /
// 84 WINS, cumulative `una` + selective ACK with ts echo, receive-window
// advertisement in `wnd`, MTU-1400 datagram packing, RTO with x1.5
// backoff, fast retransmit after 3 duplicate-ack spans.
//
// It deliberately shares no code or structure with the Python side: a
// C-style single-threaded poll loop with array-backed windows, so that
// any behavioral agreement between the two is evidence about the wire
// contract, not about shared bugs.
//
// Modes:
//   kcp_peer echo <port>
//       Bind UDP <port>. First PUSH sn==0 from a new address opens the
//       conversation (server semantics); every delivered stream byte is
//       echoed back over the same conversation. Runs until killed.
//   kcp_peer send <host> <port> <nbytes> <seed>
//       Connect, stream <nbytes> of xorshift(seed) pattern, read the
//       echo, verify byte-for-byte. Exit 0 on success, 1 on mismatch or
//       timeout. Used with a lossy UDP proxy in between.
#include <arpa/inet.h>
#include <netinet/in.h>
#include <time.h>
#include <poll.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <map>
#include <string>
#include <vector>

namespace {

constexpr int kHeader = 24;
constexpr int kMtu = 1400;
constexpr int kSegPayload = kMtu - kHeader;
constexpr uint8_t kPush = 81, kAck = 82, kWask = 83, kWins = 84;
constexpr uint32_t kRcvWnd = 256, kSndWnd = 256;
constexpr double kRtoMin = 0.03, kRtoDef = 0.2, kRtoMax = 6.0;
constexpr int kFastResend = 3;
constexpr int kDeadLink = 64;  // torture links retransmit a lot; be patient

double mono_now() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec + ts.tv_nsec * 1e-9;
}

void put32(uint8_t* p, uint32_t v) {
  p[0] = v & 0xff; p[1] = (v >> 8) & 0xff;
  p[2] = (v >> 16) & 0xff; p[3] = (v >> 24) & 0xff;
}
void put16(uint8_t* p, uint16_t v) { p[0] = v & 0xff; p[1] = (v >> 8) & 0xff; }
uint32_t get32(const uint8_t* p) {
  return p[0] | (p[1] << 8) | (p[2] << 16) | (uint32_t(p[3]) << 24);
}
uint16_t get16(const uint8_t* p) { return p[0] | (p[1] << 8); }

struct InFlight {
  std::vector<uint8_t> data;
  double resend_at = 0;
  double rto = kRtoDef;
  int xmit = 0;
  int fastack = 0;
  uint32_t ts = 0;
};

// One KCP conversation endpoint over a connected/addressed UDP socket.
struct Conv {
  uint32_t conv = 0;
  int fd = -1;
  sockaddr_in peer{};
  bool have_peer = false;
  double t0 = mono_now();

  // send side
  uint32_t snd_una = 0, snd_nxt = 0;
  std::map<uint32_t, InFlight> flight;
  std::deque<std::vector<uint8_t>> sendq;
  uint32_t rmt_wnd = 32;
  double srtt = 0, rttvar = 0, rto = kRtoDef;
  double probe_at = 0;
  bool send_wins = false;
  bool dead = false;

  // receive side
  uint32_t rcv_nxt = 0;
  std::map<uint32_t, std::vector<uint8_t>> rcv_buf;
  std::vector<std::pair<uint32_t, uint32_t>> acks;  // (sn, ts-echo)
  std::vector<uint8_t> stream_in;

  uint32_t now_ms() const {
    return uint32_t((mono_now() - t0) * 1000.0);
  }
  uint32_t wnd_unused() const {
    size_t used = rcv_buf.size();
    return used >= kRcvWnd ? 0 : uint32_t(kRcvWnd - used);
  }

  void tx(const uint8_t* buf, size_t n) {
    if (have_peer)
      sendto(fd, buf, n, 0, reinterpret_cast<const sockaddr*>(&peer),
             sizeof(peer));
    else
      send(fd, buf, n, 0);
  }

  void emit_seg(std::vector<uint8_t>& dgram, uint8_t cmd, uint32_t ts,
                uint32_t sn, const uint8_t* payload, uint32_t len) {
    if (!dgram.empty() && dgram.size() + kHeader + len > kMtu) {
      tx(dgram.data(), dgram.size());
      dgram.clear();
    }
    size_t off = dgram.size();
    dgram.resize(off + kHeader + len);
    uint8_t* p = dgram.data() + off;
    put32(p, conv);
    p[4] = cmd;
    p[5] = 0;  // frg: stream mode
    put16(p + 6, uint16_t(wnd_unused()));
    put32(p + 8, ts);
    put32(p + 12, sn);
    put32(p + 16, rcv_nxt);
    put32(p + 20, len);
    if (len) memcpy(p + kHeader, payload, len);
  }

  void queue_stream(const uint8_t* data, size_t n) {
    for (size_t off = 0; off < n; off += kSegPayload) {
      size_t len = std::min(size_t(kSegPayload), n - off);
      sendq.emplace_back(data + off, data + off + len);
    }
  }

  void flush() {
    double now = mono_now();
    uint32_t nms = now_ms();
    std::vector<uint8_t> dgram;

    for (auto& a : acks) emit_seg(dgram, kAck, a.second, a.first, nullptr, 0);
    acks.clear();

    if (rmt_wnd == 0 && now >= probe_at) {
      emit_seg(dgram, kWask, nms, 0, nullptr, 0);
      probe_at = now + 0.5;
    }
    if (send_wins) {
      emit_seg(dgram, kWins, nms, 0, nullptr, 0);
      send_wins = false;
    }

    uint32_t cwnd = std::min(kSndWnd, rmt_wnd);
    while (!sendq.empty() && snd_nxt < snd_una + cwnd) {
      InFlight f;
      f.data = std::move(sendq.front());
      sendq.pop_front();
      f.ts = nms;
      f.rto = rto;
      f.resend_at = now + f.rto;
      f.xmit = 1;
      emit_seg(dgram, kPush, f.ts, snd_nxt, f.data.data(),
               uint32_t(f.data.size()));
      flight.emplace(snd_nxt, std::move(f));
      snd_nxt++;
    }

    for (auto& [sn, f] : flight) {
      bool need = false;
      if (now >= f.resend_at) {
        need = true;
        f.rto = std::min(f.rto * 1.5, kRtoMax);
      } else if (f.fastack >= kFastResend) {
        need = true;
        f.fastack = 0;
      }
      if (need) {
        f.xmit++;
        f.ts = nms;
        f.resend_at = now + f.rto;
        emit_seg(dgram, kPush, f.ts, sn, f.data.data(),
                 uint32_t(f.data.size()));
        if (f.xmit >= kDeadLink) dead = true;
      }
    }
    if (!dgram.empty()) tx(dgram.data(), dgram.size());
  }

  void on_ack_rtt(uint32_t ts_echo) {
    double rtt = (double)((now_ms() - ts_echo) & 0xffffffffu) / 1000.0;
    if (rtt < 0 || rtt > 60) return;
    if (srtt == 0) {
      srtt = rtt;
      rttvar = rtt / 2;
    } else {
      double d = rtt > srtt ? rtt - srtt : srtt - rtt;
      rttvar = 0.75 * rttvar + 0.25 * d;
      srtt = 0.875 * srtt + 0.125 * rtt;
    }
    double cand = srtt + std::max(0.01, 4 * rttvar);
    rto = std::min(std::max(kRtoMin, cand), kRtoMax);
  }

  // Feed one datagram. Returns false if it doesn't belong to this conv.
  bool input(const uint8_t* data, size_t n) {
    // Pre-pass mirroring the Python side's contract exactly: parsing
    // stops at the first truncated/unknown-cmd segment (the valid
    // prefix IS applied), but a conv mismatch anywhere in the parsed
    // prefix drops the datagram wholesale before any state is touched.
    size_t parse_end = 0;
    {
      size_t pos = 0;
      while (n - pos >= kHeader) {
        const uint8_t* p = data + pos;
        uint8_t cmd = p[4];
        uint32_t len = get32(p + 20);
        if (cmd < kPush || cmd > kWins || len > n - pos - kHeader) break;
        if (get32(p) != conv) return false;
        pos += kHeader + len;
      }
      parse_end = pos;
    }
    size_t pos = 0;
    while (pos < parse_end) {
      const uint8_t* p = data + pos;
      uint8_t cmd = p[4];
      uint16_t wnd = get16(p + 6);
      uint32_t ts = get32(p + 8), sn = get32(p + 12), una = get32(p + 16);
      uint32_t len = get32(p + 20);
      pos += kHeader + len;

      rmt_wnd = wnd;
      if (una > snd_una) {
        flight.erase(flight.begin(), flight.lower_bound(una));
        snd_una = una;
      }
      if (cmd == kAck) {
        auto it = flight.find(sn);
        if (it != flight.end()) {
          if (it->second.xmit == 1) on_ack_rtt(ts);  // Karn's rule
          flight.erase(it);
        }
        for (auto& [s, f] : flight)
          if (s < sn) f.fastack++;
        while (snd_una < snd_nxt && !flight.count(snd_una)) snd_una++;
      } else if (cmd == kPush) {
        if (sn < rcv_nxt + kRcvWnd) acks.emplace_back(sn, ts);
        if (sn >= rcv_nxt && sn < rcv_nxt + kRcvWnd)
          rcv_buf.emplace(sn, std::vector<uint8_t>(p + kHeader,
                                                   p + kHeader + len));
        while (true) {
          auto it = rcv_buf.find(rcv_nxt);
          if (it == rcv_buf.end()) break;
          stream_in.insert(stream_in.end(), it->second.begin(),
                           it->second.end());
          rcv_buf.erase(it);
          rcv_nxt++;
        }
      } else if (cmd == kWask) {
        send_wins = true;
      }  // kWins: window already applied from wnd
    }
    return true;
  }
};

uint32_t xorshift(uint32_t& s) {
  s ^= s << 13;
  s ^= s >> 17;
  s ^= s << 5;
  return s;
}

int run_echo(int port) {
  int fd = socket(AF_INET, SOCK_DGRAM, 0);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(uint16_t(port));
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    perror("bind");
    return 1;
  }
  fprintf(stdout, "READY\n");
  fflush(stdout);

  Conv conv;  // single-session echo peer
  bool open = false;
  uint8_t buf[65536];
  while (true) {
    pollfd pfd{fd, POLLIN, 0};
    poll(&pfd, 1, 10);
    if (pfd.revents & POLLIN) {
      sockaddr_in src{};
      socklen_t slen = sizeof(src);
      ssize_t n = recvfrom(fd, buf, sizeof(buf), 0,
                           reinterpret_cast<sockaddr*>(&src), &slen);
      if (n >= kHeader) {
        if (!open) {
          // Server-open semantics: first PUSH with sn==0 creates it.
          if (buf[4] == kPush && get32(buf + 12) == 0) {
            conv.conv = get32(buf);
            conv.fd = fd;
            conv.peer = src;
            conv.have_peer = true;
            open = true;
          }
        }
        if (open && conv.input(buf, size_t(n)) && !conv.stream_in.empty()) {
          conv.queue_stream(conv.stream_in.data(), conv.stream_in.size());
          conv.stream_in.clear();
        }
      }
    }
    if (open) {
      conv.flush();
      if (conv.dead) return 2;
    }
  }
}

int run_send(const char* host, int port, size_t nbytes, uint32_t seed) {
  int fd = socket(AF_INET, SOCK_DGRAM, 0);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(uint16_t(port));
  inet_pton(AF_INET, host, &addr.sin_addr);
  if (connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    perror("connect");
    return 1;
  }
  Conv conv;
  conv.conv = (seed | 1);
  conv.fd = fd;

  std::vector<uint8_t> pattern(nbytes);
  uint32_t s = seed ? seed : 0xdecafbad;
  for (size_t i = 0; i < nbytes; i++) pattern[i] = uint8_t(xorshift(s) >> 24);
  conv.queue_stream(pattern.data(), nbytes);

  size_t verified = 0;
  double deadline = mono_now() + 60.0;
  uint8_t buf[65536];
  while (verified < nbytes) {
    if (mono_now() > deadline) {
      fprintf(stderr, "TIMEOUT verified=%zu/%zu\n", verified, nbytes);
      return 1;
    }
    conv.flush();
    if (conv.dead) {
      fprintf(stderr, "DEAD LINK\n");
      return 2;
    }
    pollfd pfd{fd, POLLIN, 0};
    poll(&pfd, 1, 10);
    if (pfd.revents & POLLIN) {
      ssize_t n = recv(fd, buf, sizeof(buf), 0);
      if (n >= kHeader) conv.input(buf, size_t(n));
    }
    if (!conv.stream_in.empty()) {
      for (uint8_t b : conv.stream_in) {
        if (verified >= nbytes) {
          fprintf(stderr, "OVERDELIVERY past %zu bytes\n", nbytes);
          return 1;
        }
        if (b != pattern[verified]) {
          fprintf(stderr, "MISMATCH at %zu: got %02x want %02x\n", verified,
                  b, pattern[verified]);
          return 1;
        }
        verified++;
      }
      conv.stream_in.clear();
    }
  }
  fprintf(stdout, "OK %zu\n", verified);
  return 0;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc >= 3 && strcmp(argv[1], "echo") == 0)
    return run_echo(atoi(argv[2]));
  if (argc >= 6 && strcmp(argv[1], "send") == 0)
    return run_send(argv[2], atoi(argv[3]), strtoul(argv[4], nullptr, 10),
                    uint32_t(strtoul(argv[5], nullptr, 10)));
  fprintf(stderr,
          "usage: kcp_peer echo <port> | kcp_peer send <host> <port> "
          "<nbytes> <seed>\n");
  return 64;
}

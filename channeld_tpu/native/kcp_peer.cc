// Standalone KCP wire peer for differential interop testing.
//
// This is an INDEPENDENT second implementation of the KCP wire contract
// spoken by core/kcp.py (itself interop-class with the reference's
// kcp-go listener, ref: pkg/channeld/connection.go:207-216): 24-byte
// little-endian header (conv u32, cmd u8, frg u8, wnd u16, ts u32,
// sn u32, una u32, len u32), commands 81 PUSH / 82 ACK / 83 WASK /
// 84 WINS, cumulative `una` + selective ACK with ts echo, receive-window
// advertisement in `wnd`, MTU-1400 datagram packing, RTO with x1.5
// backoff, fast retransmit after 3 duplicate-ack spans.
//
// It deliberately shares no code or structure with the Python side: a
// C-style single-threaded poll loop with array-backed windows, so that
// any behavioral agreement between the two is evidence about the wire
// contract, not about shared bugs. (The ARQ itself lives in
// sdk/cpp/kcp_conv.h, shared with the C++ SDK's KCP transport — both
// are the same independent C++ lineage.)
//
// Modes:
//   kcp_peer echo <port>
//       Bind UDP <port>. First PUSH sn==0 from a new address opens the
//       conversation (server semantics); every delivered stream byte is
//       echoed back over the same conversation. Runs until killed.
//   kcp_peer send <host> <port> <nbytes> <seed>
//       Connect, stream <nbytes> of xorshift(seed) pattern, read the
//       echo, verify byte-for-byte. Exit 0 on success, 1 on mismatch or
//       timeout. Used with a lossy UDP proxy in between.
#include <arpa/inet.h>
#include <netinet/in.h>
#include <time.h>
#include <poll.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <map>
#include <string>
#include <vector>

#include "../../sdk/cpp/kcp_conv.h"

namespace {

using namespace chtpu_kcp;


uint32_t xorshift(uint32_t& s) {
  s ^= s << 13;
  s ^= s >> 17;
  s ^= s << 5;
  return s;
}

int run_echo(int port) {
  int fd = socket(AF_INET, SOCK_DGRAM, 0);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(uint16_t(port));
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    perror("bind");
    return 1;
  }
  fprintf(stdout, "READY\n");
  fflush(stdout);

  Conv conv;  // single-session echo peer
  bool open = false;
  uint8_t buf[65536];
  while (true) {
    pollfd pfd{fd, POLLIN, 0};
    poll(&pfd, 1, 10);
    if (pfd.revents & POLLIN) {
      sockaddr_in src{};
      socklen_t slen = sizeof(src);
      ssize_t n = recvfrom(fd, buf, sizeof(buf), 0,
                           reinterpret_cast<sockaddr*>(&src), &slen);
      if (n >= kHeader) {
        if (!open) {
          // Server-open semantics: first PUSH with sn==0 creates it.
          if (buf[4] == kPush && get32(buf + 12) == 0) {
            conv.conv = get32(buf);
            conv.fd = fd;
            conv.peer = src;
            conv.have_peer = true;
            open = true;
          }
        }
        if (open && conv.input(buf, size_t(n)) && !conv.stream_in.empty()) {
          conv.queue_stream(conv.stream_in.data(), conv.stream_in.size());
          conv.stream_in.clear();
        }
      }
    }
    if (open) {
      conv.flush();
      if (conv.dead) return 2;
    }
  }
}

int run_send(const char* host, int port, size_t nbytes, uint32_t seed) {
  int fd = socket(AF_INET, SOCK_DGRAM, 0);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(uint16_t(port));
  inet_pton(AF_INET, host, &addr.sin_addr);
  if (connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    perror("connect");
    return 1;
  }
  Conv conv;
  conv.conv = (seed | 1);
  conv.fd = fd;

  std::vector<uint8_t> pattern(nbytes);
  uint32_t s = seed ? seed : 0xdecafbad;
  for (size_t i = 0; i < nbytes; i++) pattern[i] = uint8_t(xorshift(s) >> 24);
  conv.queue_stream(pattern.data(), nbytes);

  size_t verified = 0;
  double deadline = mono_now() + 60.0;
  uint8_t buf[65536];
  while (verified < nbytes) {
    if (mono_now() > deadline) {
      fprintf(stderr, "TIMEOUT verified=%zu/%zu\n", verified, nbytes);
      return 1;
    }
    conv.flush();
    if (conv.dead) {
      fprintf(stderr, "DEAD LINK\n");
      return 2;
    }
    pollfd pfd{fd, POLLIN, 0};
    poll(&pfd, 1, 10);
    if (pfd.revents & POLLIN) {
      ssize_t n = recv(fd, buf, sizeof(buf), 0);
      if (n >= kHeader) conv.input(buf, size_t(n));
    }
    if (!conv.stream_in.empty()) {
      for (uint8_t b : conv.stream_in) {
        if (verified >= nbytes) {
          fprintf(stderr, "OVERDELIVERY past %zu bytes\n", nbytes);
          return 1;
        }
        if (b != pattern[verified]) {
          fprintf(stderr, "MISMATCH at %zu: got %02x want %02x\n", verified,
                  b, pattern[verified]);
          return 1;
        }
        verified++;
      }
      conv.stream_in.clear();
    }
  }
  fprintf(stdout, "OK %zu\n", verified);
  return 0;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc >= 3 && strcmp(argv[1], "echo") == 0)
    return run_echo(atoi(argv[2]));
  if (argc >= 6 && strcmp(argv[1], "send") == 0)
    return run_send(argv[2], atoi(argv[3]), strtoul(argv[4], nullptr, 10),
                    uint32_t(strtoul(argv[5], nullptr, 10)));
  fprintf(stderr,
          "usage: kcp_peer echo <port> | kcp_peer send <host> <port> "
          "<nbytes> <seed>\n");
  return 64;
}

"""Wire protocol: schema (generated protobuf), framing, message registry.

Reference counterpart: pkg/channeldpb. Regenerate the ``*_pb2`` modules
with ``scripts/gen_protos.sh`` after editing the ``.proto`` files.
"""

from . import control_pb2, replay_pb2, spatial_pb2, wire_pb2
from .framing import (
    FrameDecoder,
    FramingError,
    HEADER_SIZE,
    MAX_PACKET_SIZE,
    encode_frame,
    encode_packet,
)

# MessageType -> protobuf template class for system messages
# (ref: pkg/channeld/message.go:41-62 MessageMap).
MESSAGE_TEMPLATES = {
    1: control_pb2.AuthMessage,
    3: control_pb2.CreateChannelMessage,
    4: control_pb2.RemoveChannelMessage,
    5: control_pb2.ListChannelMessage,
    6: control_pb2.SubscribedToChannelMessage,
    7: control_pb2.UnsubscribedFromChannelMessage,
    8: control_pb2.ChannelDataUpdateMessage,
    9: control_pb2.DisconnectMessage,
    10: control_pb2.CreateChannelMessage,  # CREATE_SPATIAL_CHANNEL shares the body
    11: spatial_pb2.QuerySpatialChannelMessage,
    12: spatial_pb2.ChannelDataHandoverMessage,
    13: spatial_pb2.SpatialRegionsUpdateMessage,
    14: spatial_pb2.UpdateSpatialInterestMessage,
    15: spatial_pb2.CreateEntityChannelMessage,
    16: spatial_pb2.AddEntityGroupMessage,
    17: spatial_pb2.RemoveEntityGroupMessage,
    18: spatial_pb2.SpatialChannelsReadyMessage,
    20: control_pb2.ChannelDataRecoveryMessage,
    21: control_pb2.EndRecoveryMessage,
    22: control_pb2.ChannelOwnerLostMessage,
    23: control_pb2.ChannelOwnerRecoveredMessage,
    24: control_pb2.ServerBusyMessage,
    25: spatial_pb2.CellRehostedMessage,
    26: spatial_pb2.CellMigratedMessage,
    27: control_pb2.ClientRedirectMessage,
    99: spatial_pb2.DebugGetSpatialRegionsMessage,
}

__all__ = [
    "wire_pb2",
    "control_pb2",
    "spatial_pb2",
    "FrameDecoder",
    "FramingError",
    "HEADER_SIZE",
    "MAX_PACKET_SIZE",
    "encode_frame",
    "encode_packet",
    "MESSAGE_TEMPLATES",
]

"""Packet framing: the 5-byte tag + optional snappy body.

Wire-spec parity with the reference transport
(ref: pkg/channeld/connection.go:445-541 read side, :683-697 write side):

    byte 0: 'C' (0x43)
    byte 1: 'H' (0x48)
    byte 2: body size high byte     (written over 'N')
    byte 3: body size low byte      (written over 'L')
    byte 4: CompressionType (0 none, 1 snappy)

Body is a serialized ``chtpu.Packet``, at most 0xFFFF bytes after
compression. A decoder that sees a bad magic or oversized length must
drop the connection, mirroring the reference.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from . import snappy
from .wire_pb2 import Packet

try:  # native C++ codec (scripts/build_native.sh); None -> pure Python
    from ..native import codec as _native
except ImportError:
    _native = None

HEADER_SIZE = 5
MAX_PACKET_SIZE = 0xFFFF
_MAGIC0 = 0x43  # 'C'
_MAGIC1 = 0x48  # 'H'


class FramingError(Exception):
    """Fatal stream error; the connection must be closed."""


def encode_frame(body: bytes, compression: int = 0) -> bytes:
    """Wrap a serialized Packet into one wire frame.

    The size cap applies to the *uncompressed* body (the reference caps the
    marshaled Packet at 64KB before compressing, connection.go:626-714), so
    encode and decode agree on what a legal frame is: the decoder's
    decompression-bomb cap can then assume no honest peer produced a frame
    that inflates past a small multiple of MAX_PACKET_SIZE."""
    if len(body) > MAX_PACKET_SIZE:
        raise FramingError(f"packet oversized: {len(body)}")
    if _native is not None:
        try:
            return _native.encode_frame(body, compression)
        except _native.CodecError as e:
            raise FramingError(str(e)) from None
    if compression == 1:
        compressed = snappy.compress(body)
        # Fall back to raw when compression doesn't help (and to keep the
        # size cap meaningful for small payloads).
        if len(compressed) < len(body):
            body = compressed
        else:
            compression = 0
    if len(body) > MAX_PACKET_SIZE:
        raise FramingError(f"packet oversized: {len(body)}")
    return bytes((_MAGIC0, _MAGIC1, (len(body) >> 8) & 0xFF, len(body) & 0xFF,
                  compression)) + body


def encode_packet(packet: Packet, compression: int = 0) -> bytes:
    return encode_frame(packet.SerializeToString(), compression)


@dataclass
class FrameDecoder:
    """Incremental frame decoder for a byte stream.

    ``feed`` buffers arbitrary chunks and yields complete decompressed
    packet bodies. Fragmented reads are counted for metrics parity with
    the reference's fragmentedPacketCount.
    """

    _buf: bytearray = field(default_factory=bytearray)
    fragmented_count: int = 0
    # Last compression type seen from the peer; the send path mirrors it.
    peer_compression: int = 0
    # Client-side mode: accept the reference client's 3-byte size escape
    # (tag byte 1 != 'H' carries the size's high byte, client.go:191-196)
    # so server->client packets over 64KB decode. The gateway's own
    # decoder stays strict — the reference server never WRITES >64KB and
    # treats an escaped tag as hostile. Python path only (the native
    # codec implements the strict gateway wire).
    extended_size: bool = False

    def feed(self, data: bytes) -> list[bytes]:
        # Eager, not a generator: data must land in the buffer even when
        # the caller discards the return value (no frames yet).
        self._buf.extend(data)
        if self.extended_size:
            out: list[bytes] = []
            while True:
                body = self._next_frame()
                if body is None:
                    return out
                out.append(body)
        if _native is not None:
            try:
                # bytearray passes the buffer protocol: no copy.
                frames, consumed = _native.decode_frames(self._buf)
            except _native.CodecError as e:
                raise FramingError(str(e)) from None
            del self._buf[:consumed]
            if self._buf:
                self.fragmented_count += 1
            out = []
            for body, ct in frames:
                if ct == 1:
                    self.peer_compression = 1
                out.append(body)
            return out
        out: list[bytes] = []
        while True:
            body = self._next_frame()
            if body is None:
                return out
            out.append(body)

    def _next_frame(self) -> Optional[bytes]:
        buf = self._buf
        if len(buf) < HEADER_SIZE:
            if buf:
                self.fragmented_count += 1
            return None
        if buf[0] != _MAGIC0:
            raise FramingError(f"invalid tag: {bytes(buf[:4])!r}")
        if self.extended_size and buf[1] != _MAGIC1:
            # 3-byte size escape (client.go:191-196): byte 1 carries the
            # topmost size byte, allowing server->client packets past
            # 64KB. Two wire-inherited quirks: (a) the reference client
            # treats a literal 'N' in byte 2 as zero — a misparse for
            # honest ~20KB frames whose size high byte IS 0x4E;
            # deliberately not inherited. (b) a topmost byte of 'H'
            # (0x48) is indistinguishable from the strict 2-byte form,
            # so escaped sizes 0x480000-0x48FFFF (~4.7MB) are
            # unrepresentable in this tag encoding — writers must pad
            # past the hole; sizes at/above it are rejected here rather
            # than silently desyncing the stream.
            size = (buf[1] << 16) | (buf[2] << 8) | buf[3]
            if size >= 0x480000:
                raise FramingError(
                    f"extended frame size {size} in/past the 0x48 tag "
                    "collision hole"
                )
        else:
            if buf[1] != _MAGIC1:
                raise FramingError(f"invalid tag: {bytes(buf[:4])!r}")
            size = (buf[2] << 8) | buf[3]
        if size == 0:
            raise FramingError("zero-size frame")
        full = HEADER_SIZE + size
        if len(buf) < full:
            self.fragmented_count += 1
            return None
        ct = buf[4]
        body = bytes(buf[HEADER_SIZE:full])
        del buf[:full]
        if ct == 1:
            self.peer_compression = 1
            try:
                if self.extended_size:
                    # The strict cap (a small multiple of 64KB) is the
                    # gateway's decompression-bomb guard; extended mode
                    # exists to accept large server packets, so the cap
                    # scales with the extended size ceiling instead.
                    body = snappy.uncompress(body, max_len=0x480000 * 4)
                else:
                    body = snappy.uncompress(body)
            except ValueError as e:
                # Corrupt or bomb-sized snappy data is a stream-fatal
                # framing condition, not a caller error.
                raise FramingError(str(e)) from None
        elif ct != 0:
            # Unknown compression tags are ignored (treated as raw),
            # mirroring the reference's CompressionType_name check.
            pass
        return body

    def decode_packets(self, data: bytes) -> list[Packet]:
        out = []
        for body in self.feed(data):
            p = Packet()
            p.ParseFromString(body)
            out.append(p)
        return out

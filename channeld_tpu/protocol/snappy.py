"""Snappy block-format codec via the system C library.

The wire protocol optionally compresses each packet with snappy
(ref: pkg/channeld/connection.go:497-516, CompressionType.SNAPPY).
python-snappy isn't available in this image, but libsnappy.so.1 is, and
its C API (snappy-c.h) is a stable ABI — we bind it with ctypes. The
native C++ codec extension (channeld_tpu/native) links the same library
for the batched hot path.
"""

from __future__ import annotations

import ctypes
import ctypes.util
from typing import Optional

_lib: Optional[ctypes.CDLL] = None


def _load() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is not None:
        return _lib
    for name in ("libsnappy.so.1", "libsnappy.so", ctypes.util.find_library("snappy")):
        if not name:
            continue
        try:
            lib = ctypes.CDLL(name)
        except OSError:
            continue
        lib.snappy_compress.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.snappy_compress.restype = ctypes.c_int
        lib.snappy_uncompress.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.snappy_uncompress.restype = ctypes.c_int
        lib.snappy_max_compressed_length.argtypes = [ctypes.c_size_t]
        lib.snappy_max_compressed_length.restype = ctypes.c_size_t
        lib.snappy_uncompressed_length.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.snappy_uncompressed_length.restype = ctypes.c_int
        _lib = lib
        return lib
    return None


def available() -> bool:
    return _load() is not None


def compress(data: bytes) -> bytes:
    lib = _load()
    if lib is None:
        raise RuntimeError("snappy library not available")
    out_len = ctypes.c_size_t(lib.snappy_max_compressed_length(len(data)))
    out = ctypes.create_string_buffer(out_len.value)
    status = lib.snappy_compress(data, len(data), out, ctypes.byref(out_len))
    if status != 0:
        raise RuntimeError(f"snappy_compress failed: {status}")
    return out.raw[: out_len.value]


# A frame body is capped at 64KB before compression (see framing.MAX_PACKET_SIZE),
# so no legitimate frame decompresses past a small multiple of that. Without
# this cap a <=64KB frame whose varint preamble claims ~4GiB would trigger a
# ~4GiB allocation per frame, pre-auth.
MAX_UNCOMPRESSED_SIZE = 4 * 0xFFFF


def uncompress(data: bytes, max_len: int = MAX_UNCOMPRESSED_SIZE) -> bytes:
    lib = _load()
    if lib is None:
        raise RuntimeError("snappy library not available")
    out_len = ctypes.c_size_t()
    if lib.snappy_uncompressed_length(data, len(data), ctypes.byref(out_len)) != 0:
        raise ValueError("corrupt snappy data (bad length preamble)")
    if out_len.value > max_len:
        raise ValueError(
            f"snappy uncompressed length {out_len.value} exceeds cap {max_len}"
        )
    out = ctypes.create_string_buffer(out_len.value)
    if lib.snappy_uncompress(data, len(data), out, ctypes.byref(out_len)) != 0:
        raise ValueError("corrupt snappy data")
    return out.raw[: out_len.value]

"""channeld-tpu: a TPU-native realtime state-routing framework.

A standalone gateway for massive-online interactive systems with the
capability surface of channeldorg/channeld (connections, channels,
channel-data fan-out, spatial interest management, entity handover,
recovery, replay, metrics) — re-designed so the per-tick spatial /
area-of-interest / fan-out decision pass is a batched, device-resident
JAX/Pallas computation sharded over a TPU mesh.

Layer map (host side mirrors reference pkg/channeld; device side is new):

  protocol/   wire schema + framing            (ref: pkg/channeldpb)
  core/       connections, channels, data      (ref: pkg/channeld)
  spatial/    grid + AOI + handover control    (ref: pkg/channeld/spatial.go)
  ops/        JAX/Pallas batched kernels       (new: TPU decision plane)
  parallel/   mesh sharding + halo exchange    (new: multi-chip scale-out)
  models/     example channel-data families    (ref: examples/*pb, pkg/unrealpb)
  client/     client SDK                       (ref: pkg/client)
  replay/     packet record/replay             (ref: pkg/replay)
  utils/      logging, ids, ranges
"""

__version__ = "0.1.0"

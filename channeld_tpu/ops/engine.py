"""SpatialEngine: device-resident spatial decision state + tick driver.

Host-side façade over the batched kernels in spatial_ops: fixed-capacity
slot arrays with a free-list for dynamic entity membership (the device
analog of the reference's entity maps), a query table for client AOI
interests, and the fan-out subscription clock. One ``tick()`` performs
the whole per-frame decision pass on device and returns host-consumable
results (handover list, interest masks, due subscriptions).

Dirty positions are staged host-side between ticks and shipped as one
scatter per tick — the H2D traffic is O(moved entities), not O(capacity).
"""

from __future__ import annotations

import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.logger import get_logger
from .spatial_ops import (
    AOI_BOX,
    AOI_CONE,
    AOI_NONE,
    AOI_SPHERE,
    AOI_SPOTS,
    SIM_IDLE,
    SIM_SEEK,
    GridSpec,
    QuerySet,
    SimParams,
    diff_query_masks,
    parse_query_blob,
    sim_step,
    spatial_step,
)

logger = get_logger("ops.engine")


class SpatialEngine:
    def __init__(
        self,
        grid: GridSpec,
        entity_capacity: int = 1 << 17,
        query_capacity: int = 1 << 12,
        sub_capacity: int = 1 << 16,
        max_handovers: int = 4096,
        mesh=None,
        sharding: str = "entities",
        cell_bucket: int = 0,
        query_rows_max: int = 8192,
    ):
        """``mesh``: a jax.sharding.Mesh to shard the entity slot arrays
        over (from parallel.mesh.make_mesh / make_mesh_2d). None = the
        single-device fused step. The serving results are identical either
        way (pinned by tests/test_ops.py engine parity); the mesh step
        exchanges per-cell occupancy with psum over ICI/DCN and gathers
        per-shard handover rows — the TPU answer to the reference's
        multi-server spatial world (ref: spatial.go:387-590).

        ``sharding`` picks the meshed step: "entities" (psum occupancy,
        replicated AOI) or "cells" (space-partitioned: all_to_all entity
        redistribution to per-shard cell blocks + column-block AOI +
        ring-halo borders — parallel/spatial_alltoall.py). "cells" with
        ``cell_bucket`` > 0 caps the per-(source, dest) redistribution
        bucket; overflowed entities are reported undelivered and
        re-offered next tick (0 = exact delivery)."""
        if sharding not in ("entities", "cells"):
            raise ValueError(f"unknown sharding {sharding!r}")
        self._mesh = mesh
        self._sharding = sharding
        self._cell_bucket = cell_bucket
        # shared=fence declarations (doc/concurrency.md#fences): engine
        # state is written from the tick-loop (mutators; the unguarded
        # step) AND the device-guard worker (the guarded step + the
        # in-process rebuild). The loop BLOCKS on the worker inside
        # run_step, so the only true concurrency is a watchdog-abandoned
        # zombie worker unwedging late — which the generation fence
        # makes safe: every engine-visible store re-checks the
        # generation between staging and store (machine-checked by
        # tpulint's fence-discipline rule).
        self._mesh_step = None  # tpulint: shared=fence
        # Cells-plane shed diagnostics, refreshed each mesh tick.
        self.last_overflow = 0  # tpulint: shared=fence
        if mesh is not None:
            n_dev = int(mesh.devices.size)
            # Entity arrays shard evenly over every mesh axis.
            entity_capacity = ((entity_capacity + n_dev - 1) // n_dev) * n_dev
            from jax.sharding import NamedSharding, PartitionSpec

            self._entity_ns = NamedSharding(
                mesh, PartitionSpec(tuple(mesh.axis_names))
            )
        else:
            self._entity_ns = None
        self.grid = grid
        self.entity_capacity = entity_capacity
        self.query_capacity = query_capacity
        self.sub_capacity = sub_capacity
        self.max_handovers = max_handovers

        # Host mirrors (numpy) + dirty staging.
        self._positions = np.zeros((entity_capacity, 3), np.float32)
        self._valid = np.zeros(entity_capacity, bool)
        self._free = list(range(entity_capacity - 1, -1, -1))
        self._slot_of_entity: dict[int, int] = {}
        self._entity_of_slot = np.zeros(entity_capacity, np.uint32)
        self._dirty_slots: set[int] = set()  # tpulint: shared=fence
        self._seed_cells: dict[int, int] = {}  # slot -> forced prev cell  # tpulint: shared=fence

        self._q_kind = np.zeros(query_capacity, np.int32)
        self._q_center = np.zeros((query_capacity, 2), np.float32)
        self._q_extent = np.zeros((query_capacity, 2), np.float32)
        self._q_dir = np.zeros((query_capacity, 2), np.float32)
        self._q_angle = np.zeros(query_capacity, np.float32)
        self._q_free = list(range(query_capacity - 1, -1, -1))
        self._q_of_conn: dict[int, int] = {}
        # [Q,C] spots dist table (-1 = no interest), allocated on the
        # first spots query so the common compiled step never carries it
        # (one recompile then). The device copy updates by row scatter —
        # H2D is O(changed rows x C), never the whole table.
        self._q_spot_dist: Optional[np.ndarray] = None
        # World-space spot sources per connection: the dist rows above
        # are in CELL space, so a grid swap (apply_grid — adaptive
        # partitioning) must re-rasterize every row from these.
        self._spot_sources: dict[int, tuple] = {}
        self._d_spot_dist = None  # tpulint: shared=fence
        self._spot_dirty_rows: set[int] = set()  # tpulint: shared=fence
        self._queries_dirty = True  # tpulint: shared=fence

        # Standing-query plane (doc/query_engine.md): when enabled the
        # tick diffs this tick's interest/dist masks against the
        # committed device baseline and compacts the delta to changed
        # (query, cell, dist) rows — the plane's ONE d2h transfer.
        self.track_query_changes = False
        self.query_rows_max = query_rows_max
        # Committed (interest, dist) baseline pair; None = empty baseline
        # (next diff full-emits every interested row).
        self._d_q_prev = None  # tpulint: shared=fence
        # Rows whose baseline must be zeroed before the next diff: a
        # freshly-allocated (or freed) row may be REUSED by a new query,
        # and a stale baseline would swallow the overlap between the old
        # and new masks (never re-emitted = lost subscription).
        self._q_prev_reset_rows: set[int] = set()  # tpulint: shared=fence
        # Bumped whenever the committed baseline is thrown away wholesale
        # (rebuild_device_state / apply_grid): the host plane sees the
        # epoch move and full-resyncs its mirrors instead of trusting
        # deltas that no longer connect to its last-applied state.
        self.query_epoch = 0  # tpulint: shared=fence

        # Host staging for the sub table. The device's last-fan-out column
        # is authoritative after each tick (fanout_due advances it); the
        # host mirror only carries *explicit* writes (add/reset/interval),
        # applied as row scatters — a full rebuild from the mirror would
        # snap every sub's window start back to stale values.
        self._sub_last = np.zeros(sub_capacity, np.int32)
        self._sub_interval = np.zeros(sub_capacity, np.int32)
        self._sub_active = np.zeros(sub_capacity, bool)
        self._sub_free = list(range(sub_capacity - 1, -1, -1))
        # Per-column dirty tracking: interval/active writes must never
        # drag the stale host `last` along (an interval-only change would
        # otherwise snap that sub's window start back arbitrarily far).
        self._sub_dirty_slots: set[int] = set()  # interval+active cols  # tpulint: shared=fence
        self._sub_last_dirty: set[int] = set()  # last-fan-out column  # tpulint: shared=fence

        # Device state (entity arrays sharded over the mesh when given).
        # .copy(): jax's H2D transfer is async and may read the numpy
        # buffer after this call; _positions/_valid are mutated by
        # add/update_entity before the first tick, so the live buffers
        # must never be handed to the transfer (see _flush_host_state).
        if self._entity_ns is not None:
            self._d_positions = jax.device_put(
                self._positions.copy(), self._entity_ns
            )
            self._d_valid = jax.device_put(self._valid.copy(), self._entity_ns)
            self._d_cell = jax.device_put(
                np.full(entity_capacity, -1, np.int32), self._entity_ns
            )
        else:
            self._d_positions = jnp.asarray(self._positions.copy())  # tpulint: shared=fence
            self._d_valid = jnp.asarray(self._valid.copy())  # tpulint: shared=fence
            self._d_cell = jnp.full(entity_capacity, -1, jnp.int32)  # tpulint: shared=fence
        self._d_queries: Optional[QuerySet] = None  # tpulint: shared=fence
        self._d_sub_state = None  # tpulint: shared=fence

        # Simulation plane (channeld_tpu/sim, doc/simulation.md): agents
        # occupy ORDINARY entity slots — the sim pass advances their
        # positions in the same device arrays every downstream plane
        # reads (crossings, AOI, fan-out, standing queries), so NPCs are
        # indistinguishable from humans past this point and cost zero
        # extra transfers. The kinematic columns (velocity, FSM state,
        # waypoint) follow the positions staging discipline: host
        # shadows + dirty-slot scatters, full re-upload when the device
        # copy is dropped. The device is authoritative for agent rows
        # between censuses; the host shadow refreshes only at census
        # boundaries (absorb_census), which is why a rebuild reproduces
        # the last census exactly — the replay contract doc/simulation.md
        # pins.
        self.sim_enabled = False  # tpulint: shared=fence
        self.sim_seed = 0
        self.sim_params: Optional[SimParams] = None
        self.sim_tick = 0  # counter-based RNG cursor  # tpulint: shared=fence
        # Per-tick scheduling flags, staged by the controller on the
        # tick loop before the step is dispatched (same handoff as the
        # dirty staging sets: the loop blocks on the worker, and a
        # zombie worker's commit is generation-fenced).
        self.run_sim_pass = False  # tpulint: shared=fence
        self.sim_census_due = False  # tpulint: shared=fence
        self._agent_mask = np.zeros(entity_capacity, bool)
        self._vel = np.zeros((entity_capacity, 3), np.float32)
        self._sim_state = np.zeros(entity_capacity, np.int32)
        self._sim_target = np.zeros((entity_capacity, 3), np.float32)
        self._sim_dirty: set[int] = set()  # tpulint: shared=fence
        # Danger mask (bool[num_cells]) rasterized by the sim plane from
        # query-plane sensor hits; uploaded only when a sensor's
        # interest set changes — never per tick.
        self._flee_cells: Optional[np.ndarray] = None
        self._flee_dirty = False  # tpulint: shared=fence
        self._d_agent = None  # tpulint: shared=fence
        self._d_vel = None  # tpulint: shared=fence
        self._d_sim_state = None  # tpulint: shared=fence
        self._d_sim_target = None  # tpulint: shared=fence
        self._d_flee = None  # tpulint: shared=fence
        # Double-entry ledger mirroring sim_device_rebuilds_total{result}
        # (scripts/sim_soak.py cross-checks both sides).
        self.sim_rebuild_counts: dict[str, int] = {}

        self._start = time.monotonic()
        self.last_result: Optional[dict] = None  # tpulint: shared=fence
        # Abandoned-step fence (core/device_guard.py): the watchdog bumps
        # this when it gives up on a hung step; a zombie worker thread
        # completing the old tick later must not commit its tail state
        # over a rebuilt engine (tick() re-checks before committing).
        self.generation = 0  # tpulint: shared=fence
        # Serializes concurrent rebuild bodies (a watchdog-abandoned
        # rebuild's worker vs its retry on a fresh worker): the stale
        # one must never interleave transfers with — or commit over —
        # the live one. See device_guard._rebuild_body.
        import threading

        self._rebuild_lock = threading.Lock()
        # Fused Mosaic assign+count on TPU backends (pallas_kernels);
        # the sharded step uses plain XLA inside shard_map.
        from .pallas_kernels import pallas_available

        self.use_pallas = pallas_available() and mesh is None

    # ---- entity slots ----------------------------------------------------

    def now_ms(self) -> int:
        return int((time.monotonic() - self._start) * 1000)

    def add_entity(self, entity_id: int, x: float, y: float, z: float) -> int:
        slot = self._slot_of_entity.get(entity_id)
        if slot is None:
            if not self._free:
                raise RuntimeError("entity capacity exhausted")
            slot = self._free.pop()
            self._slot_of_entity[entity_id] = slot
            self._entity_of_slot[slot] = entity_id
            # Fresh slot: clear any previous occupant's cell so reuse can't
            # fabricate a crossing on the first tick.
            self._seed_cells[slot] = -1
        self._positions[slot] = (x, y, z)
        self._valid[slot] = True
        self._dirty_slots.add(slot)
        return slot

    def seed_cell(self, slot: int, cell: int) -> None:
        """Set the device-side previous cell for a slot before its first
        tick (used to seed a just-sighted entity's old position)."""
        self._seed_cells[slot] = cell

    def update_entity(self, entity_id: int, x: float, y: float, z: float) -> None:
        slot = self._slot_of_entity.get(entity_id)
        if slot is None:
            self.add_entity(entity_id, x, y, z)
            return
        self._positions[slot] = (x, y, z)
        self._dirty_slots.add(slot)

    def remove_entity(self, entity_id: int) -> None:
        slot = self._slot_of_entity.pop(entity_id, None)
        if slot is None:
            return
        self._valid[slot] = False
        self._dirty_slots.add(slot)
        if self._agent_mask[slot]:
            # A departed agent's slot must stop stepping immediately —
            # a reused slot would otherwise inherit the sim pass.
            self._agent_mask[slot] = False
            self._sim_dirty.add(slot)
        self._free.append(slot)

    def entity_count(self) -> int:
        return len(self._slot_of_entity)

    def slot_of_entity(self, entity_id: int) -> Optional[int]:
        return self._slot_of_entity.get(entity_id)

    def entity_id_of_slot(self, slot: int) -> int:
        return int(self._entity_of_slot[slot])

    # ---- queries ---------------------------------------------------------

    def _query_slot(self, conn_id: int) -> int:
        q = self._q_of_conn.get(conn_id)
        if q is None:
            if not self._q_free:
                raise RuntimeError("query capacity exhausted")
            q = self._q_free.pop()
            self._q_of_conn[conn_id] = q
            # Fresh owner for this row: zero its diff baseline before the
            # next tick so the previous occupant's mask can't swallow the
            # overlap with the new query (see _q_prev_reset_rows).
            self._q_prev_reset_rows.add(q)
        return q

    def set_query(
        self,
        conn_id: int,
        kind: int,
        center_xz: tuple[float, float],
        extent_xz: tuple[float, float] = (0.0, 0.0),
        direction_xz: tuple[float, float] = (1.0, 0.0),
        angle: float = 0.0,
    ) -> None:
        q = self._query_slot(conn_id)
        self._spot_sources.pop(conn_id, None)  # no longer a spots query
        self._q_kind[q] = kind
        self._q_center[q] = center_xz
        self._q_extent[q] = extent_xz
        norm = float(np.hypot(*direction_xz)) or 1.0
        self._q_dir[q] = (direction_xz[0] / norm, direction_xz[1] / norm)
        self._q_angle[q] = angle
        self._queries_dirty = True

    def set_spots_query(
        self,
        conn_id: int,
        spots_xz: list[tuple[float, float]],
        dists: Optional[list[int]] = None,
    ) -> None:
        """Spots AOI on the device plane: rasterize the spot list to a
        per-cell interest row (ref: spatial.go spots loop — each spot's
        cell, dist = dists[i] when given else 0; out-of-world spots
        skipped). Where several spots land in one cell the last spot's
        dist wins — the host path's dict-overwrite order. The row is a
        dist table with -1 = no interest (see QuerySet.spot_dist)."""
        import math

        q = self._query_slot(conn_id)
        self._spot_sources[conn_id] = (
            [tuple(s) for s in spots_xz],
            list(dists) if dists is not None else None,
        )
        if self._q_spot_dist is None:
            self._q_spot_dist = np.full(
                (self.query_capacity, self.grid.num_cells), -1, np.int32
            )
        self._q_kind[q] = AOI_SPOTS
        dist_row = np.full(self.grid.num_cells, -1, np.int32)
        g = self.grid
        for i, (x, z) in enumerate(spots_xz):
            # Divide-then-floor, exactly like the host path and
            # assign_cells — float floor-division disagrees on boundaries
            # (1.0 // 0.1 == 9.0 but floor(1.0 / 0.1) == 10).
            col = math.floor((x - g.offset_x) / g.cell_w)
            row = math.floor((z - g.offset_z) / g.cell_h)
            if not (0 <= col < g.cols and 0 <= row < g.rows):
                continue
            cell = row * g.cols + col
            # Clamp to int32 max: wire dists are uint32, and 0xFFFFFFFF
            # must not alias the -1 sentinel.
            dist_row[cell] = (
                min(int(dists[i]), 2**31 - 1)
                if dists is not None and i < len(dists) else 0
            )
        self._q_spot_dist[q] = dist_row
        self._spot_dirty_rows.add(q)
        self._queries_dirty = True

    def remove_query(self, conn_id: int) -> None:
        q = self._q_of_conn.pop(conn_id, None)
        self._spot_sources.pop(conn_id, None)
        if q is not None:
            self._q_kind[q] = AOI_NONE
            if self._q_spot_dist is not None:
                self._q_spot_dist[q] = -1
                self._spot_dirty_rows.add(q)
            self._q_free.append(q)
            # A freed row emits no removal rows (the plane unsubscribes
            # synchronously at deregistration) and must hand its next
            # owner a clean diff baseline.
            self._q_prev_reset_rows.add(q)
            self._queries_dirty = True

    def query_row_of_conn(self, conn_id: int) -> Optional[int]:
        return self._q_of_conn.get(conn_id)

    # ---- subscriptions ---------------------------------------------------

    def add_subscription(self, interval_ms: int, first_due_ms: int = 0) -> int:
        if not self._sub_free:
            raise RuntimeError("subscription capacity exhausted")
        s = self._sub_free.pop()
        self._sub_last[s] = first_due_ms
        self._sub_interval[s] = interval_ms
        self._sub_active[s] = True
        self._sub_dirty_slots.add(s)
        self._sub_last_dirty.add(s)
        return s

    def remove_subscription(self, s: int) -> None:
        self._sub_active[s] = False
        self._sub_free.append(s)
        self._sub_dirty_slots.add(s)

    def set_sub_interval(self, s: int, interval_ms: int) -> None:
        """Re-subscription merged new options (ref: subscription.go:34-60)."""
        self._sub_interval[s] = interval_ms
        self._sub_dirty_slots.add(s)

    def reset_sub_clock(self, s: int, now_ms: int) -> None:
        """Snap the sub's window start to ``now`` — mirrors the host path's
        first-fan-out behavior (tick_data sets latest_fanout_time = now)."""
        self._sub_last[s] = now_ms
        self._sub_last_dirty.add(s)

    # ---- simulation plane (channeld_tpu/sim, doc/simulation.md) ----------

    def seed_agents(self, entries, seed: int, params: SimParams,
                    vels=None, states=None, targets=None) -> list[int]:
        """Register a simulated population into ordinary entity slots.

        ``entries`` is [(entity_id, x, y, z)]. ``vels``/``states``/
        ``targets`` restore a census (WAL replay, federation adoption);
        a fresh spawn starts IDLE at rest, targeting its own position.
        Mesh-sharded engines don't run the sim pass (the kernel is
        single-device; documented in doc/simulation.md). Returns the
        slots used."""
        if self._mesh is not None:
            raise RuntimeError("sim plane requires a single-device engine")
        slots = []
        for i, (eid, x, y, z) in enumerate(entries):
            slot = self.add_entity(eid, float(x), float(y), float(z))
            self._agent_mask[slot] = True
            self._vel[slot] = vels[i] if vels is not None else (0.0, 0.0, 0.0)
            self._sim_state[slot] = (
                int(states[i]) if states is not None else SIM_IDLE
            )
            self._sim_target[slot] = (
                targets[i] if targets is not None else (x, y, z)
            )
            self._sim_dirty.add(slot)
            slots.append(slot)
        self.sim_seed = int(seed) & 0xFFFFFFFF
        self.sim_params = params
        self.sim_enabled = True
        return slots

    def agent_slots(self) -> np.ndarray:
        """Live agent slot indices, ascending (host-shadow truth)."""
        return np.nonzero(self._agent_mask & self._valid)[0]

    def agent_count(self) -> int:
        return int(np.count_nonzero(self._agent_mask & self._valid))

    def agent_ids(self, slots: Optional[np.ndarray] = None) -> np.ndarray:
        """Entity ids for ``slots`` (default: all live agent slots)."""
        if slots is None:
            slots = self.agent_slots()
        return self._entity_of_slot[slots]

    def is_agent(self, entity_id: int) -> bool:
        slot = self._slot_of_entity.get(entity_id)
        return slot is not None and bool(self._agent_mask[slot])

    def absorb_census(self, slots: np.ndarray, positions, vel, state,
                      target) -> None:
        """Fold a fetched census (full-capacity device arrays, already
        numpy) back into the host shadows WITHOUT marking anything dirty
        — the values came FROM the device, so re-uploading them would be
        pure waste and re-staging them could clobber a newer device
        tick. After this call the host shadow is bit-identical to the
        device for every agent row, which is what makes the next
        rebuild/verify exact."""
        self._positions[slots] = positions[slots]
        self._vel[slots] = vel[slots]
        self._sim_state[slots] = state[slots]
        self._sim_target[slots] = target[slots]

    def set_flee_cells(self, cells) -> None:
        """Install the danger mask driving FLEE: an iterable of micro
        cell indices (query-plane sensor hits, rasterized by the sim
        plane). Uploaded on the next flush — only when this is called,
        never per tick."""
        mask = np.zeros(self.grid.num_cells, bool)
        for c in cells:
            if 0 <= c < self.grid.num_cells:
                mask[c] = True
        self._flee_cells = mask
        self._flee_dirty = True

    def sim_stampede(self, cell: int) -> None:
        """CHAOS ONLY (``sim.stampede``): herd every agent toward one
        cell — a deterministic handover/density burst that exercises
        partition splits and overload shedding from the sim plane.
        Host-staged like any other mutation, so it rides the ordinary
        fenced scatter into the next tick."""
        g = self.grid
        cx = g.offset_x + (cell % g.cols + 0.5) * g.cell_w
        cz = g.offset_z + (cell // g.cols + 0.5) * g.cell_h
        slots = self.agent_slots()
        self._sim_state[slots] = SIM_SEEK
        self._sim_target[slots, 0] = cx
        self._sim_target[slots, 2] = cz
        self._vel[slots] = 0.0
        self._sim_dirty.update(int(s) for s in slots)

    def corrupt_sim_state_for_chaos(self) -> None:
        """CHAOS ONLY (``sim.step_nan``): rot the agent rows the way a
        bad kernel output would — NaN positions/velocities on a quarter
        of the agents, plus garbage prev-cell baselines on the same rows
        so the fault carries the impossible-src-cell signature the
        readback sentinel detects (same detection path as ``device.nan``;
        the triggered rebuild re-seeds the rotted rows from the host
        shadow and the population resumes its replayable trajectory)."""
        live = self.agent_slots()
        n = max(1, len(live) // 4)
        rows = live[:n].astype(np.int32)
        self._d_cell = self._keep_entity_sharding(
            self._d_cell.at[rows].set(1 << 24)
        )
        self._d_positions = self._keep_entity_sharding(
            self._d_positions.at[rows].set(float("nan"))
        )
        if self._d_vel is not None:
            self._d_vel = self._keep_entity_sharding(
                self._d_vel.at[rows].set(float("nan"))
            )

    def _count_sim_rebuild(self, result: str) -> None:
        """Double-entry sim rebuild accounting: python ledger AND
        prometheus move together on every verification of the agent
        arrays (scripts/sim_soak.py asserts both sides agree)."""
        self.sim_rebuild_counts[result] = (
            self.sim_rebuild_counts.get(result, 0) + 1
        )
        from ..core import metrics

        metrics.sim_device_rebuilds.labels(result=result).inc()

    # ---- the tick --------------------------------------------------------

    def _keep_entity_sharding(self, arr):
        """Scatter updates must not silently migrate a mesh-sharded array
        (device_put is a no-op when the sharding already matches)."""
        if self._entity_ns is None:
            return arr
        return jax.device_put(arr, self._entity_ns)

    def _flush_host_state(self, expect_generation: Optional[int] = None) -> None:
        def _fence() -> None:
            # Stale-tick fence (core/device_guard.py): a watchdog-
            # abandoned worker that unwedges mid-flush must not commit
            # staged arrays over a rebuilt engine. Each block stages
            # its device work into locals and re-checks the generation
            # immediately before the engine-visible assignment, so the
            # exposure shrinks from the whole flush to one store.
            if (expect_generation is not None
                    and expect_generation != self.generation):
                raise RuntimeError("stale device tick abandoned by watchdog")

        _fence()
        if self._dirty_slots:
            idx = np.fromiter(self._dirty_slots, np.int32, len(self._dirty_slots))
            d_positions = self._keep_entity_sharding(
                self._d_positions.at[idx].set(self._positions[idx])
            )
            d_valid = self._keep_entity_sharding(
                self._d_valid.at[idx].set(self._valid[idx])
            )
            _fence()
            self._d_positions = d_positions
            self._d_valid = d_valid
            self._dirty_slots.clear()
        if self._seed_cells:
            slots = np.fromiter(self._seed_cells.keys(), np.int32, len(self._seed_cells))
            cells = np.fromiter(self._seed_cells.values(), np.int32, len(self._seed_cells))
            d_cell = self._keep_entity_sharding(
                self._d_cell.at[slots].set(cells)
            )
            _fence()
            self._d_cell = d_cell
            self._seed_cells.clear()
        if self.sim_enabled:
            if self._d_vel is None:
                # First upload (or post-rebuild re-upload) of the whole
                # kinematic column set. .copy(): async H2D vs later host
                # writes, same contract as every other mirror.
                d_vel = jnp.asarray(self._vel.copy())
                d_state = jnp.asarray(self._sim_state.copy())
                d_target = jnp.asarray(self._sim_target.copy())
                d_agent = jnp.asarray(self._agent_mask.copy())
                _fence()
                self._d_vel = d_vel
                self._d_sim_state = d_state
                self._d_sim_target = d_target
                self._d_agent = d_agent
                self._sim_dirty.clear()
            elif self._sim_dirty:
                idx = np.fromiter(self._sim_dirty, np.int32,
                                  len(self._sim_dirty))
                d_vel = self._d_vel.at[idx].set(self._vel[idx])
                d_state = self._d_sim_state.at[idx].set(self._sim_state[idx])
                d_target = self._d_sim_target.at[idx].set(
                    self._sim_target[idx]
                )
                d_agent = self._d_agent.at[idx].set(self._agent_mask[idx])
                _fence()
                self._d_vel = d_vel
                self._d_sim_state = d_state
                self._d_sim_target = d_target
                self._d_agent = d_agent
                self._sim_dirty.clear()
            if self._flee_cells is not None and (
                self._d_flee is None or self._flee_dirty
            ):
                d_flee = jnp.asarray(self._flee_cells.copy())
                _fence()
                self._d_flee = d_flee
                self._flee_dirty = False
        spots_changed = False
        if self._q_spot_dist is not None:
            if self._d_spot_dist is None:
                # .copy(): async H2D vs later host row writes (below).
                d_spot = jnp.asarray(self._q_spot_dist.copy())
                _fence()
                self._d_spot_dist = d_spot
                self._spot_dirty_rows.clear()
                spots_changed = True
            elif self._spot_dirty_rows:
                idx = np.fromiter(
                    self._spot_dirty_rows, np.int32, len(self._spot_dirty_rows)
                )
                d_spot = self._d_spot_dist.at[idx].set(
                    self._q_spot_dist[idx]
                )
                _fence()
                self._d_spot_dist = d_spot
                self._spot_dirty_rows.clear()
                spots_changed = True
        if self._d_queries is None or self._queries_dirty or spots_changed:
            # .copy(): jax's H2D transfer of a numpy array is async and
            # may read the buffer AFTER this call returns; these staging
            # arrays are mutated by later set_query/remove_query calls,
            # so handing jax the live buffer races host writes against
            # the deferred copy (observed on a loaded host as a query
            # table whose slot read as cleared one tick early).
            d_queries = QuerySet(
                jnp.asarray(self._q_kind.copy()),
                jnp.asarray(self._q_center.copy()),
                jnp.asarray(self._q_extent.copy()),
                jnp.asarray(self._q_dir.copy()),
                jnp.asarray(self._q_angle.copy()),
                self._d_spot_dist,
            )
            _fence()
            self._d_queries = d_queries
            self._queries_dirty = False
        if self._d_sub_state is None:
            # .copy(): async H2D vs later host writes to these mirrors.
            d_sub = (
                jnp.asarray(self._sub_last.copy()),
                jnp.asarray(self._sub_interval.copy()),
                jnp.asarray(self._sub_active.copy()),
            )
            _fence()
            self._d_sub_state = d_sub
            self._sub_dirty_slots.clear()
            self._sub_last_dirty.clear()
        elif self._sub_dirty_slots or self._sub_last_dirty:
            # Per-column row scatters of explicit host writes only — the
            # device's last-fan-out values for untouched slots stay
            # authoritative (fanout_due advances them device-side).
            last, interval, active = self._d_sub_state
            last_idx = sub_idx = None
            if self._sub_last_dirty:
                last_idx = np.fromiter(
                    self._sub_last_dirty, np.int32, len(self._sub_last_dirty)
                )
                last = last.at[last_idx].set(self._sub_last[last_idx])
            if self._sub_dirty_slots:
                sub_idx = np.fromiter(
                    self._sub_dirty_slots, np.int32, len(self._sub_dirty_slots)
                )
                interval = interval.at[sub_idx].set(self._sub_interval[sub_idx])
                active = active.at[sub_idx].set(self._sub_active[sub_idx])
            _fence()
            self._d_sub_state = (last, interval, active)
            if last_idx is not None:
                self._sub_last_dirty.clear()
            if sub_idx is not None:
                self._sub_dirty_slots.clear()

    def warmup(self) -> None:
        """Compile the tick's common (no-spots) step on empty tables —
        called at controller load, BEFORE listeners open. Without this the
        first live tick pays multi-second XLA compilation inside the
        channel tick, stalling the event loop long enough for the unauth
        reaper to blacklist slow-authing peers (observed end-to-end with
        the meshed cells plane). The warmup tick mutates nothing the
        serving path reads: tables are empty and inactive."""
        self.tick(now_ms=0)
        self.last_result = None

    def sim_warmup(self) -> None:
        """Compile the sim step at plane activation, for the same reason
        ``warmup`` exists: the first live sim tick must not pay XLA
        compilation inside the guarded window (a multi-second stall
        there reads as a hang and trips the watchdog). Runs on
        throwaway arrays of the live shapes — sim_step donates its
        inputs, so the live arrays are never handed to a warmup."""
        if self.sim_params is None:
            return
        n = self.entity_capacity
        jax.block_until_ready(
            sim_step(
                self.grid,
                jnp.zeros((n, 3), jnp.float32),
                jnp.zeros((n, 3), jnp.float32),
                jnp.zeros(n, jnp.int32),
                jnp.zeros((n, 3), jnp.float32),
                jnp.zeros(n, bool),
                jnp.zeros(self.grid.num_cells, bool),
                self.sim_params,
                jnp.uint32(self.sim_seed),
                jnp.int32(0),
            )
        )

    def tick(self, now_ms: Optional[int] = None) -> dict:
        """Run one device decision pass; returns numpy-backed results."""
        if now_ms is None:
            now_ms = self.now_ms()
        gen = self.generation
        # The flush carries the fence too: its staged commits are the
        # other place a watchdog-abandoned worker could write stale
        # arrays over a rebuilt engine (see _flush_host_state).
        self._flush_host_state(expect_generation=gen)
        # Sim pass first (device->device): agents advance, then the
        # spatial pass reads the SAME position array — crossings, AOI,
        # standing queries and fan-out all see the moved agents this
        # very tick, with zero extra transfers. The committed flags were
        # staged by the controller on the loop thread before dispatch.
        sim_committed = None
        census_due = False
        positions = self._d_positions
        if (self.sim_enabled and self.run_sim_pass and self._mesh is None
                and self._d_vel is not None):
            flee = self._d_flee
            if flee is None:
                flee = jnp.zeros(self.grid.num_cells, bool)
            sim_committed = sim_step(
                self.grid,
                positions,
                self._d_vel,
                self._d_sim_state,
                self._d_sim_target,
                self._d_agent,
                flee,
                self.sim_params,
                jnp.uint32(self.sim_seed),
                jnp.int32(self.sim_tick),
            )
            positions = sim_committed[0]
            census_due = self.sim_census_due
        if self._mesh is not None:
            out = self._mesh_tick(now_ms)
        else:
            out = spatial_step(
                self.grid,
                positions,
                self._d_cell,
                self._d_valid,
                self._d_queries,
                self._d_sub_state,
                self.max_handovers,
                jnp.int32(now_ms),
                use_pallas=self.use_pallas,
            )
        q_prev = None
        if self.track_query_changes:
            prev = self._d_q_prev
            if prev is None:
                prev = (
                    jnp.zeros(out["interest"].shape, bool),
                    jnp.zeros(out["interest"].shape, jnp.int32),
                )
            elif self._q_prev_reset_rows:
                # Reused rows start from an empty baseline (pure compute
                # on the old arrays; committed only after the gen check).
                idx = np.fromiter(
                    self._q_prev_reset_rows, np.int32,
                    len(self._q_prev_reset_rows),
                )
                prev = (prev[0].at[idx].set(False), prev[1].at[idx].set(0))
            q_blob, q_prev_i, q_prev_d = diff_query_masks(
                prev[0], prev[1], out["interest"], out["dist"],
                self.query_rows_max,
            )
            out["query_blob"] = q_blob
            out["query_epoch"] = self.query_epoch
            q_prev = (q_prev_i, q_prev_d)
        else:
            # No baseline while tracking is off — when it turns on, the
            # None baseline full-emits anyway, so pending resets are moot.
            self._q_prev_reset_rows.clear()
        if gen != self.generation:
            # The watchdog abandoned this step (device_guard): the
            # engine may already be rebuilt — committing this tick's
            # tail state would corrupt the fresh baseline.
            raise RuntimeError("stale device tick abandoned by watchdog")
        # Baseline for the next tick: crossings that overflowed the handover
        # row budget keep their old cell so they are re-detected, not lost.
        if sim_committed is not None:
            # The sim batch commits ATOMICALLY with the spatial commit
            # and only past the fence above — a watchdog-abandoned step
            # can never leave a torn population (positions advanced but
            # kinematics not, or vice versa); the abandoned tick's
            # donated buffers die with it and the guard's rebuild
            # re-uploads every column from the host shadow.
            (self._d_positions, self._d_vel, self._d_sim_state,
             self._d_sim_target) = sim_committed
            self.sim_tick += 1
            if census_due:
                # Device handles for the census columns; the guard
                # pre-fetches them to numpy inside the guarded window
                # (core/device_guard.py), the sim plane absorbs them.
                out["sim_census"] = (
                    self._d_positions, self._d_vel, self._d_sim_state,
                    self._d_sim_target,
                )
                out["sim_tick"] = self.sim_tick
        self._d_cell = out["committed_prev"]
        self._d_sub_state = (
            out["new_last_fanout_ms"],
            self._d_sub_state[1],
            self._d_sub_state[2],
        )
        if q_prev is not None:
            self._d_q_prev = q_prev
            self._q_prev_reset_rows.clear()
        self.last_result = out
        return out

    def _mesh_tick(self, now_ms: int) -> dict:
        """The sharded decision pass, normalized to the single-device
        result contract (handover_count + merged global-slot rows)."""
        from ..parallel.mesh import merge_handover_shards

        with_spots = self._d_queries.spot_dist is not None
        if self._mesh_step is None or self._mesh_step.with_spots != with_spots:
            n_shards = int(self._mesh.devices.size)
            per_shard = max(1, -(-self.max_handovers // n_shards))
            if self._sharding == "cells":
                from ..parallel.spatial_alltoall import (
                    build_cell_serving_step,
                )

                bucket = self._cell_bucket or (
                    self.entity_capacity // n_shards
                )
                self._mesh_step = build_cell_serving_step(
                    self.grid, self._mesh, bucket, per_shard, with_spots
                )
            else:
                from ..parallel.mesh import build_sharded_step

                self._mesh_step = build_sharded_step(
                    self.grid, self._mesh, per_shard, with_spots
                )
        if self._sharding == "cells":
            from ..parallel.spatial_alltoall import cell_serving_spatial_step

            out = cell_serving_spatial_step(
                self._mesh_step, self._d_positions, self._d_cell,
                self._d_valid, self._d_queries, self._d_sub_state, now_ms,
            )
            self.last_overflow = int(np.asarray(out["overflow"]).sum())
        else:
            from ..parallel.mesh import sharded_spatial_step

            out = sharded_spatial_step(
                self._mesh_step,
                self._d_positions,
                self._d_cell,
                self._d_valid,
                self._d_queries,
                self._d_sub_state,
                now_ms,
            )
        count, rows = merge_handover_shards(
            out["handover_counts"], out["handovers"]
        )
        out["handover_count"] = count
        out["handovers"] = rows
        return out

    def undelivered_slots(self, result: dict) -> list[int]:
        """Slots whose cells-plane redistribution bucket was full this
        tick (empty for exact delivery / other shardings). They remain in
        the ingest arrays and are re-offered automatically next tick;
        the controller sheds visibly (metric + security log)."""
        und = result.get("undelivered")
        if und is None:
            return []
        return np.nonzero(np.asarray(und))[0].tolist()

    def handover_list(self, result: dict) -> list[tuple[int, int, int]]:
        """[(entity_id, src_cell, dst_cell)] from a tick result.

        Every row present must be consumed: the device already committed
        these crossings (committed_prev), so a clamped row would be a
        permanently lost handover. Mesh ticks can report slightly more
        than max_handovers (per-shard budgets round up); single-device
        counts beyond the row budget re-detect next tick."""
        count = int(result["handover_count"])
        rows = np.asarray(result["handovers"])
        rows = rows[: min(count, len(rows))]
        return [
            (int(self._entity_of_slot[slot]), int(src), int(dst))
            for slot, src, dst in rows
            if slot >= 0
        ]

    def interested_cells(self, result: dict, conn_id: int) -> dict[int, int]:
        """{cell_index: grid_distance} for one connection's query."""
        q = self._q_of_conn.get(conn_id)
        if q is None:
            return {}
        interest = np.asarray(result["interest"][q])
        dist = np.asarray(result["dist"][q])
        cells = np.nonzero(interest)[0]
        return {int(c): int(dist[c]) for c in cells}

    def interested_cells_batch(
        self, result: dict, conn_ids
    ) -> dict[int, dict[int, int]]:
        """{conn_id: {cell_index: grid_distance}} for MANY queries in one
        device->host transfer of the whole interest + dist tables.

        The per-connection form above pulls one row per call — one
        device round-trip per AOI follower per tick, measured at
        ~330us/follower (BENCH_RESULTS.md round 10, ROADMAP item 1):
        past ~100 followers that alone blew the 33ms GLOBAL tick. The
        masks already live in two device arrays, so the follower pass
        fetches them once and slices rows on host — O(1) transfers per
        tick regardless of follower count."""
        rows = [
            (cid, q) for cid in conn_ids
            if (q := self._q_of_conn.get(cid)) is not None
        ]
        if not rows:
            return {}
        interest = np.asarray(result["interest"])
        dist = np.asarray(result["dist"])
        out: dict[int, dict[int, int]] = {}
        for cid, q in rows:
            cells = np.nonzero(interest[q])[0]
            drow = dist[q]
            out[cid] = {int(c): int(drow[c]) for c in cells}
        return out

    def query_changed_rows(self, result: dict) -> tuple[int, np.ndarray]:
        """(total_changed, rows i32[query_rows_max, 3]) from a tick
        result — the standing-query plane's ONE device->host transfer
        per tick (doc/query_engine.md). The fetched blob is cached back
        onto the result dict, so however many consumers ask, the
        transfer happens at most once per tick (the device guard
        pre-fetches it inside the guarded step window; this path is the
        unguarded fallback). Row layout: (query_row, cell, new_dist)
        with dist == -1 meaning interest removed; rows beyond
        min(total, query_rows_max) are -1 padding. Returns (0, empty)
        when tracking was off for this tick."""
        blob = result.get("query_blob")
        if blob is None:
            return 0, np.zeros((0, 3), np.int32)
        if not isinstance(blob, np.ndarray):
            blob = np.asarray(blob)  # tpulint: disable=hot-readback -- the plane's designed once-per-tick changed-rows fetch (unguarded path; cached on the result)
            result["query_blob"] = blob
        return parse_query_blob(blob)

    # ---- supervision & recovery (core/device_guard.py) -------------------

    def tracked_entities(self) -> list[tuple[int, int]]:
        """[(entity_id, slot)] for every live registration — what the
        device guard walks to compute per-slot rebuild baselines."""
        return list(self._slot_of_entity.items())

    def bump_generation(self) -> None:
        """Fence off an abandoned (hung) step: a zombie worker thread
        finishing the old tick later raises instead of committing its
        tail state over whatever the guard rebuilt meanwhile."""
        self.generation += 1

    def rebuild_device_state(self, slot_cells: dict[int, int],
                             now_ms: Optional[int] = None,
                             expect_generation: Optional[int] = None) -> None:
        """In-process device-state rebuild from the host-side shadow
        (doc/device_recovery.md). The host mirrors are authoritative for
        everything except two device-advanced columns:

        - the per-slot *previous cell* baseline, which the caller passes
          in as ``slot_cells`` (computed from the grid's ``_data_cell``
          placement ledger + the failover journal's in-flight dsts, so a
          mid-crossing entity re-baselines to where its data is actually
          bound — the next tick re-detects any move since);
        - the sub table's last-fan-out column, which is snapped to
          ``now``: every sub's window restarts, so fan-out resumes one
          full interval from the rebuild instead of bursting or
          silently slipping.

        Everything device-side is re-created from fresh copies; nothing
        the corrupted arrays held survives.

        ``expect_generation``: the caller's stale-rebuild fence — the
        fresh arrays are built FIRST (the wedge-prone blocking
        transfers), and nothing engine-visible mutates unless the
        generation still matches. A rebuild the watchdog abandoned
        (which bumped the generation) raises here when it unwedges
        instead of committing stale state over a later verified one."""
        if now_ms is None:
            now_ms = self.now_ms()
        if expect_generation is None:
            expect_generation = self.generation
        cells = np.full(self.entity_capacity, -1, np.int32)
        for slot, cell in slot_cells.items():
            cells[slot] = cell
        if self._entity_ns is not None:
            d_positions = jax.device_put(
                self._positions.copy(), self._entity_ns
            )
            d_valid = jax.device_put(self._valid.copy(), self._entity_ns)
            d_cell = jax.device_put(cells.copy(), self._entity_ns)
        else:
            d_positions = jnp.asarray(self._positions.copy())
            d_valid = jnp.asarray(self._valid.copy())
            d_cell = jnp.asarray(cells.copy())
        if expect_generation != self.generation:
            raise RuntimeError("stale rebuild abandoned by watchdog")
        self.generation += 1
        self._d_positions = d_positions
        self._d_valid = d_valid
        self._d_cell = d_cell
        self._dirty_slots.clear()
        self._seed_cells.clear()
        # Query tables: host staging is fully authoritative; force a
        # wholesale re-upload (the spots table re-uploads from scratch
        # on the next flush when present).
        self._d_queries = None
        self._d_spot_dist = None
        self._spot_dirty_rows.clear()
        self._queries_dirty = True
        # Sim kinematic columns: the host shadow (last census + explicit
        # stages) is authoritative; dropping the device copies forces the
        # whole-column re-upload path on the flush below, which is what
        # makes the rebuilt arrays bit-identical to the shadow
        # (verify_device_state proves it, sim_device_rebuilds_total
        # counts it).
        self._d_vel = None
        self._d_sim_state = None
        self._d_sim_target = None
        self._d_agent = None
        self._d_flee = None
        self._flee_dirty = self._flee_cells is not None
        self._sim_dirty.clear()
        # Standing-query diff baseline: gone with the rest of the device
        # state. The epoch bump tells the host plane its mirrors no
        # longer connect to the next tick's delta stream — it must
        # full-resync (every query re-emits against the empty baseline).
        self._d_q_prev = None
        self._q_prev_reset_rows.clear()
        self.query_epoch += 1
        # Sub table: intervals/active from the host mirror; the
        # device-authoritative last-fan-out column restarts at now.
        self._sub_last[self._sub_active] = now_ms
        self._d_sub_state = None
        self._sub_dirty_slots.clear()
        self._sub_last_dirty.clear()
        self._flush_host_state()
        self.last_result = None

    def apply_grid(self, grid, slot_cells: dict[int, int],
                   now_ms: Optional[int] = None,
                   expect_generation: Optional[int] = None) -> None:
        """Swap the cell grid and rebuild every grid-shaped device array
        (adaptive partitioning, doc/partitioning.md: the controller
        mirrors the cell tree's uniform micro grid onto the device at
        each geometry epoch). Reuses the supervised-rebuild machinery —
        the caller passes the same placement-ledger cell baselines
        (in NEW-grid indices) the crash rebuild uses, the generation
        fence makes a watchdog-abandoned swap unable to commit, and
        ``verify_device_state`` afterwards proves the rebuilt arrays
        bit-identical to the host shadow. Grid-shaped state that cannot
        be carried over is rebuilt from world-space sources: the spots
        dist table re-rasterizes from ``_spot_sources``; the compiled
        (mesh) step re-traces lazily on the next tick."""
        self.grid = grid
        # The grid is baked into the compiled mesh step: force a
        # re-build/re-trace on the next tick.
        self._mesh_step = None
        # Spots rows are [Q, num_cells] in cell space: drop both copies
        # and re-rasterize every row against the new grid.
        self._q_spot_dist = None
        self._d_spot_dist = None
        self._spot_dirty_rows.clear()
        # The flee mask is [num_cells] in cell space: drop it; the sim
        # plane re-rasterizes its sensors' hits against the new geometry
        # (its on_geometry hook fires after the swap).
        self._flee_cells = None
        for conn_id, (spots, dists) in list(self._spot_sources.items()):
            self.set_spots_query(conn_id, spots, dists)
        self.rebuild_device_state(slot_cells, now_ms=now_ms,
                                  expect_generation=expect_generation)

    def verify_device_state(self, slot_cells: dict[int, int]) -> list[str]:
        """Bit-identical rebuild verification: fetch the just-rebuilt
        device arrays and compare them against the host shadow (and the
        seeded cell baselines). Returns mismatch descriptions (empty ==
        verified). Rebuild-path only — never called from the tick, so
        these transfers are the designed one-off recovery cost, not a
        hot-path readback."""
        errors: list[str] = []
        cells = np.full(self.entity_capacity, -1, np.int32)
        for slot, cell in slot_cells.items():
            cells[slot] = cell
        # equal_nan on the float arrays: NaN coordinates are tolerated
        # input (they assign outside the world) and round-trip the
        # device bit-identically — without this, one NaN position would
        # fail verification forever and turn a recoverable fault into a
        # permanent outage.
        if not np.array_equal(np.asarray(self._d_positions), self._positions,
                              equal_nan=True):
            errors.append("positions differ from host shadow")
        if not np.array_equal(np.asarray(self._d_valid), self._valid):
            errors.append("valid mask differs from host shadow")
        if not np.array_equal(np.asarray(self._d_cell), cells):
            errors.append("cell baselines differ from placement seeds")
        if self._d_queries is not None:
            for name, dev, host, has_nan in (
                ("query kinds", self._d_queries.kind, self._q_kind, False),
                ("query centers", self._d_queries.center, self._q_center,
                 True),
                ("query extents", self._d_queries.extent, self._q_extent,
                 True),
            ):
                if not np.array_equal(np.asarray(dev), host,
                                      equal_nan=has_nan):
                    errors.append(f"{name} differ from host shadow")
        if self._d_sub_state is not None:
            last, interval, active = self._d_sub_state
            if not np.array_equal(np.asarray(interval), self._sub_interval):
                errors.append("sub intervals differ from host shadow")
            if not np.array_equal(np.asarray(active), self._sub_active):
                errors.append("sub active mask differs from host shadow")
            if not np.array_equal(np.asarray(last), self._sub_last):
                errors.append("sub clock differs from rebuild seed")
        if self.sim_enabled and self._d_vel is not None:
            sim_errors: list[str] = []
            for name, dev, host, has_nan in (
                ("agent velocities", self._d_vel, self._vel, True),
                ("agent FSM states", self._d_sim_state, self._sim_state,
                 False),
                ("agent waypoints", self._d_sim_target, self._sim_target,
                 True),
                ("agent mask", self._d_agent, self._agent_mask, False),
            ):
                if not np.array_equal(np.asarray(dev), host,
                                      equal_nan=has_nan):
                    sim_errors.append(f"{name} differ from host shadow")
            if self._flee_cells is not None and self._d_flee is not None:
                if not np.array_equal(np.asarray(self._d_flee),
                                      self._flee_cells):
                    sim_errors.append("flee mask differs from host shadow")
            errors.extend(sim_errors)
            self._count_sim_rebuild(
                "verified" if not sim_errors else "mismatch"
            )
        return errors

    def corrupt_device_state_for_chaos(self) -> None:
        """CHAOS ONLY (``device.nan``): silently rot the device state the
        way a bad DMA / bit-flipped HBM page would — NaN positions plus
        garbage prev-cell baselines. The NaN positions make the affected
        entities vanish from cell assignment (assign_cells maps NaN
        outside the world); the garbage baselines surface as impossible
        src cells in the next tick's handover rows, which is exactly the
        signature the readback sentinel checks for."""
        live = list(self._slot_of_entity.values())
        n = max(1, len(live) // 4)
        # Garbage baselines on one subset: their (still-valid) positions
        # produce crossing rows with an impossible src cell next tick —
        # the sentinel's detectable signature. NaN positions on a
        # DISJOINT subset: those entities silently vanish from cell
        # assignment (NaN maps outside the world), the truly silent rot
        # the sentinel-triggered rebuild also heals.
        garbage = np.fromiter(live[:n], np.int32, min(n, len(live)))
        nan_rows = np.fromiter(live[n:2 * n], np.int32, len(live[n:2 * n]))
        self._d_cell = self._keep_entity_sharding(
            self._d_cell.at[garbage].set(1 << 24)
        )
        if len(nan_rows):
            self._d_positions = self._keep_entity_sharding(
                self._d_positions.at[nan_rows].set(float("nan"))
            )

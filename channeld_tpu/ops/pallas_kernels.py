"""Pallas TPU kernels for the spatial hot path.

The fused XLA step (spatial_ops.spatial_step) is already dispatch-bound
at bench sizes, but the two memory-heaviest pieces — cell assignment and
the per-cell occupancy histogram — stream the whole entity table through
the VPU. This kernel fuses them into one VMEM pass: each grid step loads
a tile of positions, computes cell indices, and accumulates the one-hot
histogram in place, so positions are read exactly once and the [N, C]
one-hot never materializes in HBM.

``assign_and_count`` picks the Mosaic kernel on TPU backends and the XLA
implementation elsewhere (tests run the kernel in interpret mode on CPU).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .spatial_ops import GridSpec

TILE = 2048  # entities per grid step = SUBLANES x LANES
SUBLANES = 8
LANES = TILE // SUBLANES


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


def _assign_count_kernel(grid: GridSpec, c_pad: int, x_ref, z_ref, valid_ref,
                         cell_ref, counts_ref):
    from jax.experimental import pallas as pl

    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        counts_ref[...] = jnp.zeros_like(counts_ref)

    x = x_ref[...]  # (SUBLANES, LANES)
    z = z_ref[...]
    gx = jnp.floor((x - grid.offset_x) / grid.cell_w).astype(jnp.int32)
    gz = jnp.floor((z - grid.offset_z) / grid.cell_h).astype(jnp.int32)
    inside = (
        (gx >= 0) & (gx < grid.cols) & (gz >= 0) & (gz < grid.rows)
        & valid_ref[...]
    )
    cell = jnp.where(inside, gx + gz * grid.cols, -1)
    cell_ref[...] = cell

    # One-hot accumulate entirely in VMEM: rank-3 broadcast compare (no
    # reshapes — Mosaic can't re-tile (8,256)->(2048,1)) reduced over the
    # lane-block axis into per-sublane partial histograms.
    cell_ids = jax.lax.broadcasted_iota(jnp.int32, (SUBLANES, LANES, c_pad), 2)
    onehot = (cell[:, :, None] == cell_ids).astype(jnp.int32)
    counts_ref[...] += jnp.sum(onehot, axis=1)


@functools.partial(jax.jit, static_argnums=(0, 3))
def assign_and_count_pallas(grid: GridSpec, positions, valid,
                            interpret: bool = False):
    """Fused cell assignment + occupancy histogram.

    positions f32[N,3], valid bool[N] -> (cell_of i32[N], counts i32[C]).
    N is padded to a TILE multiple internally; C to a lane multiple.
    """
    from jax.experimental import pallas as pl

    n = positions.shape[0]
    n_pad = _cdiv(n, TILE) * TILE
    c = grid.num_cells
    c_pad = _cdiv(c, 128) * 128

    x = jnp.pad(positions[:, 0], (0, n_pad - n), constant_values=jnp.inf)
    z = jnp.pad(positions[:, 2], (0, n_pad - n), constant_values=jnp.inf)
    v = jnp.pad(valid, (0, n_pad - n), constant_values=False)
    tiles = n_pad // TILE
    shape = (tiles * SUBLANES, LANES)

    cell, counts = pl.pallas_call(
        functools.partial(_assign_count_kernel, grid, c_pad),
        grid=(tiles,),
        in_specs=[
            pl.BlockSpec((SUBLANES, LANES), lambda i: (i, 0)),
            pl.BlockSpec((SUBLANES, LANES), lambda i: (i, 0)),
            pl.BlockSpec((SUBLANES, LANES), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((SUBLANES, LANES), lambda i: (i, 0)),
            pl.BlockSpec((SUBLANES, c_pad), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(shape, jnp.int32),
            jax.ShapeDtypeStruct((SUBLANES, c_pad), jnp.int32),
        ],
        interpret=interpret,
    )(x.reshape(shape), z.reshape(shape), v.reshape(shape))
    return cell.reshape(n_pad)[:n], jnp.sum(counts, axis=0)[:c]


def assign_and_count(grid: GridSpec, positions, valid):
    """Backend-dispatched fused pass: Mosaic on TPU, XLA elsewhere."""
    if pallas_available():
        return assign_and_count_pallas(grid, positions, valid)
    from .spatial_ops import assign_cells, cell_counts

    cell = assign_cells(grid, positions, valid)
    return cell, cell_counts(cell, grid.num_cells)


def pallas_available() -> bool:
    """True when the current default backend can run Mosaic kernels."""
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False

"""Pallas TPU kernels for the spatial hot path.

The fused XLA step (spatial_ops.spatial_step) is already dispatch-bound
at bench sizes, but the two memory-heaviest pieces — cell assignment and
the per-cell occupancy histogram — stream the whole entity table through
the VPU. This kernel fuses them into one VMEM pass: each grid step loads
a tile of positions, computes cell indices, and accumulates the one-hot
histogram in place, so positions are read exactly once and the [N, C]
one-hot never materializes in HBM.

``assign_and_count`` picks the Mosaic kernel on TPU backends and the XLA
implementation elsewhere (tests run the kernel in interpret mode on CPU).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .spatial_ops import GridSpec

TILE = 2048  # entities per grid step = SUBLANES x LANES
SUBLANES = 8
LANES = TILE // SUBLANES


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


def _assign_count_kernel(grid: GridSpec, c_pad: int, x_ref, z_ref, valid_ref,
                         cell_ref, counts_ref):
    from jax.experimental import pallas as pl

    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        counts_ref[...] = jnp.zeros_like(counts_ref)

    x = x_ref[...]  # (SUBLANES, LANES)
    z = z_ref[...]
    gx = jnp.floor((x - grid.offset_x) / grid.cell_w).astype(jnp.int32)
    gz = jnp.floor((z - grid.offset_z) / grid.cell_h).astype(jnp.int32)
    # valid arrives as i32: a bool (i8-stored) input would need an i8->i1
    # vector truncation Mosaic can't lower on v5e.
    inside = (
        (gx >= 0) & (gx < grid.cols) & (gz >= 0) & (gz < grid.rows)
        & (valid_ref[...] != 0)
    )
    cell = jnp.where(inside, gx + gz * grid.cols, -1)
    cell_ref[...] = cell

    # One-hot accumulate entirely in VMEM: rank-3 broadcast compare (no
    # reshapes — Mosaic can't re-tile (8,256)->(2048,1)) reduced over the
    # lane-block axis into per-sublane partial histograms.
    cell_ids = jax.lax.broadcasted_iota(jnp.int32, (SUBLANES, LANES, c_pad), 2)
    onehot = (cell[:, :, None] == cell_ids).astype(jnp.int32)
    counts_ref[...] += jnp.sum(onehot, axis=1)


@functools.partial(jax.jit, static_argnums=(0, 3))
def assign_and_count_pallas(grid: GridSpec, positions, valid,
                            interpret: bool = False):
    """Fused cell assignment + occupancy histogram.

    positions f32[N,3], valid bool[N] -> (cell_of i32[N], counts i32[C]).
    N is padded to a TILE multiple internally; C to a lane multiple.
    """
    from jax.experimental import pallas as pl

    n = positions.shape[0]
    n_pad = _cdiv(n, TILE) * TILE
    c = grid.num_cells
    c_pad = _cdiv(c, 128) * 128

    x = jnp.pad(positions[:, 0], (0, n_pad - n), constant_values=jnp.inf)
    z = jnp.pad(positions[:, 2], (0, n_pad - n), constant_values=jnp.inf)
    v = jnp.pad(valid.astype(jnp.int32), (0, n_pad - n), constant_values=0)
    tiles = n_pad // TILE
    shape = (tiles * SUBLANES, LANES)

    cell, counts = pl.pallas_call(
        functools.partial(_assign_count_kernel, grid, c_pad),
        grid=(tiles,),
        in_specs=[
            pl.BlockSpec((SUBLANES, LANES), lambda i: (i, 0)),
            pl.BlockSpec((SUBLANES, LANES), lambda i: (i, 0)),
            pl.BlockSpec((SUBLANES, LANES), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((SUBLANES, LANES), lambda i: (i, 0)),
            pl.BlockSpec((SUBLANES, c_pad), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(shape, jnp.int32),
            jax.ShapeDtypeStruct((SUBLANES, c_pad), jnp.int32),
        ],
        interpret=interpret,
    )(x.reshape(shape), z.reshape(shape), v.reshape(shape))
    return cell.reshape(n_pad)[:n], jnp.sum(counts, axis=0)[:c]


SUB_Q = 8  # queries per grid step (sublane dimension)


def _aoi_kernel(grid: GridSpec, c_pad: int, kind_ref, cx_ref, cz_ref,
                ex_ref, ez_ref, dx_ref, dz_ref, ang_ref, hit_ref, dist_ref):
    """One tile: SUB_Q queries x all (padded) cells. Cell geometry is
    generated in-register from iota — nothing but the query SoA tile is
    read, and the [Q,C] interest/dist planes are written exactly once."""
    ids = jax.lax.broadcasted_iota(jnp.int32, (SUB_Q, c_pad), 1)
    col = (ids % grid.cols).astype(jnp.float32)
    row = (ids // grid.cols).astype(jnp.float32)
    ccx = grid.offset_x + (col + 0.5) * grid.cell_w
    ccz = grid.offset_z + (row + 0.5) * grid.cell_h
    cell_valid = ids < grid.num_cells  # lane padding never hits

    kind = kind_ref[...]  # (SUB_Q, 1) broadcasts along lanes
    qx, qz = cx_ref[...], cz_ref[...]
    ex, ez = ex_ref[...], ez_ref[...]

    dx = jnp.abs(qx - ccx)
    dz = jnp.abs(qz - ccz)
    half_w = grid.cell_w * 0.5
    half_h = grid.cell_h * 0.5
    gap_x = jnp.maximum(dx - half_w, 0.0)
    gap_z = jnp.maximum(dz - half_h, 0.0)
    rect_dist = jnp.sqrt(gap_x * gap_x + gap_z * gap_z)
    center_dist = jnp.sqrt((qx - ccx) ** 2 + (qz - ccz) ** 2)

    radius = ex
    sphere_hit = rect_dist <= radius
    box_hit = (dx <= ex + half_w) & (dz <= ez + half_h)
    to_x = ccx - qx
    to_z = ccz - qz
    to_len = jnp.maximum(jnp.sqrt(to_x * to_x + to_z * to_z), 1e-9)
    cosine = (to_x * dx_ref[...] + to_z * dz_ref[...]) / to_len
    in_angle = cosine >= jnp.cos(ang_ref[...])
    apex_cell = rect_dist <= 0.0
    cone_hit = (rect_dist <= radius) & (in_angle | apex_cell)

    from .spatial_ops import AOI_BOX, AOI_CONE, AOI_SPHERE

    # Pure i1 mask algebra: a where-chain with a Python bool arm lowers to
    # an i8 constant vector + i8->i1 truncation Mosaic can't compile.
    hit = (
        ((kind == AOI_SPHERE) & sphere_hit)
        | ((kind == AOI_BOX) & box_hit)
        | ((kind == AOI_CONE) & cone_hit)
    ) & cell_valid
    dist = jnp.ceil(center_dist / grid.diagonal).astype(jnp.int32)
    dist = jnp.where(rect_dist <= 0.0, 0, dist)
    hit_ref[...] = hit.astype(jnp.int32)
    dist_ref[...] = dist


@functools.partial(jax.jit, static_argnums=(0, 2))
def _aoi_masks_pallas_geom(grid: GridSpec, q_soa, interpret: bool = False):
    """Geometric AOI pass on device: (hit i32[Q,C_pad], dist i32[Q,C_pad])."""
    from jax.experimental import pallas as pl

    kind, center, extent, direction, angle = q_soa
    q = kind.shape[0]
    q_pad = _cdiv(q, SUB_Q) * SUB_Q
    c_pad = _cdiv(grid.num_cells, 128) * 128

    def col2d(arr, fill=0):
        return jnp.pad(arr, (0, q_pad - q), constant_values=fill)[:, None]

    cols = [
        col2d(kind.astype(jnp.int32)),
        col2d(center[:, 0]), col2d(center[:, 1]),
        col2d(extent[:, 0]), col2d(extent[:, 1]),
        col2d(direction[:, 0]), col2d(direction[:, 1]),
        col2d(angle),
    ]
    tiles = q_pad // SUB_Q
    hit, dist = pl.pallas_call(
        functools.partial(_aoi_kernel, grid, c_pad),
        grid=(tiles,),
        in_specs=[pl.BlockSpec((SUB_Q, 1), lambda i: (i, 0))] * len(cols),
        out_specs=[
            pl.BlockSpec((SUB_Q, c_pad), lambda i: (i, 0)),
            pl.BlockSpec((SUB_Q, c_pad), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((q_pad, c_pad), jnp.int32),
            jax.ShapeDtypeStruct((q_pad, c_pad), jnp.int32),
        ],
        interpret=interpret,
    )(*cols)
    return hit[:q, : grid.num_cells], dist[:q, : grid.num_cells]


def aoi_masks_pallas(grid: GridSpec, queries, interpret: bool = False):
    """Mosaic-fused replacement for spatial_ops.aoi_masks: same results
    (interest bool[Q,C], dist i32[Q,C]); the spots-table overlay stays in
    XLA (it is a gather, not geometry)."""
    hit, dist = _aoi_masks_pallas_geom(
        grid,
        (queries.kind, queries.center, queries.extent, queries.direction,
         queries.angle),
        interpret,
    )
    from .spatial_ops import apply_spots_overlay

    return apply_spots_overlay(hit.astype(bool), dist, queries)


def assign_and_count(grid: GridSpec, positions, valid):
    """Backend-dispatched fused pass: Mosaic on TPU, XLA elsewhere."""
    if pallas_available():
        return assign_and_count_pallas(grid, positions, valid)
    from .spatial_ops import assign_cells, cell_counts

    cell = assign_cells(grid, positions, valid)
    return cell, cell_counts(cell, grid.num_cells)


def pallas_available() -> bool:
    """True when the current default backend can run Mosaic kernels."""
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False

"""gRPC sidecar exposing the TPU spatial decision plane.

Lets an external gateway (e.g. the original Go channeld behind its
SpatialController seam) offload the per-tick AOI/handover/fan-out pass:
it ships position deltas + query/subscription changes in a StepRequest
and receives compacted decisions. Service wiring is hand-rolled generic
handlers because the image carries only the grpc runtime (no codegen
plugin); the message schema is service.proto.

Serving properties:
- Interest results are DELTA: AOI masks depend only on query geometry,
  so only connections whose query changed this step are recomputed and
  returned (request fullInterest for a complete sync). Step cost is
  therefore independent of the standing query population. Dirty
  tracking is per caller (per stream / per unary peer), so concurrent
  gateway clients each see every change exactly once; a caller's first
  step is automatically a full sync.
- Steps serialize per engine (not on a global lock): a long device step
  never blocks Configure, and an engine swap never waits on traffic to
  a doomed engine.
- Optional shared-secret auth: set ``auth_token`` (or the
  CHTPU_SIDECAR_TOKEN env var) and every call must carry it as
  ``x-chtpu-auth`` metadata.
- StepStream: a bidirectional pipeline (one response per request)
  avoiding per-call RPC setup at the 30Hz gateway cadence.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from concurrent import futures
from typing import Optional

import numpy as np

from ..utils.logger import get_logger
from .spatial_ops import AOI_SPOTS
from .service_pb2 import (
    ConfigRequest,
    Empty,
    StepRequest,
    StepResponse,
)

logger = get_logger("ops.service")

SERVICE_NAME = "chtpu.ops.SpatialDecision"
AUTH_METADATA_KEY = "x-chtpu-auth"
# Distinguishes unary callers for delta-interest tracking. context.peer()
# alone is NOT enough: grpc-python shares subchannels between channels
# with the same target+args, so two client objects in one process can
# present the same peer address.
CALLER_METADATA_KEY = "x-chtpu-caller"


class _StepValidationError(ValueError):
    """A malformed StepRequest; unary aborts, streaming reports in-band."""


_DIRTY_CALLER_TTL = 300.0  # forget unary peers silent this long
_MAX_DIRTY_CALLERS = 64  # hard cap: caller ids are client-controlled


class _EngineState:
    """One engine plus ALL its serving state, swapped atomically on
    Configure: a step racing a swap holds the doomed state's lock and
    touches only that state — never the new engine's dirty set/sub map.

    Dirty-interest tracking is PER CALLER (one set per stream, one per
    unary peer): a query mutation marks the conn dirty in every caller's
    set, and each caller's step drains only its own — so a unary Step
    racing a StepStream (or two gateway clients) can't consume each
    other's pending delta-interest notifications. A caller seen for the
    first time starts with every standing query dirty, so its first step
    is a full sync without needing fullInterest."""

    def __init__(self, engine):
        self.engine = engine
        self.lock = threading.Lock()
        self.sub_map: dict[int, int] = {}
        self._dirty_sets: dict[object, set[int]] = {}
        self._dirty_seen: dict[object, float] = {}
        self._pinned: set[object] = set()  # stream callers: no TTL/evict

    def dirty_for(self, caller: object, pinned: bool = False) -> set[int]:
        """The caller's own dirty set (created on first use). The
        registry is bounded two ways — caller ids are client-controlled
        metadata, so it must not grow with hostile or buggy traffic:
        unary peers unseen within the TTL are pruned, and at the hard
        cap the longest-unseen unary peer is evicted (it full-resyncs on
        return). ``pinned`` callers (open streams) are exempt from both;
        stream teardown drops them explicitly."""
        now = time.monotonic()
        dirty = self._dirty_sets.get(caller)
        if dirty is None:
            # Only unpinned callers count toward (and make room in) the
            # cap: a new pinned stream must not evict a unary caller's
            # pending deltas to claim a slot it is itself exempt from.
            if not pinned:
                unpinned = [k for k in self._dirty_seen
                            if k not in self._pinned]
                if len(unpinned) >= _MAX_DIRTY_CALLERS:
                    self.drop_caller(min(unpinned, key=self._dirty_seen.get))
            dirty = set(self.engine._q_of_conn.keys())
            self._dirty_sets[caller] = dirty
            if pinned:
                self._pinned.add(caller)
        self._dirty_seen[caller] = now
        for stale in [k for k, t in self._dirty_seen.items()
                      if now - t > _DIRTY_CALLER_TTL
                      and k not in self._pinned]:
            self.drop_caller(stale)
        return dirty

    def drop_caller(self, caller: object) -> None:
        self._dirty_sets.pop(caller, None)
        self._dirty_seen.pop(caller, None)
        self._pinned.discard(caller)

    def mark_dirty(self, conn_id: int) -> None:
        for dirty in self._dirty_sets.values():
            dirty.add(conn_id)

    def unmark_dirty(self, conn_id: int) -> None:
        for dirty in self._dirty_sets.values():
            dirty.discard(conn_id)


class SpatialDecisionServicer:
    def __init__(self, auth_token: Optional[str] = None):
        self.auth_token = auth_token
        # Guards state swap only; step traffic serializes on the state's
        # own lock so Configure never queues behind a slow device step.
        self._swap_lock = threading.Lock()
        self._state: Optional[_EngineState] = None

    @property
    def engine(self):
        state = self._state
        return state.engine if state is not None else None

    # ---- auth --------------------------------------------------------

    def _check_auth(self, context) -> None:
        if not self.auth_token:
            return
        import hmac

        meta = dict(context.invocation_metadata() or ())
        if not hmac.compare_digest(
            meta.get(AUTH_METADATA_KEY, ""), self.auth_token
        ):
            import grpc

            context.abort(grpc.StatusCode.UNAUTHENTICATED,
                          "missing or wrong x-chtpu-auth token")

    # ---- rpc handlers ------------------------------------------------

    def configure(self, request: ConfigRequest, context) -> Empty:
        self._check_auth(context)
        from .engine import SpatialEngine
        from .spatial_ops import GridSpec
        from ..parallel.mesh import mesh_from_config

        try:
            mesh = mesh_from_config(
                request.meshDevices, request.meshHosts or 1
            )
        except ValueError as e:
            import grpc

            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        engine = SpatialEngine(
            GridSpec(
                offset_x=request.worldOffsetX,
                offset_z=request.worldOffsetZ,
                cell_w=request.gridWidth,
                cell_h=request.gridHeight,
                cols=request.gridCols,
                rows=request.gridRows,
            ),
            entity_capacity=request.entityCapacity or (1 << 17),
            query_capacity=request.queryCapacity or (1 << 12),
            sub_capacity=request.subCapacity or (1 << 16),
            mesh=mesh,
        )
        with self._swap_lock:
            self._state = _EngineState(engine)
        logger.info(
            "configured engine: %dx%d grid, %d entity slots, mesh=%s",
            request.gridCols, request.gridRows,
            request.entityCapacity or (1 << 17),
            f"{request.meshDevices}dev" if request.meshDevices else "none",
        )
        return Empty()

    def _current_state(self, context) -> _EngineState:
        with self._swap_lock:
            state = self._state
        if state is None:
            import grpc

            context.abort(grpc.StatusCode.FAILED_PRECONDITION, "not configured")
        return state

    def step(self, request: StepRequest, context) -> StepResponse:
        self._check_auth(context)
        state = self._current_state(context)
        # One dirty set per unary caller: the x-chtpu-caller metadata if
        # the gateway sends one, else the peer address. TTL-pruned in
        # _EngineState.dirty_for.
        meta = dict(context.invocation_metadata() or ())
        caller = ("unary", meta.get(CALLER_METADATA_KEY) or context.peer())
        try:
            with state.lock:
                return self._do_step(state, request, caller)
        except _StepValidationError as e:
            import grpc

            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))

    def step_stream(self, request_iterator, context):
        """One response per request; same semantics as Step, except a
        malformed request answers in-band (StepResponse.error) instead of
        killing the pipeline with its in-flight steps."""
        self._check_auth(context)
        caller = object()  # one dirty set per stream, dropped at stream end
        state = None
        try:
            for request in request_iterator:
                state = self._current_state(context)
                try:
                    # Yield OUTSIDE the lock: a generator suspends at
                    # yield, and a stalled stream consumer must not hold
                    # the engine lock against unary steps/other streams.
                    with state.lock:
                        resp = self._do_step(state, request, caller,
                                             pinned=True)
                except _StepValidationError as e:
                    resp = StepResponse(engineNowMs=request.nowMs,
                                        error=str(e))
                yield resp
        finally:
            if state is not None:
                with state.lock:
                    state.drop_caller(caller)

    # ---- the decision pass -------------------------------------------

    def _do_step(self, state: _EngineState, request: StepRequest,
                 caller: object, pinned: bool = False) -> StepResponse:
        eng = state.engine
        dirty = state.dirty_for(caller, pinned=pinned)
        for up in request.updates:
            eng.update_entity(up.entityId, up.x, up.y, up.z)
        for eid in request.removedEntityIds:
            eng.remove_entity(eid)
        for q in request.queries:
            if q.kind == AOI_SPOTS:
                if len(q.spotX) != len(q.spotZ):
                    raise _StepValidationError(
                        f"spotX/spotZ length mismatch "
                        f"({len(q.spotX)} vs {len(q.spotZ)})"
                    )
                eng.set_spots_query(
                    q.connId, list(zip(q.spotX, q.spotZ)), list(q.spotDists)
                )
                state.mark_dirty(q.connId)
                continue
            direction = (q.dirX, q.dirZ)
            if direction == (0.0, 0.0):
                direction = (1.0, 0.0)  # unset; a zero vector is invalid
            eng.set_query(
                q.connId, q.kind, (q.centerX, q.centerZ),
                (q.extentX, q.extentZ), direction, q.angle,
            )
            state.mark_dirty(q.connId)
        for conn_id in request.removedQueryConnIds:
            eng.remove_query(conn_id)
            state.unmark_dirty(conn_id)
        sub_map = state.sub_map
        for sub in request.addSubscriptions:
            sub_map[sub.subId] = eng.add_subscription(
                sub.fanOutIntervalMs, sub.firstDueMs
            )
        for sub_id in request.removeSubIds:
            slot = sub_map.pop(sub_id, None)
            if slot is not None:
                eng.remove_subscription(slot)

        now_ms = request.nowMs or eng.now_ms()
        result = eng.tick(now_ms)

        resp = StepResponse(engineNowMs=now_ms)
        resp.handoverCount = int(result["handover_count"])
        for entity_id, src, dst in eng.handover_list(result):
            resp.handovers.add(entityId=entity_id, srcCell=src, dstCell=dst)
        resp.cellCounts.extend(
            np.asarray(result["cell_counts"]).astype(np.uint32).tolist()
        )
        # Delta interest: AOI masks are a pure function of query geometry,
        # so only changed queries need recomputation/transfer — step cost
        # is flat in the standing query population (VERDICT r1 weak #4).
        if request.fullInterest:
            report_conns = list(eng._q_of_conn.keys())
        else:
            report_conns = [c for c in dirty if c in eng._q_of_conn]
        if report_conns:
            interest = np.asarray(result["interest"])
            dist = np.asarray(result["dist"])
            for conn_id in report_conns:
                row = eng._q_of_conn[conn_id]
                cells = np.nonzero(interest[row])[0]
                ir = resp.interests.add(connId=conn_id)
                ir.cells.extend(cells.astype(np.uint32).tolist())
                ir.dists.extend(dist[row][cells].astype(np.uint32).tolist())
        dirty.clear()
        due = np.unpackbits(np.asarray(result["due_packed"]))
        slot_to_sub = {slot: sub_id for sub_id, slot in sub_map.items()}
        for slot in np.nonzero(due[: eng.sub_capacity])[0]:
            sub_id = slot_to_sub.get(int(slot))
            if sub_id is not None:
                resp.dueSubIds.append(sub_id)
        return resp


def create_server(port: int = 50051, max_workers: int = 4,
                  auth_token: Optional[str] = None):
    """Build (but don't start) the gRPC server; returns
    (server, servicer, bound_port). ``auth_token`` defaults to the
    CHTPU_SIDECAR_TOKEN env var; empty = no auth."""
    import grpc

    if auth_token is None:
        auth_token = os.environ.get("CHTPU_SIDECAR_TOKEN", "")
    servicer = SpatialDecisionServicer(auth_token=auth_token or None)
    handlers = grpc.method_handlers_generic_handler(
        SERVICE_NAME,
        {
            "Configure": grpc.unary_unary_rpc_method_handler(
                servicer.configure,
                request_deserializer=ConfigRequest.FromString,
                response_serializer=Empty.SerializeToString,
            ),
            "Step": grpc.unary_unary_rpc_method_handler(
                servicer.step,
                request_deserializer=StepRequest.FromString,
                response_serializer=StepResponse.SerializeToString,
            ),
            "StepStream": grpc.stream_stream_rpc_method_handler(
                servicer.step_stream,
                request_deserializer=StepRequest.FromString,
                response_serializer=StepResponse.SerializeToString,
            ),
        },
    )
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    server.add_generic_rpc_handlers((handlers,))
    bound = server.add_insecure_port(f"[::]:{port}")
    if bound == 0:
        raise OSError(f"failed to bind sidecar port {port}")
    return server, servicer, bound


class SpatialDecisionClient:
    """Typed client for gateways written in Python (external gateways use
    the proto schema directly).

    Unary calls are hardened for the gateway tick loop: every call
    carries a deadline (a hung sidecar must never wedge the tick
    forever), and transient failures retry with deterministic
    exponential backoff before surfacing. Retryable codes are
    per-method: Configure is idempotent, so a timed-out call retries
    safely; Step is NOT retried on DEADLINE_EXCEEDED — a step that
    executed server-side but whose response timed out has already
    drained this caller's dirty set and allocated any requested
    subscription slots, so replaying it would lose delta-interest
    updates and leak slots. StepStream is not retried at all: a broken
    stream loses its per-caller delta state, so the caller must reopen
    and accept the automatic full resync."""

    # grpc codes considered transient per method; resolved lazily
    # (grpc import).
    _RETRYABLE = {
        "Configure": ("UNAVAILABLE", "DEADLINE_EXCEEDED"),
        "Step": ("UNAVAILABLE",),  # non-idempotent: see class docstring
    }

    def __init__(self, target: str = "127.0.0.1:50051",
                 auth_token: Optional[str] = None,
                 timeout_s: float = 5.0, max_retries: int = 3,
                 backoff_s: float = 0.1):
        import grpc

        self.target = target
        self.timeout_s = timeout_s
        self.max_retries = max_retries
        self.backoff_s = backoff_s
        self._channel = grpc.insecure_channel(target)
        meta = [(CALLER_METADATA_KEY, uuid.uuid4().hex)]
        if auth_token:
            meta.append((AUTH_METADATA_KEY, auth_token))
        self._metadata = tuple(meta)
        self._configure = self._channel.unary_unary(
            f"/{SERVICE_NAME}/Configure",
            request_serializer=ConfigRequest.SerializeToString,
            response_deserializer=Empty.FromString,
        )
        self._step = self._channel.unary_unary(
            f"/{SERVICE_NAME}/Step",
            request_serializer=StepRequest.SerializeToString,
            response_deserializer=StepResponse.FromString,
        )
        self._step_stream = self._channel.stream_stream(
            f"/{SERVICE_NAME}/StepStream",
            request_serializer=StepRequest.SerializeToString,
            response_deserializer=StepResponse.FromString,
        )

    def _call_with_retry(self, method_name: str, fn, request):
        """Deadline + deterministic exponential backoff on transient
        codes. Deterministic (no jitter) on purpose: chaos replays must
        see the same retry schedule."""
        import grpc

        retryable = tuple(
            getattr(grpc.StatusCode, c)
            for c in self._RETRYABLE.get(method_name, ())
        )
        delay = self.backoff_s
        attempt = 0
        while True:
            try:
                return fn(request, metadata=self._metadata,
                          timeout=self.timeout_s)
            except grpc.RpcError as e:
                code = e.code() if hasattr(e, "code") else None
                if code not in retryable or attempt >= self.max_retries:
                    raise
                attempt += 1
                try:
                    from ..core import metrics

                    metrics.sidecar_call_retries.labels(
                        method=method_name
                    ).inc()
                except Exception:
                    pass
                logger.warning(
                    "sidecar %s transient failure (%s); retry %d/%d in %.2fs",
                    method_name, code, attempt, self.max_retries, delay,
                )
                time.sleep(delay)
                delay *= 2

    def configure(self, **kwargs) -> None:
        self._call_with_retry(
            "Configure", self._configure, ConfigRequest(**kwargs)
        )

    def step(self, request: StepRequest) -> StepResponse:
        return self._call_with_retry("Step", self._step, request)

    def step_stream(self, request_iterator):
        """Returns the response iterator for a bidirectional pipeline."""
        return self._step_stream(request_iterator, metadata=self._metadata)

    def close(self) -> None:
        self._channel.close()


def main() -> None:
    import argparse

    p = argparse.ArgumentParser(description="channeld-tpu spatial decision sidecar")
    p.add_argument("--port", type=int, default=50051)
    p.add_argument("--auth-token", type=str, default=None,
                   help="shared secret; defaults to $CHTPU_SIDECAR_TOKEN")
    args = p.parse_args()
    server, _, bound = create_server(args.port, auth_token=args.auth_token)
    server.start()
    logger.info("spatial decision sidecar listening on :%d", bound)
    server.wait_for_termination()


if __name__ == "__main__":
    main()

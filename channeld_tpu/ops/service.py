"""gRPC sidecar exposing the TPU spatial decision plane.

Lets an external gateway (e.g. the original Go channeld behind its
SpatialController seam) offload the per-tick AOI/handover/fan-out pass:
it ships position deltas + query/subscription changes in a StepRequest
and receives compacted decisions. Service wiring is hand-rolled generic
handlers because the image carries only the grpc runtime (no codegen
plugin); the message schema is service.proto.
"""

from __future__ import annotations

import threading
import time
from concurrent import futures
from typing import Optional

import numpy as np

from ..utils.logger import get_logger
from .spatial_ops import AOI_SPOTS
from .service_pb2 import (
    ConfigRequest,
    Empty,
    StepRequest,
    StepResponse,
)

logger = get_logger("ops.service")

SERVICE_NAME = "chtpu.ops.SpatialDecision"


class SpatialDecisionServicer:
    def __init__(self):
        self.engine = None
        self._lock = threading.Lock()

    # ---- rpc handlers ------------------------------------------------

    def configure(self, request: ConfigRequest, context) -> Empty:
        from .engine import SpatialEngine
        from .spatial_ops import GridSpec

        from ..parallel.mesh import mesh_from_config

        try:
            mesh = mesh_from_config(
                request.meshDevices, request.meshHosts or 1
            )
        except ValueError as e:
            import grpc

            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        with self._lock:
            self.engine = SpatialEngine(
                GridSpec(
                    offset_x=request.worldOffsetX,
                    offset_z=request.worldOffsetZ,
                    cell_w=request.gridWidth,
                    cell_h=request.gridHeight,
                    cols=request.gridCols,
                    rows=request.gridRows,
                ),
                entity_capacity=request.entityCapacity or (1 << 17),
                query_capacity=request.queryCapacity or (1 << 12),
                sub_capacity=request.subCapacity or (1 << 16),
                mesh=mesh,
            )
        logger.info(
            "configured engine: %dx%d grid, %d entity slots, mesh=%s",
            request.gridCols, request.gridRows,
            request.entityCapacity or (1 << 17),
            f"{request.meshDevices}dev" if request.meshDevices else "none",
        )
        return Empty()

    def step(self, request: StepRequest, context) -> StepResponse:
        with self._lock:
            if self.engine is None:
                import grpc

                context.abort(grpc.StatusCode.FAILED_PRECONDITION, "not configured")
            eng = self.engine
            for up in request.updates:
                eng.update_entity(up.entityId, up.x, up.y, up.z)
            for eid in request.removedEntityIds:
                eng.remove_entity(eid)
            for q in request.queries:
                if q.kind == AOI_SPOTS:
                    if len(q.spotX) != len(q.spotZ):
                        import grpc

                        context.abort(
                            grpc.StatusCode.INVALID_ARGUMENT,
                            f"spotX/spotZ length mismatch "
                            f"({len(q.spotX)} vs {len(q.spotZ)})",
                        )
                    eng.set_spots_query(
                        q.connId, list(zip(q.spotX, q.spotZ)), list(q.spotDists)
                    )
                    continue
                direction = (q.dirX, q.dirZ)
                if direction == (0.0, 0.0):
                    direction = (1.0, 0.0)  # unset; a zero vector is invalid
                eng.set_query(
                    q.connId, q.kind, (q.centerX, q.centerZ),
                    (q.extentX, q.extentZ), direction, q.angle,
                )
            for conn_id in request.removedQueryConnIds:
                eng.remove_query(conn_id)
            sub_map = getattr(eng, "_service_sub_map", None)
            if sub_map is None:
                sub_map = eng._service_sub_map = {}
            for sub in request.addSubscriptions:
                sub_map[sub.subId] = eng.add_subscription(
                    sub.fanOutIntervalMs, sub.firstDueMs
                )
            for sub_id in request.removeSubIds:
                slot = sub_map.pop(sub_id, None)
                if slot is not None:
                    eng.remove_subscription(slot)

            now_ms = request.nowMs or eng.now_ms()
            result = eng.tick(now_ms)

            resp = StepResponse(engineNowMs=now_ms)
            resp.handoverCount = int(result["handover_count"])
            for entity_id, src, dst in eng.handover_list(result):
                resp.handovers.add(entityId=entity_id, srcCell=src, dstCell=dst)
            resp.cellCounts.extend(
                np.asarray(result["cell_counts"]).astype(np.uint32).tolist()
            )
            interest = np.asarray(result["interest"])
            dist = np.asarray(result["dist"])
            for conn_id, row in eng._q_of_conn.items():
                cells = np.nonzero(interest[row])[0]
                ir = resp.interests.add(connId=conn_id)
                ir.cells.extend(cells.astype(np.uint32).tolist())
                ir.dists.extend(dist[row][cells].astype(np.uint32).tolist())
            due = np.unpackbits(np.asarray(result["due_packed"]))
            slot_to_sub = {slot: sub_id for sub_id, slot in sub_map.items()}
            for slot in np.nonzero(due[: eng.sub_capacity])[0]:
                sub_id = slot_to_sub.get(int(slot))
                if sub_id is not None:
                    resp.dueSubIds.append(sub_id)
            return resp


def create_server(port: int = 50051, max_workers: int = 4):
    """Build (but don't start) the gRPC server; returns (server, servicer)."""
    import grpc

    servicer = SpatialDecisionServicer()
    handlers = grpc.method_handlers_generic_handler(
        SERVICE_NAME,
        {
            "Configure": grpc.unary_unary_rpc_method_handler(
                servicer.configure,
                request_deserializer=ConfigRequest.FromString,
                response_serializer=Empty.SerializeToString,
            ),
            "Step": grpc.unary_unary_rpc_method_handler(
                servicer.step,
                request_deserializer=StepRequest.FromString,
                response_serializer=StepResponse.SerializeToString,
            ),
        },
    )
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    server.add_generic_rpc_handlers((handlers,))
    bound = server.add_insecure_port(f"[::]:{port}")
    if bound == 0:
        raise OSError(f"failed to bind sidecar port {port}")
    return server, servicer, bound


class SpatialDecisionClient:
    """Typed client for gateways written in Python (external gateways use
    the proto schema directly)."""

    def __init__(self, target: str = "127.0.0.1:50051"):
        import grpc

        self._channel = grpc.insecure_channel(target)
        self._configure = self._channel.unary_unary(
            f"/{SERVICE_NAME}/Configure",
            request_serializer=ConfigRequest.SerializeToString,
            response_deserializer=Empty.FromString,
        )
        self._step = self._channel.unary_unary(
            f"/{SERVICE_NAME}/Step",
            request_serializer=StepRequest.SerializeToString,
            response_deserializer=StepResponse.FromString,
        )

    def configure(self, **kwargs) -> None:
        self._configure(ConfigRequest(**kwargs))

    def step(self, request: StepRequest) -> StepResponse:
        return self._step(request)

    def close(self) -> None:
        self._channel.close()


def main() -> None:
    import argparse

    p = argparse.ArgumentParser(description="channeld-tpu spatial decision sidecar")
    p.add_argument("--port", type=int, default=50051)
    args = p.parse_args()
    server, _, bound = create_server(args.port)
    server.start()
    logger.info("spatial decision sidecar listening on :%d", bound)
    server.wait_for_termination()


if __name__ == "__main__":
    main()

"""Batched spatial decision kernels (JAX).

The TPU-native replacement for the reference's per-entity/per-subscriber
CPU loops (ref: pkg/channeld/spatial.go:169-317 cell math + AOI sampling,
data.go:175-291 fan-out due scan, spatial.go:612-626 handover detection).
Everything here is shape-static, branch-free, and jit-compatible: state
lives in fixed-capacity slot arrays with validity masks, and each tick
recomputes assignment / interest / due decisions for *all* entities,
queries, and subscriptions at once.

Semantics notes vs the host path:
- Cell assignment matches exactly: floor((p - offset) / cell), id =
  start + x + z*cols, invalid (<0) outside the world.
- AOI interest is computed as exact shape-vs-cell-rectangle overlap
  instead of the host's half-grid-step point sampling — a strict
  superset of the sampled cells for the same shape, with the same
  ceil(dist / cell-diagonal) distance metric.
- The fan-out due decision reproduces the (last, last+interval] window
  advance: a due subscriber's window moves forward one interval.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp


class GridSpec(NamedTuple):
    """Static grid geometry, baked into the compiled step."""

    offset_x: float
    offset_z: float
    cell_w: float
    cell_h: float
    cols: int
    rows: int

    @property
    def num_cells(self) -> int:
        return self.cols * self.rows

    @property
    def diagonal(self) -> float:
        return float((self.cell_w**2 + self.cell_h**2) ** 0.5)


# ---- cell assignment ------------------------------------------------------


def assign_cells(grid: GridSpec, positions: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """positions f32[N,3] -> cell index i32[N]; -1 for invalid/outside.

    (ref: spatial.go:169-180 GetChannelIdWithOffset, vectorized.)
    """
    gx = jnp.floor((positions[:, 0] - grid.offset_x) / grid.cell_w).astype(jnp.int32)
    gz = jnp.floor((positions[:, 2] - grid.offset_z) / grid.cell_h).astype(jnp.int32)
    inside = (gx >= 0) & (gx < grid.cols) & (gz >= 0) & (gz < grid.rows) & valid
    return jnp.where(inside, gx + gz * grid.cols, -1)


# ---- handover detection ---------------------------------------------------


def detect_handovers(old_cell: jnp.ndarray, new_cell: jnp.ndarray) -> jnp.ndarray:
    """bool[N]: entity crossed a cell boundary this tick
    (ref: spatial.go:613-626 src != dst check, batched)."""
    return (old_cell >= 0) & (new_cell >= 0) & (old_cell != new_cell)


def compact_handovers(
    handover_mask: jnp.ndarray,
    old_cell: jnp.ndarray,
    new_cell: jnp.ndarray,
    max_out: int,
):
    """Pack (entity_slot, src_cell, dst_cell) rows for up to ``max_out``
    crossings into a fixed-shape output (count, rows i32[max_out,3]).

    Fixed shapes keep the step recompile-free; overflow beyond max_out is
    reported via count so the host can fall back next tick.
    """
    n = handover_mask.shape[0]
    max_out = min(max_out, n)
    count = jnp.sum(handover_mask, dtype=jnp.int32)
    # Ordinal of each crossing among all crossings (slot order) — an O(N)
    # scan instead of an O(N log N) sort.
    rank = jnp.cumsum(handover_mask, dtype=jnp.int32) - 1
    reported = handover_mask & (rank < max_out)
    # First max_out crossing slots, in slot order: scatter each reported
    # slot's index into its rank (reuses the cumsum; ~25% faster on v5e
    # than the jnp.nonzero(size=...) compaction it replaced — 0.34 vs
    # 0.45 ms net at N=100K, bench_breakdown.py). Unreported slots write
    # into a discard lane.
    slot = jnp.where(reported, rank, max_out)
    idx = (
        jnp.zeros(max_out + 1, jnp.int32)
        .at[slot]
        .set(jnp.arange(n, dtype=jnp.int32), mode="drop")[:max_out]
    )
    rows = jnp.stack([idx, old_cell[idx], new_cell[idx]], axis=1)
    row_valid = jnp.arange(max_out) < jnp.minimum(count, max_out)
    rows = jnp.where(row_valid[:, None], rows, -1)
    return count, rows, reported


# ---- per-cell occupancy ---------------------------------------------------


def cell_counts(cell_of: jnp.ndarray, num_cells: int) -> jnp.ndarray:
    """Entity count per cell, i32[num_cells] (segment-sum)."""
    valid = cell_of >= 0
    return jnp.zeros(num_cells, jnp.int32).at[
        jnp.where(valid, cell_of, 0)
    ].add(valid.astype(jnp.int32))


# ---- AOI: query x cell interest masks ------------------------------------

AOI_NONE = 0
AOI_SPHERE = 1
AOI_BOX = 2
AOI_CONE = 3
AOI_SPOTS = 4


class QuerySet(NamedTuple):
    """SoA batch of client interest queries (ref: channeld.proto
    SpatialInterestQuery; one active shape per query).

    Spots queries don't reduce to a geometric test, so they ride as a
    precomputed per-query cell table (rasterized host-side when the query
    is set — spots change rarely, cells are few): one i32[Q,C] damping
    distance with -1 meaning "no interest" (the mask is ``dist >= 0``).
    The field stays ``None`` until the first spots query, keeping the
    common-case compiled step free of the table.
    """

    kind: jnp.ndarray  # i32[Q] in {NONE, SPHERE, BOX, CONE, SPOTS}
    center: jnp.ndarray  # f32[Q,2] (x,z)
    extent: jnp.ndarray  # f32[Q,2] box half-extent (x,z); radius in [:,0] for sphere/cone
    direction: jnp.ndarray  # f32[Q,2] cone direction (x,z), normalized
    angle: jnp.ndarray  # f32[Q] cone half-angle, radians
    spot_dist: Optional[jnp.ndarray] = None  # i32[Q,C]; -1 = no interest


def aoi_masks(grid: GridSpec, queries: QuerySet):
    """Interest of every query in every cell.

    Returns (interest bool[Q,C], dist i32[Q,C]) where dist is the
    ceil(center-to-sample / cell-diagonal) damping distance, matching the
    host path's metric (ref: spatial.go:182-317). One source of truth:
    the full-grid case of aoi_masks_for_cells (the cell-sharded plane
    calls it per block)."""
    return aoi_masks_for_cells(
        grid, queries, jnp.arange(grid.num_cells, dtype=jnp.int32),
        queries.spot_dist,
    )


def aoi_masks_for_cells(grid: GridSpec, queries: QuerySet, cell_ids,
                        spot_dist_slice=None):
    """``aoi_masks`` for an arbitrary i32[Cb] vector of GLOBAL cell ids —
    the cell-sharded plane computes only its owned block's columns and
    all_gathers the rest (parallel/spatial_alltoall.py). ``cell_ids`` may
    be traced (block starts depend on axis_index). Ids outside
    [0, num_cells) are padding: never interested. ``spot_dist_slice`` is
    the [Q, Cb] slice of the spots table for these cells (None = no spots
    queries registered). Parity with aoi_masks is pinned by
    tests/test_spatial_alltoall.py."""
    col = (cell_ids % grid.cols).astype(jnp.float32)
    row = (cell_ids // grid.cols).astype(jnp.float32)
    centers = jnp.stack(
        [grid.offset_x + (col + 0.5) * grid.cell_w,
         grid.offset_z + (row + 0.5) * grid.cell_h], axis=1)  # [Cb,2]
    cell_valid = (cell_ids >= 0) & (cell_ids < grid.num_cells)
    half = jnp.array([grid.cell_w * 0.5, grid.cell_h * 0.5])

    delta = jnp.abs(queries.center[:, None, :] - centers[None, :, :])
    gap = jnp.maximum(delta - half[None, None, :], 0.0)
    rect_dist = jnp.sqrt(jnp.sum(gap * gap, axis=-1))
    center_dist = jnp.sqrt(
        jnp.sum((queries.center[:, None, :] - centers) ** 2, axis=-1))

    radius = queries.extent[:, 0:1]
    sphere_hit = rect_dist <= radius
    box_hit = jnp.all(
        delta <= (queries.extent[:, None, :] + half[None, None, :]), axis=-1)
    to_cell = centers[None, :, :] - queries.center[:, None, :]
    to_len = jnp.maximum(jnp.sqrt(jnp.sum(to_cell * to_cell, axis=-1)), 1e-9)
    cosine = jnp.sum(to_cell * queries.direction[:, None, :], axis=-1) / to_len
    in_angle = cosine >= jnp.cos(queries.angle)[:, None]
    apex_cell = rect_dist <= 0.0
    cone_hit = (rect_dist <= radius) & (in_angle | apex_cell)

    hit = (
        ((queries.kind[:, None] == AOI_SPHERE) & sphere_hit)
        | ((queries.kind[:, None] == AOI_BOX) & box_hit)
        | ((queries.kind[:, None] == AOI_CONE) & cone_hit)
    ) & cell_valid[None, :]
    dist = jnp.ceil(center_dist / grid.diagonal).astype(jnp.int32)
    dist = jnp.where(rect_dist <= 0.0, 0, dist)
    if spot_dist_slice is None:
        return hit, dist
    is_spots = queries.kind[:, None] == AOI_SPOTS
    spots_hit = (spot_dist_slice >= 0) & cell_valid[None, :]
    hit = jnp.where(is_spots, spots_hit, hit)
    dist = jnp.where(is_spots & spots_hit, spot_dist_slice, dist)
    return hit, dist


def apply_spots_overlay(hit, dist, queries: QuerySet):
    """Overlay spots queries' host-rasterized table onto geometric
    interest/dist planes (ref: spatial.go spots loop — each spot's cell
    with its per-spot dist, default 0; -1 = cell not targeted). Shared by
    the XLA and Mosaic AOI paths so spots semantics can never diverge."""
    if queries.spot_dist is None:
        return hit, dist
    is_spots = queries.kind[:, None] == AOI_SPOTS
    spots_hit = queries.spot_dist >= 0
    hit = jnp.where(is_spots, spots_hit, hit)
    dist = jnp.where(is_spots & spots_hit, queries.spot_dist, dist)
    return hit, dist


def damping_intervals_ms(
    dist: jnp.ndarray,
    interest: jnp.ndarray,
    tiers: jnp.ndarray,
    tier_intervals: jnp.ndarray,
    default_interval: int,
) -> jnp.ndarray:
    """Map grid distance -> fan-out interval per (query, cell)
    (ref: message_spatial.go:10-38 damping table).

    ``tiers`` i32[T] ascending max-distances, ``tier_intervals`` i32[T].
    Beyond the last tier the default interval applies.
    """
    # Index of the first tier whose max_distance >= dist.
    idx = jnp.searchsorted(tiers, dist.ravel(), side="left").reshape(dist.shape)
    in_table = idx < tiers.shape[0]
    interval = jnp.where(
        in_table, tier_intervals[jnp.minimum(idx, tiers.shape[0] - 1)], default_interval
    )
    return jnp.where(interest, interval, 0)


# ---- fan-out due decision -------------------------------------------------


def fanout_due(
    now_ms: jnp.ndarray,
    last_fanout_ms: jnp.ndarray,
    interval_ms: jnp.ndarray,
    active: jnp.ndarray,
):
    """Which subscriptions are due, and their advanced window starts.

    Times are int32 milliseconds since engine start (int64 is emulated on
    TPU; i32 ms wraps after ~24 days, far beyond a session). Reproduces
    tick_data's window advance (ref: data.go:252-258): a due sub's
    last-fan-out moves to last+interval (not to ``now``), keeping late
    updates deliverable. Returns (due bool[S], new_last i32[S]).
    """
    next_ms = last_fanout_ms + interval_ms
    due = active & (now_ms >= next_ms)
    return due, jnp.where(due, next_ms, last_fanout_ms)


# ---- the fused per-tick step ---------------------------------------------


@partial(jax.jit, static_argnums=(0, 6, 8), donate_argnums=(2,))
def spatial_step(
    grid: GridSpec,
    positions: jnp.ndarray,  # f32[N,3]
    prev_cell: jnp.ndarray,  # i32[N] (donated; replaced by new assignment)
    valid: jnp.ndarray,  # bool[N]
    queries: QuerySet,
    sub_state: tuple,  # (last_fanout_ms i32[S], interval_ms i32[S], active bool[S])
    max_handovers: int,
    now_ms,
    use_pallas: bool = False,
):
    """One decision tick, fully on device: cell assignment + handover
    detection/compaction + per-cell occupancy + AOI interest + fan-out
    due mask. Returns everything the host needs to route messages.

    ``use_pallas`` swaps the assignment+occupancy pass for the fused
    Mosaic kernel (TPU backends only; ~1.7x for that pass)."""
    if use_pallas:
        from .pallas_kernels import aoi_masks_pallas, assign_and_count_pallas

        cell_of, counts = assign_and_count_pallas(grid, positions, valid)
    else:
        cell_of = assign_cells(grid, positions, valid)
        counts = cell_counts(cell_of, grid.num_cells)
    handover_mask = detect_handovers(prev_cell, cell_of)
    ho_count, ho_rows, reported = compact_handovers(
        handover_mask, prev_cell, cell_of, max_handovers
    )
    # Crossings that overflowed the row budget keep their *old* cell as the
    # next tick's baseline, so they are re-detected instead of lost.
    committed_prev = jnp.where(handover_mask & ~reported, prev_cell, cell_of)
    if use_pallas:
        interest, dist = aoi_masks_pallas(grid, queries)
    else:
        interest, dist = aoi_masks(grid, queries)
    last_ms, interval_ms, active = sub_state
    due, new_last = fanout_due(now_ms, last_ms, interval_ms, active)
    due_packed = jnp.packbits(due)
    # Single host-consumption blob: one D2H transfer per tick instead of
    # one per output (each transfer costs a dispatch + possibly a full
    # transport round trip). Layout (i32):
    #   [0]                count
    #   [1 : 1+3K]         handover rows, row-major
    #   [... : +C]         cell counts
    #   [... : +ceil(S/32)] due bitmask words (u8-packed, zero-padded)
    pad = (-due_packed.shape[0]) % 4
    due_words = jax.lax.bitcast_convert_type(
        jnp.pad(due_packed, (0, pad)).reshape(-1, 4), jnp.int32
    ).reshape(-1)
    consume = jnp.concatenate([
        ho_count[None], ho_rows.reshape(-1), counts, due_words
    ])
    return {
        "cell_of": cell_of,
        "committed_prev": committed_prev,
        "handover_count": ho_count,
        "handovers": ho_rows,
        "cell_counts": counts,
        "interest": interest,
        "dist": dist,
        "due": due,
        # Bit-packed due mask: 8x less D2H for the per-tick host readback
        # (unpack host-side with np.unpackbits).
        "due_packed": due_packed,
        "consume": consume,
        "new_last_fanout_ms": new_last,
    }


def parse_consume_blob(blob, max_handovers: int, num_cells: int, num_subs: int):
    """Host-side split of the packed consumption blob (numpy)."""
    import numpy as np

    blob = np.asarray(blob)
    count = int(blob[0])
    rows_end = 1 + 3 * max_handovers
    rows = blob[1:rows_end].reshape(max_handovers, 3)
    counts = blob[rows_end : rows_end + num_cells]
    due_words = blob[rows_end + num_cells :]
    due = np.unpackbits(due_words.view(np.uint8))[:num_subs]
    return count, rows, counts, due


# ---- standing-query diff / compaction (doc/query_engine.md) ---------------


@partial(jax.jit, static_argnums=(4,))
def diff_query_masks(
    prev_interest: jnp.ndarray,  # bool[Q,C] committed baseline
    prev_dist: jnp.ndarray,  # i32[Q,C]
    interest: jnp.ndarray,  # bool[Q,C] this tick's masks
    dist: jnp.ndarray,  # i32[Q,C]
    max_rows: int,
):
    """Diff this tick's query-interest masks against the committed
    baseline ON DEVICE and compact the delta to ``(query, cell, dist)``
    rows — the standing-query plane's entire per-tick host protocol.

    A (q, c) entry is *changed* when interest flipped either way, or when
    it stayed interested but the damping distance moved (the host must
    re-subscribe with refreshed fan-out options, mirroring
    apply_interest_diff's always-refresh semantics). Rows carry the NEW
    dist; ``dist == -1`` means interest was removed. Compaction reuses the
    cumsum-rank scatter of compact_handovers over the flattened [Q*C]
    plane. Changes beyond ``max_rows`` keep their *previous* baseline
    value so they re-diff next tick instead of being lost (same overflow
    contract as handovers); ``count`` reports the true total so the host
    can see the backlog.

    Returns (blob i32[1+3*max_rows], next_interest bool[Q,C],
    next_dist i32[Q,C]) where blob = [count][rows row-major] is the ONE
    device->host transfer the plane is allowed per tick, and next_* is
    the baseline to commit for the following tick.
    """
    q, c = interest.shape
    max_rows = min(max_rows, q * c)
    changed = (interest != prev_interest) | (interest & (dist != prev_dist))
    flat = changed.reshape(-1)
    n = flat.shape[0]
    count = jnp.sum(flat, dtype=jnp.int32)
    rank = jnp.cumsum(flat, dtype=jnp.int32) - 1
    reported = flat & (rank < max_rows)
    slot = jnp.where(reported, rank, max_rows)
    idx = (
        jnp.zeros(max_rows + 1, jnp.int32)
        .at[slot]
        .set(jnp.arange(n, dtype=jnp.int32), mode="drop")[:max_rows]
    )
    new_dist = jnp.where(interest.reshape(-1)[idx], dist.reshape(-1)[idx], -1)
    rows = jnp.stack([idx // c, idx % c, new_dist], axis=1)
    row_valid = jnp.arange(max_rows) < jnp.minimum(count, max_rows)
    rows = jnp.where(row_valid[:, None], rows, -1)
    keep_prev = (changed & ~reported.reshape(q, c))
    next_interest = jnp.where(keep_prev, prev_interest, interest)
    next_dist = jnp.where(keep_prev, prev_dist, dist)
    blob = jnp.concatenate([count[None], rows.reshape(-1)])
    return blob, next_interest, next_dist


def parse_query_blob(blob):
    """Host-side split of the standing-query changed-rows blob (numpy):
    (total_changed, rows i32[R,3]) where R is the blob's own row budget
    (diff_query_masks clamps the configured max to Q*C, so the effective
    budget is read from the blob, never assumed); rows beyond
    min(total, R) are -1 padding."""
    import numpy as np

    blob = np.asarray(blob)
    return int(blob[0]), blob[1:].reshape(-1, 3)


# ---- simulation plane: agent steering + behavior FSM (doc/simulation.md) --

SIM_IDLE = 0
SIM_WANDER = 1
SIM_SEEK = 2
SIM_FLEE = 3


class SimParams(NamedTuple):
    """Static steering/FSM constants, baked into the compiled sim step
    (changing a knob recompiles once; see the ``sim_*`` knob table in
    doc/simulation.md)."""

    dt: float  # integration step, seconds of world time per tick
    max_speed: float  # clamp on |v|, world units / s
    accel: float  # max steering acceleration, world units / s^2
    separation: float  # crowded-cell push weight
    cohesion: float  # sparse-cell centroid pull weight
    arrive_radius: float  # waypoint reached within this xz distance
    crowd: int  # cell occupancy above which separation wins
    p_wander: float  # per-tick idle -> wander probability
    p_seek: float  # per-tick wander -> seek probability
    p_idle: float  # per-tick wander -> idle probability


def sim_rand_u32(seed, tick, lane: int, n: int) -> jnp.ndarray:
    """Counter-based RNG: u32[n] hash of (seed, tick, lane, slot).

    Stateless and replayable — the same (seed, tick) always produces the
    same draws regardless of history, so a WAL-replayed or guard-rebuilt
    population resumes the exact trajectory it would have taken (the
    replayability contract in doc/simulation.md). A Weyl-sequence input
    through the murmur3 fmix32 finalizer; no key threading, no state
    array to rebuild.
    """
    idx = jnp.arange(n, dtype=jnp.uint32)
    x = idx * jnp.uint32(0x9E3779B9)
    x = x + jnp.asarray(seed, jnp.uint32)
    x = x ^ (jnp.asarray(tick, jnp.uint32) * jnp.uint32(0x85EBCA6B))
    x = x + jnp.uint32(lane) * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    return x


def _unit_f32(bits: jnp.ndarray) -> jnp.ndarray:
    """u32 -> f32 uniform in [0, 1) (top 24 bits, exact in f32)."""
    return (bits >> 8).astype(jnp.float32) * jnp.float32(1.0 / (1 << 24))


@partial(jax.jit, static_argnums=(0, 7), donate_argnums=(1, 2, 3, 4))
def sim_step(
    grid: GridSpec,
    positions: jnp.ndarray,  # f32[N,3] (donated; replaced by integration)
    vel: jnp.ndarray,  # f32[N,3] (donated)
    state: jnp.ndarray,  # i32[N] FSM state (donated)
    target: jnp.ndarray,  # f32[N,3] current waypoint (donated)
    agent: jnp.ndarray,  # bool[N] slot hosts a simulated agent
    flee_cells: jnp.ndarray,  # bool[C] danger mask (query-plane sensor hits)
    params: SimParams,
    seed,  # u32 scalar (traced: changing the seed never recompiles)
    tick,  # i32 scalar (traced)
):
    """One population step, fully on device: flocking steering from
    per-cell occupancy aggregates, waypoint seeking, and the 4-state
    behavior FSM — all branch-free over the SAME entity arrays the
    spatial pass reads, so the new positions feed straight into cell
    assignment with zero extra transfers.

    Flocking is the per-cell reduction of boids: separation pushes out of
    crowded cells and cohesion pulls strays toward their cell centroid,
    computed with O(N) segment-sums instead of O(N^2) pairwise distances
    (the aggregate form is what makes 100K agents a sub-millisecond MXU
    pass). FLEE is driven by the standing-query plane: ``flee_cells`` is
    the host-rasterized micro-cell mask of sensor hits, uploaded only
    when a sensor's interest set changes — never per tick.

    Non-agent lanes (humans, free slots) pass through every output
    unchanged. Returns (positions, vel, state, target).
    """
    n = positions.shape[0]
    cell_of = assign_cells(grid, positions, agent)
    in_world = cell_of >= 0
    safe_cell = jnp.where(in_world, cell_of, 0)
    live = agent & in_world

    # Per-cell occupancy + centroid of the agent population (xz plane).
    w = live.astype(jnp.float32)
    counts = jnp.zeros(grid.num_cells, jnp.float32).at[safe_cell].add(w)
    xz = positions[:, (0, 2)]
    sums = jnp.zeros((grid.num_cells, 2), jnp.float32).at[safe_cell].add(
        xz * w[:, None]
    )
    centroid = sums / jnp.maximum(counts, 1.0)[:, None]
    my_count = counts[safe_cell]
    away = xz - centroid[safe_cell]
    away_len = jnp.sqrt(jnp.sum(away * away, axis=-1, keepdims=True))
    away_dir = away / jnp.maximum(away_len, 1e-6)
    crowded = (my_count > params.crowd)[:, None]
    steer_xz = jnp.where(
        crowded,
        away_dir * params.separation,
        -away_dir * jnp.minimum(away_len, 1.0) * params.cohesion,
    )

    # FSM transitions (doc/simulation.md state diagram). One dice lane
    # per decision keeps draws independent across lanes and ticks.
    r_trans = _unit_f32(sim_rand_u32(seed, tick, 0, n))
    to_t = target - positions
    dist_t = jnp.sqrt(to_t[:, 0] ** 2 + to_t[:, 2] ** 2)
    arrived = dist_t <= params.arrive_radius
    in_danger = live & flee_cells[safe_cell]

    st = state
    new_st = jnp.where(st == SIM_SEEK, jnp.where(arrived, SIM_IDLE, st), st)
    is_wander = st == SIM_WANDER
    new_st = jnp.where(is_wander & (r_trans < params.p_seek), SIM_SEEK, new_st)
    new_st = jnp.where(
        is_wander
        & (r_trans >= params.p_seek)
        & (r_trans < params.p_seek + params.p_idle),
        SIM_IDLE,
        new_st,
    )
    new_st = jnp.where(
        (st == SIM_IDLE) & (r_trans < params.p_wander), SIM_WANDER, new_st
    )
    # Sensor hits override everything; an escaped fleer calms to WANDER.
    new_st = jnp.where(
        in_danger, SIM_FLEE, jnp.where((st == SIM_FLEE) & ~in_danger, SIM_WANDER, new_st)
    )

    # Waypoints: a fresh SEEK draws a world-uniform target; FLEE aims at
    # the reflection of the danger cell's center through the agent (run
    # straight away from the hit cell).
    r_tx = _unit_f32(sim_rand_u32(seed, tick, 1, n))
    r_tz = _unit_f32(sim_rand_u32(seed, tick, 2, n))
    rand_target = jnp.stack(
        [
            grid.offset_x + r_tx * (grid.cols * grid.cell_w),
            positions[:, 1],
            grid.offset_z + r_tz * (grid.rows * grid.cell_h),
        ],
        axis=1,
    )
    cell_cx = grid.offset_x + (
        (safe_cell % grid.cols).astype(jnp.float32) + 0.5
    ) * grid.cell_w
    cell_cz = grid.offset_z + (
        (safe_cell // grid.cols).astype(jnp.float32) + 0.5
    ) * grid.cell_h
    flee_target = jnp.stack(
        [
            positions[:, 0] * 2.0 - cell_cx,
            positions[:, 1],
            positions[:, 2] * 2.0 - cell_cz,
        ],
        axis=1,
    )
    entered_seek = (new_st == SIM_SEEK) & (st != SIM_SEEK)
    entered_flee = (new_st == SIM_FLEE) & (st != SIM_FLEE)
    new_target = jnp.where(entered_seek[:, None], rand_target, target)
    new_target = jnp.where(entered_flee[:, None], flee_target, new_target)

    # Desired velocity by state (xz plane; y is carried, never integrated).
    to_nt = new_target - positions
    nt_len = jnp.sqrt(to_nt[:, 0] ** 2 + to_nt[:, 2] ** 2)
    goal_dir = to_nt / jnp.maximum(nt_len, 1e-6)[:, None]
    r_jx = _unit_f32(sim_rand_u32(seed, tick, 3, n)) * 2.0 - 1.0
    r_jz = _unit_f32(sim_rand_u32(seed, tick, 4, n)) * 2.0 - 1.0
    jitter = jnp.stack([r_jx, jnp.zeros(n, jnp.float32), r_jz], axis=1)
    seeking = (new_st == SIM_SEEK) | (new_st == SIM_FLEE)
    desired = jnp.where(
        seeking[:, None],
        goal_dir * params.max_speed,
        jnp.where(
            (new_st == SIM_WANDER)[:, None],
            vel * 0.9 + jitter * params.max_speed * 0.5,
            jnp.zeros_like(vel),
        ),
    )
    desired = desired.at[:, 0].add(steer_xz[:, 0] * params.max_speed)
    desired = desired.at[:, 2].add(steer_xz[:, 1] * params.max_speed)

    # Accelerate toward desired, clamp speed, integrate, clamp into the
    # world (a clamped agent stays assignable — it can never escape the
    # grid and vanish from the spatial pass).
    dv = desired - vel
    dv_len = jnp.sqrt(jnp.sum(dv * dv, axis=-1, keepdims=True))
    step = jnp.minimum(dv_len, params.accel * params.dt)
    new_vel = vel + dv / jnp.maximum(dv_len, 1e-6) * step
    speed = jnp.sqrt(jnp.sum(new_vel * new_vel, axis=-1, keepdims=True))
    new_vel = new_vel * jnp.minimum(
        jnp.float32(1.0), params.max_speed / jnp.maximum(speed, 1e-6)
    )
    new_vel = new_vel.at[:, 1].set(0.0)
    new_pos = positions + new_vel * params.dt
    margin = jnp.float32(min(grid.cell_w, grid.cell_h) * 1e-3)
    new_pos = new_pos.at[:, 0].set(
        jnp.clip(
            new_pos[:, 0],
            grid.offset_x + margin,
            grid.offset_x + grid.cols * grid.cell_w - margin,
        )
    )
    new_pos = new_pos.at[:, 2].set(
        jnp.clip(
            new_pos[:, 2],
            grid.offset_z + margin,
            grid.offset_z + grid.rows * grid.cell_h - margin,
        )
    )

    lane = agent[:, None]
    return (
        jnp.where(lane, new_pos, positions),
        jnp.where(lane, new_vel, vel),
        jnp.where(agent, new_st, state),
        jnp.where(lane, new_target, target),
    )

"""ChannelData: state, update buffering, merge, and fan-out scheduling.

Capability parity with the reference data plane (ref: pkg/channeld/data.go):
the channel state message, a bounded ring of buffered updates, per-subscriber
fan-out on independent cadences with accumulation of the updates that arrived
in (lastFanOut, nextFanOut], first-fan-out-sends-full-state, field-mask
filtering, and reflection- or custom-merge with merge options.

The per-subscriber "is it due / what accumulates" decision here is the
host-semantics path; ops/fanout.py provides the batched device equivalent
used by the TPU decision plane.
"""

from __future__ import annotations

import time as _time
from bisect import bisect_left, bisect_right
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Callable, Optional, Protocol, runtime_checkable

from google.protobuf.message import Message

from ..protocol import control_pb2
from ..utils.anyutil import pack_any, unpack_any
from ..utils.fieldmask import filter_fields
from ..utils.logger import get_logger
from .overload import governor as _governor
from .slo import slo as _slo
from .types import ChannelDataAccess, ChannelType, MessageType

if TYPE_CHECKING:
    from .channel import Channel

logger = get_logger("data")

MAX_UPDATE_MSG_BUFFER_SIZE = 512

# Balancer handle bound lazily (core must not import the spatial package
# at module load).
_balancer = None


def _note_spatial_fanout(channel, nbytes: int) -> None:
    """Feed the balancer's per-cell fan-out byte signal (SPATIAL
    channels only — entity/global fan-out is attributed via entity
    counts and server pressure instead)."""
    global _balancer
    if _balancer is None:
        from ..spatial.balancer import balancer as _balancer_mod

        _balancer = _balancer_mod
    _balancer.note_fanout_bytes(channel.id, nbytes)

# channel-type -> protobuf template for reflection-created channel data
# (ref: data.go:62 RegisterChannelDataType).
_channel_data_type_registry: dict[int, Message] = {}
# channel-type -> ChannelDataExtension factory (ref: data.go:390-416).
_channel_data_extension_registry: dict[int, Callable[[], "ChannelDataExtension"]] = {}


def register_channel_data_type(channel_type: int, template: Message) -> None:
    if channel_type in _channel_data_type_registry:
        logger.warning("channel data type already registered for %s", channel_type)
        return
    _channel_data_type_registry[channel_type] = template


def set_channel_data_extension(
    channel_type: int, factory: Callable[[], "ChannelDataExtension"]
) -> None:
    _channel_data_extension_registry[channel_type] = factory


def reflect_channel_data_message(channel_type: int) -> Optional[Message]:
    template = _channel_data_type_registry.get(channel_type)
    if template is None:
        return None
    return type(template)()


def reset_registries() -> None:
    """Test hook."""
    _channel_data_type_registry.clear()
    _channel_data_extension_registry.clear()


@runtime_checkable
class MergeableChannelData(Protocol):
    """Custom-merge hook (ref: data.go:321-324). Implemented by game data
    types that can fold an update in faster than reflection merge."""

    def merge(
        self,
        src: Message,
        options: Optional[control_pb2.ChannelDataMergeOptions],
        spatial_notifier,
    ) -> None: ...


@runtime_checkable
class ChannelDataInitializer(Protocol):
    """(ref: data.go:30-33)."""

    def init_data(self) -> None: ...


class ChannelDataExtension(Protocol):
    """Per-channel auxiliary state used for recovery payloads
    (ref: data.go:390-393)."""

    def init(self, channel: "Channel") -> None: ...
    def get_recovery_data_message(self) -> Optional[Message]: ...


# Channel time is integer nanoseconds since channel start (ref: ChannelTime
# is an int64 time.Duration) — integer math keeps window comparisons exact.
NS_PER_MS = 1_000_000


@dataclass
class UpdateBufferElement:
    update_msg: Message
    arrival_time: int  # ns, channel time
    sender_conn_id: int
    message_index: int
    # Host-monotonic connection-read stamp (0 = internal update): the
    # delivery-SLO plane measures ingest->fan-out against this
    # (core/slo.py record_delivery).
    ingest_ns: int = 0


@dataclass
class FanOutConnection:
    """(ref: data.go:39-44)."""

    conn: object  # ConnectionInChannel
    had_first_fanout: bool = False
    last_fanout_time: int = 0  # ns, channel time
    last_message_index: int = 0
    # Device fan-out plane (spatial channels with a TPU controller): the
    # engine sub-table slot whose batched due bit replaces the per-sub
    # host time check (consumed via the controller's pending due queue).
    device_sub_slot: Optional[int] = None


class IncompatibleUpdateError(TypeError):
    """An update's message type doesn't match the channel's data type.
    Family merges raise this (not bare TypeError) so the drop guard can't
    swallow genuine programming TypeErrors from inside merge logic."""


class ChannelData:
    def __init__(
        self,
        msg: Optional[Message],
        merge_options: Optional[control_pb2.ChannelDataMergeOptions] = None,
        channel_type: Optional[int] = None,
    ):
        self.msg = msg
        self.merge_options = merge_options
        # For late-binding adoption checks (first update sets the data):
        # if a data type gets registered for this channel type, an
        # adopting update must match it.
        self.channel_type = channel_type
        self.update_msg_buffer: list[UpdateBufferElement] = []
        self.accumulated_update_msg: Optional[Message] = (
            type(msg)() if msg is not None else None
        )
        self.msg_index = 0
        self.max_fanout_interval_ms = 0
        # Arrival time (channel ns) of the newest update EVICTED from the
        # ring: a subscriber whose catch-up window starts at or before
        # this mark has a delta gap and must take a full-state resync.
        self.evicted_through = 0
        self.extension: Optional[ChannelDataExtension] = None

    def on_update(
        self,
        update_msg: Message,
        arrival_time: int,
        sender_conn_id: int,
        spatial_notifier=None,
        now_ns: Optional[int] = None,
        ingest_ns: int = 0,
    ) -> None:
        """(ref: data.go:149-173). ``now_ns`` optionally bounds stray
        arrival stamps to the channel's own clock; ``ingest_ns`` is the
        connection-read host stamp the delivery-SLO plane threads to
        the fan-out (0 = internal)."""
        if self.msg is None:
            # Adoption (channeld-tpu extension; the reference drops updates
            # until data is initialized): only write-access subscribers
            # reach here, and if a data type IS registered for this
            # channel type by now, the adopting update must match it — a
            # single mistyped update must not wedge the channel forever.
            if self.channel_type is not None:
                expected = reflect_channel_data_message(self.channel_type)
                if expected is not None and type(expected) is not type(update_msg):
                    logger.warning(
                        "refusing to initialize channel data with %s "
                        "(registered type is %s)",
                        type(update_msg).DESCRIPTOR.full_name,
                        type(expected).DESCRIPTOR.full_name,
                    )
                    return
            self.msg = update_msg
            logger.info(
                "initialized channel data with update message from conn %d",
                sender_conn_id,
            )
        else:
            merged = merge_with_options(
                self.msg, update_msg, self.merge_options, spatial_notifier
            )
            if not merged:
                # Dropped (incompatible type): it must not enter the
                # update ring either — a buffered wrong-type message would
                # fan out verbatim or crash window accumulation later.
                return
        self.msg_index += 1
        # The fan-out windowing bisects this buffer, which requires arrival
        # times to be monotonic in this channel's clock. Clamp stray stamps
        # in both directions (e.g. a context forwarded from another channel
        # carries that channel's time base): never before the tail, never
        # ahead of this channel's own now.
        tail = self.update_msg_buffer[-1].arrival_time if self.update_msg_buffer else 0
        if now_ns is not None:
            arrival_time = min(arrival_time, now_ns)
        arrival_time = max(arrival_time, tail)
        self.update_msg_buffer.append(
            UpdateBufferElement(update_msg, arrival_time, sender_conn_id,
                                self.msg_index, ingest_ns)
        )
        if len(self.update_msg_buffer) > MAX_UPDATE_MSG_BUFFER_SIZE:
            oldest = self.update_msg_buffer[0]
            # Only drop it once every subscriber must have seen it. Under
            # a brownout stretch the subscribers legitimately run slower,
            # so the retention horizon stretches with them; subscribers
            # held even longer (the L2+ priority shed) are caught by the
            # evicted_through mark and resynced with full state.
            retention_ns = self.max_fanout_interval_ms * NS_PER_MS
            if _governor.level:
                retention_ns = int(retention_ns * _governor.fanout_stretch())
            if oldest.arrival_time + retention_ns < arrival_time:
                self.update_msg_buffer.pop(0)
                if oldest.arrival_time > self.evicted_through:
                    self.evicted_through = oldest.arrival_time


def _accumulate_window(data: "ChannelData", window: list, fresh: bool = False):
    """Merge a window of buffered updates: first entry is a plain copy,
    the rest merge with options (ref: data.go hasEverMerged). ``fresh``
    returns a new message (safe to cache); otherwise the channel's
    scratch accumulator is reused (consume before the next call)."""
    if fresh:
        acc = type(data.msg)()
    else:
        if data.accumulated_update_msg is None:
            data.accumulated_update_msg = type(data.msg)()
        else:
            data.accumulated_update_msg.Clear()
        acc = data.accumulated_update_msg
    acc.MergeFrom(window[0].update_msg)
    for be in window[1:]:
        merge_with_options(acc, be.update_msg, data.merge_options, None)
    return acc


def _newest_ingest_ns(window: list) -> int:
    """The newest non-zero connection-read stamp in a delivered window
    (0 when every update was internal). Windows are small (bounded by
    the update ring); the scan usually exits on the last element."""
    for be in reversed(window):
        if be.ingest_ns:
            return be.ingest_ns
    return 0


def _record_window_delivery(channel: "Channel", window: list,
                            path: str) -> None:
    """One delivery-latency sample for a just-sent fan-out window,
    stamped with the NEWEST externally-ingested update it carries
    (core/slo.py; the pipeline-transit reading of delivery latency)."""
    ingest_ns = _newest_ingest_ns(window)
    if ingest_ns:
        _slo.record_delivery(channel.channel_type.name, path, ingest_ns)


def _device_due_view(channel: "Channel"):
    """(ctl, engine_seq, pending {slot: seq}) from the TPU controller's
    batched ticks, for spatial channels with device-registered subs;
    None -> host path (ref: the host scan this replaces is
    data.go:175-291). Pending entries survive engine ticks until this
    channel consumes them."""
    if not channel.device_sub_slots:
        return None
    from ..spatial.controller import get_spatial_controller

    ctl = get_spatial_controller()
    view = getattr(ctl, "device_due", None)
    if view is None:
        return None
    due = view(channel.id)
    if due is None:
        return None
    return (ctl,) + due


def tick_data(channel: "Channel", now: int) -> None:
    """The per-tick fan-out decision + send loop (ref: data.go:175-291).

    ``now`` is channel time (integer ns since channel start) so tests can
    drive it with a synthetic clock.

    Spatial channels under a TPU controller consume the batched device due
    mask: only subscribers the engine marked due are visited (flat host
    cost in subscriber count); subscriptions without a device slot — table
    full or pre-engine — keep the host time check.
    """
    data = channel.data
    if data is None or data.msg is None:
        return

    # Buffered updates arrive in channel-time order, so each subscriber's
    # inclusive [last, last+interval] window (the reference's bounds,
    # boundary elements delivered twice like data.go:230-258) is a
    # contiguous slice — O(log B) to locate instead of scanning the whole
    # ring per subscriber. Built lazily: ticks with no due subscriber pay
    # nothing.
    arrivals = None
    # Subscribers sharing the same window slice get the same accumulated
    # message unless skip-self excludes one of their own updates from it:
    # (lo, hi) -> [sender_id_set, merged_msg_or_None]. Scoped to this
    # tick; fan_out_data_update never mutates what it sends.
    shared_windows: dict = {}
    body_cache: dict = {}  # id(update_msg) -> (msg ref, shared MessageContext)

    # Overload brownout (doc/overload.md), resolved once per tick:
    # L1+ stretches every subscriber's effective fan-out interval (the
    # update ring keeps accumulating, so delivery coalesces — nothing is
    # lost); L2+ withholds updates from the lowest-priority
    # subscriptions entirely, each withheld delivery counted.
    stretch = _governor.fanout_stretch() if _governor.level else 1.0
    shed_floor = _governor.shed_priority_floor() if _governor.level else None

    queue = channel.fan_out_queue
    device = _device_due_view(channel)
    if device is not None:
        ctl, seq, pending = device
        # Consume this channel's own pending due decisions — O(own due),
        # never an iteration of the slot table or the fan-out queue —
        # plus any host-fallback entries.
        iterate = []
        slots = channel.device_sub_slots
        for slot in list(pending):
            del pending[slot]
            foc = slots.get(slot)
            if foc is not None:
                iterate.append(foc)
        iterate.extend(channel.device_fallback_focs)
    else:
        iterate = list(queue)

    for foc in iterate:
        conn = foc.conn
        if conn is None or conn.is_closing():
            try:
                queue.remove(foc)
            except ValueError:
                pass
            from .subscription import release_device_fanout

            release_device_fanout(channel, foc)
            continue
        cs = channel.subscribed_connections.get(conn)
        if cs is None or cs.options.dataAccess == ChannelDataAccess.NO_ACCESS:
            continue

        #  |------FanOutDelay------|---FanOutInterval---|
        #  subTime                 firstFanOut          secondFanOut
        interval_ns = cs.options.fanOutIntervalMs * NS_PER_MS
        if stretch != 1.0:
            interval_ns = int(interval_ns * stretch)
        next_fanout_time = foc.last_fanout_time + interval_ns
        if device is None or foc.device_sub_slot is None:
            # Host time check (no engine, or no device slot for this sub).
            if now < next_fanout_time:
                continue
        else:
            # The device already decided this sub is due. Under a
            # brownout stretch the governor overrides the device's
            # cadence: hold the fan-out until the stretched interval
            # elapses (the engine re-marks the sub due next window, so
            # nothing is starved — just coalesced harder).
            if stretch != 1.0 and now < next_fanout_time:
                continue
            # The engine clock can run marginally ahead of this
            # channel's; clamp the window end so the bisect below never
            # claims unseen future arrivals.
            next_fanout_time = min(next_fanout_time, now)

        if (
            shed_floor is not None
            and cs.priority >= shed_floor
            and foc.had_first_fanout
        ):
            # Shed: a DUE delivery is withheld while the ladder holds
            # (first fan-out still goes out so fresh subs handshake) —
            # one count per withheld delivery. The window keeps
            # accumulating from last_fanout_time; delivery resumes,
            # coalesced, once the ladder releases.
            _governor.count_shed("update_priority")
            continue

        latest_fanout_time = next_fanout_time

        if not foc.had_first_fanout:
            # First fan-out carries the full channel state.
            fan_out_data_update(channel, conn, cs, data.msg, body_cache)
            foc.had_first_fanout = True
            foc.last_message_index = data.msg_index
            latest_fanout_time = now
            if device is not None and foc.device_sub_slot is not None:
                # Mirror the window snap on the device sub clock.
                ctl.device_sub_first_fanout(foc.device_sub_slot)
        elif (
            data.evicted_through > 0
            and foc.last_fanout_time <= data.evicted_through
        ):
            # Ring gap: updates this subscriber never saw were evicted
            # (it was held past the retention horizon — e.g. the L2+
            # priority shed). Deltas can't reconstruct its view, so
            # resync with full state — this is what keeps the brownout
            # lossless at the STATE level no matter how long the hold.
            fan_out_data_update(channel, conn, cs, data.msg, body_cache)
            foc.last_message_index = data.msg_index
            latest_fanout_time = now
        elif data.update_msg_buffer:
            if arrivals is None:
                arrivals = [be.arrival_time for be in data.update_msg_buffer]
            last_update_time = max(foc.last_fanout_time, 0)
            lo = bisect_left(arrivals, last_update_time)
            hi = bisect_right(arrivals, next_fanout_time)
            entry = shared_windows.get((lo, hi))
            if entry is None:
                entry = shared_windows[(lo, hi)] = [
                    {be.sender_conn_id for be in data.update_msg_buffer[lo:hi]},
                    None,
                    False,  # delivery-SLO sample taken for this window
                ]
            if cs.options.skipSelfUpdateFanOut and conn.id in entry[0]:
                # This subscriber's own update is in the slice: accumulate
                # its personal window with the self-updates excluded.
                window = [
                    be for be in data.update_msg_buffer[lo:hi]
                    if be.sender_conn_id != conn.id
                ]
                if window:
                    foc.last_message_index = window[-1].message_index
                    if len(window) == 1:
                        # A single foreign update is a stable buffered
                        # message — cache-safe like the shared path.
                        fan_out_data_update(
                            channel, conn, cs, window[0].update_msg, body_cache
                        )
                    else:
                        # The scratch accumulator is reused next call; its
                        # bytes must not enter the shared cache.
                        fan_out_data_update(
                            channel, conn, cs, _accumulate_window(data, window)
                        )
                    if _slo.enabled:
                        _record_window_delivery(
                            channel, window,
                            "device" if device is not None
                            and foc.device_sub_slot is not None
                            else "host",
                        )
            elif hi > lo:
                # Shared path: merge the slice once, reuse for every
                # subscriber with this exact window. The cached message
                # outlives this iteration, so it gets its own object
                # rather than the per-sub scratch accumulator.
                if entry[1] is None:
                    window = data.update_msg_buffer[lo:hi]
                    entry[1] = (
                        window[0].update_msg
                        if len(window) == 1
                        else _accumulate_window(data, window, fresh=True)
                    )
                foc.last_message_index = data.update_msg_buffer[hi - 1].message_index
                fan_out_data_update(channel, conn, cs, entry[1], body_cache)
                if _slo.enabled and not entry[2]:
                    # ONE sample per distinct window per tick, however
                    # many subscribers share it (bounded cost; the
                    # first deliverer's path labels it).
                    entry[2] = True
                    _record_window_delivery(
                        channel, data.update_msg_buffer[lo:hi],
                        "device" if device is not None
                        and foc.device_sub_slot is not None
                        else "host",
                    )

        foc.last_fanout_time = latest_fanout_time

    # Keep the queue ordered by last_fanout_time (the reference maintains
    # this invariant with in-place move-to-back; a stable sort is the same
    # end state). Device mode doesn't iterate the queue, so its order is
    # re-established lazily if the engine ever goes away.
    if device is None:
        queue.sort(key=lambda f: f.last_fanout_time)


def fan_out_data_update(
    channel: "Channel", conn, cs, update_msg: Message,
    body_cache: Optional[dict] = None,
) -> None:
    """(ref: data.go:293-318).

    ``body_cache`` (tick-scoped) shares the serialized update across
    subscribers receiving the identical message: a broadcast channel
    encodes each window once, not once per recipient. Values hold the
    source message alongside the bytes so an ``id()`` key can't be
    recycled mid-tick.
    """
    if cs.options.dataFieldMasks:
        update_msg = _filtered_copy(update_msg, list(cs.options.dataFieldMasks))
        body_cache = None  # per-subscriber content
    from .message import MessageContext  # local: message imports data

    spatial = channel.channel_type == ChannelType.SPATIAL
    hit = body_cache.get(id(update_msg)) if body_cache is not None else None
    if hit is not None:
        if spatial and hit[1].raw_body is not None:
            _note_spatial_fanout(channel, len(hit[1].raw_body))
        conn.send(hit[1])
        return
    ctx = MessageContext(
        msg_type=MessageType.CHANNEL_DATA_UPDATE,
        msg=control_pb2.ChannelDataUpdateMessage(data=pack_any(update_msg)),
        channel=channel,
        channel_id=channel.id,
    )
    ctx.ensure_raw_body()
    if spatial and ctx.raw_body is not None:
        _note_spatial_fanout(channel, len(ctx.raw_body))
    if body_cache is not None:
        # The queued sender consumes the context immediately (tuple into
        # the send queue), so one context object serves every recipient.
        body_cache[id(update_msg)] = (update_msg, ctx)
    conn.send(ctx)


def _filtered_copy(msg: Message, masks: list[str]) -> Message:
    # The same accumulated message fans out to many subscribers with
    # different masks — never mutate the shared instance.
    out = type(msg)()
    out.CopyFrom(msg)
    filter_fields(out, masks)
    return out


def merge_with_options(
    dst: Message,
    src: Message,
    options: Optional[control_pb2.ChannelDataMergeOptions],
    spatial_notifier=None,
) -> bool:
    """(ref: data.go:326-347). Returns False when the update was DROPPED
    as type-incompatible (the caller must then keep it out of the update
    ring); True otherwise. The reference's reflection merge would panic
    the channel goroutine on mismatched descriptors; here it is a clean
    warning drop — one line, not a stack trace, or a hostile client
    could flood the log."""
    merge = getattr(dst, "merge", None)
    if callable(merge):
        if options is None:
            options = control_pb2.ChannelDataMergeOptions(
                shouldCheckRemovableMapField=True
            )
        try:
            merge(src, options, spatial_notifier)
        except IncompatibleUpdateError as e:
            logger.warning("dropping incompatible update: %s", e)
            return False
        except Exception:
            # Genuine merge bugs keep their stack traces.
            logger.exception("custom merge error")
    else:
        if type(dst) is not type(src):
            logger.warning(
                "dropping update of type %s: channel data is %s",
                type(src).DESCRIPTOR.full_name, type(dst).DESCRIPTOR.full_name,
            )
            return False
        reflect_merge(dst, src, options)
    return True


def reflect_merge(
    dst: Message,
    src: Message,
    options: Optional[control_pb2.ChannelDataMergeOptions],
) -> None:
    """Reflection-based merge honoring merge options (ref: data.go:349-388)."""
    dst.MergeFrom(src)
    if options is None:
        return
    for fd, value in dst.ListFields():
        is_map = (
            fd.type == fd.TYPE_MESSAGE and fd.message_type.GetOptions().map_entry
        )
        if is_map:
            if options.shouldCheckRemovableMapField:
                field_map = getattr(dst, fd.name)
                value_desc = fd.message_type.fields_by_name["value"]
                if value_desc.type == value_desc.TYPE_MESSAGE and (
                    "removed" in value_desc.message_type.fields_by_name
                ):
                    for key in [
                        k for k, v in field_map.items() if getattr(v, "removed", False)
                    ]:
                        del field_map[key]
        elif fd.is_repeated:
            lst = getattr(dst, fd.name)
            if options.shouldReplaceList:
                src_list = getattr(src, fd.name)
                del lst[:]
                lst.extend(src_list)
            if options.listSizeLimit > 0:
                offset = len(lst) - options.listSizeLimit
                if offset > 0:
                    if options.truncateTop:
                        keep = list(lst[offset:])
                    else:
                        keep = list(lst[: options.listSizeLimit])
                    del lst[:]
                    lst.extend(keep)


def unwrap_update_any(any_msg) -> Message:
    return unpack_any(any_msg)


def channel_now() -> float:
    return _time.monotonic()

"""Core enums and id-space constants.

Values match the wire protocol (ref: pkg/channeldpb/channeld.proto:43-169)
so host code can use them without importing generated protobuf modules.
"""

from __future__ import annotations

from enum import IntEnum, IntFlag


class ConnectionType(IntEnum):
    NO_CONNECTION = 0
    SERVER = 1
    CLIENT = 2


class ChannelType(IntEnum):
    UNKNOWN = 0
    GLOBAL = 1
    PRIVATE = 2
    SUBWORLD = 3
    SPATIAL = 4
    ENTITY = 5
    TEST = 100
    TEST1 = 101
    TEST2 = 102
    TEST3 = 103
    TEST4 = 104


class BroadcastType(IntFlag):
    NO_BROADCAST = 0
    SINGLE_CONNECTION = 1
    ALL = 2
    ALL_BUT_SENDER = 4
    ALL_BUT_OWNER = 8
    ALL_BUT_CLIENT = 16
    ALL_BUT_SERVER = 32
    ADJACENT_CHANNELS = 64

    def check(self, flag: "BroadcastType") -> bool:
        """Bit test helper (ref: pkg/channeldpb/extension.go:5-7)."""
        return bool(self & flag)


class MessageType(IntEnum):
    INVALID = 0
    AUTH = 1
    CREATE_CHANNEL = 3
    REMOVE_CHANNEL = 4
    LIST_CHANNEL = 5
    SUB_TO_CHANNEL = 6
    UNSUB_FROM_CHANNEL = 7
    CHANNEL_DATA_UPDATE = 8
    DISCONNECT = 9
    CREATE_SPATIAL_CHANNEL = 10
    QUERY_SPATIAL_CHANNEL = 11
    CHANNEL_DATA_HANDOVER = 12
    SPATIAL_REGIONS_UPDATE = 13
    UPDATE_SPATIAL_INTEREST = 14
    CREATE_ENTITY_CHANNEL = 15
    ENTITY_GROUP_ADD = 16
    ENTITY_GROUP_REMOVE = 17
    SPATIAL_CHANNELS_READY = 18
    RECOVERY_CHANNEL_DATA = 20
    RECOVERY_END = 21
    CHANNEL_OWNER_LOST = 22
    CHANNEL_OWNER_RECOVERED = 23
    SERVER_BUSY = 24
    CELL_REHOSTED = 25
    CELL_MIGRATED = 26
    CLIENT_REDIRECT = 27
    # Adaptive partitioning (spatial/partition.py, 28;
    # doc/partitioning.md).
    CELL_GEOMETRY_UPDATE = 28
    # Federation trunk plane (gateway<->gateway links only, 30-37;
    # doc/federation.md).
    TRUNK_HELLO = 30
    TRUNK_HEARTBEAT = 31
    TRUNK_HANDOVER_PREPARE = 32
    TRUNK_HANDOVER_ACK = 33
    TRUNK_ABORT_NOTICE = 34
    TRUNK_STAGE_REDIRECT = 35
    TRUNK_STAGE_ACK = 36
    TRUNK_DIRECTORY_UPDATE = 37
    # Global control plane (federation/control.py, 38-45;
    # doc/global_control.md).
    TRUNK_LOAD_REPORT = 38
    TRUNK_SHARD_EPOCH = 39
    TRUNK_SHARD_MIGRATE = 40
    TRUNK_MIGRATE_STATUS = 41
    TRUNK_GATEWAY_DEAD = 42
    TRUNK_ADOPT_DONE = 43
    TRUNK_ADOPT_QUERY = 44
    TRUNK_ADOPT_CLAIMS = 45
    # Durable persistence plane (core/wal.py, 46; doc/persistence.md).
    TRUNK_RESURRECT_HELLO = 46
    DEBUG_GET_SPATIAL_REGIONS = 99
    USER_SPACE_START = 100


class CompressionType(IntEnum):
    NO_COMPRESSION = 0
    SNAPPY = 1


class ChannelDataAccess(IntEnum):
    NO_ACCESS = 0
    READ_ACCESS = 1
    WRITE_ACCESS = 2


class EntityGroupType(IntEnum):
    HANDOVER = 0
    LOCK = 1


class ChannelAccessLevel(IntEnum):
    """Per-operation channel ACL (ref: pkg/channeld/channel_acl.go:6-24)."""

    NONE = 0
    OWNER_ONLY = 1
    OWNER_AND_GLOBAL_OWNER = 2
    ANY = 3


class ConnectionState(IntEnum):
    """(ref: pkg/channeld/connection.go connection state constants)."""

    UNAUTHENTICATED = 0
    AUTHENTICATED = 1
    CLOSING = 2


# Channel id spaces (ref: pkg/channeld/settings.go:94-95, channel.go:218-253):
# GLOBAL = 0; non-spatial ids 1..0xFFFF; spatial from 0x10000; entity from 0x80000.
GLOBAL_CHANNEL_ID = 0

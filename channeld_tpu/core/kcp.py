"""KCP wire-protocol transport (interop-class with the reference's kcp-go
listener, ref: pkg/channeld/connection.go:207-216).

Implements the KCP segment format and ARQ semantics so that a peer
speaking KCP (e.g. kcp-go with no FEC and no block crypt, the reference's
configuration) can interoperate at the wire level:

    0               4   5   6       8 (little-endian)
    +---------------+---+---+-------+
    |     conv      |cmd|frg|  wnd  |
    +---------------+---+---+-------+ 8
    |      ts       |      sn       |
    +---------------+---------------+ 16
    |      una      |      len      |
    +---------------+---------------+ 24
    |            data (len)         |

Commands: 81 PUSH (data), 82 ACK, 83 WASK (window probe), 84 WINS
(window answer). Multiple segments may be packed per datagram. ``una``
on every segment cumulatively acknowledges all sn < una; ACK segments
additionally ack one exact sn and echo its ts for RTT estimation.

Semantics implemented: send/receive windows, cumulative (una) + selective
(ACK) acknowledgement, RTO with kcp's x1.5 backoff, fast retransmit after
3 duplicate ack spans, zero-window probing (WASK/WINS), dead-link
detection, and in-order stream delivery. ``frg`` is always 0 on send
(stream mode) — the byte stream carries this package's 5-byte-tag
framing, so message boundaries live a layer up, exactly like the TCP
path; fragmented peer messages (frg>0) still reassemble correctly
because delivery concatenates payloads in sn order.

Deviations that do NOT affect the wire format: congestion control is
plain windowing (kcp-go ships with congestion control off for games:
nocwnd), and RTO bounds are tuned for interactive traffic.
"""

from __future__ import annotations

import asyncio
import secrets
import socket
import struct
import threading
import time
from collections import deque
from typing import Callable, Iterator, Optional

from ..chaos.injector import chaos as _chaos
from ..utils.logger import get_logger

logger = get_logger("kcp")

# conv, cmd, frg, wnd, ts, sn, una, len — the canonical 24-byte header.
_HEADER = struct.Struct("<IBBHIIII")
HEADER_SIZE = _HEADER.size
assert HEADER_SIZE == 24

CMD_PUSH = 81
CMD_ACK = 82
CMD_WASK = 83
CMD_WINS = 84
_VALID_CMDS = (CMD_PUSH, CMD_ACK, CMD_WASK, CMD_WINS)

MTU = 1400
SEG_PAYLOAD = MTU - HEADER_SIZE

RCV_WND = 256  # segments
SND_WND = 256
DEFAULT_RMT_WND = 32  # until the peer advertises (kcp IKCP_WND_RCV)

RTO_MIN = 0.03
RTO_DEF = 0.2
RTO_MAX = 6.0
DEAD_LINK = 20  # retransmissions of one segment before declaring the peer dead
FASTACK_RESEND = 3
PROBE_INTERVAL = 0.5  # zero-window probe cadence
MAX_QUEUE_BYTES = 1 << 20  # pending bytes before shedding a black-holed peer


def parse_segments(data: bytes) -> Iterator[tuple]:
    """Yield (conv, cmd, frg, wnd, ts, sn, una, payload) per packed
    segment; stops at the first truncated/hostile segment."""
    pos = 0
    n = len(data)
    while n - pos >= HEADER_SIZE:
        conv, cmd, frg, wnd, ts, sn, una, length = _HEADER.unpack_from(data, pos)
        pos += HEADER_SIZE
        if cmd not in _VALID_CMDS or length > n - pos:
            return
        yield conv, cmd, frg, wnd, ts, sn, una, data[pos : pos + length]
        pos += length


class _SndSeg:
    __slots__ = ("sn", "data", "ts", "rto", "resend_at", "xmit", "fastack")

    def __init__(self, sn: int, data: bytes):
        self.sn = sn
        self.data = data
        self.ts = 0
        self.rto = RTO_DEF
        self.resend_at = 0.0
        self.xmit = 0
        self.fastack = 0


class KcpConn:
    """One KCP conversation (either side). Byte-stream in, byte-stream
    out; datagrams via the ``output`` callback."""

    def __init__(self, conv: int, output: Callable[[bytes], None]):
        self.conv = conv
        self._output = output
        self._lock = threading.Lock()
        self._start = time.monotonic()

        # send side
        self.snd_una = 0  # oldest unacked sn
        self.snd_nxt = 0  # next sn to assign
        self._snd_buf: dict[int, _SndSeg] = {}  # in flight
        self._snd_queue: deque[bytes] = deque()  # awaiting window
        self._queue_bytes = 0
        self.rmt_wnd = DEFAULT_RMT_WND

        # receive side
        self.rcv_nxt = 0
        self._rcv_buf: dict[int, bytes] = {}
        self._acklist: list[tuple[int, int]] = []  # (sn, ts echo)

        # rtt estimation
        self._srtt = 0.0
        self._rttvar = 0.0
        self.rto = RTO_DEF

        # zero-window probing
        self._probe_wask_at = 0.0
        self._send_wins = False

        self.closed = False
        self.shed = False
        self.paused = False  # receiver backpressure: hold delivery
        self._chaos_held: list[bytes] = []  # reorder-fault holding pen
        self.on_stream: Optional[Callable[[bytes], None]] = None
        self.on_close: Optional[Callable[[], None]] = None

    def _now_ms(self) -> int:
        return int((time.monotonic() - self._start) * 1000) & 0xFFFFFFFF

    # -- sending ----------------------------------------------------------

    def send_stream(self, data: bytes) -> None:
        if self.closed or self.shed:
            return
        with self._lock:
            for off in range(0, len(data), SEG_PAYLOAD):
                seg = data[off : off + SEG_PAYLOAD]
                self._snd_queue.append(seg)
                self._queue_bytes += len(seg)
            overflow = self._queue_bytes > MAX_QUEUE_BYTES
        if overflow:
            self.shed = True
            logger.warning("kcp conv %d: send queue overflow, shedding peer",
                           self.conv)
            self._close()
            return
        self.flush()

    def _wnd_unused(self) -> int:
        return max(RCV_WND - len(self._rcv_buf), 0)

    def _pack(self, cmd: int, ts: int, sn: int, payload: bytes = b"") -> bytes:
        return _HEADER.pack(self.conv, cmd, 0, self._wnd_unused(), ts, sn,
                            self.rcv_nxt, len(payload)) + payload

    def flush(self) -> None:
        """Emit pending acks, probes, window-permitted queued segments, and
        due retransmissions, coalesced into MTU-bounded datagrams."""
        if self.closed:
            return
        now = time.monotonic()
        now_ms = self._now_ms()
        out: list[bytes] = []
        dead = False
        with self._lock:
            # Acks first (kcp flushes acks before data).
            for sn, ts in self._acklist:
                out.append(self._pack(CMD_ACK, ts, sn))
            self._acklist.clear()

            # Window management.
            if self.rmt_wnd == 0 and now >= self._probe_wask_at:
                out.append(self._pack(CMD_WASK, now_ms, 0))
                self._probe_wask_at = now + PROBE_INTERVAL
            if self._send_wins:
                out.append(self._pack(CMD_WINS, now_ms, 0))
                self._send_wins = False

            # Queue -> flight while the effective window allows.
            cwnd = min(SND_WND, max(self.rmt_wnd, 0))
            while self._snd_queue and self.snd_nxt < self.snd_una + cwnd:
                data = self._snd_queue.popleft()
                self._queue_bytes -= len(data)
                seg = _SndSeg(self.snd_nxt, data)
                seg.ts = now_ms
                seg.rto = self.rto
                seg.resend_at = now + seg.rto
                seg.xmit = 1
                self._snd_buf[seg.sn] = seg
                self.snd_nxt += 1
                out.append(self._pack(CMD_PUSH, seg.ts, seg.sn, seg.data))

            # Retransmissions: timeout or fast-ack threshold.
            for seg in self._snd_buf.values():
                need = False
                if now >= seg.resend_at:
                    need = True
                    seg.rto = min(seg.rto * 1.5, RTO_MAX)  # kcp backoff
                elif seg.fastack >= FASTACK_RESEND:
                    need = True
                    seg.fastack = 0
                if need:
                    seg.xmit += 1
                    seg.ts = now_ms
                    seg.resend_at = now + seg.rto
                    out.append(self._pack(CMD_PUSH, seg.ts, seg.sn, seg.data))
                    if seg.xmit >= DEAD_LINK:
                        dead = True
        self._emit(out)
        if dead and not self.closed:
            logger.warning("kcp conv %d: dead link", self.conv)
            self._close()

    def _emit(self, segments: list[bytes]) -> None:
        if not segments:
            return
        buf = bytearray()
        for seg in segments:
            if buf and len(buf) + len(seg) > MTU:
                self._send_datagram(bytes(buf))
                buf.clear()
            buf.extend(seg)
        if buf:
            self._send_datagram(bytes(buf))

    def _send_datagram(self, datagram: bytes) -> None:
        """Datagram egress, with the chaos loss/reorder/dup gate in front
        — the faults the ARQ exists to absorb. A held (reordered)
        datagram flushes after the next one; if traffic stops, the RTO
        retransmission regenerates it, so holding is equivalent to loss."""
        if _chaos.armed:
            if _chaos.fire("kcp.loss"):
                return
            if _chaos.fire("kcp.dup"):
                self._output(datagram)
            if _chaos.fire("kcp.reorder"):
                self._chaos_held.append(datagram)
                return
        self._output(datagram)
        if self._chaos_held:
            held, self._chaos_held = self._chaos_held, []
            for h in held:
                self._output(h)

    # -- receiving --------------------------------------------------------

    def input(self, data: bytes) -> None:
        """Feed one received datagram (possibly several packed segments)."""
        if self.closed:
            return
        # Validate conv across the WHOLE datagram before touching any
        # state: a mid-datagram conv mismatch must drop the datagram
        # wholesale, not strand payloads that earlier iterations already
        # dequeued (rcv_nxt would advance past them, so retransmits
        # arrive as duplicates and the bytes are lost forever).
        segments = list(parse_segments(data))
        if any(seg[0] != self.conv for seg in segments):
            return  # whole datagram suspect; no state applied
        deliver: list[bytes] = []
        with self._lock:
            for conv, cmd, frg, wnd, ts, sn, una, payload in segments:
                self.rmt_wnd = wnd
                # Cumulative ack: everything below una is delivered.
                if una > self.snd_una:
                    for s in [s for s in self._snd_buf if s < una]:
                        del self._snd_buf[s]
                    self.snd_una = una
                if cmd == CMD_ACK:
                    seg = self._snd_buf.pop(sn, None)
                    if seg is not None and seg.xmit == 1:
                        # RTT sample only from unretransmitted segments
                        # (Karn's rule; retransmitted echoes are ambiguous).
                        self._update_rtt((self._now_ms() - ts) & 0xFFFFFFFF)
                    # Fast-retransmit accounting: older in-flight segments
                    # skipped by this ack accumulate a span count.
                    for s, fseg in self._snd_buf.items():
                        if s < sn:
                            fseg.fastack += 1
                    while self.snd_una not in self._snd_buf and \
                            self.snd_una < self.snd_nxt:
                        self.snd_una += 1
                elif cmd == CMD_PUSH:
                    if sn < self.rcv_nxt + RCV_WND:
                        # Ack in-window and already-delivered (duplicate)
                        # segments so lost acks recover. Never ack ABOVE
                        # the window: the segment is dropped here, and an
                        # acked-but-dropped segment would leave the sender
                        # believing it delivered — a permanent stream gap.
                        self._acklist.append((sn, ts))
                    if self.rcv_nxt <= sn < self.rcv_nxt + RCV_WND:
                        self._rcv_buf.setdefault(sn, payload)
                        self._collect_deliverable(deliver)
                elif cmd == CMD_WASK:
                    self._send_wins = True
                # CMD_WINS carries the window in wnd — already applied.
        for chunk in deliver:
            if self.on_stream is not None:
                self.on_stream(chunk)
        self.flush()

    def _collect_deliverable(self, deliver: list[bytes]) -> None:
        while not self.paused and self.rcv_nxt in self._rcv_buf:
            deliver.append(self._rcv_buf.pop(self.rcv_nxt))
            self.rcv_nxt = (self.rcv_nxt + 1) & 0xFFFFFFFF

    def keepalive(self) -> None:
        """Emit a lone WASK probe. Costs one 24-byte datagram; the server
        counts it as inbound traffic, so a quiet-but-alive client is not
        idle-reaped (after which its mid-stream sn>0 PUSHes would be
        silently dropped — a new session requires PUSH sn 0)."""
        if self.closed:
            return
        with self._lock:
            seg = self._pack(CMD_WASK, self._now_ms(), 0)
        self._emit([seg])

    # -- backpressure ------------------------------------------------------

    def pause(self) -> None:
        """Stop delivering; buffered segments stay in rcv_buf and the
        advertised window shrinks, stalling the peer (KCP-native
        backpressure — the analog of not reading a TCP socket)."""
        self.paused = True

    def resume(self) -> None:
        self.paused = False
        deliver: list[bytes] = []
        with self._lock:
            self._collect_deliverable(deliver)
        for chunk in deliver:
            if self.on_stream is not None:
                self.on_stream(chunk)
        self.flush()  # re-advertise the opened window

    # -- rtt ---------------------------------------------------------------

    def _update_rtt(self, rtt_ms: int) -> None:
        rtt = rtt_ms / 1000.0
        if rtt < 0 or rtt > 60:
            return
        if self._srtt == 0:
            self._srtt = rtt
            self._rttvar = rtt / 2
        else:
            delta = abs(rtt - self._srtt)
            self._rttvar = 0.75 * self._rttvar + 0.25 * delta
            self._srtt = 0.875 * self._srtt + 0.125 * rtt
        self.rto = min(max(RTO_MIN, self._srtt + max(0.01, 4 * self._rttvar)),
                       RTO_MAX)

    # -- lifecycle ---------------------------------------------------------

    def _close(self) -> None:
        self.closed = True
        if self.on_close is not None:
            self.on_close()

    def close(self) -> None:
        self.closed = True


IDLE_TIMEOUT = 30.0  # reap sessions with no inbound traffic (dead peers)
KEEPALIVE_INTERVAL = 10.0  # client probes well inside IDLE_TIMEOUT
MAX_SESSIONS = 4096  # spoofed-source flood ceiling


class KcpServerProtocol(asyncio.DatagramProtocol):
    """Server side. Sessions are keyed by source address (kcp-go listener
    semantics): the first datagram from a new address creates the session
    with that datagram's conv; later datagrams must match both the address
    and the conv.

    Flood guards on top of the kcp-go model (KCP has no handshake, so a
    single datagram can otherwise allocate state): a new session requires
    a PUSH for sn 0 (every legitimate conversation's first emission), the
    session table is capped, and idle sessions are reaped — on top of the
    gateway's own unauth-connection reaper (core/ddos.py)."""

    def __init__(self, on_session: Callable[[KcpConn, tuple], None]):
        self.on_session = on_session
        self.transport: Optional[asyncio.DatagramTransport] = None
        self.sessions: dict[tuple, KcpConn] = {}
        self._last_input: dict[tuple, float] = {}
        self._update_task: Optional[asyncio.Task] = None

    def connection_made(self, transport) -> None:
        self.transport = transport
        self._update_task = asyncio.ensure_future(self._update_loop())

    async def _update_loop(self) -> None:
        while True:
            now = time.monotonic()
            for addr, sess in list(self.sessions.items()):
                if sess.closed:
                    self._remove(addr)
                    continue
                if now - self._last_input.get(addr, now) > IDLE_TIMEOUT:
                    sess._close()  # fires on_close -> gateway conn close
                    self._remove(addr)
                    continue
                sess.flush()
            await asyncio.sleep(0.01)

    def _remove(self, addr) -> None:
        self.sessions.pop(addr, None)
        self._last_input.pop(addr, None)

    def datagram_received(self, data: bytes, addr) -> None:
        sess = self.sessions.get(addr)
        if sess is None:
            if len(self.sessions) >= MAX_SESSIONS:
                return
            first = next(parse_segments(data), None)
            # Only a PUSH for sn 0 opens a conversation: all other
            # well-formed segments (random cmd bytes, mid-stream sn,
            # probes) are dropped instead of allocating session +
            # gateway-connection state.
            if first is None or first[1] != CMD_PUSH or first[5] != 0:
                return
            conv = first[0]
            sess = KcpConn(conv,
                           lambda d, a=addr: self.transport.sendto(d, a))
            self.sessions[addr] = sess
            self.on_session(sess, addr)
        self._last_input[addr] = time.monotonic()
        sess.input(data)
        if sess.closed:
            self._remove(addr)

    def close(self) -> None:
        if self._update_task is not None:
            self._update_task.cancel()
        if self.transport is not None:
            self.transport.close()


class KcpClient:
    """Blocking client conversation (used by the client SDK). Picks a
    random conv like kcp-go's DialWithOptions."""

    def __init__(self, host: str, port: int, timeout: float = 5.0):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.connect((host, port))
        self._sock.settimeout(timeout)
        self.conv = secrets.randbits(32) or 1
        self._last_tx = time.monotonic()
        self.session = KcpConn(self.conv, self._tx)
        self._recv_buffer = bytearray()
        self._recv_lock = threading.Lock()
        self.session.on_stream = self._on_stream

    def _tx(self, data: bytes) -> None:
        self._last_tx = time.monotonic()
        self._sock.send(data)

    def _on_stream(self, seg: bytes) -> None:
        with self._recv_lock:
            self._recv_buffer.extend(seg)

    def send(self, data: bytes) -> None:
        try:
            self.session.send_stream(data)
        except OSError:
            self.session.closed = True

    def _maybe_keepalive(self) -> None:
        if time.monotonic() - self._last_tx > KEEPALIVE_INTERVAL:
            self.session.keepalive()

    def recv(self, timeout: float = 0.0) -> bytes:
        deadline = time.monotonic() + max(timeout, 0.0)
        try:
            # Wait for the first datagram in keepalive-bounded slices: a
            # single long quiet recv() must not outlast the server's
            # idle reaper (IDLE_TIMEOUT) just because the probe check
            # only ran between calls.
            while True:
                self._maybe_keepalive()
                now = time.monotonic()
                wait = min(max(deadline - now, 0.0),
                           max(self._last_tx + KEEPALIVE_INTERVAL - now,
                               0.05))
                self._sock.settimeout(wait if wait > 0 else 0.000001)
                try:
                    data = self._sock.recv(65536)
                    break
                except socket.timeout:
                    if time.monotonic() >= deadline:
                        raise
            self.session.input(data)
            # Drain whatever else is queued without blocking.
            self._sock.settimeout(0.000001)
            while True:
                self.session.input(self._sock.recv(65536))
        except (socket.timeout, BlockingIOError):
            pass
        except OSError:
            self.session.closed = True
            return b""
        try:
            self.session.flush()
            self._maybe_keepalive()
        except OSError:
            self.session.closed = True
        with self._recv_lock:
            out = bytes(self._recv_buffer)
            self._recv_buffer.clear()
        return out

    def close(self) -> None:
        self.session.close()
        self._sock.close()

"""Per-channel-type access control for sub/unsub/remove operations.

Capability parity with the reference ACL (ref: pkg/channeld/channel_acl.go):
four levels — NONE, OWNER_ONLY, OWNER_AND_GLOBAL_OWNER, ANY — configured
per channel type and operation in the channel-settings JSON.
"""

from __future__ import annotations

from enum import IntEnum
from typing import TYPE_CHECKING, Optional

from .settings import global_settings
from .types import ChannelAccessLevel, ChannelType

if TYPE_CHECKING:
    from .channel import Channel


class ChannelAccessType(IntEnum):
    SUB = 0
    UNSUB = 1
    REMOVE = 2


def check_acl(channel: "Channel", conn, access_type: ChannelAccessType) -> tuple[bool, Optional[str]]:
    """Returns (has_access, reason_if_denied).

    ``conn is None`` means an internal operation, which is always allowed
    (ref: channel_acl.go:30-35 and handleRemoveChannel's nil-conn path).
    """
    if conn is None:
        return True, None

    acl = global_settings.get_channel_settings(ChannelType(channel.channel_type)).acl
    level = {
        ChannelAccessType.SUB: acl.sub,
        ChannelAccessType.UNSUB: acl.unsub,
        ChannelAccessType.REMOVE: acl.remove,
    }[access_type]

    if level == ChannelAccessLevel.NONE:
        return False, "access level is None"
    if level == ChannelAccessLevel.ANY:
        return True, None

    from .channel import get_global_channel

    owner = channel.get_owner()
    if owner is not None and owner is conn:
        return True, None
    if level == ChannelAccessLevel.OWNER_AND_GLOBAL_OWNER:
        gch = get_global_channel()
        if gch is not None and gch.get_owner() is conn:
            return True, None
        return False, "connection is not the channel owner nor the global owner"
    return False, "connection is not the channel owner"

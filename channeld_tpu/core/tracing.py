"""Always-on flight recorder: tick-timeline tracing.

Aggregate Prometheus histograms answer "how slow was the gateway last
minute"; they cannot answer "where did THIS tick's budget go" or "what
happened to THAT handover as it crossed two gateways". The flight
recorder closes that gap as a permanent layer (CheetahGIS-style
streaming-spatial operation and Spider-style cross-node transactions
both presuppose correlated, low-overhead telemetry):

- **Fixed memory, lock-free on the hot path.** Spans live in per-thread
  ring buffers (``threading.local``; the asyncio runtime is effectively
  one writer per thread, so an index bump + list store is race-free).
  The ring never grows: overflow overwrites the OLDEST span and is
  counted exactly (``dropped``), so the recorder always holds the
  newest ticks — flight-recorder semantics, not a log.
- **Tick-scoped, sampling-free.** Every span is stamped with the
  current GLOBAL tick number (``set_tick`` from the GLOBAL channel
  tick). Tick-scoped stages are few per tick (ingest drain, message
  dispatch, fan-out encode, device step, readback, handover
  orchestration, trunk I/O), so recording each one costs two
  ``monotonic_ns`` reads and a ring store (~100-200ns) — cheap enough
  to never sample.
- **Trace ids across gateways.** A cross-gateway handover or client
  redirect carries its trace id over the trunk (``traceId`` on
  TrunkHandoverPrepare/Ack/StageRedirect), so one id stitches spans
  from both gateways' recorders into a single reconstructible trace.
- **Three exits**: ``dump_trace()`` writes Chrome/Perfetto
  ``trace_event`` JSON (open in ui.perfetto.dev or chrome://tracing —
  the same story as ``-profile tpu``); anomalies (tick-budget blow,
  overload transition, handover/migration abort, failover epoch)
  freeze the ring and auto-dump the last N ticks, counted in
  ``trace_dumps_total{trigger}``; and per-stage cost feeds the
  ``tick_stage_ms{stage}`` histograms whether or not span recording is
  enabled.

See doc/observability.md.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import Optional

from ..utils.logger import get_logger
from .affinity import affinity as _affinity

logger = get_logger("tracing")

# Span kinds (trace_event "ph" values).
_COMPLETE = "X"
_INSTANT = "i"

_trace_counter = itertools.count(1)
_dump_counter = itertools.count(1)


def new_trace_id(prefix: str = "") -> str:
    """Process-unique trace id; ``prefix`` ties it to an origin (e.g.
    the federation gateway id) so a stitched cross-gateway trace shows
    where it started."""
    return f"{prefix or 'g'}-{os.getpid():x}-{next(_trace_counter):x}"


class _Ring:
    """Fixed-capacity span store for ONE writer thread. Overflow
    overwrites the oldest entry and bumps ``dropped`` — the recorder
    keeps the newest spans with exact drop accounting."""

    __slots__ = ("buf", "cap", "idx", "count", "dropped", "tid")

    def __init__(self, cap: int, tid: int):
        self.cap = cap
        self.buf: list = [None] * cap
        self.idx = 0  # next write position
        self.count = 0  # live entries (<= cap)
        self.dropped = 0  # entries overwritten by wrap
        self.tid = tid

    def put(self, entry: tuple) -> None:
        i = self.idx
        # Entry lands BEFORE the count bump: a cross-thread snapshot
        # reading buf[:count] must never see a not-yet-stored slot.
        self.buf[i] = entry
        if self.count == self.cap:
            self.dropped += 1
        else:
            self.count += 1
        self.idx = (i + 1) % self.cap

    def snapshot(self) -> list:
        """Entries oldest-first (freeze-and-copy; O(cap))."""
        if self.count < self.cap:
            return [e for e in self.buf[: self.count]]
        return self.buf[self.idx:] + self.buf[: self.idx]


class FlightRecorder:
    """Process-wide recorder (one instance: ``recorder``).

    Hot-path contract: call sites guard on ``recorder.enabled`` (one
    attribute load while disabled) and use ``now()`` + ``span()`` /
    ``stage()`` / ``instant()``. Entries are tuples
    ``(kind, name, lane, start_ns, dur_ns, tick, trace_id)``.
    """

    def __init__(self):
        self._local = threading.local()
        self._rings: dict[int, _Ring] = {}
        self._rings_lock = threading.Lock()
        self.configure()

    # ---- configuration ---------------------------------------------------

    def configure(
        self,
        enabled: bool = True,
        ring_spans: int = 8192,
        dump_ticks: int = 200,
        dump_path: str = "profiles",
        anomaly_cooldown_s: float = 5.0,
        origin: str = "",
    ) -> None:
        self.enabled = enabled
        self.ring_spans = max(16, int(ring_spans))
        self.dump_ticks = max(1, int(dump_ticks))
        self.dump_path = dump_path
        self.anomaly_cooldown_s = anomaly_cooldown_s
        self.origin = origin
        self.tick = 0
        self.anomalies: list[dict] = []
        self._last_dump_at = -1e9
        with self._rings_lock:
            self._rings.clear()
        self._local = threading.local()
        self._epoch_ns = time.monotonic_ns()

    def reset(self) -> None:
        """Test hook: drop every ring and restore defaults."""
        self.configure()

    # ---- hot path --------------------------------------------------------

    @staticmethod
    def now() -> int:
        return time.monotonic_ns()

    def _ring(self) -> _Ring:
        ring = getattr(self._local, "ring", None)
        if ring is None:
            ring = _Ring(self.ring_spans, threading.get_ident())
            self._local.ring = ring
            with self._rings_lock:
                self._rings[ring.tid] = ring
        return ring

    def span(self, name: str, start_ns: int, lane: int = 0,
             trace: Optional[str] = None,
             end_ns: Optional[int] = None) -> None:
        """Record one complete span that began at ``start_ns`` (from
        :meth:`now`) and ends now (or at ``end_ns``)."""
        if not self.enabled:
            return
        if end_ns is None:
            end_ns = time.monotonic_ns()
        self._ring().put((
            _COMPLETE, name, lane, start_ns, end_ns - start_ns,
            self.tick, trace,
        ))

    def instant(self, name: str, lane: int = 0,
                trace: Optional[str] = None) -> None:
        if not self.enabled:
            return
        self._ring().put((
            _INSTANT, name, lane, time.monotonic_ns(), 0, self.tick, trace,
        ))

    def stage(self, stage: str, start_ns: int, lane: int = 0,
              trace: Optional[str] = None,
              end_ns: Optional[int] = None) -> None:
        """A named per-tick stage: records the span AND observes the
        ``tick_stage_ms{stage}`` histogram (the histogram moves even
        with span recording disabled, so live dashboards keep their
        per-stage budgets either way). ``end_ns`` overrides "now" for
        aggregated stages (e.g. the per-follower readback total)."""
        if end_ns is None:
            end_ns = time.monotonic_ns()
        _stage_ms(stage).observe((end_ns - start_ns) / 1e6)
        if self.enabled:
            self._ring().put((
                _COMPLETE, stage, lane, start_ns, end_ns - start_ns,
                self.tick, trace,
            ))

    def set_tick(self, tick: int) -> None:
        """Stamp subsequent spans with the GLOBAL tick number (called
        once per GLOBAL tick)."""
        _affinity.expect("tick-loop")
        self.tick = tick

    # ---- introspection ---------------------------------------------------

    def stats(self) -> dict:
        with self._rings_lock:
            rings = list(self._rings.values())
        return {
            "enabled": self.enabled,
            "rings": len(rings),
            "spans": sum(r.count for r in rings),
            "dropped": sum(r.dropped for r in rings),
            "tick": self.tick,
            "anomalies": len(self.anomalies),
        }

    def snapshot(self, last_ticks: Optional[int] = None) -> list[dict]:
        """Freeze every ring and return span dicts (oldest-first per
        ring), optionally restricted to the last N ticks."""
        with self._rings_lock:
            rings = list(self._rings.values())
        floor = None
        if last_ticks is not None:
            floor = self.tick - last_ticks + 1
        out: list[dict] = []
        for ring in rings:
            for e in ring.snapshot():
                kind, name, lane, start_ns, dur_ns, tick, trace = e
                if floor is not None and tick < floor:
                    continue
                d = {
                    "kind": kind, "name": name, "lane": lane,
                    "start_ns": start_ns, "dur_ns": dur_ns, "tick": tick,
                    "tid": ring.tid,
                }
                if trace is not None:
                    d["trace"] = trace
                out.append(d)
        out.sort(key=lambda d: d["start_ns"])
        return out

    # ---- dumps -----------------------------------------------------------

    def _dump_path(self, trigger: str) -> str:
        """profiles/trace_<trigger>_<stamp>.<seq>_<pid>.json — the seq
        component keeps same-second dumps (sub-second anomaly cooldowns,
        back-to-back SIGUSR2s) from overwriting each other."""
        os.makedirs(self.dump_path, exist_ok=True)
        stamp = time.strftime("%Y%m%d%H%M%S")
        seq = next(_dump_counter)
        return os.path.join(
            self.dump_path,
            f"trace_{trigger}_{stamp}.{seq}_{os.getpid()}.json",
        )

    def to_trace_events(self, spans: list[dict]) -> dict:
        """Chrome/Perfetto ``trace_event`` JSON object for ``spans``
        (as returned by :meth:`snapshot`)."""
        pid = os.getpid()
        events = []
        # One timeline row per (thread, lane): channel ticks get their
        # own rows, the default lane groups the rest. Row ids are
        # allocated per dump (first-seen order) — spatial channel ids
        # start at 0x10000, so any arithmetic fold would collide
        # distinct channels onto one row and render false nesting.
        rows: dict[tuple, int] = {}
        for s in spans:
            ts_us = (s["start_ns"] - self._epoch_ns) / 1e3
            ev = {
                "name": s["name"],
                "ph": s["kind"],
                "ts": ts_us,
                "pid": pid,
                "tid": rows.setdefault((s["tid"], s["lane"]), len(rows)),
                "args": {"tick": s["tick"], "lane": s["lane"]},
            }
            if s["kind"] == _COMPLETE:
                ev["dur"] = s["dur_ns"] / 1e3
            else:
                ev["s"] = "t"  # instant scope: thread
            if "trace" in s:
                ev["args"]["trace"] = s["trace"]
            events.append(ev)
        with self._rings_lock:
            # The anomaly path calls this from its off-thread writer; a
            # writer thread registering its first ring mid-iteration
            # must not kill the dump with dict-changed-size.
            dropped = sum(r.dropped for r in self._rings.values())
        meta = {
            "origin": self.origin or f"pid:{pid}",
            "tick": self.tick,
            "dropped": dropped,
        }
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": meta,
        }

    def dump_trace(self, path: Optional[str] = None,
                   last_ticks: Optional[int] = None,
                   trigger: str = "manual") -> str:
        """Write the ring contents as Perfetto JSON; returns the path.
        Counted in ``trace_dumps_total{trigger}`` like the anomaly
        path, so manual/sigusr2/shutdown dumps show on /metrics too."""
        from . import metrics

        metrics.trace_dumps.labels(trigger=trigger).inc()
        doc = self.to_trace_events(self.snapshot(last_ticks))
        doc["otherData"]["trigger"] = trigger
        if path is None:
            path = self._dump_path(trigger)
        with open(path, "w") as f:
            json.dump(doc, f)
        logger.info("flight-recorder trace (%s, %d events) -> %s",
                    trigger, len(doc["traceEvents"]), path)
        return path

    def note_anomaly(self, trigger: str, detail: str = "",
                     force: bool = False) -> Optional[str]:
        """An anomalous tick: count it, and (cooldown permitting) freeze
        the ring and auto-dump the last ``dump_ticks`` ticks. Returns
        the dump path when one was written. A disabled recorder is a
        full no-op — call sites guard on ``recorder.enabled`` and this
        matches them: ``-trace false`` means no anomaly accounting at
        all, not a metric without dumps. ``force`` skips the cooldown
        CHECK (the window still resets): for triggers that are rare by
        construction AND must always ship a timeline — an SLO breach
        (core/slo.py: rising-edge + min-events gated) would otherwise
        lose its dump slot to a storm of per-tick tick_budget anomalies
        on a saturated box. The snapshot is synchronous (a bounded ring
        copy); the JSON write runs on a daemon thread so the tick that
        tripped the anomaly is not stalled by disk I/O."""
        if not self.enabled:
            return None
        from . import metrics

        metrics.trace_dumps.labels(trigger=trigger).inc()
        record = {"trigger": trigger, "detail": detail, "tick": self.tick,
                  "t": time.monotonic()}
        self.anomalies.append(record)
        del self.anomalies[:-256]  # bounded like everything else here
        now = time.monotonic()
        if not force and now - self._last_dump_at < self.anomaly_cooldown_s:
            return None
        self._last_dump_at = now
        # Only the ring freeze (a bounded copy) runs on the tick path;
        # event formatting + JSON + disk all happen off-thread — an
        # anomaly dump must never widen the very tick it is recording.
        spans = self.snapshot(self.dump_ticks)
        path = self._dump_path(trigger)
        record["path"] = path

        def _write():
            _affinity.enter("trace-dumper")
            try:
                doc = self.to_trace_events(spans)
                doc["otherData"]["trigger"] = trigger
                doc["otherData"]["detail"] = detail
                with open(path, "w") as f:
                    json.dump(doc, f)
                logger.warning(
                    "anomaly %s (%s): last %d ticks (%d spans) frozen -> %s",
                    trigger, detail or "-", self.dump_ticks, len(spans), path,
                )
            except OSError as e:  # pragma: no cover - disk trouble
                logger.error("anomaly dump failed: %s", e)

        threading.Thread(target=_write, daemon=True,
                         name=f"trace-dump-{trigger}").start()
        return path


# Cached per-stage histogram children (label resolution is dict work;
# stages are a small fixed set, so resolve each once).
_stage_children: dict = {}


def _stage_ms(stage: str):
    child = _stage_children.get(stage)
    if child is None:
        from . import metrics

        child = metrics.tick_stage_ms.labels(stage=stage)
        _stage_children[stage] = child
    return child


recorder = FlightRecorder()


def configure_from_settings() -> None:
    """Apply the -trace* flags (run_server boot path)."""
    from .settings import global_settings as st

    recorder.configure(
        enabled=st.trace_enabled,
        ring_spans=st.trace_ring_spans,
        dump_ticks=st.trace_dump_ticks,
        dump_path=st.profile_path,
        anomaly_cooldown_s=st.trace_anomaly_cooldown_s,
        origin=st.federation_gateway_id,
    )


def install_trace_dump_signal() -> bool:
    """Bind SIGUSR2 to a manual flight-recorder dump: ``kill -USR2
    <pid>`` freezes the ring and writes the full timeline as Perfetto
    JSON (path logged). Installed at server start; False where SIGUSR2
    does not exist or outside the main thread."""
    import signal

    def _on_sigusr2(signum, frame) -> None:
        recorder.dump_trace(trigger="sigusr2")

    sig = getattr(signal, "SIGUSR2", None)
    if sig is None:
        return False
    try:
        signal.signal(sig, _on_sigusr2)
    except ValueError:
        return False  # not the main thread
    return True


def register_shutdown_dump() -> None:
    """Dump the ring on process exit (run_server boot path only — a
    library embedding must opt in, or every pytest run would write
    profiles/)."""
    import atexit

    def _on_exit() -> None:
        if recorder.enabled and any(
            r.count for r in recorder._rings.values()
        ):
            recorder.dump_trace(trigger="shutdown")

    atexit.register(_on_exit)


def reset_tracing() -> None:
    """Test hook."""
    recorder.reset()

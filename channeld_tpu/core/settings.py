"""Global settings and CLI flag surface.

Capability parity with the reference settings system
(ref: pkg/channeld/settings.go:16-235): the same ~25 flags, the same
channel-settings JSON schema (keyed by numeric ChannelType), and the
same defaults, so reference config files drop in unchanged.
"""

from __future__ import annotations

import argparse
import json
from dataclasses import dataclass, field, replace
from typing import Optional

from .types import ChannelAccessLevel, ChannelType, CompressionType


@dataclass
class ACLSettings:
    sub: ChannelAccessLevel = ChannelAccessLevel.NONE
    unsub: ChannelAccessLevel = ChannelAccessLevel.NONE
    remove: ChannelAccessLevel = ChannelAccessLevel.NONE

    @classmethod
    def from_dict(cls, d: dict) -> "ACLSettings":
        return cls(
            sub=ChannelAccessLevel(d.get("Sub", 0)),
            unsub=ChannelAccessLevel(d.get("Unsub", 0)),
            remove=ChannelAccessLevel(d.get("Remove", 0)),
        )


@dataclass
class ChannelSettings:
    """(ref: settings.go:64-74 ``ChannelSettingsType``)."""

    tick_interval_ms: int = 10
    default_fanout_interval_ms: int = 20
    default_fanout_delay_ms: int = 0
    remove_channel_after_owner_removed: bool = False
    send_owner_lost_and_recovered: bool = False
    acl: ACLSettings = field(default_factory=ACLSettings)
    data_msg_full_name: str = ""

    @classmethod
    def from_dict(cls, d: dict) -> "ChannelSettings":
        return cls(
            tick_interval_ms=d.get("TickIntervalMs", 10),
            default_fanout_interval_ms=d.get("DefaultFanOutIntervalMs", 20),
            default_fanout_delay_ms=d.get("DefaultFanOutDelayMs", 0),
            remove_channel_after_owner_removed=d.get(
                "RemoveChannelAfterOwnerRemoved", False
            ),
            send_owner_lost_and_recovered=d.get("SendOwnerLostAndRecovered", False),
            acl=ACLSettings.from_dict(d.get("ACLSettings", {})),
            data_msg_full_name=d.get("DataMsgFullName", ""),
        )


@dataclass
class GlobalSettings:
    """(ref: settings.go:16-56 ``GlobalSettingsType`` + defaults :76-105)."""

    development: bool = False
    log_level: Optional[int] = None
    log_file: Optional[str] = None
    profile: str = ""
    profile_path: str = "profiles"

    server_network: str = "tcp"
    server_address: str = ":11288"
    server_read_buffer_size: int = 0x0001FFFF
    server_write_buffer_size: int = 256
    server_fsm: str = "config/server_authoritative_fsm.json"
    server_bypass_auth: bool = True
    server_conn_recoverable: bool = False
    server_conn_recover_timeout_ms: int = 0

    client_network_wait_master_server: bool = True
    client_network: str = "tcp"
    client_address: str = ":12108"
    client_read_buffer_size: int = 0x0001FFFF
    client_write_buffer_size: int = 512
    client_fsm: str = "config/client_non_authoritative_fsm.json"

    compression_type: CompressionType = CompressionType.NO_COMPRESSION

    max_connection_id_bits: int = 31

    connection_auth_timeout_ms: int = 5000
    max_failed_auth_attempts: int = 5
    max_fsm_disallowed: int = 10

    spatial_controller_config: Optional[str] = None
    spatial_channel_id_start: int = 0x00010000
    entity_channel_id_start: int = 0x00080000

    channel_settings: dict[ChannelType, ChannelSettings] = field(
        default_factory=lambda: {
            ChannelType.GLOBAL: ChannelSettings(
                tick_interval_ms=10,
                default_fanout_interval_ms=20,
            )
        }
    )

    enable_record_packet: bool = False
    replay_session_persistence_dir: str = ""

    # Python modules imported at init so game-defined protobuf types are
    # resolvable from Any payloads (the reference gets this for free from
    # each main importing its pb package; ours is a flag/config concern).
    import_modules: list[str] = field(default_factory=list)

    # Durable snapshots (new — the reference has no persistence).
    snapshot_path: str = ""
    snapshot_interval_s: float = 30.0

    # Durable write-ahead journal (new — doc/persistence.md). Empty
    # path = the WAL plane stays disarmed and every hook is one
    # attribute load. With a path, every authoritative state transition
    # (coalesced per-tick channel images, handover-journal transitions,
    # placement flips, staged handles, directory versions, blacklists)
    # is appended CRC-framed and fsync-batched on an off-thread writer,
    # so a kill -9 loses at most one fsync batch instead of one
    # snapshot interval; the periodic snapshot checkpoints (truncates)
    # the journal, and boot replays snapshot + WAL tail (a torn final
    # record is truncated at the first bad CRC).
    wal_path: str = ""
    # The writer's fsync batch window: smaller = tighter RPO, more
    # fsyncs. The tick path only ever enqueues; fsync never runs on it.
    wal_fsync_ms: float = 20.0
    # Operator bound on restart-to-serving (boot restore + WAL replay +
    # controller re-seed); overruns warn and fail the crash soak — a
    # slow replay still beats lost state.
    wal_restart_deadline_s: float = 30.0

    # Prometheus /metrics port (the reference hardcodes :8080,
    # metrics.go; a flag lets N gateways share one host).
    metrics_port: int = 8080

    # TPU decision-plane settings (new — no reference counterpart).
    spatial_backend: str = "host"  # "host" | "tpu"
    tpu_entity_capacity: int = 1 << 17
    tpu_query_capacity: int = 1 << 12
    # Chaos fault-injection scenario JSON (new — see doc/chaos.md).
    # Empty = the injector stays disarmed and every hook is a no-op.
    chaos_config: str = ""

    # Overload governor (new — doc/overload.md). The four-level
    # degradation ladder: enter/exit thresholds are deliberately apart
    # (hysteresis), the ladder moves one step per GLOBAL tick at most,
    # and de-escalation additionally requires the smoothed pressure to
    # hold under the exit threshold for overload_down_hold_s.
    # Thresholds are budget-utilization style (1.0 == the tick exactly
    # spends its budget): degradation starts when the gateway OVERRUNS,
    # not when it is merely busy — a tick at 80% of budget is healthy.
    overload_enabled: bool = True
    overload_alpha: float = 0.25  # EWMA smoothing of the raw pressure
    overload_enter_thresholds: tuple = (0.95, 1.15, 1.40)  # L1/L2/L3
    overload_exit_thresholds: tuple = (0.75, 1.00, 1.20)
    overload_up_hold_ticks: int = 3
    overload_down_hold_s: float = 2.0
    # After a step down, up-transitions wait out this cooldown so the
    # release itself (resumed fan-outs, full-state resyncs, the
    # deferred-handover drain) cannot bounce the ladder straight back
    # up. If the release work is genuinely heavy the governor may still
    # re-brake afterwards — by design it just never climbs above the
    # overload's own peak on the way down.
    overload_up_cooldown_s: float = 3.0
    overload_l1_stretch: float = 2.0  # fan-out interval multiplier
    overload_l2_stretch: float = 4.0
    overload_backlog_norm: int = 64  # stash-parked conns == pressure 1.0
    # L3 hard accept gate: unauthenticated connections tolerated before
    # raw CLIENT accepts are refused outright (separate knob from the
    # pressure normalizer above — they tune independently).
    overload_accept_headroom: int = 256
    overload_handover_batch_cap: int = 256  # crossings/tick at L2+
    overload_retry_after_ms: int = 2000  # ServerBusyMessage back-off

    # Adversarial edge plane (new — doc/edge_hardening.md): the
    # per-connection resource envelope. Unlike the overload ladder
    # (global, load-driven), the edge plane is PER-PEER: one broken or
    # hostile socket is bounded, resynced, quarantined and finally
    # disconnected without the rest of the fleet noticing.
    edge_enabled: bool = True
    # Egress envelope: the send queue is bounded in entries AND bytes.
    # Past either cap the oldest entries are dropped (counted,
    # egress_dropped_total) and every SHED-eligible subscription of the
    # connection is marked for full-state resync — a bounded queue
    # degrades to a coarser cadence, never to silent state loss.
    edge_send_queue_max_msgs: int = 8192
    edge_send_queue_max_bytes: int = 4 * 1024 * 1024
    # Watermarks as fractions of either cap. Above HIGH the connection
    # is a slow-consumer suspect; back under LOW it is healthy again
    # (the gap is hysteresis — a queue oscillating around one threshold
    # must not flap the suspect state).
    edge_high_watermark: float = 0.5
    edge_low_watermark: float = 0.125
    # Sustained-high grace: a connection holding above HIGH for this
    # long is dropped-to-resync once; holding for another full grace
    # window after that escalates to quarantine.
    edge_slow_grace_s: float = 2.0
    # Quarantine -> structured disconnect deadline. While quarantined
    # the egress queue is frozen (nothing new enqueued) and the peer is
    # sent nothing but the final DisconnectMessage.
    edge_quarantine_grace_s: float = 1.0
    # Ingress accumulation bound: a per-connection frames/s cap (token
    # bucket, burst = one second's allowance; 0 disables). Sustained
    # violation quarantines the peer (ingress_flood). Frame-SIZE bounds
    # are the framing layer's MAX_PACKET_SIZE (connection-fatal,
    # counted malformed_frames_total{stage=framing}).
    edge_max_frame_rate: int = 4000
    # Per-tick drain fairness: send-queue entries one connection may
    # flush per pump pass before it yields (re-queued for the next
    # pass); 0 disables the bound. Keeps one hot connection from
    # starving the 1ms pump for everyone else.
    edge_flush_fair_msgs: int = 4096
    # Transport-backpressure gate: when a connection's transport reports
    # more than this many unsent bytes buffered (a peer not draining its
    # socket), the shared pump stops feeding it and leaves the entries
    # in the send queue — which is what the envelope bounds and the
    # slow-consumer ladder watches. Without the gate a slow TCP reader
    # hides in the transport's unbounded-in-practice write buffer until
    # the MAX_SEND_BUFFER abort; with it the peer walks the counted
    # ladder (resync -> quarantine -> structured disconnect) instead.
    # 0 disables. Direct flushes (disconnect, drain) bypass the gate.
    edge_transport_high_bytes: int = 1 << 20
    # Auth-window deadline (-auth-deadline, ms): sockets that never
    # complete the FSM handshake within it are reaped and counted
    # (conn_reaped_total{reason=auth_timeout}); recovery-handle
    # reconnects are exempt. 0 = inherit connection_auth_timeout_ms
    # (the reference's -cat knob) so existing configs keep their
    # behavior.
    auth_deadline_ms: int = 0

    # Spatial authority failover (new — doc/failover.md). When a
    # recoverable server's recovery window expires for good, its
    # orphaned spatial cells are re-hosted onto surviving servers
    # (fewest-owned-cells first) instead of going dark. The deadline is
    # the operator's bound on one failover pass; overruns only warn —
    # a slow re-host still beats a dead cell.
    failover_enabled: bool = True
    failover_rehost_deadline_s: float = 5.0
    # Entity weight in the shared placement score (core/failover.py
    # placement_score, used by failover re-host AND the balancer): one
    # hosted entity costs this many owned cells — a server with few but
    # huge cells is no longer mis-ranked as idle.
    failover_placement_entity_weight: float = 0.0625

    # Live spatial load balancer (new — doc/balancer.md). Planned,
    # zero-loss migration of live cells between live servers: the
    # balancer folds per-server load (entities, crossing rate, fan-out
    # bytes, overload pressure) into an imbalance score (max/mean) with
    # two-sided hysteresis, a per-epoch migration budget and a per-cell
    # post-migration cooldown so it never flaps and never fights the
    # overload ladder (migrations are vetoed at L2+).
    balancer_enabled: bool = True
    balancer_imbalance_enter: float = 1.6
    balancer_imbalance_exit: float = 1.25
    balancer_hold_ticks: int = 5  # consecutive over-threshold updates
    balancer_epoch_ticks: int = 300  # GLOBAL ticks per migration epoch
    balancer_budget_per_epoch: int = 2  # committed migrations per epoch
    balancer_cooldown_ticks: int = 600  # per-cell re-migration lockout
    # Hottest-coldest per-server entity gap below which the world is too
    # small to be worth migrating (keeps tiny test worlds untouched).
    balancer_min_entity_delta: int = 8
    # Freeze-phase bounds, in GLOBAL ticks: at least min (queued entity
    # hops on the cell channel must run before the bootstrap snapshot),
    # at most the drain deadline (a journal that never clears aborts the
    # migration back to the old owner).
    balancer_freeze_min_ticks: int = 2
    balancer_drain_deadline_ticks: int = 120
    # Load-fold weights: one crossing per update == this many entities;
    # one KiB of fan-out per update == this many; one unit of per-server
    # overload pressure == this many.
    balancer_crossing_weight: float = 2.0
    balancer_bytes_weight: float = 0.5
    balancer_pressure_weight: float = 32.0
    # Per-destination veto: a candidate whose exported overload pressure
    # is at/above this never receives a migration (the gateway-wide
    # ladder at L2+ vetoes ALL migrations regardless).
    balancer_dest_pressure_max: float = 1.15

    # Adaptive partitioning (new — doc/partitioning.md). Cell geometry
    # becomes a runtime, versioned property: a density governor splits
    # hot cells quadtree-style and merges cold sibling groups back,
    # executed as transactional geometry epochs (freeze -> drain ->
    # commit/abort) riding the balancer's freeze machinery and the WAL.
    # OFF by default: every pre-existing envelope assumes the static
    # grid; soaks that want it opt in explicitly.
    partition_enabled: bool = False
    # Structural depth bound: the cell-id blocks for depths
    # 0..partition_max_depth are reserved at load (validated against
    # entity_channel_id_start), whether or not the governor is enabled.
    partition_max_depth: int = 2
    # Split when a cell's resident entities hold at/above this; merge a
    # sibling group when the group TOTAL holds at/below the merge
    # threshold (kept well apart — two-sided hysteresis, no flapping).
    partition_split_entities: int = 48
    partition_merge_entities: int = 12
    # Consecutive over/under-threshold evaluations before acting, and
    # GLOBAL ticks between evaluations.
    partition_hold_ticks: int = 3
    partition_eval_ticks: int = 30
    # Committed geometry ops per epoch, epoch length, and per-cell
    # re-op lockout — the balancer's anti-flap discipline.
    partition_budget_per_epoch: int = 1
    partition_epoch_ticks: int = 300
    partition_cooldown_ticks: int = 600
    # Freeze-phase bounds (GLOBAL ticks): minimum freeze before the
    # repartition snapshot; a handover journal that never drains aborts.
    partition_freeze_min_ticks: int = 2
    partition_drain_deadline_ticks: int = 120

    # Standing-query plane (new — doc/query_engine.md). With the TPU
    # backend every standing interest (entity followers, client AOI
    # queries, server sensors) becomes a device query row: one batched
    # mask pass + on-device diff per tick, one changed-rows transfer,
    # O(changed) host apply. ON by default with spatial_backend=tpu;
    # host backend ignores it (host interest stays per-query).
    queryplane_enabled: bool = True
    # Changed-rows budget per tick (the fixed compaction width; changes
    # beyond it stay in the device baseline and re-emit next tick).
    queryplane_rows_max: int = 8192
    # Upper bound on a client spots query's spot list — beyond this the
    # UpdateSpatialInterest message is rejected as malformed.
    queryplane_max_spots: int = 256

    # Simulation plane (new — doc/simulation.md). OFF by default: when
    # enabled the gateway hosts a server-driven agent population
    # stepped ON DEVICE inside the guarded spatial tick — agents occupy
    # ordinary entity slots, so crossings, handover, partitioning,
    # standing queries and fan-out see them exactly like humans, with
    # zero extra device<->host transfers per tick.
    sim_enabled: bool = False
    # Population spawned at controller load (ignored when a WAL-replayed
    # census restores the exact prior population instead).
    sim_agents: int = 1000
    # Counter-based RNG seed: same seed + same tick count = the same
    # trajectories, bit-exact (the replayability contract).
    sim_seed: int = 1
    # Sim passes per spatial tick denominator: step every Nth tick
    # (1 = every tick). The overload ladder's L2 additionally halves
    # this cadence (skips every other scheduled pass) before human
    # traffic degrades.
    sim_step_every_ticks: int = 1
    # Census cadence: every Nth SIM pass the kinematic columns are
    # fetched (the plane's only readback), folded into the host shadow,
    # journaled to the WAL, and committed through the authority path.
    sim_census_every_ticks: int = 50
    # World-time integration step per sim pass, seconds, and the
    # kinematic envelope (units/s, units/s^2).
    sim_step_dt: float = 0.05
    sim_max_speed: float = 6.0
    sim_accel: float = 24.0
    # Steering weights: separation pushes agents out of cells more
    # crowded than sim_crowd occupants; cohesion pulls strays toward
    # their cell's centroid.
    sim_separation: float = 0.6
    sim_cohesion: float = 0.15
    sim_crowd: int = 32
    # Waypoint arrival radius (world units) and the per-tick FSM dice:
    # idle->wander, wander->seek, wander->idle probabilities.
    sim_arrive_radius: float = 1.5
    sim_p_wander: float = 0.2
    sim_p_seek: float = 0.1
    sim_p_idle: float = 0.05
    # Cap on CHANNEL-BACKED agents: up to this many agents get real
    # entity channels owned by the internal authority connection (full
    # handover/fan-out semantics). Agents beyond the cap are engine-only
    # (device-tracked, no channel data — crossings need no
    # orchestration); intended for engine-direct benches at 100K+.
    sim_channel_agents: int = 4096
    # Channel attachments performed per tick while the world boots (the
    # authority retries cells whose channels don't exist yet).
    sim_attach_per_tick: int = 256

    # Cross-gateway federation plane (new — doc/federation.md). Empty
    # config path = the plane stays disarmed and every hook is a cheap
    # no-op (the gateway is a self-contained world, the pre-federation
    # behavior). With a config, G gateways jointly host one spatial
    # world: each owns the server blocks the directory assigns it,
    # trunk links carry cross-gateway handovers (the PR 3 transactional
    # journal extended over the wire), and clients whose interest
    # anchor crosses a shard boundary are redirected with a pre-staged
    # recovery handle.
    federation_config: str = ""
    federation_gateway_id: str = ""
    federation_heartbeat_ms: int = 500
    # Heartbeats missed (as a time window) before the trunk is declared
    # down and in-flight handovers toward that peer abort back to src.
    federation_trunk_timeout_ms: int = 2500
    # One cross-gateway handover batch's prepare->ack deadline; a batch
    # past it aborts (restore to src) even on a live trunk.
    federation_handover_timeout_ms: int = 3000
    # Reconnect backoff: base * 2^attempt, capped, +-20% jitter
    # (federation/trunk.py backoff_schedule — unit-tested).
    federation_reconnect_base_ms: int = 100
    federation_reconnect_max_ms: int = 5000

    # Global control plane (new — doc/global_control.md). Only armed
    # when the federation plane is (it rides the trunks): each gateway
    # exports a load vector + replicates its shard state to every trunk
    # peer once per control epoch; the deterministic leader (lowest
    # live gateway id) folds the vectors into a fleet max/mean
    # imbalance and plans per-cell cross-gateway shard migrations with
    # the balancer's guard discipline (hysteresis, budget, cooldown,
    # improvement, hard veto at overload L2+); a gateway whose trunks
    # stay silent past the miss threshold is declared dead by the
    # leader and its shard is adopted by the least-loaded survivor from
    # the epoch replica.
    global_control_enabled: bool = True
    global_epoch_ms: int = 500
    global_imbalance_enter: float = 1.5
    global_imbalance_exit: float = 1.2
    global_hold_epochs: int = 3
    # Committed shard migrations allowed per budget window, and the
    # window itself (in control epochs).
    global_budget_per_window: int = 2
    global_budget_window_epochs: int = 20
    # Per-cell re-migration lockout after a terminal plan, in epochs.
    global_cooldown_epochs: int = 20
    # Hottest-coldest per-gateway entity gap below which the fleet is
    # too small/even to be worth moving shards around.
    global_min_entity_delta: int = 8
    # Consecutive epochs a peer's trunk must stay down before the
    # leader declares it dead and reassigns its shard.
    global_death_miss_epochs: int = 4
    # One shard-migration plan's leader-side deadline (plan -> terminal
    # TrunkMigrateStatus), and the adoption census handshake's wait for
    # survivor claims.
    global_migrate_timeout_ms: int = 8000
    global_adopt_claims_timeout_ms: int = 750

    # Device supervision & in-process engine recovery (new —
    # doc/device_recovery.md). The device step runs under a watchdog:
    # the guarded step is dispatched to a dedicated worker thread and
    # the tick waits at most ``device_step_deadline_s`` (the jax call
    # blocks, so hang detection must be off-thread). Transient step
    # errors retry with exponential backoff up to ``device_retry_max``
    # attempts; a hang, a sentinel-detected corruption, or an exhausted
    # retry budget is FATAL and triggers an in-process engine rebuild
    # from the host-side shadow (entity registry, query params, sub
    # intervals, placement ledger), verified bit-identical before the
    # gateway resumes device service. While the engine is down the
    # gateway degrades instead of dying: device-dependent work is held
    # and the overload ladder is pinned to L2+.
    device_guard_enabled: bool = True
    device_step_deadline_s: float = 2.0
    device_retry_max: int = 2
    device_retry_backoff_ms: int = 100
    # Operator bound on one full recovery (failure detect -> verified
    # rebuilt engine serving again); overruns warn and fail soaks — a
    # slow recovery still beats a dead gateway.
    device_recovery_deadline_s: float = 10.0

    # Flight recorder (new — doc/observability.md). Always-on by
    # default: the recorder is fixed-memory (per-thread span rings) and
    # its hot-path cost is two clock reads + a ring store per tick
    # stage (<3% of the tick hot path, measured in TRACE_r11.json).
    # Disabling it only stops span recording and anomaly auto-dumps;
    # the tick_stage_ms histograms keep moving either way.
    trace_enabled: bool = True
    trace_ring_spans: int = 8192  # spans kept per writer thread
    trace_dump_ticks: int = 200  # ticks frozen into an anomaly dump
    trace_anomaly_cooldown_s: float = 5.0

    # Fleet health plane (new — doc/observability.md). With the SLO
    # plane armed, forwarded updates carry a monotonic ingest stamp to
    # the fan-out send (delivery_latency_ms — the live measurement
    # behind the < 5ms p99 claim), a declarative SLO table (delivery
    # p99, tick budget, trunk RTT, WAL fsync RPO) is evaluated
    # in-process with multi-window burn rates every GLOBAL tick, each
    # breach freezes a flight-recorder slo_breach anomaly dump, and
    # federated gateways attach a metric digest to the control-epoch
    # load report so any gateway's /fleet endpoint shows the whole
    # fleet in one scrape. Soaks with deterministic envelopes pin the
    # plane off (their accounting predates the extra samples).
    slo_enabled: bool = True
    # Operator SLO table (JSON list of core/slo.py SloSpec rows);
    # empty = the built-in defaults.
    slo_config: str = ""

    # Runtime thread-affinity assertions (doc/concurrency.md): the
    # static thread model's runtime twin. Off in production by default
    # (hooks cost one attribute load); tier-1 arms it for the whole
    # run via tests/conftest.py, and -debug-affinity arms it on a live
    # gateway (violations are recorded + warned, never raised).
    debug_affinity: bool = False

    # Device mesh for the spatial engine: 0 devices = single-device step;
    # N>0 shards the entity arrays over the first N jax devices, and
    # hosts>1 arranges them as a (hosts, chips) DCN x ICI mesh — the TPU
    # equivalent of the reference's multi-server spatial world
    # (ref: spatial.go:387-590).
    tpu_mesh_devices: int = 0
    tpu_mesh_hosts: int = 1

    def effective_auth_deadline_ms(self) -> int:
        """The auth-window reap deadline the edge plane enforces:
        -auth-deadline when set, else the reference -cat knob."""
        if self.auth_deadline_ms > 0:
            return self.auth_deadline_ms
        return self.connection_auth_timeout_ms

    def get_channel_settings(self, ct: ChannelType) -> ChannelSettings:
        # By-value copy, like the Go struct return — mutating the result
        # must not silently retune another channel type's settings.
        st = self.channel_settings_view(ct)
        return replace(st, acl=replace(st.acl))

    def channel_settings_view(self, ct: ChannelType) -> ChannelSettings:
        """Read-only view (no defensive copy): for hot paths that only
        READ settings — the copying form is two dataclasses.replace per
        call, visible at handover-batch rates. Callers must not mutate."""
        st = self.channel_settings.get(ct)
        if st is None:
            st = self.channel_settings.get(ChannelType.GLOBAL)
            if st is None:
                st = ChannelSettings()
        return st

    def load_channel_settings(self, path: str) -> None:
        """Load the reference-schema channel settings JSON (keys = numeric type)."""
        with open(path) as f:
            raw = json.load(f)
        for key, val in raw.items():
            self.channel_settings[ChannelType(int(key))] = ChannelSettings.from_dict(val)

    def parse_flags(self, argv: Optional[list[str]] = None) -> None:
        """CLI flags, names matching the reference (ref: settings.go:144-235)."""
        # allow_abbrev=False: Go's flag package (which the reference CLI
        # uses) never prefix-matches, and abbreviation lets a typo like
        # `-imp x` silently bind to -imports.
        p = argparse.ArgumentParser(
            prog="channeld-tpu", add_help=True, allow_abbrev=False
        )
        p.add_argument("-dev", action="store_true", help="run in development mode")
        p.add_argument("-loglevel", type=int, default=None,
                       help="-1 Debug, 0 Info, 1 Warn, 2 Error")
        p.add_argument("-logfile", type=str, default=None)
        p.add_argument("-profile", type=str, default="",
                       help="cpu | mem | tpu | tasks (process profile, "
                            "device trace, or asyncio task dump)")
        p.add_argument("-profilepath", type=str, default=self.profile_path)
        p.add_argument("-sn", type=str, default=self.server_network,
                       help="server network type: tcp | ws")
        p.add_argument("-sa", type=str, default=self.server_address)
        p.add_argument("-srb", type=int, default=self.server_read_buffer_size)
        p.add_argument("-swb", type=int, default=self.server_write_buffer_size)
        p.add_argument("-sfsm", type=str, default=self.server_fsm)
        p.add_argument("-sba", type=lambda s: s.lower() != "false",
                       default=self.server_bypass_auth,
                       help="server bypasses authentication")
        p.add_argument("-scr", action="store_true",
                       help="server connections recoverable")
        p.add_argument("-scrt", type=int, default=self.server_conn_recover_timeout_ms)
        p.add_argument("-cwm", type=lambda s: s.lower() != "false",
                       default=self.client_network_wait_master_server)
        p.add_argument("-cn", type=str, default=self.client_network)
        p.add_argument("-ca", type=str, default=self.client_address)
        p.add_argument("-crb", type=int, default=self.client_read_buffer_size)
        p.add_argument("-cwb", type=int, default=self.client_write_buffer_size)
        p.add_argument("-cfsm", type=str, default=self.client_fsm)
        p.add_argument("-erp", action="store_true",
                       help="record packets sent from clients")
        p.add_argument("-rspd", type=str, default="")
        p.add_argument("-ct", type=int, default=0, help="0 = none, 1 = snappy")
        p.add_argument("-scc", type=str, default=None,
                       help="spatial controller config JSON path")
        p.add_argument("-scs", type=int, default=self.spatial_channel_id_start)
        p.add_argument("-ecs", type=int, default=self.entity_channel_id_start)
        p.add_argument("-mcb", type=int, default=self.max_connection_id_bits)
        p.add_argument("-cat", type=int, default=self.connection_auth_timeout_ms)
        p.add_argument("-mfaa", type=int, default=self.max_failed_auth_attempts)
        p.add_argument("-mfd", type=int, default=self.max_fsm_disallowed)
        p.add_argument("-chs", type=str, default="config/channel_settings_hifi.json")
        p.add_argument("-imports", type=str, default="",
                       help="comma-separated Python modules providing game "
                            "protobuf types (e.g. mygame.data_pb2)")
        p.add_argument("-snapshot", type=str, default="",
                       help="path for periodic gateway state snapshots; "
                            "restored at boot when present")
        p.add_argument("-mport", type=int, default=self.metrics_port,
                       help="Prometheus /metrics port (0 disables)")
        p.add_argument("-wal", type=str, default="",
                       help="path for the durable write-ahead journal "
                            "(doc/persistence.md); replayed over the "
                            "snapshot at boot, truncated by each "
                            "snapshot write; empty disables")
        p.add_argument("-wal-fsync-ms", type=float,
                       default=self.wal_fsync_ms,
                       help="WAL fsync batch window (off-thread writer; "
                            "the RPO of a kill -9)")
        p.add_argument("-snapshot-interval", type=float,
                       default=self.snapshot_interval_s)
        p.add_argument("-spatial-backend", type=str, default=self.spatial_backend,
                       choices=("host", "tpu"),
                       help="where the AOI/fan-out decision pass runs")
        p.add_argument("-chaos", type=str, default="",
                       help="chaos scenario JSON path; arms deterministic "
                            "fault injection (doc/chaos.md)")
        p.add_argument("-overload",
                       type=lambda s: s.lower() not in
                       ("false", "0", "no", "off"),
                       default=self.overload_enabled,
                       help="adaptive overload-control ladder "
                            "(doc/overload.md); false pins L0")
        p.add_argument("-overload-retry-after", type=int,
                       default=self.overload_retry_after_ms,
                       help="retry-after (ms) in L3 ServerBusyMessage "
                            "admission refusals")
        p.add_argument("-overload-down-hold", type=float,
                       default=self.overload_down_hold_s,
                       help="seconds the pressure must hold under the exit "
                            "threshold before the ladder steps down")
        p.add_argument("-failover",
                       type=lambda s: s.lower() not in
                       ("false", "0", "no", "off"),
                       default=self.failover_enabled,
                       help="re-host a dead server's spatial cells onto "
                            "surviving servers (doc/failover.md); false "
                            "leaves them ownerless")
        p.add_argument("-failover-deadline", type=float,
                       default=self.failover_rehost_deadline_s,
                       help="seconds one failover pass may take before "
                            "the overrun is logged as a warning")
        p.add_argument("-balancer",
                       type=lambda s: s.lower() not in
                       ("false", "0", "no", "off"),
                       default=self.balancer_enabled,
                       help="live spatial load balancer: planned "
                            "zero-loss cell migration between live "
                            "servers (doc/balancer.md); false pins the "
                            "static placement")
        p.add_argument("-balancer-imbalance", type=float,
                       default=self.balancer_imbalance_enter,
                       help="max/mean per-server load ratio above which "
                            "a migration is planned (exit threshold "
                            "stays at its default unless retuned in "
                            "code)")
        p.add_argument("-balancer-budget", type=int,
                       default=self.balancer_budget_per_epoch,
                       help="committed migrations allowed per epoch "
                            "(epoch = balancer_epoch_ticks GLOBAL "
                            "ticks)")
        p.add_argument("-balancer-cooldown", type=int,
                       default=self.balancer_cooldown_ticks,
                       help="GLOBAL ticks a migrated cell is locked out "
                            "of re-migration (anti-oscillation)")
        p.add_argument("-partition",
                       type=lambda s: s.lower() not in
                       ("false", "0", "no", "off"),
                       default=self.partition_enabled,
                       help="adaptive partitioning: live quadtree cell "
                            "split/merge under extreme density "
                            "(doc/partitioning.md); false pins the "
                            "static grid geometry")
        p.add_argument("-partition-split", type=int,
                       default=self.partition_split_entities,
                       help="resident entities at/above which a cell is "
                            "planned for a split")
        p.add_argument("-partition-merge", type=int,
                       default=self.partition_merge_entities,
                       help="sibling-group total at/below which a merge "
                            "is planned")
        p.add_argument("-partition-depth", type=int,
                       default=self.partition_max_depth,
                       help="max quadtree split depth (id space for all "
                            "depths is validated against the entity "
                            "channel id start)")
        p.add_argument("-partition-budget", type=int,
                       default=self.partition_budget_per_epoch,
                       help="committed geometry ops allowed per epoch "
                            "(epoch = partition_epoch_ticks GLOBAL "
                            "ticks)")
        p.add_argument("-queryplane",
                       type=lambda s: s.lower() not in
                       ("false", "0", "no", "off"),
                       default=self.queryplane_enabled,
                       help="device standing-query plane: followers, "
                            "client AOI queries and server sensors "
                            "evaluated in one batched device pass per "
                            "tick (doc/query_engine.md); false keeps "
                            "the per-follower host readback path")
        p.add_argument("-queryplane-rows", type=int,
                       default=self.queryplane_rows_max,
                       help="changed (query, cell, dist) rows budget per "
                            "tick; overflow re-emits next tick")
        p.add_argument("-queryplane-max-spots", type=int,
                       default=self.queryplane_max_spots,
                       help="max spots per client spots query; larger "
                            "lists are rejected as malformed")
        p.add_argument("-sim",
                       type=lambda s: s.lower() not in
                       ("false", "0", "no", "off"),
                       default=self.sim_enabled,
                       help="device simulation plane: a server-driven "
                            "agent population stepped on device inside "
                            "the guarded spatial tick "
                            "(doc/simulation.md); agents are ordinary "
                            "entities to every other plane")
        p.add_argument("-sim-agents", type=int, default=self.sim_agents,
                       help="population spawned at controller load "
                            "(a WAL-replayed census wins over this)")
        p.add_argument("-sim-seed", type=int, default=self.sim_seed,
                       help="counter-based RNG seed: same seed + tick "
                            "count = bit-exact trajectories")
        p.add_argument("-sim-census", type=int,
                       default=self.sim_census_every_ticks,
                       help="census cadence in sim passes: the plane's "
                            "only device readback, folded to the host "
                            "shadow + WAL + authority path")
        p.add_argument("-fed", type=str, default="",
                       help="federation config JSON path (shard directory "
                            "+ trunk addresses, doc/federation.md); empty "
                            "disables the federation plane")
        p.add_argument("-fed-id", type=str, default="",
                       help="this gateway's id in the federation config")
        p.add_argument("-global-control",
                       type=lambda s: s.lower() not in
                       ("false", "0", "no", "off"),
                       default=self.global_control_enabled,
                       help="federation-level control plane: cross-"
                            "gateway shard rebalancing + gateway-death "
                            "failover (doc/global_control.md); false "
                            "pins the static shard map")
        p.add_argument("-global-epoch-ms", type=int,
                       default=self.global_epoch_ms,
                       help="control-epoch cadence: load-vector export, "
                            "shard replication, leader planning")
        p.add_argument("-global-imbalance", type=float,
                       default=self.global_imbalance_enter,
                       help="max/mean per-gateway load ratio above which "
                            "the leader plans a shard migration")
        p.add_argument("-global-death-epochs", type=int,
                       default=self.global_death_miss_epochs,
                       help="consecutive control epochs a trunk must "
                            "stay down before the leader declares the "
                            "gateway dead and re-hosts its shard")
        p.add_argument("-device-guard",
                       type=lambda s: s.lower() not in
                       ("false", "0", "no", "off"),
                       default=self.device_guard_enabled,
                       help="device watchdog + in-process engine "
                            "recovery (doc/device_recovery.md); false "
                            "runs the device step unguarded")
        p.add_argument("-device-deadline", type=float,
                       default=self.device_step_deadline_s,
                       help="seconds one guarded device step may take "
                            "before it is declared hung (fatal; the "
                            "engine rebuilds from the host shadow)")
        p.add_argument("-device-recovery-deadline", type=float,
                       default=self.device_recovery_deadline_s,
                       help="seconds one full device recovery (failure "
                            "detect -> verified rebuild) may take "
                            "before the overrun is logged as a warning")
        p.add_argument("-trace",
                       type=lambda s: s.lower() not in
                       ("false", "0", "no", "off"),
                       default=self.trace_enabled,
                       help="flight-recorder span recording + anomaly "
                            "auto-dumps (doc/observability.md); false "
                            "keeps only the tick_stage_ms histograms")
        p.add_argument("-trace-ring", type=int,
                       default=self.trace_ring_spans,
                       help="spans kept per writer thread (fixed memory; "
                            "overflow drops the oldest, counted exactly)")
        p.add_argument("-trace-dump-ticks", type=int,
                       default=self.trace_dump_ticks,
                       help="GLOBAL ticks frozen into an anomaly dump")
        p.add_argument("-slo",
                       type=lambda s: s.lower() not in
                       ("false", "0", "no", "off"),
                       default=self.slo_enabled,
                       help="delivery-SLO plane: ingest->fan-out "
                            "latency stamping, burn-rate tracking, "
                            "breach anomaly dumps, fleet metric "
                            "digests (doc/observability.md); false "
                            "disarms every hook")
        p.add_argument("-slo-config", type=str, default=self.slo_config,
                       help="JSON SLO table overriding the built-in "
                            "defaults (core/slo.py SloSpec rows)")
        p.add_argument("-edge",
                       type=lambda s: s.lower() not in
                       ("false", "0", "no", "off"),
                       default=self.edge_enabled,
                       help="adversarial edge plane: per-connection "
                            "resource envelopes, slow-consumer "
                            "quarantine, ingress caps "
                            "(doc/edge_hardening.md); false disarms "
                            "every bound")
        p.add_argument("-edge-queue-msgs", type=int,
                       default=self.edge_send_queue_max_msgs,
                       help="per-connection egress queue entry cap")
        p.add_argument("-edge-queue-bytes", type=int,
                       default=self.edge_send_queue_max_bytes,
                       help="per-connection egress queue byte cap")
        p.add_argument("-edge-frame-rate", type=int,
                       default=self.edge_max_frame_rate,
                       help="per-connection inbound frames/s cap "
                            "(0 disables)")
        p.add_argument("-auth-deadline", type=int,
                       default=self.auth_deadline_ms,
                       help="ms a socket may stay unauthenticated before "
                            "it is reaped (conn_reaped_total); 0 "
                            "inherits -cat")
        p.add_argument("-debug-affinity",
                       type=lambda s: s.lower() not in
                       ("false", "0", "no", "off"),
                       default=self.debug_affinity,
                       help="arm runtime thread-affinity assertions "
                            "(doc/concurrency.md): violations of the "
                            "declared thread model are recorded and "
                            "logged at warning")
        p.add_argument("-mesh-devices", type=int, default=self.tpu_mesh_devices,
                       help="shard the spatial engine over N devices "
                            "(0 = single-device step)")
        p.add_argument("-mesh-hosts", type=int, default=self.tpu_mesh_hosts,
                       help="arrange the mesh devices as (hosts, chips)")
        args = p.parse_args(argv)

        self.development = args.dev
        self.log_level = args.loglevel
        self.log_file = args.logfile
        self.profile = args.profile
        self.profile_path = args.profilepath
        self.server_network = args.sn
        self.server_address = args.sa
        self.server_read_buffer_size = args.srb
        self.server_write_buffer_size = args.swb
        self.server_fsm = args.sfsm
        self.server_bypass_auth = args.sba
        self.server_conn_recoverable = args.scr
        self.server_conn_recover_timeout_ms = args.scrt
        self.client_network_wait_master_server = args.cwm
        self.client_network = args.cn
        self.client_address = args.ca
        self.client_read_buffer_size = args.crb
        self.client_write_buffer_size = args.cwb
        self.client_fsm = args.cfsm
        self.enable_record_packet = args.erp
        self.replay_session_persistence_dir = args.rspd
        self.compression_type = CompressionType(args.ct)
        self.spatial_controller_config = args.scc
        self.spatial_channel_id_start = args.scs
        self.entity_channel_id_start = args.ecs
        self.max_connection_id_bits = args.mcb
        self.connection_auth_timeout_ms = args.cat
        self.max_failed_auth_attempts = args.mfaa
        self.max_fsm_disallowed = args.mfd
        self.chaos_config = args.chaos
        self.overload_enabled = args.overload
        self.overload_retry_after_ms = args.overload_retry_after
        self.overload_down_hold_s = args.overload_down_hold
        self.failover_enabled = args.failover
        self.failover_rehost_deadline_s = args.failover_deadline
        self.balancer_enabled = args.balancer
        self.balancer_imbalance_enter = args.balancer_imbalance
        # The flag only moves the ENTER threshold; keep the exit strictly
        # below it or the two-sided hysteresis band inverts (armed one
        # tick, disarmed the next, forever).
        self.balancer_imbalance_exit = min(
            self.balancer_imbalance_exit, args.balancer_imbalance * 0.8
        )
        self.balancer_budget_per_epoch = args.balancer_budget
        self.balancer_cooldown_ticks = args.balancer_cooldown
        self.partition_enabled = args.partition
        self.partition_split_entities = args.partition_split
        # Keep the merge threshold strictly under the split threshold or
        # the two-sided density hysteresis band inverts (split one
        # epoch, merge the next, forever).
        self.partition_merge_entities = min(
            args.partition_merge, args.partition_split // 2,
        )
        self.partition_max_depth = args.partition_depth
        self.partition_budget_per_epoch = args.partition_budget
        self.queryplane_enabled = args.queryplane
        self.queryplane_rows_max = args.queryplane_rows
        self.queryplane_max_spots = args.queryplane_max_spots
        self.sim_enabled = args.sim
        self.sim_agents = args.sim_agents
        self.sim_seed = args.sim_seed
        self.sim_census_every_ticks = args.sim_census
        self.federation_config = args.fed
        self.federation_gateway_id = args.fed_id
        self.global_control_enabled = args.global_control
        self.global_epoch_ms = args.global_epoch_ms
        self.global_imbalance_enter = args.global_imbalance
        # Same hysteresis-band guard as the balancer flag: the exit
        # threshold must stay strictly under the enter threshold.
        self.global_imbalance_exit = min(
            self.global_imbalance_exit, args.global_imbalance * 0.85
        )
        self.global_death_miss_epochs = args.global_death_epochs
        self.device_guard_enabled = args.device_guard
        self.device_step_deadline_s = args.device_deadline
        self.device_recovery_deadline_s = args.device_recovery_deadline
        self.trace_enabled = args.trace
        self.trace_ring_spans = args.trace_ring
        self.trace_dump_ticks = args.trace_dump_ticks
        self.slo_enabled = args.slo
        self.slo_config = args.slo_config
        self.edge_enabled = args.edge
        self.edge_send_queue_max_msgs = args.edge_queue_msgs
        self.edge_send_queue_max_bytes = args.edge_queue_bytes
        self.edge_max_frame_rate = args.edge_frame_rate
        self.auth_deadline_ms = args.auth_deadline
        self.debug_affinity = args.debug_affinity
        self.spatial_backend = args.spatial_backend
        self.tpu_mesh_devices = args.mesh_devices
        self.tpu_mesh_hosts = args.mesh_hosts
        self.snapshot_path = args.snapshot
        self.snapshot_interval_s = args.snapshot_interval
        self.wal_path = args.wal
        self.wal_fsync_ms = args.wal_fsync_ms
        self.metrics_port = args.mport
        self.import_modules = [m for m in args.imports.split(",") if m]
        self.load_channel_settings(args.chs)


# The process-wide settings instance (ref: settings.go ``GlobalSettings``).
global_settings = GlobalSettings()


def reset_global_settings() -> None:
    """Test hook: restore defaults."""
    global global_settings
    fresh = GlobalSettings()
    for f in fresh.__dataclass_fields__:
        setattr(global_settings, f, getattr(fresh, f))

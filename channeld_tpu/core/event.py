"""Typed in-process event bus.

Capability parity with the reference event system
(ref: pkg/channeld/event.go:40-96): Listen / ListenOnce / ListenFor /
UnlistenFor / Wait / Broadcast, plus the set of global events declared
in event.go:10-31. Handlers run synchronously in broadcast order;
``wait()`` integrates with asyncio.
"""

from __future__ import annotations

import asyncio
from typing import Any, Callable, Generic, Optional, TypeVar

T = TypeVar("T")


class Event(Generic[T]):
    def __init__(self, name: str = ""):
        self.name = name
        # list of (owner, handler, once)
        self._handlers: list[tuple[Any, Callable[[T], None], bool]] = []
        self._waiters: list[asyncio.Future] = []

    def listen(self, handler: Callable[[T], None]) -> Callable[[T], None]:
        self._handlers.append((None, handler, False))
        return handler

    def listen_once(self, handler: Callable[[T], None]) -> None:
        self._handlers.append((None, handler, True))

    def listen_for(self, owner: Any, handler: Callable[[T], None]) -> None:
        self._handlers.append((owner, handler, False))

    def unlisten(self, handler: Callable[[T], None]) -> None:
        self._handlers = [h for h in self._handlers if h[1] is not handler]

    def unlisten_for(self, owner: Any) -> None:
        self._handlers = [h for h in self._handlers if h[0] is not owner]

    def broadcast(self, data: T) -> None:
        # Snapshot so handlers may (un)register during the broadcast; only
        # once-handlers that actually fired are pruned.
        fired = list(self._handlers)
        for owner, handler, once in fired:
            handler(data)
        fired_once = {id(h) for h in fired if h[2]}
        self._handlers = [h for h in self._handlers if id(h) not in fired_once]
        waiters, self._waiters = self._waiters, []
        for fut in waiters:
            if not fut.done():
                fut.set_result(data)

    async def wait(self, timeout: Optional[float] = None) -> T:
        """Await the next broadcast of this event."""
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._waiters.append(fut)
        if timeout is None:
            return await fut
        return await asyncio.wait_for(fut, timeout)

    def handler_count(self) -> int:
        return len(self._handlers)

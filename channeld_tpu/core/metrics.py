"""Prometheus metrics (ref: pkg/channeld/metrics.go:7-131).

Same metric families as the reference — message/packet/byte rates in and
out, dropped/fragmented/combined packets, live connection and channel
gauges, per-channel-type tick duration — plus new TPU decision-plane
metrics (device step latency, AOI batch size).
"""

from __future__ import annotations

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    start_http_server,
)

registry = CollectorRegistry()

msg_received = Counter(
    "messages_in", "Messages received", ["conn_type", "channel_type", "msg_type"],
    registry=registry,
)
msg_sent = Counter(
    "messages_out", "Messages sent", ["conn_type", "channel_type", "msg_type"],
    registry=registry,
)
packet_received = Counter(
    "packets_in", "Packets received", ["conn_type"], registry=registry
)
packet_sent = Counter("packets_out", "Packets sent", ["conn_type"], registry=registry)
bytes_received = Counter("bytes_in", "Bytes received", ["conn_type"], registry=registry)
bytes_sent = Counter("bytes_out", "Bytes sent", ["conn_type"], registry=registry)
packet_dropped = Counter(
    "packets_drop", "Dropped packets", ["conn_type"], registry=registry
)
packet_fragmented = Counter(
    "packets_frag", "Partially-read packets", ["conn_type"], registry=registry
)
packet_combined = Counter(
    "packets_comb", "Messages combined into one packet", ["conn_type"],
    registry=registry,
)
connection_num = Gauge(
    "connection_num", "Live connections", ["conn_type"], registry=registry
)
channel_num = Gauge("channel_num", "Live channels", ["channel_type"], registry=registry)
connection_closed = Counter(
    "connection_closed", "Connections closed", ["conn_type"], registry=registry
)
channel_tick_duration = Histogram(
    "channel_tick_duration",
    "Channel tick duration",
    ["channel_type"],
    buckets=(0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5),
    registry=registry,
)
fanout_decision_latency = Histogram(
    "fanout_decision_latency_seconds",
    "Latency of one fan-out decision pass (host or device)",
    ["backend"],
    buckets=(0.0001, 0.0005, 0.001, 0.002, 0.005, 0.01, 0.033, 0.1),
    registry=registry,
)
log_events = Counter("logs", "Warn+ log records", ["level"], registry=registry)

# TPU decision plane (new).
tpu_step_latency = Histogram(
    "tpu_spatial_step_seconds",
    "Device AOI/fan-out step latency incl. transfers",
    buckets=(0.0005, 0.001, 0.002, 0.005, 0.01, 0.02, 0.033, 0.1),
    registry=registry,
)
tpu_entities = Gauge("tpu_entities", "Entities resident on device", registry=registry)
tpu_cell_overflow = Gauge(
    "tpu_cell_overflow",
    "Entities whose cells-plane redistribution bucket was full last tick "
    "(re-offered next tick)",
    registry=registry,
)
tpu_cell_overflow_total = Counter(
    "tpu_cell_overflow_entities",
    "Cumulative entities whose cells-plane redistribution bucket was full "
    "(each was re-offered the next tick; the gauge above is the last-tick "
    "snapshot, this counter is the soak-visible total)",
    registry=registry,
)
tpu_capacity_shed = Counter(
    "tpu_capacity_shed",
    "Device-plane registrations shed to the host path at capacity",
    ["table"],
    registry=registry,
)
handover_count = Counter(
    "handovers",
    "Cross-cell entity handovers orchestrated",
    registry=registry,
)
# Robustness plane (chaos + recovery + sidecar hardening).
chaos_faults = Counter(
    "chaos_faults",
    "Faults injected by the chaos layer (only moves while a scenario is "
    "armed; see channeld_tpu.chaos)",
    ["point"],
    registry=registry,
)
connection_recovered = Counter(
    "connection_recovered",
    "Recoverable server connections that reclaimed their previous id",
    registry=registry,
)
recover_handles_evicted = Counter(
    "recover_handles_evicted",
    "Recovery handles evicted at the table cap (oldest-first)",
    registry=registry,
)
sidecar_call_retries = Counter(
    "sidecar_call_retries",
    "gRPC sidecar calls retried after a transient failure",
    ["method"],
    registry=registry,
)

# Failover plane (core/failover.py; doc/failover.md).
ownerless_drops = Counter(
    "ownerless_drops",
    "Updates dropped because the target channel has no owner connection "
    "(previously only a rate-limited warn log); a sustained non-zero rate "
    "on SPATIAL/ENTITY channels means a dead server's cells were never "
    "re-hosted",
    ["channel_type"],
    registry=registry,
)
server_lost = Counter(
    "server_lost",
    "Recoverable server connections declared dead for good (recovery "
    "window expired or handle evicted); one ServerLostEvent fires per "
    "increment",
    registry=registry,
)
failover_rehost = Counter(
    "failover_rehost",
    "Orphaned spatial cells re-hosted onto surviving servers after a "
    "permanent server loss",
    registry=registry,
)
failover_rehost_ms = Histogram(
    "failover_rehost_ms",
    "Duration of one failover pass (ServerLostEvent -> every orphaned "
    "cell re-hosted and every orphaned entity channel re-pointed), "
    "milliseconds",
    buckets=(0.5, 1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0, 500.0),
    registry=registry,
)
handover_journal = Counter(
    "handover_journal",
    "Transactional handover-journal records by terminal state "
    "(prepared == committed + aborted once the gateway quiesces; the "
    "python-side ledger in core/failover.py must match exactly)",
    ["state"],
    registry=registry,
)

# Live spatial load balancer (spatial/balancer.py; doc/balancer.md).
spatial_cell_entities = Gauge(
    "spatial_cell_entities",
    "Entities resident in one spatial cell's authoritative data "
    "(sampled once per GLOBAL tick by the balancer's load pass)",
    ["cell"],
    registry=registry,
)
spatial_cell_crossings = Counter(
    "spatial_cell_crossings",
    "Entity handovers orchestrated touching one spatial cell "
    "(direction=out: the cell was the crossing's src; direction=in: its "
    "dst) — the balancer's crossing-rate signal, fed from the tick "
    "loop's handover orchestration",
    ["cell", "direction"],
    registry=registry,
)
balancer_migrations = Counter(
    "balancer_migrations",
    "Planned live-cell migrations by terminal result (committed: owner "
    "flipped, zero loss; aborted: deterministic rollback to the old "
    "owner — dst died, drain timed out, overload outranked, or the "
    "world changed underneath; vetoed: never planned because the "
    "destination or the gateway sat at overload L2+; python ledger in "
    "spatial/balancer.py must match exactly)",
    ["result"],
    registry=registry,
)
balancer_migration_ms = Histogram(
    "balancer_migration_ms",
    "Duration of one planned cell migration, freeze -> commit/abort, "
    "milliseconds (includes the crossing-drain window)",
    buckets=(5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0, 1000.0, 5000.0),
    registry=registry,
)
balancer_imbalance = Gauge(
    "balancer_imbalance",
    "Per-server load imbalance (max/mean of the entity+crossing+bytes+"
    "pressure fold; 1.0 == perfectly even; the balancer plans a "
    "migration when this holds above the enter threshold)",
    registry=registry,
)

# Adaptive partitioning plane (spatial/partition.py; doc/partitioning.md).
spatial_cell_depth = Gauge(
    "spatial_cell_depth",
    "Quadtree depth of one live leaf cell (0 == base grid; published "
    "for every live leaf each governor evaluation, zeroed when the "
    "leaf is split away or merged back)",
    ["cell"],
    registry=registry,
)
partition_ops = Counter(
    "partition_ops",
    "Adaptive-partitioning geometry operations by terminal result "
    "(op=split|merge; result=committed: geometry epoch advanced, "
    "entities repartitioned zero-loss; aborted: deterministic rollback "
    "— drain timeout, owner loss, or overload outranked; vetoed: never "
    "planned because the overload ladder sat at L2+ or the depth/"
    "in-flight guards refused; python ledger in spatial/partition.py "
    "must match exactly)",
    ["op", "result"],
    registry=registry,
)
partition_geometry_epoch = Gauge(
    "partition_geometry_epoch",
    "Monotonic cell-geometry epoch (bumps on every committed split/"
    "merge and every adopted remote geometry; 0 == boot static grid)",
    registry=registry,
)
partition_device_rebuilds = Counter(
    "partition_device_rebuilds",
    "Device micro-grid rebuilds triggered by geometry epochs whose max "
    "active depth changed (result=verified: rebuilt arrays bit-identical "
    "to the host shadow; mismatch: verify_device_state found divergence "
    "— flight recorder force-dumps)",
    ["result"],
    registry=registry,
)

# Cross-gateway federation plane (channeld_tpu/federation;
# doc/federation.md).
federation_handover = Counter(
    "federation_handover",
    "Cross-gateway handover batches by terminal result. Initiator side: "
    "committed (remote ack, src copy torn down), aborted (trunk loss / "
    "timeout / remote refusal — entities restored to the src cell), "
    "refused (the abort was a remote L3 ServerBusy refusal; also counted "
    "in aborted's restore path ledger). Receiver side: applied (entities "
    "adopted into the local shard), refused_remote (local L3 refused the "
    "prepare), reconciled (an applied batch purged after the initiator's "
    "abort notice — source-wins). The python ledger in "
    "federation/plane.py must match exactly",
    ["result"],
    registry=registry,
)
trunk_msgs = Counter(
    "trunk_msgs",
    "Messages crossing gateway<->gateway trunk links (direction=out "
    "counts post-chaos egress, i.e. frames actually written)",
    ["direction"],
    registry=registry,
)
redirects = Counter(
    "redirects",
    "ClientRedirectMessages issued (one per client steered to the "
    "gateway now hosting its interest anchor; staged recovery handle "
    "confirmed by the destination before each send; the python ledger "
    "in federation/plane.py must match exactly)",
    registry=registry,
)
trunk_rtt_ms = Histogram(
    "trunk_rtt_ms",
    "Trunk heartbeat round-trip time, milliseconds",
    buckets=(0.5, 1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0, 500.0),
    registry=registry,
)

# Global control plane (federation/control.py; doc/global_control.md).
global_migrations = Counter(
    "global_migrations",
    "Leader-planned cross-gateway shard migrations by result "
    "(planned: a plan was opened — every plan also lands exactly one "
    "terminal committed/aborted/refused, so sum terminal labels, not "
    "the whole family; committed: the cell's residents drained to the "
    "destination "
    "gateway over the trunk and the source copy was torn down; "
    "aborted: the drain never completed — trunk loss, deadline, or the "
    "world changed — and the directory override reverted to the "
    "source; refused: the destination refused the drain at overload "
    "L3; vetoed: never planned because the overload ladder sat at L2+ "
    "on either end. Counted on the LEADER that owns the plan; the "
    "python ledger in federation/control.py must match exactly)",
    ["result"],
    registry=registry,
)
gateway_adoptions = Counter(
    "gateway_adoptions",
    "Dead gateways whose shard this gateway adopted (cell channels "
    "recreated from the trunk-replicated epoch snapshot, in-flight "
    "journal records replayed source-wins, staged recovery handles "
    "re-staged so redirected clients resume without re-auth); the "
    "python ledger in federation/control.py must match exactly",
    registry=registry,
)
gateway_deaths = Counter(
    "gateway_deaths",
    "Gateway-death declarations processed on this gateway (the leader "
    "declares after global_death_miss_epochs of trunk silence; every "
    "survivor counts the TrunkGatewayDeadMessage it acted on)",
    registry=registry,
)
global_imbalance = Gauge(
    "global_imbalance",
    "Fleet-level per-gateway load imbalance (max/mean of the "
    "entities+crossings+pressure fold over every live gateway's "
    "exported load vector; 1.0 == perfectly even; leader-computed)",
    registry=registry,
)
shard_replica_entities = Gauge(
    "shard_replica_entities",
    "Entities held in trunk-replicated peer-shard snapshots on this "
    "gateway (the adoption bootstrap material; refreshed every control "
    "epoch per live peer)",
    registry=registry,
)

# Device supervision & in-process engine recovery (core/device_guard.py;
# doc/device_recovery.md).
device_state = Gauge(
    "device_state",
    "Device-engine supervision state (0 active, 1 degraded: transient "
    "step failure retrying with backoff, 2 rebuilding: fatal failure, "
    "in-process rebuild from the host shadow in progress, 3 failed: "
    "the rebuild itself failed, retrying on a backoff). Anything "
    "non-zero means device-dependent work is held and the overload "
    "ladder is pinned to L2+",
    registry=registry,
)
device_recoveries = Counter(
    "device_recoveries",
    "Device-engine recoveries completed, by the failure cause that "
    "triggered them (transient: a retried step succeeded without a "
    "rebuild; step_error: retries exhausted, engine rebuilt; hang: the "
    "watchdog deadline expired, engine rebuilt; corruption: the "
    "readback sentinel caught impossible values, engine rebuilt). The "
    "python ledger in core/device_guard.py must match exactly",
    ["cause"],
    registry=registry,
)
device_step_failures = Counter(
    "device_step_failures",
    "Guarded device-step failures observed, by cause (step_error / "
    "hang / corruption / rebuild_fail); every transient retry counts, "
    "so this moves faster than device_recoveries_total",
    ["cause"],
    registry=registry,
)
device_rebuild_ms = Histogram(
    "device_rebuild_ms",
    "Duration of one in-process engine rebuild (host-shadow re-seed + "
    "warmup + bit-identical verification), milliseconds",
    buckets=(5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0, 1000.0,
             5000.0),
    registry=registry,
)

# Durable persistence plane (core/wal.py; doc/persistence.md).
wal_records = Counter(
    "wal_records",
    "Write-ahead journal records appended, by kind (channel_state: "
    "coalesced per-tick channel images; channel_removed: tombstones; "
    "journal: handover prepare/commit/abort transitions; batch / "
    "batch_done / applied: remote-batch lifecycle; flip: placement-"
    "ledger moves; staged_handle / directory / blacklist: the non-"
    "channel durable state). The python ledger in core/wal.py "
    "(record_counts) must match exactly",
    ["kind"],
    registry=registry,
)
wal_replayed = Counter(
    "wal_replayed",
    "Write-ahead journal records applied by boot replay, by kind (the "
    "restart-side half of the wal_records double entry; torn-tail "
    "records truncated at the first bad CRC are never counted). The "
    "python ledger in core/wal.py (replay_counts) must match exactly",
    ["kind"],
    registry=registry,
)
wal_fsync_ms = Histogram(
    "wal_fsync_ms",
    "Duration of one WAL fsync batch on the off-thread writer "
    "(append() itself never blocks the tick path; this is the "
    "durability interval — RPO is one of these batches), milliseconds",
    buckets=(0.5, 1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0, 500.0),
    registry=registry,
)
resurrection = Counter(
    "resurrection",
    "Fleet resurrection-protocol outcomes (announced: a crash-restarted "
    "gateway sent its trunk hello; yielded: it learned its shard was "
    "adopted while down and handed the adopter its missing WAL-"
    "recovered entities; reclaimed: death was never declared and it "
    "kept its shard; unresolved: no peer answered by the restart "
    "deadline, ordinary zombie evacuation took over; peer_yielded / "
    "peer_reclaimed: the receiving "
    "side's count of each reply it sent). The python ledger in "
    "federation/control.py (resurrections) must match exactly",
    ["outcome"],
    registry=registry,
)
snapshot_writes = Counter(
    "snapshot_writes",
    "Periodic-snapshot loop outcomes (written: state changed and an "
    "fsync-then-rename write landed; skipped: the packed state hashed "
    "identical to the previous write — no disk traffic; failed: the "
    "write raised and will retry next interval)",
    ["result"],
    registry=registry,
)
snapshot_bytes = Gauge(
    "snapshot_bytes",
    "Serialized size of the last written gateway snapshot",
    registry=registry,
)
snapshot_ms = Histogram(
    "snapshot_ms",
    "Duration of one periodic snapshot cycle (pack + hash, plus the "
    "off-thread fsync'd write when the state changed), milliseconds",
    buckets=(0.5, 1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0, 500.0),
    registry=registry,
)

# Overload-control plane (core/overload.py; doc/overload.md).
overload_level = Gauge(
    "overload_level",
    "Current degradation-ladder level (0 normal .. 3 admission control)",
    registry=registry,
)
overload_pressure = Gauge(
    "overload_pressure",
    "Smoothed overload pressure (1.0 == saturated on the worst signal)",
    registry=registry,
)
overload_sheds = Counter(
    "overload_sheds",
    "Work shed by the overload governor (update_priority: low-priority "
    "channel updates withheld; handover_fanout: redundant handover "
    "payloads to already-subscribed dst clients skipped; "
    "handover_defer: crossings re-offered next tick; "
    "follow_interest_defer: follower-interest passes skipped; "
    "sim_cadence_defer: sim passes skipped at L2+ — the agent "
    "population halves its cadence before human traffic degrades "
    "(counted in agents held still); "
    "admission_connection / admission_subscription: L3 refusals with a "
    "ServerBusyMessage; admission_accept: raw CLIENT accepts refused at "
    "the socket past the unauthenticated-backlog headroom. The python "
    "ledger in core/overload.py (shed_counts) must match exactly)",
    ["reason"],
    registry=registry,
)
# Adversarial edge plane (core/edge.py; doc/edge_hardening.md). Every
# counter here is double-entry: the python ledger in core/edge.py
# (EdgeLedgers) must match exactly, and the abuse soak asserts it on a
# live gateway.
conn_quarantine = Counter(
    "conn_quarantine",
    "Connections quarantined by the edge plane (slow_consumer: egress "
    "held at the high watermark past the grace window even after "
    "drop-to-full-resync; ingress_flood: sustained frame-rate cap "
    "violations). Quarantine is per-peer and ends in a structured "
    "disconnect; global load shedding stays with the overload ladder. "
    "The python ledger in core/edge.py (quarantine_counts) must match "
    "exactly",
    ["reason"],
    registry=registry,
)
malformed_frames = Counter(
    "malformed_frames",
    "Inbound wire violations, counted at the stage that rejected them "
    "(framing: bad magic/length/compression tag at the frame decoder; "
    "packet: frame body failed protobuf Packet parse; message: a "
    "MessagePack body failed its template parse or hit an undefined "
    "type). Each is connection-fatal at worst, never gateway-fatal. "
    "The python ledger in core/edge.py (malformed_counts) must match "
    "exactly",
    ["stage"],
    registry=registry,
)
egress_dropped = Counter(
    "egress_dropped",
    "Send-queue entries dropped by the per-connection egress envelope "
    "(queue_msgs: entry cap hit; queue_bytes: byte cap hit; "
    "slow_consumer: queue cleared by the drop-to-full-resync step of "
    "the slow-consumer ladder; quarantine: queue discarded at "
    "quarantine entry). Every cap/ladder drop marks the connection "
    "for full-state resync on its SHED-eligible subscriptions, so a "
    "bounded queue degrades to a coarser cadence, never to silent "
    "state loss. The python ledger in core/edge.py "
    "(egress_drop_counts) must match exactly",
    ["reason"],
    registry=registry,
)
conn_reaped = Counter(
    "conn_reaped",
    "Sockets reaped by edge deadlines (auth_timeout: never completed "
    "the FSM handshake within the auth window — recovery-handle "
    "reconnects exempt; quarantine: the quarantine grace expired and "
    "the peer was disconnected; send_buffer: the MAX_SEND_BUFFER "
    "backstop aborted a peer whose transport backlog outran even the "
    "flush gate). The python ledger in core/edge.py (reap_counts) "
    "must match exactly",
    ["reason"],
    registry=registry,
)
conn_quarantined_num = Gauge(
    "conn_quarantined_num",
    "Connections currently in quarantine (egress frozen, awaiting the "
    "structured disconnect deadline)",
    registry=registry,
)

follower_interest_ms = Histogram(
    "follower_interest_ms",
    "Host cost of one _apply_follow_interests pass, milliseconds "
    "(the previously-unmeasured share of the GLOBAL tick budget)",
    buckets=(0.05, 0.1, 0.5, 1.0, 2.0, 5.0, 10.0, 33.0, 100.0),
    registry=registry,
)

# Standing-query plane (spatial/queryplane.py; doc/query_engine.md).
# Every counter below has a python-side double-entry ledger on the
# plane (QueryPlane.ledgers) that must match exactly — the soak/bench
# invariant gates compare the two.
standing_queries = Gauge(
    "standing_queries",
    "Live standing-query registrations on the device query plane "
    "(scope: follow = entity-follow AOI, client = UpdateSpatialInterest "
    "query rows, sensor = server-facing sensor API)",
    ["scope"],
    registry=registry,
)
query_rows_changed = Counter(
    "query_rows_changed_total",
    "Changed (query, cell, dist) rows consumed from the per-tick "
    "device diff — the plane's entire host workload is O(this), "
    "not O(standing queries)",
    registry=registry,
)
query_pass_ms = Histogram(
    "query_pass_ms",
    "Host cost of one standing-query plane pass (consume the changed "
    "rows + apply pending sub/unsub diffs), milliseconds",
    buckets=(0.05, 0.1, 0.5, 1.0, 2.0, 5.0, 10.0, 33.0, 100.0),
    registry=registry,
)
query_plane_transfers = Counter(
    "query_plane_transfers_total",
    "Changed-rows blobs consumed — by design exactly ONE device->host "
    "transfer per tick however many standing queries exist (the bench "
    "gate divides this by ticks and demands 1.0)",
    registry=registry,
)
query_full_resyncs = Counter(
    "query_full_resyncs_total",
    "Query-plane mirror full resyncs: the engine's query epoch moved "
    "(device-guard rebuild or geometry epoch threw the diff baseline "
    "away), so every registered query re-applies from scratch",
    registry=registry,
)
query_malformed = Counter(
    "query_malformed_total",
    "UpdateSpatialInterest messages rejected before touching any "
    "query table (field: which validation tripped — hostile NaN/inf "
    "centers, negative radius/angle, oversize spot lists)",
    ["field"],
    registry=registry,
)

# Simulation plane (channeld_tpu/sim; doc/simulation.md). Every
# counter below is double-entry: the python ledger on the plane
# (SimPlane.ledgers) or engine (sim_rebuild_counts) must match exactly
# — the sim soak/bench invariant gates compare the two.
sim_agents_num = Gauge(
    "sim_agents_num",
    "Simulated agents currently registered in the engine's entity "
    "arrays (they ARE ordinary entities; this gauge is the sim-plane "
    "slice of entity_num)",
    registry=registry,
)
sim_ticks = Counter(
    "sim_ticks_total",
    "Sim passes actually stepped on device (cadence skips and overload "
    "deferrals don't count; the counter-based RNG cursor advances "
    "exactly once per increment, which is the replayability contract)",
    registry=registry,
)
sim_census_transfers = Counter(
    "sim_census_transfers_total",
    "Census batches fetched device->host — by design the sim plane's "
    "ONLY device readback, at census cadence, never per tick (the "
    "bench gate demands zero additional per-tick transfers vs a "
    "no-sim tick; same contract as query_plane_transfers_total)",
    registry=registry,
)
sim_device_rebuilds = Counter(
    "sim_device_rebuilds",
    "Verifications of the rebuilt agent kinematic arrays against the "
    "host shadow (result=verified: bit-identical; mismatch: divergence "
    "found). Fires on every verify_device_state over a live sim plane "
    "— device-guard recovery and geometry-epoch rebuilds both land "
    "here. The engine ledger (sim_rebuild_counts) must match exactly",
    ["result"],
    registry=registry,
)
sim_pass_ms = Histogram(
    "sim_pass_ms",
    "Host cost of one sim-plane pass (census absorb + authority "
    "commit when due; ~0 on non-census ticks), milliseconds",
    buckets=(0.05, 0.1, 0.5, 1.0, 2.0, 5.0, 10.0, 33.0, 100.0),
    registry=registry,
)

# Fleet health plane: end-to-end delivery SLOs (core/slo.py;
# doc/observability.md). The bucket edges are shared with the SLO
# plane's python-side tally (slo.delivery_quantile — the soak's <5ms
# verdict cross-check), so they live in ONE tuple.
DELIVERY_LATENCY_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 5.0, 10.0,
                            33.0, 100.0, 1000.0)
delivery_latency_ms = Histogram(
    "delivery_latency_ms",
    "End-to-end ingest->fan-out delivery latency, milliseconds: the "
    "monotonic ingest stamp placed on a forwarded update at the "
    "connection read (fast and slow paths) measured against the send "
    "of the fan-out that delivers it. One sample per delivered fan-out "
    "window, stamped with the NEWEST update the window carries — the "
    "gateway-pipeline transit the < 5ms north-star claim is about; "
    "cadence-held staleness is fanout_staleness_ms. path=fast: the "
    "batched native-ingest forward to the GLOBAL owner; path=host / "
    "path=device: the host-scan and device-due ChannelData fan-outs",
    ["channel_type", "path"],
    buckets=DELIVERY_LATENCY_BUCKETS,
    registry=registry,
)
fanout_staleness_ms = Histogram(
    "fanout_staleness_ms",
    "Age of the newest merged-but-undelivered channel state per "
    "subscriber class, milliseconds (sub_class: p0 WRITE/authority, "
    "p1 default-cadence READ, p2 background observers — the overload "
    "ladder's shed order). Sampled once per GLOBAL tick for one "
    "round-robin channel with live data (bounded cost; core/slo.py)",
    ["channel_type", "sub_class"],
    buckets=(1.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 1000.0, 5000.0),
    registry=registry,
)
slo_burn_rate = Gauge(
    "slo_burn_rate",
    "Multi-window SLO error-budget burn rate (1.0 == consuming the "
    "budget exactly as fast as the objective allows; core/slo.py "
    "evaluates each declared SLO's bad-event fraction over every "
    "configured window each GLOBAL tick)",
    ["slo", "window"],
    registry=registry,
)
slo_breaches = Counter(
    "slo_breaches",
    "SLO burn-rate alarm firings by SLO (a window's burn rate crossed "
    "its alarm threshold — counted once per rising edge per window, "
    "and each breach freezes a flight-recorder slo_breach anomaly "
    "dump so the violating tick timeline ships with the alarm). The "
    "python ledger in core/slo.py (breach_counts) must match exactly",
    ["slo"],
    registry=registry,
)

# Flight recorder / tick-timeline tracing (core/tracing.py;
# doc/observability.md).
tick_stage_ms = Histogram(
    "tick_stage_ms",
    "Host cost of one named per-tick stage, milliseconds (ingest: "
    "deferred-read drain; stash_retry: backpressure re-dispatch; "
    "messages: channel queue drain incl. FSM dispatch; fanout: "
    "ChannelData fan-out encode/send; device_step: batched engine "
    "dispatch+step; readback: device->host interest-mask transfers; "
    "follow_interests: the full follower pass; handover: crossing "
    "orchestration; overload: governor update; trunk: trunk ingress "
    "dispatch). The flight recorder observes these whether or not span "
    "recording is enabled",
    ["stage"],
    buckets=(0.01, 0.05, 0.1, 0.5, 1.0, 2.0, 5.0, 10.0, 33.0, 100.0),
    registry=registry,
)
trace_dumps = Counter(
    "trace_dumps",
    "Anomaly-triggered flight-recorder freezes by trigger (tick_budget: "
    "a tick overran its interval; overload_transition: the degradation "
    "ladder moved; handover_abort: a cross-gateway batch aborted; "
    "migration_abort: a balancer cell migration rolled back; "
    "failover_epoch: a dead server's cells were re-hosted; "
    "device_failure: the device engine failed fatally and is "
    "rebuilding in-process; slo_breach: an SLO burn-rate alarm fired "
    "(core/slo.py); "
    "manual/sigusr2/shutdown: explicit dump_trace calls). Anomaly "
    "triggers count even when the dump itself was suppressed by the "
    "cooldown; a disabled recorder (-trace false) counts nothing",
    ["trigger"],
    registry=registry,
)
follower_readbacks = Counter(
    "follower_readbacks",
    "Device->host interest-mask transfers performed by "
    "_apply_follow_interests — one BATCHED transfer per pass covering "
    "every AOI follower (engine.interested_cells_batch). Before the "
    "batching this counted one transfer per follower per pass "
    "(ROADMAP item 1's measured bottleneck, ~330us each; "
    "BENCH_RESULTS.md round 12 has the before/after)",
    registry=registry,
)

# The goroutine-count analog: live asyncio tasks (one per channel tick,
# listener, pump). Updated by the server's heartbeat (serve loops) and by
# any caller of sample_runtime().
asyncio_tasks = Gauge(
    "asyncio_tasks", "Live asyncio tasks", registry=registry
)

# Python process + GC runtime families — the analog of the reference
# dashboard's go_memstats/go_gc/goroutines panels (grafana/dashboard.json).
try:  # pragma: no cover - collector support is environment-dependent
    from prometheus_client.gc_collector import GCCollector
    from prometheus_client.process_collector import ProcessCollector

    ProcessCollector(registry=registry)
    GCCollector(registry=registry)
except Exception:
    pass


def sample_runtime() -> None:
    """Refresh point-in-time runtime gauges (asyncio task count)."""
    import asyncio

    try:
        loop = asyncio.get_running_loop()
    except RuntimeError:
        return
    asyncio_tasks.set(len(asyncio.all_tasks(loop)))


def serve_metrics(port: int = 8080) -> None:
    """Expose /metrics (reference serves this from main, cmd/main.go:50)."""
    start_http_server(port, registry=registry)

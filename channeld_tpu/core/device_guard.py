"""Device supervision & in-process engine recovery (doc/device_recovery.md).

Every resilience plane before this one (chaos, overload, failover,
balancer, federation, global control) assumed the device engine itself
never fails: an XLA error, a hung dispatch, or silently corrupted device
state in ``SpatialEngine.tick()`` would propagate up through
``channel.tick_once`` and take down the whole gateway — stranding its
shard until the fleet's death declaration adopts it. This module makes a
single-chip fault a local, bounded event instead:

- **Watchdog.** The guarded step runs on a dedicated worker thread and
  the tick waits at most ``device_step_deadline_s`` (the jax call
  blocks, so hang detection must be off-thread). A timed-out step is
  abandoned: the engine's generation fence is bumped so the zombie
  worker can never commit its tail state over a rebuilt engine, the
  worker pool is discarded, and the failure is FATAL (a wedged chip
  does not get better by retrying into it).

- **Classification.** Step exceptions are transient-vs-fatal:
  transient (queue pressure, allocator hiccups — the retryable XLA
  status codes) retries with exponential backoff up to
  ``device_retry_max`` attempts while the gateway degrades; anything
  else, an exhausted retry budget, a hang, or a sentinel hit is fatal.

- **Corruption sentinel.** NaN/out-of-range device rot is caught from
  the *already-fetched* batched readback arrays — the handover rows,
  the handover count, the due bitmap — with pure-host range checks. No
  new device->host transfers are added (tpulint's hot-readback rule
  stays clean): a NaN position maps outside the world and a rotted cell
  baseline surfaces as an impossible src cell in a crossing row, which
  is exactly what the checks pin.

- **In-process rebuild.** On a fatal failure the engine is rebuilt from
  the host-side shadow: the entity registry, query params and sub
  intervals are already authoritative on host, and the per-slot cell
  baselines are re-seeded from the grid's ``_data_cell`` placement
  ledger with the failover journal's in-flight dsts outranking it (a
  mid-crossing entity re-baselines to where its data is actually
  bound). The rebuilt arrays are verified bit-identical against the
  shadow before the gateway resumes device service; entities that
  moved during the outage re-detect their crossings from the reseeded
  baseline, so nothing is lost or duplicated.

While the engine is down the gateway *degrades instead of dying*:
``run_step`` returns None, the controller holds device-dependent work
(due fan-out decisions, crossing orchestration, follower passes), the
overload ladder is pinned to L2+ (shedding outranks a dead engine), and
the flight recorder freezes an anomaly dump at the failure tick. A
fatal failure and a completed rebuild each write an immediate snapshot
through the shared fsync'd ``write_snapshot`` path, so a crash during
recovery still boot-restores to the newest state.

Every recovery is counted twice on purpose — the
``device_recoveries_total{cause}`` counter AND the guard's python-side
ledger — so ``scripts/device_soak.py`` proves the accounting exact.
"""

from __future__ import annotations

import concurrent.futures
import time
from enum import IntEnum
from typing import Optional

import numpy as np

from ..chaos.injector import chaos as _chaos
from ..utils.logger import get_logger
from .affinity import affinity as _affinity
from .settings import global_settings

logger = get_logger("device_guard")


class DeviceState(IntEnum):
    ACTIVE = 0  # serving
    DEGRADED = 1  # transient step failure; retrying with backoff
    REBUILDING = 2  # fatal failure; in-process rebuild in progress
    FAILED = 3  # the rebuild itself failed; retrying on a backoff


class DeviceStepError(RuntimeError):
    """A device step failure with an explicit transient/fatal tag (used
    by the chaos injection and available to engine wrappers)."""

    def __init__(self, message: str, transient: bool = False):
        super().__init__(message)
        self.transient = transient


class _StepHang(RuntimeError):
    pass


# Substrings of the retryable XLA/jax status families. Real runtime
# errors surface as RuntimeError/XlaRuntimeError with the status name in
# the message; everything NOT matching is treated as fatal — when in
# doubt, rebuild (a wrong "transient" guess burns the whole retry budget
# inside a corrupted engine).
_TRANSIENT_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "UNAVAILABLE",
    "ABORTED",
    "DEADLINE_EXCEEDED",
)


def classify_failure(exc: BaseException) -> str:
    """'transient' or 'fatal' for one device-step exception."""
    if isinstance(exc, DeviceStepError):
        return "transient" if exc.transient else "fatal"
    text = str(exc)
    if any(marker in text for marker in _TRANSIENT_MARKERS):
        return "transient"
    return "fatal"


class DeviceGuard:
    """Process-wide device supervision state machine (one instance:
    ``guard``). The TPU spatial controller routes its per-tick engine
    step through :meth:`run_step`; everything else reads state."""

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        self.state = DeviceState.ACTIVE
        # Python-side recovery ledger; must match
        # device_recoveries_total exactly (the soak cross-checks).
        self.recovery_counts: dict[str, int] = {}
        self.failure_counts: dict[str, int] = {}
        self.events: list[dict] = []
        self.held_ticks = 0
        self.recovery_times_s: list[float] = []
        self._retry_count = 0
        self._not_before = 0.0
        self._rebuild_attempts = 0
        self._rebuild_fut: Optional[concurrent.futures.Future] = None
        self._rebuild_t0 = 0.0
        self._failed_at: Optional[float] = None
        self._fatal_cause = ""
        self._pool: Optional[concurrent.futures.ThreadPoolExecutor] = None
        self._started = time.monotonic()
        self._publish_state()

    # ---- plumbing --------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return global_settings.device_guard_enabled

    def _publish_state(self) -> None:
        try:  # lazy: metrics must not be a module-load dependency
            from . import metrics

            metrics.device_state.set(int(self.state))
        except Exception:
            pass

    def _set_state(self, state: DeviceState) -> None:
        if state == self.state:
            return
        old = self.state
        self.state = state
        self.events.append({
            "t": round(time.monotonic() - self._started, 3),
            "from": old.name,
            "to": state.name,
        })
        log = logger.info if state == DeviceState.ACTIVE else logger.warning
        log("device state %s -> %s", old.name, state.name)
        self._publish_state()

    def _count_recovery(self, cause: str) -> None:
        """Double-entry recovery accounting: python ledger AND the
        prometheus counter move together, always."""
        self.recovery_counts[cause] = self.recovery_counts.get(cause, 0) + 1
        from . import metrics

        metrics.device_recoveries.labels(cause=cause).inc()

    def _count_failure(self, cause: str) -> None:
        self.failure_counts[cause] = self.failure_counts.get(cause, 0) + 1
        from . import metrics

        metrics.device_step_failures.labels(cause=cause).inc()

    def _executor(self) -> concurrent.futures.ThreadPoolExecutor:
        if self._pool is None:
            self._pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="device-step"
            )
        return self._pool

    def _abandon_executor(self) -> None:
        """Give up on a hung worker: the pool (and its stuck thread) is
        discarded without waiting; the next step gets a fresh one."""
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None

    def shutdown(self) -> None:
        """Test/teardown hook: release the worker thread."""
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None

    # ---- the guarded step ------------------------------------------------

    def run_step(self, controller) -> Optional[dict]:
        """Run one supervised engine step for ``controller``
        (TPUSpatialController). Returns the step result with the batched
        readback arrays already materialized on host — or None while the
        engine is down/held (the controller must hold all
        device-dependent work for that tick)."""
        # Affinity: the guard's state machine is loop-thread-only; all
        # device waits happen on the worker via _dispatch.
        _affinity.expect("tick-loop")
        now = time.monotonic()
        if self.state != DeviceState.ACTIVE:
            if now < self._not_before:
                self.held_ticks += 1
                return None
            if self.state in (DeviceState.REBUILDING, DeviceState.FAILED):
                self._attempt_rebuild(controller)
                self.held_ticks += 1
                return None  # serve again from the NEXT tick
            # DEGRADED: backoff elapsed — retry the step below.
        if _chaos.armed and _chaos.fire("device.nan"):
            # Chaos: silent device-state rot (NaN positions + garbage
            # cell baselines). Planted BEFORE the step so the sentinel
            # must catch it from the ordinary readback, exactly like a
            # real bit-flip would have to be caught.
            controller.engine.corrupt_device_state_for_chaos()
        try:
            result = self._dispatch(controller.engine)
        except _StepHang:
            self._count_failure("hang")
            logger.error(
                "device step exceeded the %.2fs watchdog deadline; "
                "abandoning the worker and rebuilding",
                global_settings.device_step_deadline_s,
            )
            self._enter_fatal(controller, "hang")
            return None
        except Exception as exc:
            self._count_failure("step_error")
            if (
                classify_failure(exc) == "transient"
                and self._retry_count < global_settings.device_retry_max
            ):
                self._retry_count += 1
                backoff = (
                    global_settings.device_retry_backoff_ms / 1000.0
                ) * (2 ** (self._retry_count - 1))
                self._not_before = time.monotonic() + backoff
                if self._failed_at is None:
                    self._failed_at = now
                logger.warning(
                    "transient device step failure (%r); retry %d/%d "
                    "in %.0fms", exc, self._retry_count,
                    global_settings.device_retry_max, backoff * 1000.0,
                )
                self._set_state(DeviceState.DEGRADED)
                self._pin_ladder()
                return None
            self._enter_fatal(controller, "step_error")
            return None
        corrupt = self._sentinel(controller.engine, result)
        if corrupt:
            self._count_failure("corruption")
            logger.error("device readback sentinel: %s; rebuilding",
                         corrupt)
            self._enter_fatal(controller, "corruption")
            return None
        if self.state == DeviceState.DEGRADED:
            # A retried step came back clean: transient recovery,
            # no rebuild needed.
            self._finish_recovery("transient")
        self._retry_count = 0
        return result

    def _dispatch(self, engine) -> dict:
        gen = engine.generation
        fut = self._executor().submit(self._step_body, engine, gen)
        try:
            return fut.result(
                timeout=max(global_settings.device_step_deadline_s, 0.001)
            )
        except concurrent.futures.TimeoutError:
            # Fence first, then abandon: the zombie re-checks the
            # generation before touching the engine and before
            # committing its tail state (ops/engine.py tick()).
            engine.bump_generation()
            self._abandon_executor()
            fut.add_done_callback(_log_zombie)
            raise _StepHang()

    @staticmethod
    def _step_body(engine, gen: int) -> dict:
        """Worker-thread body: chaos gates, the engine step, and the
        batched readback fetch — ALL device waits happen here so the
        watchdog deadline covers dispatch and transfer alike."""
        _affinity.enter("device-worker")
        if _chaos.armed:
            stall = _chaos.stall_s("device.step_hang")
            if stall:
                # Models a wedged dispatch: the blocking sleep stands in
                # for a jax call that never completes within deadline.
                time.sleep(stall)
            if _chaos.fire("device.step_error"):
                raise DeviceStepError(
                    "chaos: injected device step error "
                    "(RESOURCE_EXHAUSTED)", transient=True,
                )
        if gen != engine.generation:
            # This step was abandoned while the chaos stall (or a real
            # queue wait) held the worker: never touch the engine.
            raise RuntimeError("stale device tick abandoned by watchdog")
        result = engine.tick()
        # The per-tick batched readbacks, fetched ONCE inside the
        # guarded window (a hung transfer is a hang, not a mystery
        # stall in the controller) and handed on as numpy so the
        # controller's handover_list/_publish_due add no new transfers.
        result["handovers"] = np.asarray(result["handovers"])  # tpulint: disable=hot-readback -- THE designed once-per-tick batched fetch; downstream reuses these arrays
        result["handover_count"] = int(result["handover_count"])  # tpulint: disable=hot-readback -- rides the same designed per-tick fetch as the rows above
        result["due_packed"] = np.asarray(result["due_packed"])  # tpulint: disable=hot-readback -- rides the same designed per-tick fetch as the rows above
        if result.get("query_blob") is not None:
            result["query_blob"] = np.asarray(result["query_blob"])  # tpulint: disable=hot-readback -- the standing-query plane's ONE changed-rows transfer, pre-fetched inside the guarded window (doc/query_engine.md)
        if result.get("sim_census") is not None:
            result["sim_census"] = tuple(
                np.asarray(a)  # tpulint: disable=hot-readback -- the sim plane's census-cadence batched fetch (its ONLY readback, doc/simulation.md), pre-fetched inside the guarded window; NOT per-tick
                for a in result["sim_census"]
            )
        return result

    # ---- corruption sentinel ---------------------------------------------

    @staticmethod
    def _sentinel(engine, result: dict) -> Optional[str]:
        """Range/shape checks over the already-fetched readback arrays;
        returns a description of the rot, or None when clean. All
        device readbacks in this engine are integer/bool arrays, so
        float NaN/inf rot cannot surface literally — it surfaces as
        impossible values (a NaN position assigns outside the world; a
        rotted baseline produces a crossing from a cell that does not
        exist), which is exactly what is pinned here."""
        count = result["handover_count"]
        rows = result["handovers"]
        if count < 0 or count > engine.entity_capacity:
            return f"handover count {count} outside [0, capacity]"
        n_cells = engine.grid.num_cells
        head = rows[: min(count, len(rows))]
        if len(head):
            slots = head[:, 0]
            cells = head[:, 1:]
            if int(slots.max(initial=0)) >= engine.entity_capacity:
                return "handover row slot beyond entity capacity"
            bad = (cells < 0) | (cells >= n_cells)
            # The compaction's discard lane can leave slot == -1 rows;
            # only rows naming a real slot must carry real cells.
            if bool((bad & (slots >= 0)[:, None]).any()):
                return (
                    "handover row cites an impossible cell "
                    f"(grid has {n_cells})"
                )
        due = result["due_packed"]
        if len(due) != (engine.sub_capacity + 7) // 8:
            return "due bitmap length mismatch"
        q_blob = result.get("query_blob")
        if q_blob is not None:
            q_count = int(q_blob[0])  # tpulint: disable=hot-readback -- q_blob was pre-fetched as host numpy in _step_body; this indexes host memory, not the device
            q_cap = engine.query_capacity * n_cells
            if q_count < 0 or q_count > q_cap:
                return f"query change count {q_count} outside [0, Q*C]"
            q_rows = q_blob[1:].reshape(-1, 3)
            head = q_rows[: min(q_count, len(q_rows))]
            if len(head):
                live = head[:, 0] >= 0
                if int(head[:, 0].max(initial=0)) >= engine.query_capacity:
                    return "query change row beyond query capacity"
                bad_cell = (head[:, 1] < 0) | (head[:, 1] >= n_cells)
                if bool((bad_cell & live).any()):
                    return (
                        "query change row cites an impossible cell "
                        f"(grid has {n_cells})"
                    )
        return None

    # ---- failure / recovery ----------------------------------------------

    def _pin_ladder(self) -> None:
        from .overload import governor

        governor.pin_floor(2, "device engine down")

    def _release_ladder(self) -> None:
        from .overload import governor

        governor.release_floor()

    def _enter_fatal(self, controller, cause: str) -> None:
        if self._failed_at is None:
            self._failed_at = time.monotonic()
        self._fatal_cause = cause
        self._rebuild_attempts = 0
        self._retry_count = 0
        self._set_state(DeviceState.REBUILDING)
        self._pin_ladder()
        from .tracing import recorder as _trace

        if _trace.enabled:
            # Freeze the timeline at the failure tick: the dump holds
            # the stages that led into the fault.
            _trace.note_anomaly(
                "device_failure", f"{cause}: engine down, rebuilding"
            )
        controller.on_device_fatal(cause)
        # Crash-during-recovery durability: snapshot NOW, before the
        # rebuild runs, through the shared fsync'd path — written
        # SYNCHRONOUSLY: a loop task would not get a turn until after
        # _attempt_rebuild releases the loop thread, which is exactly
        # too late for the crash-during-rebuild case this write exists
        # for (the tick is already stalled for the rebuild anyway).
        self._snapshot("device_fatal", sync=True)
        self._attempt_rebuild(controller)

    def _attempt_rebuild(self, controller) -> None:
        """Drive the in-process rebuild WITHOUT parking the event loop:
        the rebuild's device calls (device_put, the verification
        readbacks) run on the SAME deadline-guarded worker as the step —
        against a genuinely wedged device a synchronous rebuild would
        block the loop thread for seconds: no ticks, no trunk
        heartbeats (a federated peer would declare this gateway DEAD
        over a fault it is actively recovering from), no SIGTERM drain.
        Instead the wait per tick is bounded at min(step deadline, 1s):
        the common millisecond rebuild completes inside it
        (synchronous semantics), a slow one degrades to per-tick
        polling, and one wedged past 4x the step deadline is abandoned
        into FAILED (backoff retry) behind the same generation fence as
        a hung step."""
        from . import metrics

        engine = controller.engine
        if self._rebuild_fut is None:
            self._set_state(DeviceState.REBUILDING)
            try:
                if _chaos.armed and _chaos.fire("device.rebuild_fail"):
                    raise RuntimeError("chaos: injected rebuild failure")
                seeds = controller.rebuild_seed_cells()
            except Exception as exc:
                self._rebuild_failed(exc)
                return
            self._rebuild_t0 = time.monotonic()
            self._rebuild_fut = self._executor().submit(
                self._rebuild_body, engine, seeds, engine.generation
            )
        fut = self._rebuild_fut
        try:
            mismatches = fut.result(timeout=min(
                max(global_settings.device_step_deadline_s, 0.001), 1.0
            ))
        except concurrent.futures.TimeoutError:
            deadline = max(global_settings.device_step_deadline_s * 4, 0.004)
            if time.monotonic() - self._rebuild_t0 >= deadline:
                self._rebuild_fut = None
                engine.bump_generation()
                self._abandon_executor()
                fut.add_done_callback(_log_zombie)
                self._rebuild_failed(RuntimeError(
                    "rebuild exceeded the watchdog deadline (device "
                    "still wedged)"
                ))
            return  # still rebuilding: poll again next tick
        except Exception as exc:
            self._rebuild_fut = None
            self._rebuild_failed(exc)
            return
        self._rebuild_fut = None
        if mismatches:
            self._rebuild_failed(RuntimeError(
                f"rebuild verification failed: {mismatches}"
            ))
            return
        took_ms = (time.monotonic() - self._rebuild_t0) * 1000.0
        metrics.device_rebuild_ms.observe(took_ms)
        logger.warning(
            "engine rebuilt in-process from the host shadow: %d entities "
            "re-seeded, verified bit-identical (%.1fms)",
            engine.entity_count(), took_ms,
        )
        self._finish_recovery(self._fatal_cause)
        # Recovery durability: the rebuilt state is the newest truth.
        self._snapshot("device_recovered")

    def _rebuild_failed(self, exc: BaseException) -> None:
        self._count_failure("rebuild_fail")
        self._rebuild_attempts += 1
        backoff = (
            global_settings.device_retry_backoff_ms / 1000.0
        ) * (2 ** min(self._rebuild_attempts, 6))
        self._not_before = time.monotonic() + backoff
        logger.error(
            "in-process engine rebuild failed (attempt %d: %r); "
            "retrying in %.0fms", self._rebuild_attempts, exc,
            backoff * 1000.0,
        )
        self._set_state(DeviceState.FAILED)

    @staticmethod
    def _rebuild_body(engine, seeds: dict, gen: int):
        """Worker-thread rebuild: re-seed from the host shadow, then the
        bit-identical verification readbacks. Two fences keep an
        abandoned (timed-out) rebuild from ever clobbering a later
        successful one when the device unwedges: the engine's rebuild
        lock serializes concurrent rebuild bodies outright, and
        ``expect_generation`` inside rebuild_device_state refuses to
        commit once the watchdog bumped the generation — the stale
        worker raises AFTER its blocking transfers, BEFORE any
        engine-visible mutation."""
        _affinity.enter("device-worker")
        if not engine._rebuild_lock.acquire(
            timeout=max(global_settings.device_step_deadline_s * 4, 0.004)
        ):
            raise RuntimeError(
                "rebuild lock held by an abandoned rebuild (device "
                "still wedged)"
            )
        try:
            if gen != engine.generation:
                raise RuntimeError("stale rebuild abandoned by watchdog")
            engine.rebuild_device_state(seeds, expect_generation=gen)
            return engine.verify_device_state(seeds)
        finally:
            engine._rebuild_lock.release()

    def _finish_recovery(self, cause: str) -> None:
        recovery_s = (
            time.monotonic() - self._failed_at
            if self._failed_at is not None else 0.0
        )
        self.recovery_times_s.append(recovery_s)
        deadline = global_settings.device_recovery_deadline_s
        if recovery_s > deadline:
            logger.warning(
                "device recovery took %.2fs (deadline %.2fs)",
                recovery_s, deadline,
            )
        self._count_recovery(cause)
        self.events.append({
            "t": round(time.monotonic() - self._started, 3),
            "recovered": cause,
            "recovery_s": round(recovery_s, 3),
        })
        self._failed_at = None
        self._fatal_cause = ""
        self._retry_count = 0
        self._not_before = 0.0
        self._set_state(DeviceState.ACTIVE)
        self._release_ladder()

    def _snapshot(self, reason: str, sync: bool = False) -> None:
        """Immediate snapshot through the shared fsync'd write path
        (core/snapshot.py). ``sync`` writes inline (the fatal-entry
        snapshot: it must be durable BEFORE the rebuild stalls the loop
        thread); otherwise the disk IO runs off-thread when an event
        loop is up so the tick never stalls on fsync."""
        path = global_settings.snapshot_path
        if not path:
            return
        try:
            from .snapshot import take_snapshot, write_snapshot

            snap = take_snapshot()
            import asyncio

            if sync:
                write_snapshot(snap, path)
                logger.info("snapshot written on %s (%d channels)",
                            reason, len(snap.channels))
                return
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                write_snapshot(snap, path)
            else:
                task = loop.create_task(
                    asyncio.to_thread(write_snapshot, snap, path)
                )
                task.add_done_callback(_log_snapshot_error)
            logger.info("snapshot scheduled on %s (%d channels)",
                        reason, len(snap.channels))
        except Exception:
            logger.exception("%s snapshot failed", reason)

    # ---- reporting -------------------------------------------------------

    def report(self) -> dict:
        return {
            "state": self.state.name,
            "recovery_counts": dict(self.recovery_counts),
            "failure_counts": dict(self.failure_counts),
            "recovery_times_s": [round(s, 3) for s in self.recovery_times_s],
            "held_ticks": self.held_ticks,
            "events": list(self.events),
        }


def _log_snapshot_error(task) -> None:
    """Off-thread snapshot writes must never surface as unretrieved
    task exceptions (e.g. the target dir vanished under a test
    teardown); the failure is logged, the gateway unaffected."""
    if task.cancelled():
        return
    exc = task.exception()
    if exc is not None:
        logger.warning("device-recovery snapshot write failed: %r", exc)


def _log_zombie(fut) -> None:
    exc = fut.exception()
    if exc is not None:
        logger.info("abandoned device step finished with %r", exc)
    else:
        logger.info("abandoned device step finished late (discarded)")


# The process-wide guard. The TPU controller holds a module reference;
# a disabled guard costs one attribute load per tick.
guard = DeviceGuard()


def reset_device_guard() -> None:
    """Test hook."""
    guard.shutdown()
    guard.reset()

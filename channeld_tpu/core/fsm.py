"""Per-connection finite-state-machine message filter.

Capability parity with the reference FSM (ref: pkg/fsm/fsm.go:13-171):
JSON-defined states carrying msg-type whitelists/blacklists written as
range specs ("1", "2-65535"), optional msg-type-triggered transitions,
and sequential ``move_to_next_state``. Each connection gets its own
copy (ref: pkg/channeld/connection.go:317-330) so transition state is
per-connection.

The reference JSON schema is accepted verbatim so existing
``*_fsm.json`` configs keep working:

    {"States": [{"Name": ..., "MsgTypeWhitelist": "1",
                 "MsgTypeBlacklist": ""}],
     "InitState": "INIT",
     "Transitions": [{"FromState": ..., "ToState": ..., "MsgType": 2}]}
"""

from __future__ import annotations

import copy
import json
from dataclasses import dataclass, field
from typing import Optional

from ..utils.ranges import RangeSet


@dataclass
class FsmState:
    name: str
    allowed: RangeSet = field(default_factory=RangeSet)
    blocked: RangeSet = field(default_factory=RangeSet)

    def is_allowed(self, msg_type: int) -> bool:
        return msg_type in self.allowed and msg_type not in self.blocked


class MessageFsm:
    def __init__(
        self,
        states: list[FsmState],
        transitions: dict[tuple[str, int], str],
        init_state: Optional[str] = None,
    ):
        if not states:
            raise ValueError("FSM needs at least one state")
        self.states = states
        self._by_name = {s.name: s for s in states}
        self.transitions = transitions
        self._init_index = 0
        if init_state is not None:
            if init_state not in self._by_name:
                raise KeyError(f"unknown InitState: {init_state}")
            self._init_index = states.index(self._by_name[init_state])
        self._current_index = self._init_index

    # ---- construction -------------------------------------------------

    @classmethod
    def from_dict(cls, spec: dict) -> "MessageFsm":
        states = [
            FsmState(
                name=s["Name"],
                allowed=RangeSet.parse(s.get("MsgTypeWhitelist", "")),
                blocked=RangeSet.parse(s.get("MsgTypeBlacklist", "")),
            )
            for s in spec.get("States", [])
        ]
        transitions = {
            (t["FromState"], int(t["MsgType"])): t["ToState"]
            for t in spec.get("Transitions", [])
        }
        return cls(states, transitions, init_state=spec.get("InitState"))

    @classmethod
    def load(cls, path: str) -> "MessageFsm":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    def clone(self) -> "MessageFsm":
        """Fresh per-connection copy with state reset to the init state."""
        fsm = copy.copy(self)
        fsm._current_index = self._init_index
        return fsm

    # ---- runtime ------------------------------------------------------

    @property
    def current(self) -> FsmState:
        return self.states[self._current_index]

    def is_allowed(self, msg_type: int) -> bool:
        return self.current.is_allowed(msg_type)

    def on_received(self, msg_type: int) -> None:
        """Apply a msg-type-triggered transition, if one is defined."""
        target = self.transitions.get((self.current.name, msg_type))
        if target is not None:
            self._move_to(target)

    def move_to_next_state(self) -> bool:
        """Advance to the next state in declaration order (auth success path)."""
        if self._current_index + 1 < len(self.states):
            self._current_index += 1
            return True
        return False

    def _move_to(self, name: str) -> None:
        state = self._by_name.get(name)
        if state is None:
            raise KeyError(f"unknown FSM state: {name}")
        self._current_index = self.states.index(state)

"""Per-connection finite-state-machine message filter.

Capability parity with the reference FSM (ref: pkg/fsm/fsm.go:13-171):
JSON-defined states carrying msg-type whitelists/blacklists written as
range specs ("1", "2-65535"), optional msg-type-triggered transitions,
and sequential ``move_to_next_state``. Each connection gets its own
copy (ref: pkg/channeld/connection.go:317-330) so transition state is
per-connection.

The reference JSON schema is accepted verbatim so existing
``*_fsm.json`` configs keep working:

    {"States": [{"Name": ..., "MsgTypeWhitelist": "1",
                 "MsgTypeBlacklist": ""}],
     "InitState": "INIT",
     "Transitions": [{"FromState": ..., "ToState": ..., "MsgType": 2}]}
"""

from __future__ import annotations

import copy
import json
from dataclasses import dataclass, field
from typing import Optional

from ..utils.ranges import RangeSet

# First user-space message type (ref: channeld.pb USER_SPACE_START);
# kept as a local constant so the FSM stays importable on its own.
USER_SPACE_START = 100


@dataclass
class FsmState:
    name: str
    allowed: RangeSet = field(default_factory=RangeSet)
    blocked: RangeSet = field(default_factory=RangeSet)
    # msg_type -> verdict memo. The range sets are immutable after load
    # and states are shared across per-connection clones, so one warm
    # cache serves every connection (two bisect walks per message
    # otherwise dominate the FSM's share of the receive path).
    _memo: dict = field(default_factory=dict, repr=False, compare=False)

    def is_allowed(self, msg_type: int) -> bool:
        v = self._memo.get(msg_type)
        if v is None:
            v = self._memo[msg_type] = (
                msg_type in self.allowed and msg_type not in self.blocked
            )
        return v


class MessageFsm:
    def __init__(
        self,
        states: list[FsmState],
        transitions: dict[tuple[str, int], str],
        init_state: Optional[str] = None,
    ):
        if not states:
            raise ValueError("FSM needs at least one state")
        self.states = states
        self._by_name = {s.name: s for s in states}
        self.transitions = transitions
        # Per-state transition table (msg_type -> target name): saves the
        # per-message (name, msg_type) tuple build in on_received.
        self._state_transitions: list[dict[int, str]] = [
            {mt: to for (frm, mt), to in transitions.items() if frm == s.name}
            for s in states
        ]
        # Whether any transition out of each state is triggered by a
        # user-space msgType; gates the batched-ingest fast path.
        self._state_user_transitions: list[bool] = [
            any(mt >= USER_SPACE_START for mt in table)
            for table in self._state_transitions
        ]
        self._init_index = 0
        if init_state is not None:
            if init_state not in self._by_name:
                raise KeyError(f"unknown InitState: {init_state}")
            self._init_index = states.index(self._by_name[init_state])
        self._current_index = self._init_index

    # ---- construction -------------------------------------------------

    @classmethod
    def from_dict(cls, spec: dict) -> "MessageFsm":
        states = [
            FsmState(
                name=s["Name"],
                allowed=RangeSet.parse(s.get("MsgTypeWhitelist", "")),
                blocked=RangeSet.parse(s.get("MsgTypeBlacklist", "")),
            )
            for s in spec.get("States", [])
        ]
        transitions = {
            (t["FromState"], int(t["MsgType"])): t["ToState"]
            for t in spec.get("Transitions", [])
        }
        return cls(states, transitions, init_state=spec.get("InitState"))

    @classmethod
    def load(cls, path: str) -> "MessageFsm":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    def clone(self) -> "MessageFsm":
        """Fresh per-connection copy with state reset to the init state."""
        fsm = copy.copy(self)
        fsm._current_index = self._init_index
        return fsm

    # ---- runtime ------------------------------------------------------

    @property
    def current(self) -> FsmState:
        return self.states[self._current_index]

    def is_allowed(self, msg_type: int) -> bool:
        return self.current.is_allowed(msg_type)

    def on_received(self, msg_type: int) -> None:
        """Apply a msg-type-triggered transition, if one is defined."""
        table = self._state_transitions[self._current_index]
        if table:
            target = table.get(msg_type)
            if target is not None:
                self._move_to(target)

    def user_space_fast(self, msg_types) -> bool:
        """True when every msgType in ``msg_types`` is allowed in the
        current state and none can trigger a transition — the batched
        ingest path may then skip per-message FSM work (the per-message
        outcome would be: allowed, no state change)."""
        if self._state_user_transitions[self._current_index]:
            return False
        is_allowed = self.states[self._current_index].is_allowed
        for mt in msg_types:
            if not is_allowed(mt):
                return False
        return True

    def move_to_next_state(self) -> bool:
        """Advance to the next state in declaration order (auth success path)."""
        if self._current_index + 1 < len(self.states):
            self._current_index += 1
            return True
        return False

    def _move_to(self, name: str) -> None:
        state = self._by_name.get(name)
        if state is None:
            raise KeyError(f"unknown FSM state: {name}")
        self._current_index = self.states.index(state)

"""Delivery SLO plane: the number users experience, tracked in-process.

Every prior observability layer measures *internals* — per-stage tick
budgets (core/tracing.py), aggregate rates (core/metrics.py). None of
them measures the one number a player feels: how long an update takes
from the moment its bytes hit the gateway to the moment the fan-out
that carries it is sent. This module closes that gap and makes the
north-star "< 5ms p99 fan-out delivery at the live gateway" claim a
*live* measurement instead of a bench artifact:

- **End-to-end delivery latency.** ``core/connection.py`` stamps a
  monotonic ingest time on every externally-received message (the
  batched native fast path and the protobuf slow path both), the stamp
  rides the message context through channel dispatch and the update
  ring (``core/data.py``), and the fan-out send that delivers a window
  records ``delivery_latency_ms{channel_type,path}`` — one sample per
  delivered window, stamped with the NEWEST update it carries (the
  pipeline-transit reading; the cadence-held component is measured
  separately as staleness). Stamps survive backpressure stashes and
  overload-stretched intervals: a held-then-released delivery reports
  its true (large) latency, never a negative or dropped sample.
- **Fan-out staleness.** Once per GLOBAL tick, ONE round-robin channel
  with live data is sampled: for each subscriber priority class (the
  overload ladder's shed order) the age of the newest state that class
  has not yet been sent lands in
  ``fanout_staleness_ms{channel_type,sub_class}`` — bounded cost, and
  the honest counterweight to the delivery number (a browned-out
  observer is *stale*, not slow).
- **SLO tracker.** A declarative SLO table (delivery p99, tick budget
  utilization, trunk RTT, WAL fsync RPO by default; operators override
  via ``-slo-config``) is evaluated in-process every GLOBAL tick with
  multi-window burn rates: each SLO buckets good/bad events into
  per-second rings, and ``burn = bad_fraction / error_budget`` is
  exported per window (``slo_burn_rate{slo,window}``). A window whose
  burn crosses its alarm threshold fires a breach — counted
  double-entry (``slo_breaches_total{slo}`` + the python
  ``breach_counts`` ledger) on the rising edge, and each breach
  freezes a flight-recorder ``slo_breach`` anomaly dump so every SLO
  violation arrives with the tick timeline that caused it.

The plane is armed by ``-slo`` (default on for served gateways; soaks
with deterministic envelopes pin it off). Disabled, every hook is one
attribute load. See doc/observability.md.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from ..utils.logger import get_logger
from .affinity import affinity as _affinity

logger = get_logger("slo")

NS_PER_MS = 1_000_000

# Hot-path handle bound lazily on first use (channel.py imports this
# module at load, so importing channel here would cycle).
_all_channels = None


@dataclass
class SloSpec:
    """One declarative SLO row.

    ``source`` names the event stream feeding it (``delivery`` is fed
    by :meth:`SloPlane.record_delivery`; anything else by
    :meth:`SloPlane.observe` under that name). An event is *bad* when
    its value exceeds ``threshold`` (delivery/trunk_rtt/wal_fsync in
    ms; tick_budget in budget-utilization units). ``objective`` is the
    allowed good fraction (0.99 -> a 1% error budget); ``windows`` are
    the burn-rate evaluation horizons in seconds; ``burn_alarm`` is
    the per-window burn-rate multiple that fires a breach.
    """

    name: str
    source: str
    threshold: float
    objective: float = 0.99
    windows: tuple = (60, 300)
    burn_alarm: float = 1.0
    # Events below which a window is not judged (a single bad sample
    # in an idle second must not alarm a 99% objective).
    min_events: int = 20


def default_slos() -> list[SloSpec]:
    """The gateway's built-in SLO table (doc/observability.md)."""
    from .settings import global_settings as st

    return [
        # The north-star clause: ingest->fan-out delivery under 5ms.
        SloSpec(name="delivery_p99", source="delivery", threshold=5.0,
                objective=0.99, windows=(60, 300), burn_alarm=1.0),
        # A tick that overruns its interval ate someone's latency.
        SloSpec(name="tick_budget", source="tick_budget", threshold=1.0,
                objective=0.99, windows=(60, 300), burn_alarm=1.0),
        # Inter-gateway control-plane health (doc/federation.md).
        SloSpec(name="trunk_rtt", source="trunk_rtt", threshold=50.0,
                objective=0.99, windows=(60, 300), burn_alarm=1.0,
                min_events=5),
        # Durability RPO: one fsync batch (doc/persistence.md).
        SloSpec(name="wal_fsync_rpo", source="wal_fsync",
                threshold=max(st.wal_fsync_ms * 4.0, 50.0),
                objective=0.99, windows=(60, 300), burn_alarm=1.0,
                min_events=5),
    ]


def load_slo_config(path: str) -> list[SloSpec]:
    """Operator SLO table: a JSON list of SloSpec field dicts."""
    with open(path) as f:
        rows = json.load(f)
    specs = []
    for row in rows:
        row = dict(row)
        if "windows" in row:
            row["windows"] = tuple(int(w) for w in row["windows"])
        specs.append(SloSpec(**row))
    return specs


class _WindowRing:
    """Per-second (good, bad) buckets over the largest window; burn
    rates for smaller windows read a suffix. Observers may run on
    other threads (the WAL writer, trunk reads) — a small lock guards
    the bucket map; the per-event cost is one dict update."""

    __slots__ = ("span", "buckets", "lock")

    def __init__(self, span_s: int):
        self.span = span_s
        self.buckets: dict[int, list] = {}  # second -> [good, bad]  # tpulint: shared=lock
        self.lock = threading.Lock()

    def add(self, second: int, bad: bool) -> None:
        with self.lock:
            b = self.buckets.get(second)
            if b is None:
                b = self.buckets[second] = [0, 0]
                # Amortized trim: drop seconds past the span.
                if len(self.buckets) > self.span + 2:
                    floor = second - self.span
                    for s in [s for s in self.buckets if s < floor]:
                        del self.buckets[s]
            b[bad] += 1

    def window_counts(self, now_second: int, window_s: int) -> tuple:
        """(good, bad) over the trailing ``window_s`` seconds."""
        good = bad = 0
        with self.lock:
            floor = now_second - window_s
            for s, (g, b) in self.buckets.items():
                if s > floor:
                    good += g
                    bad += b
        return good, bad


@dataclass
class _SloState:
    spec: SloSpec
    ring: _WindowRing
    # window seconds -> alarm currently firing (rising-edge breach
    # accounting: a sustained burn counts once until it clears).
    alarmed: dict[int, bool] = field(default_factory=dict)
    burn: dict[int, float] = field(default_factory=dict)


class SloPlane:
    """Process-wide SLO tracker (one instance: ``slo``)."""

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        self.enabled = False
        self._states: dict[str, _SloState] = {}
        self._by_source: dict[str, list[_SloState]] = {}
        # Python-side breach ledger; must match slo_breaches_total.
        self.breach_counts: dict[str, int] = {}
        self.breach_events: list[dict] = []
        # Delivery-latency python tally (soak cross-checks + cheap p99
        # without scraping): the ONE bucket-edge tuple shared with the
        # delivery_latency_ms histogram — a retune in metrics.py can
        # never silently diverge the two.
        from .metrics import DELIVERY_LATENCY_BUCKETS

        self.delivery_edges = DELIVERY_LATENCY_BUCKETS
        self.delivery_counts = [0] * (len(self.delivery_edges) + 1)
        self.delivery_total = 0
        self._delivery_children: dict[tuple, object] = {}
        self._staleness_children: dict[tuple, object] = {}
        # Round-robin staleness ring: channel ids with live data +
        # subscribers, rebuilt at the eval cadence; the per-tick sample
        # visits ONE entry (strictly bounded cost however many
        # channels exist).
        self._sample_ring: list[int] = []
        self._sample_pos = 0
        # Burn-rate evaluation cadence (rings bucket per second; tests
        # set 0.0 to evaluate on every tick).
        self.eval_interval_s = 1.0
        self._next_eval = 0.0
        self._epoch = time.monotonic()

    def configure(self, enabled: bool = True,
                  specs: Optional[list[SloSpec]] = None) -> None:
        self.reset()
        self.enabled = enabled
        if not enabled:
            return
        for spec in (specs if specs is not None else default_slos()):
            span = max(spec.windows)
            state = _SloState(spec=spec, ring=_WindowRing(span))
            for w in spec.windows:
                state.alarmed[w] = False
                state.burn[w] = 0.0
            self._states[spec.name] = state
            self._by_source.setdefault(spec.source, []).append(state)

    # ---- event intake (hot paths; guard on slo.enabled) ------------------

    def record_delivery(self, channel_type_name: str, path: str,
                        ingest_ns: int, now_ns: Optional[int] = None) -> None:
        """One delivered fan-out window whose newest update was stamped
        at ``ingest_ns`` (host monotonic). Clamped at zero: a stamp can
        never produce a negative sample, whatever clock the caller fed
        (the overload-stretch hold test pins this)."""
        if not self.enabled or ingest_ns <= 0:
            return
        if now_ns is None:
            now_ns = time.monotonic_ns()
        ms = max(now_ns - ingest_ns, 0) / NS_PER_MS
        child = self._delivery_children.get((channel_type_name, path))
        if child is None:
            from . import metrics

            child = metrics.delivery_latency_ms.labels(
                channel_type=channel_type_name, path=path)
            self._delivery_children[(channel_type_name, path)] = child
        child.observe(ms)
        # Python-side tally (linear scan over 11 edges; the branch
        # usually exits in the first few buckets).
        i = 0
        edges = self.delivery_edges
        while i < len(edges) and ms > edges[i]:
            i += 1
        self.delivery_counts[i] += 1
        self.delivery_total += 1
        self._feed("delivery", ms)

    def observe(self, source: str, value: float) -> None:
        """Feed one event into every SLO declared on ``source``
        (trunk_rtt ms, wal_fsync ms, tick_budget utilization, ...).
        Thread-safe; callers guard on ``slo.enabled``."""
        if not self.enabled:
            return
        self._feed(source, value)

    def _feed(self, source: str, value: float) -> None:
        states = self._by_source.get(source)
        if not states:
            return
        second = int(time.monotonic())
        for state in states:
            state.ring.add(second, value > state.spec.threshold)

    # ---- the per-tick evaluation -----------------------------------------

    def on_global_tick(self) -> None:
        """The staleness sample (every call) + the burn-rate evaluation
        (at ``eval_interval_s`` cadence — the rings bucket per second,
        so evaluating faster than 1Hz buys nothing and the window scan
        over every SLO would tax the tick); called from the GLOBAL
        channel tick (single-writer context). Disabled = no-op (call
        sites also guard)."""
        if not self.enabled:
            return
        _affinity.expect("tick-loop")
        now = time.monotonic()
        if now >= self._next_eval:
            self._next_eval = now + self.eval_interval_s
            self._rebuild_sample_ring()
            self._evaluate(now)
        self._sample_staleness()

    def _evaluate(self, now: float) -> None:
        from . import metrics
        from .tracing import recorder as _trace

        now_second = int(now)
        for name, state in self._states.items():
            spec = state.spec
            budget = max(1.0 - spec.objective, 1e-9)
            for w in spec.windows:
                good, bad = state.ring.window_counts(now_second, w)
                total = good + bad
                if total < spec.min_events:
                    # Not enough signal to judge; burn decays to zero
                    # and an active alarm clears (the traffic ended).
                    state.burn[w] = 0.0
                    state.alarmed[w] = False
                    metrics.slo_burn_rate.labels(
                        slo=name, window=f"{w}s").set(0.0)
                    continue
                burn = (bad / total) / budget
                state.burn[w] = burn
                metrics.slo_burn_rate.labels(
                    slo=name, window=f"{w}s").set(burn)
                firing = burn >= spec.burn_alarm
                if firing and not state.alarmed[w]:
                    state.alarmed[w] = True
                    self._count_breach(name)
                    detail = (f"{name}[{w}s] burn={burn:.2f} "
                              f"(bad {bad}/{total}, "
                              f"budget {budget:.4f})")
                    logger.warning("SLO breach: %s", detail)
                    self.breach_events.append({
                        "slo": name, "window_s": w,
                        "burn": round(burn, 3), "bad": bad,
                        "total": total,
                        "t": round(time.monotonic() - self._epoch, 3),
                    })
                    del self.breach_events[:-256]
                    if _trace.enabled:
                        # Every SLO violation ships with the frozen
                        # tick timeline that produced it — forced past
                        # the anomaly cooldown (breaches are rare by
                        # construction: rising-edge + min-events
                        # gated; a tick_budget anomaly storm on a
                        # saturated box must not eat their dump slot).
                        _trace.note_anomaly("slo_breach", detail,
                                            force=True)
                elif not firing:
                    state.alarmed[w] = False

    def _count_breach(self, name: str, n: int = 1) -> None:
        """Double-entry: the prometheus counter AND the python ledger
        (soaks assert they match exactly)."""
        self.breach_counts[name] = self.breach_counts.get(name, 0) + n
        from . import metrics

        metrics.slo_breaches.labels(slo=name).inc(n)

    # ---- staleness sampling ----------------------------------------------

    def _rebuild_sample_ring(self) -> None:
        """Refresh the staleness round-robin (channels with live data
        AND subscribers) — runs at the eval cadence, so the full
        channel scan is paid once a second, never per tick."""
        from .channel import all_channels

        self._sample_ring = [
            cid for cid, ch in all_channels().items()
            if ch.data is not None and ch.data.update_msg_buffer
            and ch.subscribed_connections
        ]

    def _sample_staleness(self) -> None:
        """One round-robin channel per GLOBAL tick: for each subscriber
        priority class, the age of the newest state that class has not
        yet been sent. O(one channel's subscribers) per tick — bounded
        whatever the world size (the candidate ring is rebuilt at the
        eval cadence)."""
        ring = self._sample_ring
        if not ring:
            return
        # Lazy one-time bind (channel imports slo at module load, so
        # the import must not run at OUR load — but paying the import
        # machinery per tick is measurable on the hot path).
        global _all_channels
        if _all_channels is None:
            from .channel import all_channels as _ac

            _all_channels = _ac
        channels = _all_channels()
        nxt = None
        # A ring entry can go stale between rebuilds (channel removed,
        # buffer drained): skip up to two per tick, still bounded.
        for _ in range(2):
            if not ring:
                return
            self._sample_pos %= len(ring)
            ch = channels.get(ring[self._sample_pos])
            self._sample_pos += 1
            if (ch is not None and not ch.is_removing()
                    and ch.data is not None and ch.data.update_msg_buffer
                    and ch.subscribed_connections):
                nxt = ch
                break
        if nxt is None:
            return
        data = nxt.data
        newest = data.update_msg_buffer[-1]
        newest_ns = newest.ingest_ns
        if newest_ns <= 0:
            return
        # One age for the whole channel (the newest ingest is shared);
        # the per-sub work is a dict get + two int compares — the
        # subscription's shed priority is precomputed at subscribe time
        # (core/subscription.py), never re-derived here.
        age_ms = max(time.monotonic_ns() - newest_ns, 0) / NS_PER_MS
        msg_index = data.msg_index
        per_class: dict[int, float] = {}
        for foc in nxt.fan_out_queue:
            conn = foc.conn
            if conn is None or conn.is_closing():
                continue
            if foc.last_message_index >= msg_index:
                continue  # fully delivered; nothing is stale for it
            cs = nxt.subscribed_connections.get(conn)
            if cs is None:
                continue
            per_class[cs.priority] = age_ms
        ct_name = nxt.channel_type.name
        for klass, age_ms in per_class.items():
            key = (ct_name, klass)
            child = self._staleness_children.get(key)
            if child is None:
                from . import metrics

                child = metrics.fanout_staleness_ms.labels(
                    channel_type=ct_name, sub_class=f"p{klass}")
                self._staleness_children[key] = child
            child.observe(age_ms)

    # ---- reporting -------------------------------------------------------

    def delivery_quantile(self, q: float) -> Optional[float]:
        """Quantile estimate (ms) from the python-side delivery tally
        (upper bucket edge, the conservative reading); None without
        samples."""
        total = self.delivery_total
        if not total:
            return None
        target = q * total
        acc = 0
        for i, n in enumerate(self.delivery_counts):
            acc += n
            if acc >= target:
                return (self.delivery_edges[i]
                        if i < len(self.delivery_edges)
                        else float("inf"))
        return float("inf")

    def status(self) -> dict:
        """Per-SLO burn/alarm snapshot for /introspect and the soaks.
        Runs on the ops HTTP thread: list() snapshots the table first
        (a concurrent configure() must degrade to a stale read, never a
        dict-changed-size error in a probe)."""
        out = {}
        for name, state in list(self._states.items()):
            out[name] = {
                "objective": state.spec.objective,
                "threshold": state.spec.threshold,
                "burn": {f"{w}s": round(state.burn[w], 3)
                         for w in state.spec.windows},
                "alarmed": {f"{w}s": state.alarmed[w]
                            for w in state.spec.windows},
                "breaches": self.breach_counts.get(name, 0),
            }
        return out

    def report(self) -> dict:
        return {
            "enabled": self.enabled,
            "slos": self.status(),
            "breach_counts": dict(self.breach_counts),
            "breach_events": list(self.breach_events),
            "delivery_total": self.delivery_total,
            "delivery_p50_ms": self.delivery_quantile(0.50),
            "delivery_p99_ms": self.delivery_quantile(0.99),
        }


# The process-wide plane. Hot-path hook sites hold a module reference
# and guard on ``slo.enabled`` — one attribute load while disarmed.
slo = SloPlane()


def configure_from_settings() -> None:
    """Apply the -slo / -slo-config flags (run_server boot path)."""
    from .settings import global_settings as st

    specs = None
    if st.slo_config:
        specs = load_slo_config(st.slo_config)
    slo.configure(enabled=st.slo_enabled, specs=specs)


def reset_slo() -> None:
    """Test hook."""
    slo.reset()

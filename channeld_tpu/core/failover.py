"""Spatial authority failover: cell re-hosting + transactional handover.

Beyond-reference capability (the reference pkg/channeld has recovery for
servers that COME BACK, but a server that dies for good leaves its
spatial and entity channels ownerless forever — every update to them is
dropped). This module closes that gap, in the authority-re-assignment
tradition of geo-replicated service architectures (PAPERS.md: Spider's
replicated-authoritative-state failover): the gateway already holds the
authoritative ChannelData for every cell, so when a recoverable server's
recovery window expires (``ServerLostEvent``), the orphaned cells are
re-hosted onto surviving spatial servers instead of going dark.

Two cooperating pieces (doc/failover.md):

- :class:`HandoverJournal` — a per-entity prepare -> commit/abort ledger
  wrapped around the cross-cell handover orchestration
  (``spatial/grid.py _orchestrate_pair``). The data move runs as two
  queued ``Channel.execute`` hops (remove in the src tick, add in the
  dst tick); the journal records the transaction so a server crash (or
  channel removal) between the hops deterministically resolves to
  exactly ONE owning cell — never a duplicated or lost entity. The
  authoritative ``_data_cell`` placement ledger only flips on COMMIT
  (the add actually ran); aborted handovers re-add the data to the src
  cell through the same FIFO queue and are re-offered after failover.

- :class:`FailoverPlane` — listens for ``ServerLostEvent``, then (inside
  the GLOBAL channel tick, the same execution context as handover
  orchestration): resolves in-flight journal records, picks surviving
  spatial servers by load (fewest owned cells, tie-break lowest conn
  id), re-hosts each orphaned cell (owner + WRITE subscription +
  authoritative-state bootstrap reusing the snapshot pack path),
  re-points orphaned entity channels to their cell's new owner, forces a
  full-state resync for every remaining subscriber, and emits structured
  ``CellRehostedMessage`` notifications (msgType 25) so engine SDKs can
  respawn authority.

Every re-host/abort is counted twice on purpose — prometheus counters
AND python-side ledgers — so the failover soak
(``scripts/failover_soak.py``) proves the accounting exact.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional

from ..utils.logger import get_logger
from .settings import global_settings
from .types import ChannelDataAccess, MessageType

logger = get_logger("failover")

# Handover-journal record states. PREPARED -> REMOVED happens in the src
# cell's tick, -> COMMITTED in the dst cell's tick; ABORTED is the
# failover resolution when the dst can never run its add.
PREPARED = "prepared"
REMOVED = "removed"
COMMITTED = "committed"
ABORTED = "aborted"


def placement_score(cells_owned: int, entities_hosted: int) -> float:
    """Entity-weighted placement load of one candidate server — the ONE
    scoring function shared by failover re-host and the live balancer
    (spatial/balancer.py). Lower is better. Owned-cell count alone (the
    old failover rule) mis-ranks a server with few but HUGE cells as
    idle; entities are the actual per-tick cost driver, so they weigh
    in at ``failover_placement_entity_weight`` cells each."""
    return (
        cells_owned
        + entities_hosted * global_settings.failover_placement_entity_weight
    )


def entity_count_of(ch) -> int:
    """Entities resident in one channel's authoritative data (0 when the
    data type has no entity table)."""
    if ch is None or ch.data is None:
        return 0
    ents = getattr(ch.data.msg, "entities", None)
    return len(ents) if ents is not None else 0


def collect_spatial_loads() -> dict:
    """conn -> [cells_owned, entities_hosted] over every live-owned
    spatial cell — the candidate table both placement consumers feed
    into :func:`placement_score`."""
    from .channel import all_channels

    lo = global_settings.spatial_channel_id_start
    hi = global_settings.entity_channel_id_start
    loads: dict = {}
    for cid, ch in all_channels().items():
        if lo <= cid < hi and ch.has_owner():
            row = loads.setdefault(ch.get_owner(), [0, 0])
            row[0] += 1
            row[1] += entity_count_of(ch)
    return loads


def pick_placement(loads: dict):
    """The candidate with the lowest entity-weighted placement score,
    tie-break lowest conn id; None when there are no candidates. The
    caller mutates ``loads`` between picks so one loss/migration wave
    spreads evenly."""
    if not loads:
        return None
    return min(
        loads,
        key=lambda c: (placement_score(loads[c][0], loads[c][1]), c.id),
    )


def announce_authority_change(ch, new_owner, msg_type, build_msg) -> None:
    """The ONE announce path for a cell authority change, shared by
    failover re-host (CellRehostedMessage) and planned migration
    (CellMigratedMessage). Serialized through the cell's own queue so
    any queued entity remove/add lands before the bootstrap snapshot is
    taken: the new owner's copy carries the packed authoritative state
    (the snapshot pack path); every other subscriber gets the
    identifier-only copy — encoded once, shared — plus a forced
    full-state resync (a delta stream is void across an authority
    change)."""
    from .message import MessageContext
    from .snapshot import pack_channel_state

    def _announce(c, owner=new_owner):
        base = build_msg(c)
        boot = type(base)()
        boot.CopyFrom(base)
        packed = pack_channel_state(c)
        if packed is not None:
            boot.channelData.CopyFrom(packed)
        owner.send(MessageContext(
            msg_type=msg_type, msg=boot, channel_id=c.id,
        ))
        shared = MessageContext(
            msg_type=msg_type, msg=base, channel_id=c.id,
        )
        shared.ensure_raw_body()
        for conn, sub in list(c.subscribed_connections.items()):
            if conn is owner or conn.is_closing():
                continue
            conn.send(shared)
            sub.fanout_conn.had_first_fanout = False

    ch.execute(_announce)


@dataclass
class HandoverRecord:
    txn_id: int
    entity_id: int
    src_channel_id: int
    dst_channel_id: int
    # The entity data message captured at prepare time — what an abort
    # re-adds to the src cell. None for group members that carried no
    # data (their "move" is removal-only, nothing to restore).
    data: object
    state: str = PREPARED
    # True for cross-gateway handovers (federation/plane.py): the dst
    # channel id names a REMOTE cell, so the local failover resolution
    # must never judge the txn by local channel existence — the
    # federation plane owns its commit/abort (trunk ack or timeout).
    remote: bool = False


class HandoverJournal:
    """Transactional per-entity handover ledger (one in-flight record per
    entity; a chained second hop overwrites the in-flight slot, and the
    first hop's commit only clears the slot if it still owns it)."""

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        self._in_flight: dict[int, HandoverRecord] = {}
        self._txn = 0
        # entity id -> highest txn id whose commit flipped the placement
        # ledger. Commits land in CHANNEL-TICK order, not txn order — a
        # chained hop's commit can run before its predecessor's — so a
        # flip is only granted to a txn newer than the last granted one.
        self._flip_txn: dict[int, int] = {}
        # Python-side ledger; must match handover_journal_total exactly.
        self.counts: dict[str, int] = {}

    def _count(self, state: str, n: int = 1) -> None:
        self.counts[state] = self.counts.get(state, 0) + n
        from . import metrics

        metrics.handover_journal.labels(state=state).inc(n)

    def _wal_log(self, op: str, rec: "HandoverRecord") -> None:
        """Journal transitions ride the WAL (doc/persistence.md): a
        crash mid-handover replays to exactly one owning cell — the
        restored src on a lost commit, with a source-wins abort notice
        at a remote batch's destination."""
        from .wal import wal

        if wal.enabled:
            wal.log_journal(op, rec)

    # ---- the transaction surface (called from grid orchestration) -------

    def prepare(
        self, entities: dict, src_channel_id: int, dst_channel_id: int,
        remote: bool = False,
    ) -> list[HandoverRecord]:
        records = []
        for entity_id, data in entities.items():
            self._txn += 1
            rec = HandoverRecord(
                self._txn, entity_id, src_channel_id, dst_channel_id, data,
                remote=remote,
            )
            self._in_flight[entity_id] = rec
            records.append(rec)
            self._wal_log(PREPARED, rec)
        self._count(PREPARED, len(records))
        return records

    def note_removed(self, records: list[HandoverRecord]) -> None:
        """The src cell's remove ran (src tick). Aborted records stay
        aborted — their restoring re-add is already queued behind this
        very remove."""
        for rec in records:
            if rec.state == PREPARED:
                rec.state = REMOVED

    def commit(self, records: list[HandoverRecord]) -> list[int]:
        """The dst cell's add ran (dst tick): the entity now lives in
        exactly the dst cell. Returns the entity ids whose placement
        ledger should flip to this txn's dst — txn-id ordered, so a
        predecessor's late commit never clobbers a chained successor's
        flip."""
        committed = 0
        flips: list[int] = []
        for rec in records:
            if rec.state in (PREPARED, REMOVED):
                rec.state = COMMITTED
                committed += 1
                self._wal_log(COMMITTED, rec)
                # Flip only on a REAL commit: an ABORTED record (entity
                # destroyed mid-flight) must not resurrect a ledger row
                # its cleanup already removed.
                if self._flip_txn.get(rec.entity_id, 0) < rec.txn_id:
                    self._flip_txn[rec.entity_id] = rec.txn_id
                    flips.append(rec.entity_id)
            if self._in_flight.get(rec.entity_id) is rec:
                del self._in_flight[rec.entity_id]
        if committed:
            self._count(COMMITTED, committed)
        return flips

    def abort(self, rec: HandoverRecord) -> None:
        if rec.state not in (COMMITTED, ABORTED):
            rec.state = ABORTED
            self._count(ABORTED)
            self._wal_log(ABORTED, rec)
        if self._in_flight.get(rec.entity_id) is rec:
            del self._in_flight[rec.entity_id]

    # ---- queries ---------------------------------------------------------

    def pending_dst(self, entity_id: int) -> Optional[int]:
        """The dst channel id of the entity's in-flight handover, or
        None. The batched detector consults this BEFORE the committed
        placement ledger: mid-flight, the data is bound for the pending
        dst even though ``_data_cell`` still says src."""
        rec = self._in_flight.get(entity_id)
        return rec.dst_channel_id if rec is not None else None

    def in_flight_count(self) -> int:
        return len(self._in_flight)

    def remote_in_flight(self, entity_id: int) -> bool:
        """True while the entity's in-flight slot holds a CROSS-GATEWAY
        record: local orchestration (and a second remote offer) must
        skip it — the trunk ack tears it down on commit, the abort path
        restores and re-offers it. Orchestrating the entity locally
        mid-flight would double its data (the remote batch already
        captured a copy)."""
        rec = self._in_flight.get(entity_id)
        return (
            rec is not None and rec.remote
            and rec.state in (PREPARED, REMOVED)
        )

    def in_flight_records(self) -> list[HandoverRecord]:
        """ALL in-flight records, local hops included. The epoch
        replica exports these: an entity mid-LOCAL-crossing sits in
        NEITHER cell's data rows (removed from src, the dst add/commit
        still queued), so a snapshot of cell data alone goes blind to
        it — and a gateway killed with its final snapshot taken in that
        window would lose the entity for good (the herding storms that
        precede a death are exactly when crossings are densest)."""
        return [
            rec for rec in self._in_flight.values()
            if rec.state in (PREPARED, REMOVED)
        ]

    def in_flight_touching(self, channel_id: int) -> int:
        """In-flight handover records reading or writing one spatial
        channel — the balancer's drain barrier: a cell migration only
        executes once no transaction still references the cell."""
        return sum(
            1
            for rec in self._in_flight.values()
            if rec.src_channel_id == channel_id
            or rec.dst_channel_id == channel_id
        )

    def forget_entity(self, entity_id: int) -> None:
        """The entity was destroyed/untracked mid-flight: the transaction
        is moot (nothing left to place)."""
        self._flip_txn.pop(entity_id, None)
        rec = self._in_flight.pop(entity_id, None)
        if rec is not None and rec.state not in (COMMITTED, ABORTED):
            rec.state = ABORTED
            self._count(ABORTED)
            self._wal_log(ABORTED, rec)

    # ---- failover resolution --------------------------------------------

    def resolve_in_flight(self) -> list[HandoverRecord]:
        """Deterministic crash resolution: a record whose dst channel can
        never run its add (removed/missing) is aborted — the entity
        belongs to the SRC cell. The restoring re-add is queued on the
        src channel, so FIFO ordering guarantees it lands after any
        still-pending remove regardless of which hop had executed when
        the crash hit. Returns the aborted records (the caller re-offers
        them after failover completes)."""
        from .channel import get_channel

        aborted = []
        for entity_id, rec in list(self._in_flight.items()):
            if rec.remote:
                # Cross-gateway txn: the dst cell lives on another
                # gateway, so "no local dst channel" is its NORMAL
                # in-flight state — the federation plane resolves it
                # (trunk ack, timeout, or trunk loss), never this pass.
                continue
            dst = get_channel(rec.dst_channel_id)
            if dst is not None and not dst.is_removing():
                continue  # the queued add still runs; commit will land
            src = get_channel(rec.src_channel_id)
            if (
                src is not None
                and not src.is_removing()
                and rec.data is not None
            ):
                def _readd(ch, e=rec.entity_id, d=rec.data):
                    adder = getattr(ch.get_data_message(), "add_entity", None)
                    if adder is not None:
                        adder(e, d)

                src.execute(_readd)
            self.abort(rec)
            aborted.append(rec)
            logger.warning(
                "handover txn %d aborted: entity %d stays in cell %d "
                "(dst %d is gone)",
                rec.txn_id, entity_id, rec.src_channel_id,
                rec.dst_channel_id,
            )
        return aborted

    def report(self) -> dict:
        return {
            "counts": dict(self.counts),
            "in_flight": self.in_flight_count(),
        }


# The process-wide journal; grid orchestration and the failover plane
# share it (one attribute load on the handover hot path).
journal = HandoverJournal()


class FailoverPlane:
    """ServerLostEvent -> cell re-hosting. One instance (``plane``),
    (re-)installed by ``init_channels``."""

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        # Python-side re-host ledger; must match the prometheus counters.
        self.ledger: dict[str, int] = {
            "servers_lost": 0,
            "cells_rehosted": 0,
            "cells_unrehostable": 0,
            "entities_repointed": 0,
            "entities_stranded": 0,
            "handovers_aborted": 0,
        }
        self.events: list[dict] = []  # one record per ServerLost, for soaks

    def install(self) -> None:
        from . import events

        events.server_lost.unlisten_for(self)
        events.server_lost.listen_for(self, self._on_server_lost)

    # ---- event intake ----------------------------------------------------

    def _on_server_lost(self, data) -> None:
        self.ledger["servers_lost"] += 1
        if not global_settings.failover_enabled:
            logger.warning(
                "failover disabled: server %s (conn %d) lost for good; its "
                "%d owned channels stay ownerless",
                data.pit, data.prev_conn_id, len(data.owned_channel_ids),
            )
            return
        from .channel import get_global_channel

        gch = get_global_channel()
        if gch is None or gch.is_removing():
            self._run(data)  # no runtime (tests): resolve inline
        else:
            # Channel state is single-writer; re-hosting touches many
            # channels, so it runs where handover orchestration already
            # does — inside the GLOBAL channel tick.
            gch.execute(lambda _ch, d=data: self._run(d))

    # ---- the failover pass (GLOBAL tick context) -------------------------

    def _run(self, data) -> None:
        from . import metrics
        from .channel import all_channels, get_channel
        from ..spatial.controller import get_spatial_controller

        t0 = time.monotonic()
        st = global_settings
        ctl = get_spatial_controller()
        spatial_lo = st.spatial_channel_id_start
        spatial_hi = st.entity_channel_id_start

        # In-flight handovers whose dst died with the server resolve to
        # exactly one owning cell before any bootstrap is snapshotted.
        aborted = journal.resolve_in_flight()
        self.ledger["handovers_aborted"] += len(aborted)

        orphan_cells = []
        orphan_entities = []
        for cid in data.owned_channel_ids:
            ch = get_channel(cid)
            if ch is None or ch.is_removing():
                continue
            if spatial_lo <= cid < spatial_hi and not ch.has_owner():
                orphan_cells.append(cid)
            elif cid >= spatial_hi:
                orphan_entities.append(cid)

        # Surviving spatial servers by entity-weighted load (the shared
        # placement_score), updated as orphans are assigned so one loss
        # spreads evenly — an entity-heavy server is deprioritized even
        # when it owns few cells.
        loads = collect_spatial_loads()
        assignments: dict[int, object] = {}
        if loads:
            for cid in sorted(orphan_cells):
                target = pick_placement(loads)
                loads[target][0] += 1
                loads[target][1] += entity_count_of(get_channel(cid))
                assignments[cid] = target
        unrehostable = len(orphan_cells) - len(assignments)
        if unrehostable:
            self.ledger["cells_unrehostable"] += unrehostable
            logger.error(
                "no surviving spatial server: %d orphaned cells stay "
                "ownerless (updates to them are counted in "
                "ownerless_drops_total)", unrehostable,
            )

        # Orphaned entity channels re-point to the owner of the cell
        # their data lives in (the committed placement ledger when a TPU
        # controller runs; last-known position otherwise). The sweep
        # covers the dead server's stash AND every other ownerless
        # entity channel: a handover orchestrated INTO an orphaned cell
        # during the recovery window stamps the entity with that cell's
        # (dead) owner, and those channels appear in nobody's stash.
        repointed: dict[int, list[int]] = {}
        seen = set(orphan_entities)
        sweep = list(orphan_entities)
        for cid, ch in all_channels().items():
            if cid >= spatial_hi and cid not in seen and not ch.has_owner():
                sweep.append(cid)
        for eid in sweep:
            ech = get_channel(eid)
            if ech is None or ech.is_removing() or ech.has_owner():
                # Already re-owned by a live server (a handover landed
                # it in a living cell during the window): leave it.
                continue
            cell_id = self._cell_of_entity(ctl, eid)
            new_owner = assignments.get(cell_id)
            if new_owner is None and cell_id is not None:
                cell_ch = get_channel(cell_id)
                if cell_ch is not None and cell_ch.has_owner():
                    new_owner = cell_ch.get_owner()
            if new_owner is None:
                self.ledger["entities_stranded"] += 1
                continue
            self._repoint_entity(ech, new_owner)
            self.ledger["entities_repointed"] += 1
            if cell_id is not None:
                repointed.setdefault(cell_id, []).append(eid)

        for cid, target in assignments.items():
            self._rehost_cell(
                get_channel(cid), target, data.prev_conn_id,
                sorted(repointed.get(cid, [])),
            )

        # Aborted handovers re-offer once failover is done: the entity
        # re-orchestrates from its (restored) src cell to wherever its
        # position now maps — through the normal batched detector.
        for rec in aborted:
            self._reoffer(ctl, rec)

        elapsed_ms = (time.monotonic() - t0) * 1000.0
        metrics.failover_rehost_ms.observe(elapsed_ms)
        from .tracing import recorder as _trace

        if _trace.enabled:
            # A failover epoch is a flight-recorder anomaly: the frozen
            # timeline holds the ticks around the loss plus this whole
            # re-host pass (its span lands just below).
            _trace.span("failover.rehost", int(t0 * 1e9))
            _trace.note_anomaly(
                "failover_epoch",
                f"{data.pit}: {len(assignments)}/{len(orphan_cells)} "
                f"cells re-hosted in {elapsed_ms:.1f}ms",
            )
        deadline_ms = st.failover_rehost_deadline_s * 1000.0
        log = logger.warning if elapsed_ms > deadline_ms else logger.info
        log(
            "failover for %s (conn %d): %d/%d cells re-hosted, %d entity "
            "channels re-pointed (%d stranded), %d in-flight handovers "
            "aborted, %.1fms",
            data.pit, data.prev_conn_id, len(assignments),
            len(orphan_cells), sum(len(v) for v in repointed.values()),
            self.ledger["entities_stranded"], len(aborted), elapsed_ms,
        )
        self.events.append({
            "pit": data.pit,
            "prev_conn_id": data.prev_conn_id,
            "reason": data.reason,
            "orphan_cells": sorted(orphan_cells),
            "rehosted": {
                str(cid): conn.id for cid, conn in assignments.items()
            },
            "entities_repointed": sum(len(v) for v in repointed.values()),
            "handovers_aborted": len(aborted),
            "duration_ms": round(elapsed_ms, 3),
        })

    # ---- pieces ----------------------------------------------------------

    def _cell_of_entity(self, ctl, entity_id: int) -> Optional[int]:
        if ctl is None:
            return None
        cell = getattr(ctl, "_data_cell", {}).get(entity_id)
        if cell is not None:
            return cell
        info = getattr(ctl, "_last_positions", {}).get(entity_id)
        if info is not None:
            try:
                return ctl.get_channel_id(info)
            except ValueError:
                return None
        return None

    def _repoint_entity(self, ech, new_owner) -> None:
        from .subscription import subscribe_to_channel
        from .subscription_messages import send_subscribed

        ech.set_owner(new_owner)
        # Full first fan-out on purpose: the entity channel's own state
        # streams to the new authority (the cell bootstrap carries only
        # the spatial data).
        cs, should_send = subscribe_to_channel(new_owner, ech, None)
        if should_send and cs is not None:
            send_subscribed(new_owner, ech, new_owner, 0, cs.options)

    def _rehost_cell(self, ch, new_owner, prev_conn_id, entity_ids) -> None:
        from . import metrics
        from ..protocol import control_pb2, spatial_pb2
        from .subscription import subscribe_to_channel
        from .subscription_messages import send_subscribed

        ch.set_owner(new_owner)
        opts = control_pb2.ChannelSubscriptionOptions(
            dataAccess=ChannelDataAccess.WRITE_ACCESS,
            skipSelfUpdateFanOut=True,
            # The authoritative bootstrap rides the CellRehostedMessage;
            # a second full-state fan-out would be redundant bytes.
            skipFirstFanOut=True,
        )
        cs, should_send = subscribe_to_channel(new_owner, ch, opts)
        if should_send and cs is not None:
            send_subscribed(new_owner, ch, new_owner, 0, cs.options)
        self.ledger["cells_rehosted"] += 1
        metrics.failover_rehost.inc()

        announce_authority_change(
            ch, new_owner, MessageType.CELL_REHOSTED,
            lambda c, eids=list(entity_ids): spatial_pb2.CellRehostedMessage(
                channelId=c.id,
                prevOwnerConnId=prev_conn_id,
                newOwnerConnId=new_owner.id,
                entityIds=eids,
            ),
        )
        # Device plane: the new owner's WRITE sub registered a fresh
        # engine fan-out slot above (subscribe_to_channel); controllers
        # keeping extra per-cell state get the explicit hook.
        from ..spatial.controller import get_spatial_controller

        ctl = get_spatial_controller()
        hook = getattr(ctl, "on_cell_rehosted", None)
        if hook is not None:
            hook(ch.id, new_owner)

    def _reoffer(self, ctl, rec: HandoverRecord) -> None:
        """Queue an aborted handover for re-orchestration through the
        batched detector (TPU controller) once failover completed."""
        if ctl is None:
            return
        deferred = getattr(ctl, "_deferred_crossings", None)
        if deferred is None or rec.entity_id in deferred:
            return
        last = getattr(ctl, "_last_positions", {}).get(rec.entity_id)
        start = global_settings.spatial_channel_id_start
        try:
            old_info = ctl._cell_center(rec.src_channel_id - start)
        except AttributeError:
            return
        provider = getattr(ctl, "_providers", {}).get(
            rec.entity_id, lambda s, d, e=rec.entity_id: e
        )
        deferred[rec.entity_id] = (old_info, last or old_info, provider)

    def report(self) -> dict:
        return {
            "ledger": dict(self.ledger),
            "events": list(self.events),
            "journal": journal.report(),
        }


plane = FailoverPlane()


def reset_failover() -> None:
    """Test hook (also run by init_channels at world boot)."""
    journal.reset()
    plane.reset()

"""Gateway server: listeners, per-connection reactors, bootstrap.

Capability parity with the reference entrypoint wiring
(ref: cmd/main.go:39-54, pkg/channeld/connection.go:186-242):
ParseFlag -> InitLogs -> InitMetrics -> InitConnections -> InitChannels ->
InitSpatialController -> serve /metrics -> StartListening(SERVER) ->
[wait GlobalChannelPossessed] -> StartListening(CLIENT).

Transports: TCP (asyncio streams) and WebSocket (ref: connection_websocket.go);
both feed the same Connection byte path. A single 1ms flush task batches the
send queues of every connection (the reference runs one flush goroutine per
connection; a shared pump is the asyncio-idiomatic equivalent).
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from typing import Optional

from ..chaos.injector import chaos as _chaos
from ..utils.logger import get_logger, init_logs
from . import events
from .channel import congestion_wait, connection_congested, init_channels
from .connection import (
    Connection,
    add_connection,
    drain_pending_flush,
    flush_pending_ingest,
    init_connections,
    requeue_flush,
)
from . import edge as _edge
from .edge import edge_tick
from .connection_recovery import connection_recovery_loop
from .ddos import init_anti_ddos, unauth_reaper_loop
from .settings import global_settings
from .types import ConnectionType

logger = get_logger("server")

# Outbound shed limit per connection. The reference's per-connection writer
# goroutine blocks on the socket, which is natural backpressure; an asyncio
# transport instead buffers in memory, so a stalled client subscribed to a
# busy channel would accumulate unbounded bytes. Past this limit the client
# is considered dead-slow and is disconnected (it can reconnect and recover
# via the C19 recovery path).
MAX_SEND_BUFFER = 4 * 1024 * 1024


class TcpTransport:
    """Byte sink over a raw asyncio.Transport (no StreamWriter layer)."""

    def __init__(self, transport: asyncio.Transport):
        self.transport = transport
        try:
            transport.set_write_buffer_limits(high=MAX_SEND_BUFFER)
        except (AttributeError, NotImplementedError):
            pass

    def write(self, data: bytes) -> None:
        t = self.transport
        if t.is_closing():
            return
        try:
            buffered = t.get_write_buffer_size()
        except (AttributeError, NotImplementedError):
            buffered = 0
        if buffered + len(data) > MAX_SEND_BUFFER:
            # Backstop behind the edge plane's transport gate
            # (edge_transport_high_bytes normally defers the pump well
            # before this point); double-entry counted like every other
            # edge reap (doc/edge_hardening.md).
            logger.warning("tcp peer %s too slow (%d bytes unsent); closing",
                           self.remote_addr(), buffered)
            _edge.ledgers.count_reap("send_buffer")
            t.close()
            return
        t.write(data)

    def get_write_buffer_size(self) -> int:
        """Unsent bytes buffered in the transport — the edge plane's
        flush gate reads this to detect a peer not draining its socket."""
        try:
            return self.transport.get_write_buffer_size()
        except (AttributeError, NotImplementedError):
            return 0

    def close(self) -> None:
        if not self.transport.is_closing():
            self.transport.close()

    def remote_addr(self) -> Optional[tuple]:
        return self.transport.get_extra_info("peername")


class _TcpServerProtocol(asyncio.Protocol):
    """Raw-protocol TCP receive path. The previous streams-based reactor
    paid a Future + task switch per read; at 10K mostly-1-message reads
    per second that machinery was a measurable share of the per-message
    budget. Backpressure keeps the reference semantics (a congested
    channel pauses exactly the connection that fed it,
    ref: channel.go:295-310) via transport.pause_reading()."""

    __slots__ = ("conn_type", "conn", "transport", "_draining")

    def __init__(self, conn_type: ConnectionType):
        self.conn_type = conn_type
        self.conn: Optional[Connection] = None
        self.transport: Optional[asyncio.Transport] = None
        self._draining = False

    def connection_made(self, transport: asyncio.Transport) -> None:
        self.transport = transport
        sock = transport.get_extra_info("socket")
        if sock is not None:
            import socket as _socket

            try:
                sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
            except OSError:
                pass
        try:
            self.conn = add_connection(TcpTransport(transport), self.conn_type)
        except ConnectionRefusedError:
            transport.abort()

    def data_received(self, data: bytes) -> None:
        conn = self.conn
        if conn is None:
            return
        # Transport/connection faults target CLIENT sockets: the chaos
        # story is "the gateway degrades gracefully under hostile client
        # weather"; server-plane loss is exercised by the C19 recovery
        # scenarios instead.
        inject = _chaos.armed and self.conn_type == ConnectionType.CLIENT
        if inject:
            data = self._chaos_ingress(data)
            if data is None:
                return
        conn.on_bytes(data)
        if inject and not conn.is_closing() and _chaos.fire(
            "connection.eof_race"
        ):
            # The peer vanishes right after this read: EOF races any
            # deferred ingest batch — close() must deliver the final
            # burst before teardown (pinned by test_chaos).
            self.transport.close()
            conn.close(unexpected=True)
            return
        if conn.is_closing():
            self.transport.close()
            return
        if conn.has_pending() or connection_congested(conn):
            # Stop reading from *this* socket until the stash drains —
            # TCP backpressure, like the reference's blocking queue send.
            try:
                self.transport.pause_reading()
            except RuntimeError:
                return
            if not self._draining:
                self._draining = True
                asyncio.ensure_future(self._drain())

    def _chaos_ingress(self, data: bytes):
        """Armed-only transport fault gate: None = read consumed by the
        fault (socket reset), else the (possibly corrupted) bytes."""
        conn = self.conn
        if _chaos.fire("transport.reset"):
            # Peer reset before the read was processed: bytes lost, the
            # connection takes the unexpected-close path (recovery
            # eligibility, metrics, channel prune).
            self.transport.abort()
            conn.close(unexpected=True)
            return None
        if _chaos.fire("transport.truncate"):
            # Peer died mid-frame: a prefix arrives, then the reset. The
            # decoder must hold the partial frame without corrupting
            # state, and teardown must not double-count.
            conn.on_bytes(bytes(data[: max(1, len(data) // 2)]))
            self.transport.abort()
            conn.close(unexpected=True)
            return None
        if _chaos.fire("transport.corrupt"):
            # One flipped byte: framing/protobuf violations are
            # connection-fatal (never silently misparsed).
            data = bytes([data[0] ^ 0xFF]) + data[1:]
        return data

    async def _drain(self) -> None:
        conn = self.conn
        try:
            while not conn.is_closing() and (
                conn.has_pending() or connection_congested(conn)
            ):
                await congestion_wait(conn)
                if conn.has_pending() and not conn.flush_pending():
                    await asyncio.sleep(0)  # still full; wait again
        finally:
            self._draining = False
            if conn.is_closing():
                self.transport.close()
            elif not self.transport.is_closing():
                try:
                    self.transport.resume_reading()
                except RuntimeError:
                    pass

    def connection_lost(self, exc) -> None:
        # EOF/error: an unexpected close from the peer's side.
        if self.conn is not None:
            self.conn.close(unexpected=True)


class WebSocketTransport:
    """Wraps a ``websockets`` server connection as a byte sink; each frame
    is one binary WS message (ref: connection_websocket.go:14-61). Frames
    queue through a single drain task so pending bytes are bounded — a
    stalled WS peer is shed at MAX_SEND_BUFFER instead of accumulating
    fire-and-forget send tasks."""

    def __init__(self, ws, loop: asyncio.AbstractEventLoop):
        self.ws = ws
        self.loop = loop
        self._queue: deque[bytes] = deque()
        self._queued_bytes = 0
        self._drainer: Optional[asyncio.Future] = None
        self._shed = False

    def write(self, data: bytes) -> None:
        if self._shed:
            return
        if self._queued_bytes + len(data) > MAX_SEND_BUFFER:
            logger.warning("ws peer %s too slow (%d bytes unsent); closing",
                           self.remote_addr(), self._queued_bytes)
            self._shed = True
            self.close()
            return
        self._queue.append(data)
        self._queued_bytes += len(data)
        if self._drainer is None or self._drainer.done():
            self._drainer = asyncio.ensure_future(self._drain(), loop=self.loop)

    async def _drain(self) -> None:
        try:
            while self._queue:
                data = self._queue.popleft()
                self._queued_bytes -= len(data)
                await self.ws.send(data)
        except Exception:
            # The socket is dead: stop accepting writes and close, so the
            # connection doesn't look healthy while dropping every frame.
            self._queue.clear()
            self._queued_bytes = 0
            self._shed = True
            self.close()

    def close(self) -> None:
        asyncio.ensure_future(self.ws.close(), loop=self.loop)

    def remote_addr(self) -> Optional[tuple]:
        return self.ws.remote_address


def _parse_addr(addr: str) -> tuple[str, int]:
    host, _, port = addr.rpartition(":")
    return host or "0.0.0.0", int(port)


async def start_listening(conn_type: ConnectionType, network: str, addr: str):
    """(ref: connection.go:186-242). Returns the server object."""
    host, port = _parse_addr(addr)
    if network == "tcp":
        # Deep accept backlog: a connect storm (10K clients joining after
        # a match start) must queue, not get RSTs (the reference's
        # listener inherits Go's somaxconn-sized backlog).
        loop = asyncio.get_running_loop()
        server = await loop.create_server(
            lambda: _TcpServerProtocol(conn_type), host, port, backlog=4096
        )
        logger.info("listening for %s on tcp %s:%d", conn_type.name, host, port)
        return server
    elif network in ("ws", "websocket"):
        import websockets

        loop = asyncio.get_running_loop()

        async def on_ws(ws):
            try:
                conn = add_connection(WebSocketTransport(ws, loop), conn_type)
            except ConnectionRefusedError:
                await ws.close()
                return
            from .channel import congestion_wait, connection_congested

            try:
                async for message in ws:
                    if isinstance(message, str):
                        message = message.encode()
                    conn.on_bytes(message)
                    if conn.is_closing():
                        break
                    while not conn.is_closing() and (
                        conn.has_pending() or connection_congested(conn)
                    ):
                        await congestion_wait(conn)
                        if conn.has_pending() and not conn.flush_pending():
                            await asyncio.sleep(0)
            except websockets.ConnectionClosed:
                pass
            finally:
                conn.close(unexpected=True)

        server = await websockets.serve(on_ws, host, port, max_size=1 << 20)
        logger.info("listening for %s on ws %s:%d", conn_type.name, host, port)
        return server
    elif network == "rudp":
        from .rudp import RudpServerProtocol, RudpSession

        class RudpTransport:
            def __init__(self, session: RudpSession, addr):
                self.session = session
                self.addr = addr

            def write(self, data: bytes) -> None:
                self.session.send_stream(data)

            def close(self) -> None:
                self.session.fin()

            def remote_addr(self):
                return self.addr

        def on_session(session: RudpSession, addr) -> None:
            try:
                conn = add_connection(RudpTransport(session, addr), conn_type)
            except ConnectionRefusedError:
                session.fin()
                return

            from .channel import connection_congested

            def on_stream(seg: bytes) -> None:
                # ARQ backpressure: while this connection's channels are
                # congested (or messages are stashed behind a full
                # queue), drop the segment *before* it is acked — the
                # peer retransmits, so nothing is lost and its send window
                # stalls, the reliable-UDP analog of pausing a TCP read.
                if conn.has_pending() or connection_congested(conn):
                    session.drop_unacked()
                    return
                conn.on_bytes(seg)
                if conn.has_pending():
                    asyncio.ensure_future(_drain_rudp_stash(conn))

            session.on_stream = on_stream
            # FIN / peer loss must close the gateway connection like the
            # TCP/WS reactors do (recovery depends on this close event).
            session.on_close = lambda: conn.close(unexpected=True)

        async def _drain_rudp_stash(conn) -> None:
            from .channel import congestion_wait

            while not conn.is_closing():
                await congestion_wait(conn)
                if conn.flush_pending():
                    break
                await asyncio.sleep(0)

        loop = asyncio.get_running_loop()
        transport, protocol = await loop.create_datagram_endpoint(
            lambda: RudpServerProtocol(on_session), local_addr=(host, port)
        )
        logger.info("listening for %s on rudp %s:%d", conn_type.name, host, port)
        return protocol
    elif network == "kcp":
        from .channel import congestion_wait, connection_congested
        from .kcp import KcpConn, KcpServerProtocol

        class KcpTransport:
            def __init__(self, session: KcpConn, addr):
                self.session = session
                self.addr = addr

            def write(self, data: bytes) -> None:
                self.session.send_stream(data)

            def close(self) -> None:
                self.session.close()

            def remote_addr(self):
                return self.addr

        def on_session(session: KcpConn, addr) -> None:
            try:
                conn = add_connection(KcpTransport(session, addr), conn_type)
            except ConnectionRefusedError:
                session.close()
                return

            def on_stream(seg: bytes) -> None:
                conn.on_bytes(seg)
                if conn.has_pending() or connection_congested(conn):
                    # KCP-native backpressure: pause delivery; the
                    # advertised receive window shrinks and the peer
                    # stalls. Resume once the congested channel drains
                    # and any stashed messages re-dispatched (lossless).
                    session.pause()
                    asyncio.ensure_future(_resume_when_clear(conn, session))

            session.on_stream = on_stream
            # Dead link / shed closes the gateway connection like the
            # TCP/WS reactors (recovery depends on this close event).
            session.on_close = lambda: conn.close(unexpected=True)

        async def _resume_when_clear(conn, session) -> None:
            while not conn.is_closing():
                await congestion_wait(conn)
                if conn.flush_pending():
                    break
                await asyncio.sleep(0)  # still full; wait for next drain
            if not session.closed:
                session.resume()

        loop = asyncio.get_running_loop()
        transport, protocol = await loop.create_datagram_endpoint(
            lambda: KcpServerProtocol(on_session), local_addr=(host, port)
        )
        logger.info("listening for %s on kcp %s:%d", conn_type.name, host, port)
        return protocol
    raise ValueError(f"unsupported network type: {network}")


async def flush_loop(interval: float = 0.001) -> None:
    """Shared send pump (ref: the per-conn 1ms flush goroutine,
    connection.go:180-184). The 1ms cadence is the packet-coalescing
    window; each cycle only visits connections that queued output since
    the last one, so idle connections cost nothing."""
    from . import metrics

    last_sample = 0.0
    while True:
        # Inbound first: deferred fast-path runs reach their channel
        # queue this cycle, so a tick landing between pump cycles sees
        # them no later than the per-read dispatch would have allowed.
        flush_pending_ingest()
        for conn in drain_pending_flush():
            if not conn.is_closing() and conn.send_queue:
                conn.flush(fair=True)
                if conn.send_queue and not conn.is_closing():
                    # Fairness carry-over: the cap left entries queued;
                    # they go out next cycle, after everyone else's turn.
                    requeue_flush(conn)
        # Advance the edge plane's slow-consumer/quarantine ladder —
        # free while no peer is in distress (core/edge.py).
        edge_tick()
        now = time.monotonic()
        if now - last_sample >= 5.0:  # asyncio_tasks gauge (goroutines analog)
            last_sample = now
            metrics.sample_runtime()
            # Re-publish the overload gauges on the same heartbeat so a
            # scrape never reads a stale level after a quiet stretch
            # (the governor also publishes on every transition).
            from .overload import governor

            metrics.overload_level.set(int(governor.level))
            metrics.overload_pressure.set(governor.pressure)
        await asyncio.sleep(interval)


async def drain_gateway(listeners: Optional[list] = None) -> dict:
    """Graceful SIGTERM drain (doc/device_recovery.md): stop accepting,
    park every client with a structured ``ServerBusyMessage`` (they back
    off ``overload_retry_after_ms`` and reconnect — to this gateway
    post-restart, or wherever a redirect points them), say goodbye on
    every live trunk so the control-plane leader re-maps this shard
    immediately instead of waiting out ``global_death_miss_epochs``, and
    write a final fsync'd snapshot through the shared ``write_snapshot``
    path. Returns a small report (tested directly; the SIGTERM handler
    is just this plus process exit)."""
    from .connection import all_connections
    from .message import MessageContext
    from .overload import governor
    from .types import MessageType
    from ..protocol import control_pb2

    report = {"clients_parked": 0, "goodbye_peers": 0, "snapshot": ""}
    logger.warning("SIGTERM: draining gateway (park clients, trunk "
                   "goodbye, final snapshot)")
    for srv in listeners or []:
        try:
            srv.close()
        except Exception:
            pass
    # Park clients: a structured retry-after, then the socket closes —
    # the same ServerBusyMessage shape L3 admission refusals use, so
    # every client library already knows how to honor it.
    busy = control_pb2.ServerBusyMessage(
        reason="shutdown",
        retryAfterMs=global_settings.overload_retry_after_ms,
        overloadLevel=int(governor.level),
    )
    for conn in list(all_connections().values()):
        if conn.connection_type != ConnectionType.CLIENT:
            continue
        if conn.is_closing():
            continue
        conn.send(MessageContext(
            msg_type=MessageType.SERVER_BUSY, msg=busy, channel_id=0,
        ))
        conn.flush()
        report["clients_parked"] += 1
    for conn in list(all_connections().values()):
        if conn.connection_type == ConnectionType.CLIENT:
            conn.close()
    # Trunk goodbye: peers drop the link now and the leader fast-tracks
    # the death declaration (federation/control.py on_peer_goodbye).
    if global_settings.federation_config:
        from ..federation import plane as fed_plane

        if fed_plane.active:
            report["goodbye_peers"] = fed_plane.announce_goodbye()
    # Final snapshot LAST, after the parks above stopped mutating
    # subscriber state: fsync-then-rename, so a kill -9 racing this
    # drain still leaves a consistent file.
    if global_settings.snapshot_path:
        from .snapshot import take_snapshot, write_snapshot
        from .wal import wal

        try:
            snap = take_snapshot()
            await asyncio.to_thread(
                write_snapshot, snap, global_settings.snapshot_path
            )
            wal.checkpoint(snap.walSeq)
            report["snapshot"] = global_settings.snapshot_path
            logger.info("final snapshot of %d channels written to %s",
                        len(snap.channels), global_settings.snapshot_path)
        except Exception:
            logger.exception("final shutdown snapshot failed")
    if global_settings.wal_path:
        # Final durability barrier off the loop: everything appended so
        # far fsyncs before the process exits (a parallel snapshot
        # failure above must not lose the journal tail either).
        from .wal import wal

        if wal.enabled:
            await asyncio.to_thread(wal.flush)
            wal.stop()
    logger.warning(
        "drain complete: %d clients parked, %d trunk peers said goodbye",
        report["clients_parked"], report["goodbye_peers"],
    )
    return report


def install_sigterm_drain(listeners: list, tasks: list,
                          serve_task: Optional[asyncio.Task] = None) -> None:
    """Wire SIGTERM to the graceful drain; after the drain the serve
    tasks are cancelled so run_server's gather returns and the process
    exits through the normal (trace-dump-registered) teardown.
    ``serve_task`` (run_server's own task) is cancelled too: during the
    wait-for-master boot phase run_server blocks on the GLOBAL-channel
    possession event, not on any task in ``tasks`` — without this a
    SIGTERM in that window would drain and then hang forever, exactly
    the stuck-boot case where an operator reaches for SIGTERM."""
    import signal

    def _on_sigterm() -> None:
        async def _drain_and_exit():
            try:
                await drain_gateway(listeners)
            finally:
                for t in tasks:
                    t.cancel()
                if serve_task is not None and not serve_task.done():
                    serve_task.cancel()

        asyncio.ensure_future(_drain_and_exit())

    try:
        asyncio.get_running_loop().add_signal_handler(
            signal.SIGTERM, _on_sigterm
        )
    except (NotImplementedError, RuntimeError):
        logger.info("SIGTERM drain unavailable on this platform")


async def run_server(argv: Optional[list[str]] = None) -> None:
    """Full bootstrap (ref: cmd/main.go:12-56)."""
    global_settings.parse_flags(argv)
    # Map the reference's zap levels (-4 Trace..2 Error) onto logging,
    # clamping out-of-range values toward the nearest end.
    level_map = {-4: 4, -3: 6, -2: 8, -1: 10, 0: 20, 1: 30, 2: 40}
    zap_level = global_settings.log_level
    if zap_level is None:
        zap_level = 0
    zap_level = max(-4, min(2, zap_level))
    init_logs(
        level=level_map[zap_level],
        log_file=global_settings.log_file,
        development=global_settings.development,
    )
    if global_settings.log_file:
        from ..utils.logger import attach_security_log_file

        attach_security_log_file(global_settings.log_file)
    if global_settings.profile:
        from .profiling import start_profiling

        start_profiling(global_settings.profile, global_settings.profile_path)
    # Flight recorder (doc/observability.md): configure from the -trace*
    # flags, then wire the diagnostic signals — SIGUSR1 dumps live
    # tasks/threads (no -profile tasks pre-arming needed), SIGUSR2 dumps
    # the recorder ring as Perfetto JSON — and the shutdown dump.
    from . import tracing
    from .affinity import configure_from_settings as configure_affinity
    from .profiling import install_task_dump_signal

    configure_affinity()
    tracing.configure_from_settings()
    install_task_dump_signal(global_settings.profile_path)
    tracing.install_trace_dump_signal()
    if global_settings.trace_enabled:
        tracing.register_shutdown_dump()
        logger.info(
            "flight recorder armed: %d spans/thread, anomaly dumps keep "
            "the last %d ticks under %s/ (SIGUSR2 = manual dump, "
            "SIGUSR1 = task dump; doc/observability.md)",
            global_settings.trace_ring_spans,
            global_settings.trace_dump_ticks,
            global_settings.profile_path,
        )
    if global_settings.chaos_config:
        from ..chaos import arm_from_file

        arm_from_file(global_settings.chaos_config)
        logger.warning(
            "CHAOS ARMED from %s — deterministic fault injection is live",
            global_settings.chaos_config,
        )
    init_connections(global_settings.server_fsm, global_settings.client_fsm)
    init_channels()
    init_anti_ddos()
    if global_settings.overload_enabled:
        logger.info(
            "overload governor armed: ladder L0-L3, enter=%s exit=%s, "
            "retry-after %dms (doc/overload.md)",
            global_settings.overload_enter_thresholds,
            global_settings.overload_exit_thresholds,
            global_settings.overload_retry_after_ms,
        )
    if global_settings.balancer_enabled:
        logger.info(
            "spatial load balancer armed: imbalance enter=%.2f exit=%.2f, "
            "budget %d/epoch (%d ticks), cooldown %d ticks "
            "(doc/balancer.md)",
            global_settings.balancer_imbalance_enter,
            global_settings.balancer_imbalance_exit,
            global_settings.balancer_budget_per_epoch,
            global_settings.balancer_epoch_ticks,
            global_settings.balancer_cooldown_ticks,
        )

    # Fail boot on a missing auth provider outside development: raising at
    # auth time would be swallowed by the per-message isolator and the
    # misconfiguration would only surface as dangling unauthenticated
    # connections in the logs.
    from .auth import get_auth_provider

    if get_auth_provider() is None and not global_settings.development:
        logger.error(
            "no auth provider configured and not in development mode; "
            "set one with set_auth_provider() before run_server()"
        )
        raise SystemExit(1)

    from ..spatial.controller import init_spatial_controller

    init_spatial_controller()

    fed_plane = None
    if global_settings.federation_config:
        from ..federation import init_federation, plane as fed_plane
        from ..spatial.controller import get_spatial_controller

        init_federation(
            global_settings.federation_config,
            global_settings.federation_gateway_id,
            get_spatial_controller(),
        )
        logger.info(
            "federation armed: gateway %r in %s (doc/federation.md)",
            global_settings.federation_gateway_id,
            global_settings.federation_config,
        )

    # Delivery-SLO plane (doc/observability.md): ingest->fan-out
    # latency stamping, burn-rate tracking, breach anomaly dumps, and
    # (federated) the fleet metric digests on the control epoch.
    from . import slo as slo_mod

    slo_mod.configure_from_settings()
    if global_settings.slo_enabled:
        logger.info(
            "SLO plane armed: %s (burn-rate windows per SLO; breaches "
            "freeze a flight-recorder dump; doc/observability.md)",
            ", ".join(sorted(slo_mod.slo.status())),
        )

    # The ops surface replaces the bare metrics listener: /metrics is
    # one of its routes (scrape configs unchanged), /healthz + /readyz
    # feed the k8s/compose probes, /introspect + /fleet feed operators
    # and scripts/fleetctl.py (doc/observability.md).
    from .opshttp import serve_ops

    if global_settings.metrics_port:
        try:
            serve_ops(global_settings.metrics_port)
        except OSError:
            logger.warning("metrics port %d unavailable; ops surface "
                           "disabled", global_settings.metrics_port)

    # Durable-state boot BEFORE the trunks/listeners come up: restore
    # the snapshot and replay the WAL tail (doc/persistence.md) so the
    # resurrection announce is armed by the time the first trunk
    # handshakes, then start the journal writer continuing the sequence
    # above everything replay observed.
    if global_settings.wal_path:
        from .wal import boot_replay, wal

        replay_report = boot_replay(
            global_settings.snapshot_path, global_settings.wal_path
        )
        wal.start(global_settings.wal_path,
                  initial_seq=replay_report.get("max_seq", 0))
    elif global_settings.snapshot_path:
        from .snapshot import boot_restore

        # Restore-at-boot (corrupt/missing files never block boot).
        boot_restore(global_settings.snapshot_path)

    tasks = [
        asyncio.ensure_future(flush_loop()),
        asyncio.ensure_future(unauth_reaper_loop()),
    ]
    if fed_plane is not None:
        # Trunk listener + per-peer dial loops + the handover timeout
        # reaper; staged-handle expiry needs the recovery reaper too.
        await fed_plane.start()
        if not global_settings.server_conn_recoverable:
            tasks.append(asyncio.ensure_future(connection_recovery_loop()))
    if global_settings.server_conn_recoverable:
        tasks.append(asyncio.ensure_future(connection_recovery_loop()))

    if global_settings.snapshot_path:
        from .snapshot import snapshot_loop

        # The periodic skip-unchanged fsync-then-rename writer on
        # -snapshot-interval (each write checkpoints the WAL).
        tasks.append(asyncio.ensure_future(snapshot_loop(
            global_settings.snapshot_path, global_settings.snapshot_interval_s
        )))

    listeners: list = []
    try:
        listeners.append(await start_listening(
            ConnectionType.SERVER,
            global_settings.server_network,
            global_settings.server_address,
        ))
    except OSError as e:
        logger.error(
            "cannot listen on %s %s: %s", global_settings.server_network,
            global_settings.server_address, e,
        )
        raise SystemExit(1)
    # SIGTERM drains instead of killing mid-tick: final fsync'd
    # snapshot, clients parked with ServerBusyMessage{retryAfterMs},
    # trunk goodbye so the shard re-maps immediately
    # (doc/device_recovery.md). The current task is handed over so a
    # SIGTERM during the wait-for-master phase below exits instead of
    # draining into a hang.
    try:
        serve_task = asyncio.current_task()
    except RuntimeError:
        serve_task = None
    install_sigterm_drain(listeners, tasks, serve_task)
    try:
        if global_settings.client_network_wait_master_server:
            logger.info("waiting for the GLOBAL channel to be possessed...")
            await events.global_channel_possessed.wait()
        listeners.append(await start_listening(
            ConnectionType.CLIENT,
            global_settings.client_network,
            global_settings.client_address,
        ))
        await asyncio.gather(*tasks)
    except asyncio.CancelledError:
        logger.info("serve tasks cancelled; gateway exiting")

"""SUB/UNSUB result notifications (ref: pkg/channeld/subscription.go:150-187)."""

from __future__ import annotations

from typing import TYPE_CHECKING, Optional

from ..protocol import control_pb2
from .types import MessageType

if TYPE_CHECKING:
    from .channel import Channel


def send_subscribed(
    recipient, ch: "Channel", conn_to_sub, stub_id: int, sub_options
) -> None:
    from .message import MessageContext

    recipient.send(
        MessageContext(
            msg_type=MessageType.SUB_TO_CHANNEL,
            msg=control_pb2.SubscribedToChannelResultMessage(
                connId=conn_to_sub.id,
                subOptions=sub_options,
                connType=conn_to_sub.connection_type,
                channelType=ch.channel_type,
            ),
            channel_id=ch.id,
            stub_id=stub_id,
        )
    )


def send_unsubscribed(
    recipient, ch: "Channel", conn_to_unsub: Optional[object], stub_id: int
) -> None:
    from .message import MessageContext

    if conn_to_unsub is None:
        conn_to_unsub = recipient
    recipient.send(
        MessageContext(
            msg_type=MessageType.UNSUB_FROM_CHANNEL,
            msg=control_pb2.UnsubscribedFromChannelResultMessage(
                connId=conn_to_unsub.id,
                connType=conn_to_unsub.connection_type,
                channelType=ch.channel_type,
            ),
            channel_id=ch.id,
            stub_id=stub_id,
        )
    )

"""Runtime thread-affinity assertions: the thread model's twin.

``analysis/threadmodel.py`` is a *static* claim about which execution
domain every function runs in; this module is the cheap runtime checker
that validates the claim against reality (doc/concurrency.md).  The
domain names are the same on both sides — ``tests/test_affinity.py``
pins that the two tables agree — so a static-model drift and a runtime
drift cannot diverge silently.

Semantics:

- Domains map to OS *threads*: every loop domain (tick-loop,
  trunk-reader, boot-loop) collapses onto the one loop thread; each
  own-thread domain is its own (:data:`DOMAIN_THREADS`).
- A domain's **entry point** calls :func:`enter` — it (re)binds the
  domain's thread key to the current thread ident.  The WAL writer
  binds ``wal-writer`` at loop start, the device worker binds
  ``device-worker`` per body, the GLOBAL tick re-binds ``loop`` every
  tick (so a fresh event loop in a new test rebinds cleanly).
- A function that must only run in a domain calls :func:`expect` — a
  mismatch against the bound ident is a **violation**: recorded (with
  the call site), counted, warned once per site, and raised when
  ``strict``.  An unbound domain auto-binds (the checker observes
  reality before it enforces it).

Disarmed (the default in production) every hook is ONE attribute load.
Tier-1 arms the checker for the whole run (tests/conftest.py) and
fails any test that produced a violation; ``-debug-affinity`` arms it
on a live gateway.
"""

from __future__ import annotations

import threading

from ..utils.logger import get_logger

logger = get_logger("affinity")

# Domain -> thread key. MUST mirror analysis/threadmodel.py DOMAINS
# (loop domains share the loop thread; own-thread domains are their
# own key). tests/test_affinity.py asserts the two tables agree.
DOMAIN_THREADS: dict[str, str] = {
    "tick-loop": "loop",
    "trunk-reader": "loop",
    "boot-loop": "loop",
    "wal-writer": "wal-writer",
    "device-worker": "device-worker",
    "trace-dumper": "trace-dumper",
    "ops-http": "ops-http",
    "grpc-pool": "grpc-pool",
    "loop-offload": "loop-offload",
}


class AffinityViolation(AssertionError):
    pass


class AffinityChecker:
    """Process-wide checker (one instance: ``affinity``)."""

    def __init__(self):
        self.armed = False
        self.strict = False
        self.reset()

    def reset(self) -> None:
        """Drop every binding and recorded violation (test hook; also
        safe live — domains re-bind on their next entry)."""
        self._bound: dict[str, int] = {}
        self.violations: list[dict] = []
        self._warned: set[tuple] = set()

    def arm(self, strict: bool = False) -> None:
        self.reset()
        self.armed = True
        self.strict = strict

    def disarm(self) -> None:
        self.armed = False
        self.reset()

    # ---- the two hooks (hot paths guard on .armed: one attr load) --------

    def enter(self, domain: str) -> None:
        """The current thread IS ``domain``'s thread from here on —
        called by the domain's entry point (thread body / handler /
        the GLOBAL tick). Re-binding is the point: a fresh writer
        thread or a new event loop takes the binding over."""
        if not self.armed:
            return
        self._bound[DOMAIN_THREADS[domain]] = threading.get_ident()

    def expect(self, domain: str) -> None:
        """Assert the caller is on ``domain``'s bound thread. Unbound
        auto-binds (observe first, enforce after)."""
        if not self.armed:
            return
        key = DOMAIN_THREADS[domain]
        ident = threading.get_ident()
        bound = self._bound.get(key)
        if bound is None:
            self._bound[key] = ident
            return
        if bound != ident:
            self._violate(domain, key, bound, ident)

    # ---- violation plumbing ----------------------------------------------

    def _violate(self, domain: str, key: str, bound: int,
                 ident: int) -> None:
        import sys

        frame = sys._getframe(2)
        where = f"{frame.f_code.co_filename}:{frame.f_lineno}"
        names = {t.ident: t.name for t in threading.enumerate()}
        record = {
            "domain": domain,
            "thread_key": key,
            "bound": names.get(bound, str(bound)),
            "actual": names.get(ident, str(ident)),
            "where": where,
        }
        self.violations.append(record)
        del self.violations[:-256]
        site = (domain, where)
        if site not in self._warned:
            self._warned.add(site)
            logger.warning(
                "thread-affinity violation: %s code ran on thread %r "
                "(bound to %r) at %s (doc/concurrency.md)",
                domain, record["actual"], record["bound"], where,
            )
        if self.strict:
            raise AffinityViolation(
                f"{domain} code on thread {record['actual']!r} "
                f"(bound {record['bound']!r}) at {where}"
            )

    def report(self) -> dict:
        return {
            "armed": self.armed,
            "strict": self.strict,
            "bound": dict(self._bound),
            "violations": list(self.violations),
        }


# The process-wide checker. Hook sites hold a module reference and the
# disarmed cost is one attribute load.
affinity = AffinityChecker()


def configure_from_settings() -> None:
    """Apply the -debug-affinity flag (run_server boot path)."""
    from .settings import global_settings as st

    if st.debug_affinity:
        affinity.arm(strict=False)
        logger.info(
            "runtime thread-affinity assertions ARMED (-debug-affinity): "
            "violations are recorded and warned, not raised "
            "(doc/concurrency.md)",
        )


def reset_affinity() -> None:
    """Test hook."""
    affinity.disarm()

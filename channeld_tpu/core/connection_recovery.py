"""Server-connection recovery (ref: pkg/channeld/connection_recovery.go).

When a recoverable server connection drops unexpectedly, a PIT-keyed
handle preserves its previous connection id, and each channel stashes the
old subscription (and owner flag). When a connection re-authenticates
with the same PIT, it reclaims the previous id, channels re-subscribe it
(skipping the first fan-out), stream ``ChannelDataRecoveryMessage`` with
full state + extension payload, and after the recovery window a single
``RECOVERY_END`` closes the process.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Optional

from ..protocol import control_pb2
from ..utils.anyutil import pack_any
from ..utils.logger import get_logger
from .settings import global_settings
from .types import BroadcastType, ChannelType, GLOBAL_CHANNEL_ID, MessageType

if TYPE_CHECKING:
    from .channel import Channel
    from .connection import Connection

logger = get_logger("recovery")

# Window for all channels to stream their recovery data before RECOVERY_END
# (ref: connection_recovery.go:15-16).
CHANNEL_DATA_RECOVERY_TIMEOUT = 1.0


# Lifetime of a PRE-STAGED handle (client redirect, federation/plane.py)
# when server_conn_recover_timeout_ms is 0 ("never"): a redirected
# client that never shows up must not pin its reserved conn id and
# per-channel stash entries forever.
STAGED_HANDLE_TTL_MS = 30_000


@dataclass
class ConnectionRecoverHandle:
    prev_conn_id: int
    disconn_time: float
    new_conn: Optional["Connection"] = None
    start_recovery_time: float = 0.0
    # True for a handle created ahead of any connection (a client
    # redirect's pre-staged session, doc/federation.md): its conn id is
    # reserved (not a dead socket's), and its expiry is a quiet cleanup
    # — never a ServerLostEvent.
    staged: bool = False

    def is_timed_out(self) -> bool:
        if self.new_conn is not None and not self.new_conn.is_closing():
            # Claimed: recovery is in progress (RECOVERY_END ends it
            # within the recovery window). Expiring now would purge the
            # per-channel stashes out from under the live resume — a
            # reconnect landing just inside the window must finish.
            return False
        timeout_ms = global_settings.server_conn_recover_timeout_ms
        if self.staged and timeout_ms <= 0:
            timeout_ms = STAGED_HANDLE_TTL_MS
        return timeout_ms > 0 and (time.monotonic() - self.disconn_time) > timeout_ms / 1000.0


@dataclass
class RecoverableSubscription:
    conn_handle: ConnectionRecoverHandle
    is_owner: bool
    old_sub_time: float
    old_sub_options: control_pb2.ChannelSubscriptionOptions = field(
        default_factory=control_pb2.ChannelSubscriptionOptions
    )


# PIT -> handle (ref: connectionRecoverHandles map).
_recover_handles: dict[str, ConnectionRecoverHandle] = {}

# Hard cap on outstanding handles. With server_conn_recover_timeout_ms=0
# handles never time out, so a fleet of crashed-and-replaced servers
# (each with a fresh PIT) would grow the table forever — chaos soaks
# with repeated transport resets surfaced exactly this. At the cap the
# oldest-disconnected handle is evicted: its server has had the longest
# window to return, and an evicted PIT simply re-joins without recovery.
MAX_RECOVER_HANDLES = 4096


def get_recover_handle(pit: str) -> Optional[ConnectionRecoverHandle]:
    return _recover_handles.get(pit)


def make_recoverable(conn: "Connection") -> None:
    """(ref: connection_recovery.go:34-41)."""
    if (
        conn.pit not in _recover_handles
        and len(_recover_handles) >= MAX_RECOVER_HANDLES
    ):
        from . import metrics

        # Never evict an in-progress recovery (new_conn set): the reaper
        # only scans this table, so an evicted in-progress handle would
        # never get RECOVERY_END and its connection would stay in
        # recovery forever. Idle handles (server not back yet) are safe
        # to drop — the server simply re-joins without recovery. With no
        # idle handle to evict (every slot mid-recovery — a mass-restart
        # burst), the safe degradation is to make THIS close
        # non-recoverable rather than wedge a recovering peer.
        idle = [p for p, h in _recover_handles.items() if h.new_conn is None]
        if not idle:
            logger.warning(
                "recovery handle table full (%d) with every handle "
                "mid-recovery; %s will re-join without recovery",
                MAX_RECOVER_HANDLES, conn.pit,
            )
            return
        oldest = min(idle, key=lambda p: _recover_handles[p].disconn_time)
        # An evicted server can never recover — same terminal fate as a
        # window expiry, so it takes the same single ServerLost path
        # (stash purge + one event; failover re-hosts its cells).
        expire_recover_handle(oldest, _recover_handles[oldest],
                              reason="evicted")
        metrics.recover_handles_evicted.inc()
        logger.warning(
            "recovery handle table full (%d); evicted oldest idle pit %s",
            MAX_RECOVER_HANDLES, oldest,
        )
    handle = ConnectionRecoverHandle(
        prev_conn_id=conn.id, disconn_time=time.monotonic()
    )
    _recover_handles[conn.pit] = handle
    conn.recover_handle = handle


def expire_recover_handle(
    pit: str, handle: ConnectionRecoverHandle, reason: str = "timeout"
) -> bool:
    """THE server-dead-for-good path. Every way a recovery can end
    without the server returning — window expiry noticed by the reaper
    loop, expiry noticed by a channel tick, handle eviction at the table
    cap — funnels here, so failover, metrics and tests all key off ONE
    ``ServerLostEvent`` per loss. Idempotent: only the caller that still
    finds the handle installed processes it.

    Collects (and purges) the dead server's per-channel recovery stash —
    without the purge, a crash-looping fleet would leak a
    RecoverableSubscription into every channel each server subscribed
    to. Channels configured to die with their owner still do; everything
    else is left for the failover plane (spatial cells re-host, other
    types stay ownerless with their drops counted).

    A STAGED handle (pre-created for a client redirect that never
    arrived, doc/federation.md) expires quietly instead: purge its
    stash, release its reserved conn id, no ServerLostEvent — no
    server died."""
    if _recover_handles.get(pit) is not handle:
        return False
    del _recover_handles[pit]
    if handle.staged:
        from .channel import all_channels as _staged_channels
        from .connection import release_connection_id

        for ch in list(_staged_channels().values()):
            ch.recoverable_subs.pop(pit, None)
        release_connection_id(handle.prev_conn_id)
        logger.info(
            "staged recovery handle for %s expired unclaimed (%s); "
            "reserved conn id %d released", pit, reason,
            handle.prev_conn_id,
        )
        return True
    from . import events, metrics
    from .channel import _remove_channel_after_owner_removed, all_channels

    owned: list[int] = []
    subscribed: list[int] = []
    for ch in list(all_channels().values()):
        rsub = ch.recoverable_subs.pop(pit, None)
        if rsub is None:
            continue
        if getattr(rsub, "is_owner", False):
            owned.append(ch.id)
            if global_settings.get_channel_settings(
                ch.channel_type
            ).remove_channel_after_owner_removed:
                _remove_channel_after_owner_removed(ch)
        else:
            subscribed.append(ch.id)
    metrics.server_lost.inc()
    logger.warning(
        "server %s (conn %d) lost for good (%s): %d owned / %d "
        "subscribed channels stashed",
        pit, handle.prev_conn_id, reason, len(owned), len(subscribed),
    )
    events.server_lost.broadcast(events.ServerLostData(
        pit=pit,
        prev_conn_id=handle.prev_conn_id,
        owned_channel_ids=owned,
        subscribed_channel_ids=subscribed,
        reason=reason,
    ))
    return True


def stage_recovery_handle(
    pit: str, channel_ids: list[int], sub_options=None
) -> ConnectionRecoverHandle:
    """Pre-create the recovery state a redirected client will claim on
    arrival (doc/federation.md): a handle keyed by the client's PIT
    holding a RESERVED connection id, plus a recoverable subscription on
    each of ``channel_ids`` — so when the client connects here and auths
    with that PIT, the ordinary recovery machinery (recover_from_handle
    + tick_recoverable_subscriptions) restores its session: previous-id
    reclaim, re-subscription with skipFirstFanOut, full state via
    ChannelDataRecoveryMessage, RECOVERY_END. No fresh login, no
    SUB_TO_CHANNEL round-trips.

    Re-staging an outstanding PIT (a second redirect racing the first,
    or a redirect while the client already holds a recovery handle here)
    merges: the existing handle and its conn id are kept, the new
    channels' stashes are added, and the staging clock restarts."""
    from .channel import get_channel
    from .connection import release_connection_id, reserve_connection_id

    handle = _recover_handles.get(pit)
    if handle is not None and handle.new_conn is None:
        # Outstanding handle (staged earlier, or a real disconnect whose
        # window is still open): reuse it — its prev_conn_id is the id
        # this client should reclaim regardless of which path made it.
        handle.disconn_time = time.monotonic()
    else:
        if (
            pit not in _recover_handles
            and len(_recover_handles) >= MAX_RECOVER_HANDLES
        ):
            # Same cap policy as make_recoverable, same safe degradation:
            # with no room, the redirect proceeds unstaged (the client
            # re-joins the destination without recovery).
            raise RuntimeError("recovery handle table full")
        handle = ConnectionRecoverHandle(
            prev_conn_id=reserve_connection_id(),
            disconn_time=time.monotonic(),
            staged=True,
        )
        old = _recover_handles.get(pit)
        if old is not None and old.staged:
            release_connection_id(old.prev_conn_id)
        _recover_handles[pit] = handle

    from .wal import wal as _wal

    if _wal.enabled:
        # Staged handles are durable (doc/persistence.md): a redirected
        # client must still resume here after a crash-restart.
        _wal.log_staged_handle(pit, channel_ids)
    opts = control_pb2.ChannelSubscriptionOptions()
    if sub_options is not None:
        opts.MergeFrom(sub_options)
    now = time.monotonic()
    for cid in channel_ids:
        ch = get_channel(cid)
        if ch is None or ch.is_removing():
            continue
        ch.recoverable_subs[pit] = RecoverableSubscription(
            conn_handle=handle,
            is_owner=False,
            old_sub_time=now,
            old_sub_options=opts,
        )
    return handle


def staged_handle_snapshot() -> list[tuple[str, list[int]]]:
    """(pit, channel ids) for every outstanding STAGED handle — the
    gateway snapshot's extras (doc/persistence.md): a staged redirect
    must survive a crash-restart or the redirected client re-auths
    against a gateway that promised it recovery. Live-session handles
    (a real disconnect mid-window) ride too: their channel set is
    whatever channels hold their recoverable subs."""
    from .channel import all_channels

    channels_of: dict[str, list[int]] = {}
    for cid, ch in all_channels().items():
        if ch.is_removing():
            continue
        for pit, rsub in ch.recoverable_subs.items():
            channels_of.setdefault(pit, []).append(cid)
    out: list[tuple[str, list[int]]] = []
    for pit, handle in _recover_handles.items():
        if handle.new_conn is not None:
            continue  # mid-recovery; the live connection owns it now
        out.append((pit, sorted(channels_of.get(pit, []))))
    return sorted(out)


def recover_from_handle(conn: "Connection", handle: ConnectionRecoverHandle) -> None:
    """Reclaim the previous connection id (ref: connection_recovery.go:47-63)."""
    from . import connection as connection_mod

    prev = connection_mod._all_connections.pop(handle.prev_conn_id, None)
    if prev is not None and prev is not conn and not prev.is_closing():
        # Previous id is still actively used — recovery fails.
        connection_mod._all_connections[handle.prev_conn_id] = prev
        conn.logger.error("failed to recover: previous connection id is in use")
        return
    connection_mod._all_connections.pop(conn.id, None)
    conn.id = handle.prev_conn_id
    connection_mod._all_connections[conn.id] = conn
    # A staged handle's id was only a reservation until this moment.
    connection_mod.release_connection_id(handle.prev_conn_id)
    conn.recover_handle = handle
    handle.new_conn = conn
    handle.start_recovery_time = time.monotonic()
    from . import metrics

    metrics.connection_recovered.inc()


def tick_connection_recovery_once() -> None:
    """Reap timed-out handles; end completed recoveries
    (ref: connection_recovery.go:65-92)."""
    from .message import MessageContext

    for pit, handle in list(_recover_handles.items()):
        if handle.is_timed_out():
            expire_recover_handle(pit, handle)
            continue
        if handle.new_conn is None:
            continue
        if time.monotonic() - handle.start_recovery_time > CHANNEL_DATA_RECOVERY_TIMEOUT:
            handle.new_conn.send(
                MessageContext(
                    msg_type=MessageType.RECOVERY_END,
                    msg=control_pb2.EndRecoveryMessage(),
                    channel_id=GLOBAL_CHANNEL_ID,
                )
            )
            handle.new_conn.recover_handle = None
            del _recover_handles[pit]


async def connection_recovery_loop() -> None:
    while True:
        tick_connection_recovery_once()
        await asyncio.sleep(1.0)


def tick_recoverable_subscriptions(ch: "Channel") -> None:
    """Per-channel recovery tick (ref: connection_recovery.go:94-171)."""
    from .message import MessageContext
    from .subscription import subscribe_to_channel

    for pit, rsub in list(ch.recoverable_subs.items()):
        handle = rsub.conn_handle
        if handle.is_timed_out():
            # Per-PIT expiry through the single ServerLost path (which
            # also pops this channel's stash). The old in-place clear
            # wiped OTHER servers' stashes on this channel and never
            # told anyone the server was gone.
            expire_recover_handle(pit, handle)
            continue

        if handle.new_conn is None:
            continue

        new_conn = handle.new_conn
        if rsub.is_owner:
            if ch.has_owner():
                ch.logger.warning("failed to restore channel owner: already owned")
            else:
                ch.set_owner(new_conn)
                if ch.channel_type == ChannelType.GLOBAL:
                    from . import events

                    events.global_channel_possessed.broadcast(ch)

        # The recovered subscriber already has (stale) state; recovery data
        # replaces the first full fan-out.
        rsub.old_sub_options.skipFirstFanOut = True
        subscribe_to_channel(new_conn, ch, rsub.old_sub_options)

        data_msg = ch.get_data_message()
        if data_msg is None:
            del ch.recoverable_subs[pit]
            continue
        recovery_msg = control_pb2.ChannelDataRecoveryMessage(
            channelId=ch.id,
            channelType=ch.channel_type,
            metadata=ch.metadata,
            subTime=int(rsub.old_sub_time * 1000),
            subOptions=rsub.old_sub_options,
            channelData=pack_any(data_msg),
        )
        if ch.has_owner():
            recovery_msg.ownerConnId = ch.get_owner().id
        if ch.data is not None and ch.data.extension is not None:
            ext_msg = ch.data.extension.get_recovery_data_message()
            if ext_msg is not None:
                recovery_msg.recoveryData.CopyFrom(pack_any(ext_msg))
        new_conn.send(
            MessageContext(
                msg_type=MessageType.RECOVERY_CHANNEL_DATA,
                msg=recovery_msg,
                channel_id=ch.id,
            )
        )
        del ch.recoverable_subs[pit]

        if global_settings.get_channel_settings(
            ch.channel_type
        ).send_owner_lost_and_recovered:
            _schedule_owner_recovered_broadcast(ch)


def _schedule_owner_recovered_broadcast(ch: "Channel") -> None:
    """Broadcast CHANNEL_OWNER_RECOVERED after the recovery window."""
    from .message import MessageContext

    def _broadcast():
        ch.broadcast(
            MessageContext(
                msg_type=MessageType.CHANNEL_OWNER_RECOVERED,
                msg=control_pb2.ChannelOwnerRecoveredMessage(),
                broadcast=BroadcastType.ALL_BUT_OWNER,
                channel_id=ch.id,
            )
        )

    try:
        loop = asyncio.get_running_loop()
        loop.call_later(CHANNEL_DATA_RECOVERY_TIMEOUT, _broadcast)
    except RuntimeError:
        _broadcast()  # no loop (tests): deliver immediately


def reset_recovery() -> None:
    """Test hook."""
    _recover_handles.clear()
